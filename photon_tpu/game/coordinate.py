"""GAME coordinates: fixed-effect and random-effect training units.

Rebuild of the reference's ``algorithm.Coordinate`` hierarchy
(``FixedEffectCoordinate`` / ``RandomEffectCoordinate`` — SURVEY.md §2.2,
§3.1): a coordinate owns one slice of the model, can ``train`` it against the
residuals (offsets) of the other coordinates, and can ``score`` data with it.

TPU-native shapes (SURVEY.md §2.5 parallelism table):

- **FixedEffectCoordinate** — whole-dataset GLM fit: the batch is sharded
  over the mesh's data axis and gradients ``psum`` over ICI
  (DistributedGlmObjective); the reference's broadcast + treeAggregate loop
  collapses into one XLA program per optimizer run.
- **RandomEffectCoordinate** — per-entity independent solves: each row-count
  bucket is a ``[E, R, ...]`` block, and the whole per-entity solver
  (L-BFGS/OWL-QN/TRON with masked line search) runs under ``jax.vmap`` over
  the entity axis — thousands of entity solves advance in lockstep, with
  converged lanes frozen (SURVEY.md §7 'hard parts').  Under a mesh the
  entity axis is sharded across chips, the analog of the reference's
  ``RandomEffectDatasetPartitioner`` hash partitioning.

Device-resident data is cached in dataset objects (``FixedEffectDeviceData``
/ ``RandomEffectDeviceData``) that coordinates share across sweep
configurations and descent iterations — only the per-iteration offsets move
host→device (the reference, by contrast, re-broadcasts coefficients every
iteration).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Protocol, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from photon_tpu.core.normalization import NormalizationContext
from photon_tpu.core.objective import GlmObjective
from photon_tpu.core.optimizers import OptimizationStatesTracker
from photon_tpu.core.problem import GlmOptimizationProblem, ProblemConfig
from photon_tpu.data.batch import DenseBatch, SparseBatch, with_offset
from photon_tpu.game.data import (
    DenseShard,
    EntityBucket,
    Float,
    GameDataset,
    RandomEffectDataset,
    _gather_shard_rows,
    build_random_effect_dataset,
    SparseShard,
    entity_index_for,
    keys_match,
    pad_bucket_entities,
    pad_bucket_rows,
)
from photon_tpu.game.model import (
    FixedEffectModel,
    RandomEffectModel,
    _shard_feats,
    shard_to_batch,
)
from photon_tpu.models.glm import Coefficients, model_for_task
from photon_tpu.telemetry import NULL_SESSION
from photon_tpu.parallel.mesh import (
    DATA_AXIS,
    first_axis_name,
    mesh_shards,
    pad_to_multiple,
    put_sharded,
    reshard,
    shard_batch,
    to_host,
)

Array = jax.Array


@jax.jit
def _gather_rows(offsets: Array, row_index: Array) -> Array:
    """Device row gather: the fixed effect's downsample selection applied to
    a device-resident offsets vector."""
    return offsets[row_index]


@jax.jit
def _gather_bucket_offsets(offsets: Array, row_index: Array, mask: Array) -> Array:
    """Per-bucket offset gather on device: ``offsets[row_index] * mask``
    against the pre-uploaded ``[E, R]`` row-index/mask buffers — replaces the
    host fancy-index + fresh upload the seed paid per bucket per iteration."""
    return offsets[row_index] * mask


@jax.jit
def _accumulate_solve_stats(
    acc: Array, entity_index: Array, num_entities, converged: Array,
    iterations: Array, good: Array, cg_iterations: Array | None = None,
) -> Array:
    """Fold one bucket's solve results into the per-coordinate ``[6]``
    int32 stats accumulator ``[entities, converged, iterations_max,
    quarantined, cg_iters, cg_entities]`` — entirely on device, so a coordinate's
    train() emits NO host sync of its own: the descent loop drains every
    coordinate's accumulator (plus the score-table guard flags) in ONE
    ``device_get`` per outer iteration.  Padded entities (``entity_index
    >= num_entities``) — bin-padding and mesh-padding slots alike — are
    masked out of every component, so they can never inflate ``entities``
    or ``converged``; a quarantined (non-finite) entity is not counted
    converged either — its "solution" was discarded.  ``cg_iterations``
    (per-entity inner-CG totals, Newton-CG bins only — see
    ``OptimizerResult.cg_iterations``) sums into the ``cg_iters`` slot,
    and the SAME bins' real entities into ``cg_entities`` — the correct
    per-entity-mean denominator when a coordinate mixes CG and non-CG
    bins (projected buckets can differ in solve_dim); other routes
    contribute 0 to both."""
    real = entity_index < num_entities
    real_i = real.astype(jnp.int32)
    if cg_iterations is None:
        cg = cg_ents = jnp.asarray(0, jnp.int32)
    else:
        cg = (cg_iterations.astype(jnp.int32) * real_i).sum()
        cg_ents = real_i.sum()
    return jnp.stack([
        acc[0] + real_i.sum(),
        acc[1] + ((converged & good).astype(jnp.int32) * real_i).sum(),
        jnp.maximum(
            acc[2],
            jnp.max(jnp.where(real, iterations.astype(jnp.int32), 0)),
        ),
        acc[3] + ((~good).astype(jnp.int32) * real_i).sum(),
        acc[4] + cg,
        acc[5] + cg_ents,
    ])


@jax.jit
def _count_quarantined(acc: Array, good: Array) -> Array:
    """Add a non-finite-row count to the accumulator's quarantined slot
    (the factored coordinate's materialized-table guard)."""
    return acc.at[3].add((~good).astype(jnp.int32).sum())


class DeferredSolveStats:
    """A coordinate train()'s convergence stats as ONE device int32 vector.

    The descent loop collects these per coordinate and drains them all in
    a single host sync at the outer-iteration boundary
    (``descent.host_syncs``); :meth:`resolve` turns the fetched vector into
    the stats dict the telemetry/logging paths consume.  Direct callers
    (tests, benches) can index it like the old dict — the first access
    lazily fetches.  ``extra`` carries static host-side entries (e.g. the
    factored coordinate's ``latent_iterations``)."""

    KEYS = ("entities", "converged", "iterations_max", "quarantined",
            "cg_iters", "cg_entities")

    def __init__(self, device: Array, extra: Optional[dict] = None):
        self.device = device
        self.extra = dict(extra or {})
        self._resolved: Optional[dict] = None

    def resolve(self, host_vec=None) -> dict:
        """The stats dict; ``host_vec`` is the pre-fetched ``[6]`` vector
        from the descent boundary drain (without it, direct callers pay
        their own fetch here — off the descent hot loop)."""
        if self._resolved is None:
            if host_vec is None:
                # host-sync: direct-caller fetch (tests/benches) — the
                # descent loop always passes the batched host_vec instead.
                host_vec = np.asarray(self.device)
            stats = {k: int(host_vec[i]) for i, k in enumerate(self.KEYS)}
            stats.update(self.extra)
            self._resolved = stats
        return self._resolved

    def __getitem__(self, key):
        return self.resolve()[key]

    def get(self, key, default=None):
        return self.resolve().get(key, default)

    def __contains__(self, key):
        return key in self.resolve()

    def __str__(self):
        return str(self.resolve()) if self._resolved is not None else (
            f"DeferredSolveStats(pending, extra={self.extra})"
        )


def _foreign_src_idx(device_data, model_keys) -> np.ndarray:
    """Cached foreign-vocabulary join: ``src_idx[e]`` is the row of
    ``model_keys`` holding this dataset's entity ``e`` (-1 = absent).

    The O(E) host key join used to run once per warm start — once per
    (configuration × iteration) for a sweep warm-started from disk.  It is
    keyed by the keys OBJECT's identity and cached on the shared device
    data (the cached entry pins the keys array, so the id cannot be
    recycled), closing part of the ROADMAP "host-resident paths" edge.
    A cache entry may hold an io-pool Future (the join PREFETCHED while the
    fixed-effect coordinate trains — :func:`prefetch_warm_joins`); the
    first consumer resolves it, so the first-hit join overlaps compute
    instead of blocking the coordinate sweep."""
    from concurrent.futures import Future

    cache = device_data._warm_join_cache
    hit = cache.get(id(model_keys))
    if hit is not None and hit[0] is model_keys:
        src_idx = hit[1]
        if isinstance(src_idx, Future):
            # host-sync: resolving a prefetched join Future — host numpy
            # computed on the io pool, no device data involved.
            src_idx = src_idx.result()
            cache[id(model_keys)] = (model_keys, src_idx)
        return src_idx
    # host-sync: foreign-vocabulary key join (host keys) — once per
    # distinct warm-start vocabulary, cached after.
    src_idx = entity_index_for(
        device_data.dataset.keys, np.asarray(model_keys)
    )
    if len(cache) >= 8:
        cache.pop(next(iter(cache)))
    cache[id(model_keys)] = (model_keys, src_idx)
    return src_idx


def prefetch_warm_joins(coordinates, initial_model, telemetry=None) -> int:
    """Schedule the FIRST-HIT foreign-vocabulary warm-start key joins on
    the io pool so they overlap the fixed-effect coordinate's training
    instead of blocking the first random coordinate's train() (ROADMAP
    "remaining known edges"; ISSUE 10 satellite).

    For every random-effect coordinate whose warm-start model carries a
    vocabulary that is NOT this run's own keys object, the O(E) host
    ``entity_index_for`` join is submitted as a background job and parked
    in the coordinate's warm-join cache as a Future;
    :func:`_foreign_src_idx` resolves it on first use.  The
    ``descent.host_transfer_bytes{path=warm_start}`` accounting is
    untouched — it meters the table transfers in ``_align_foreign_table``,
    which still run at consume time.  Returns the number of joins
    scheduled (``descent.warm_join_prefetch`` counts them)."""
    from photon_tpu.game.model import RandomEffectModel
    from photon_tpu.utils import io_pool

    telemetry = telemetry or NULL_SESSION
    scheduled = 0
    for name, coord in coordinates.items():
        device_data = getattr(coord, "device_data", None)
        dataset = getattr(device_data, "dataset", None)
        if dataset is None:
            continue
        model = initial_model.coordinates.get(name)
        if not isinstance(model, RandomEffectModel):
            continue
        # host-sync: key identity/value compare (host vocabularies) — the
        # same gate _initial_table applies; same-run models skip the join.
        if keys_match(model.keys, dataset.keys):
            continue
        cache = device_data._warm_join_cache
        hit = cache.get(id(model.keys))
        if hit is not None and hit[0] is model.keys:
            continue  # already joined (or already scheduled)
        model_keys = model.keys
        fut = io_pool.submit(
            # host-sync: the prefetched join is pure host numpy, computed
            # on an io-pool thread while the fixed effect trains.
            lambda keys=dataset.keys, mk=model_keys: entity_index_for(
                keys, np.asarray(mk)
            )
        )
        if len(cache) >= 8:
            cache.pop(next(iter(cache)))
        cache[id(model_keys)] = (model_keys, fut)
        scheduled += 1
        telemetry.counter(
            "descent.warm_join_prefetch", coordinate=name
        ).inc()
    return scheduled


def _align_foreign_table(coord, initial_model) -> np.ndarray:
    """Key-aligned host ``[E+1, dim]`` table of a FOREIGN warm-start model
    (unseen entities zero; the dummy slot absorbs padded entities), with the
    join's host traffic recorded as ``descent.host_transfer_bytes``
    ``path=warm_start`` — the once-per-warm-start transfers the ROADMAP
    flags, now visible next to the engines' steady-state counters."""
    telemetry = getattr(coord, "telemetry", NULL_SESSION)
    aligned = np.zeros(
        (coord.dataset.num_entities + 1, coord.dim), np.float32
    )
    src_idx = _foreign_src_idx(coord.device_data, initial_model.keys)
    found = src_idx >= 0
    # host-sync: foreign warm start — the table fetch of the join.
    table = to_host(initial_model.table)
    telemetry.counter(
        "descent.host_transfer_bytes", direction="d2h", path="warm_start"
    ).inc(table.nbytes)
    aligned[:-1][found] = table[src_idx[found]]
    telemetry.counter(
        "descent.host_transfer_bytes", direction="h2d", path="warm_start"
    ).inc(aligned.nbytes)
    return aligned


def _bucket_offsets(device_data, i: int, bucket, offsets) -> Array:
    """Training offsets for bucket ``i``: a jitted device gather when the
    residual engine hands a device vector, the seed's host fancy-index +
    upload when given a numpy vector (``PHOTON_RESIDUALS=host``)."""
    if isinstance(offsets, jax.Array):
        row_index, row_mask = device_data.gather_buffers(i)
        return _gather_bucket_offsets(offsets, row_index, row_mask)
    return jnp.asarray(
        offsets[bucket.row_index] * (bucket.row_weight > 0), jnp.float32
    )


@jax.jit
def _restrict_index_map(table: Array, proj_ids: Array, mask: Array) -> Array:
    """Device warm-start restriction for index-map projections: gather each
    entity's active global columns into its local slots (the device analog
    of ``IndexMapBucketProjection.restrict_table``)."""
    return jnp.take_along_axis(table, proj_ids, axis=1) * mask


@jax.jit
def _restrict_random(table: Array, matrix: Array, inv_col_norms: Array) -> Array:
    """Device warm-start restriction for random projections: the
    column-normalized least-squares pullback of
    ``RandomProjectionMatrix.restrict_table``."""
    return (table @ matrix) * inv_col_norms


def _score_pad(coord) -> int:
    """Padded row count of the coordinate's scoring caches and score rows:
    the training row count rounded up to a multiple of the mesh size (the
    residual engine pads identically, so score rows line up shard for
    shard)."""
    return pad_to_multiple(coord.data.num_examples, mesh_shards(coord.mesh))


def _scoring_feats(coord) -> tuple:
    """The coordinate's training-shard features as device arrays, uploaded
    once and cached on the coordinate's shared ``device_data`` (which the
    estimator reuses across sweep configurations, unlike the coordinate
    objects themselves), SHARDED over the mesh data axis: the residual
    engine re-scores every coordinate every outer iteration, and the seed's
    ``model.score(data)`` re-uploaded the shard each time.

    This cache is a SECOND device copy of the shard's features (the training
    copies live row-selected/bucketed in the batch structures and cannot
    serve full-row-order scoring) — a deliberate memory-for-transfers
    trade.  Sharding it over the data axis (rows zero-padded to the mesh
    multiple) keeps that trade to ONE extra copy across the whole mesh
    rather than the one-per-device the replicated cache used to cost.
    ``_score_cache_bytes`` makes the residency visible (the descent loop
    exports it as the ``residuals.scoring_cache_bytes`` gauge — global
    bytes; per-device residency divides by the mesh size);
    ``PHOTON_RESIDUALS=host`` never pays it."""
    holder = coord.device_data
    if holder._score_feats is None:
        from photon_tpu.game.model import _shard_feats_padded

        leaves, dense = _shard_feats_padded(
            coord.data.shard(coord.config.shard_name), _score_pad(coord)
        )
        dev_feats = put_sharded(leaves, coord.mesh)
        holder._score_feats = (dev_feats, dense)
        holder._score_cache_bytes += sum(
            leaf.nbytes for leaf in jax.tree.leaves(dev_feats)
        )
    return holder._score_feats


def _random_score_device(coord, model) -> Array:
    """Device-resident training-data margins for a random-effect model:
    gather-join against the cached per-row entity index (the common case —
    the model was trained on this coordinate's vocabulary); a warm-start
    model with a different vocabulary joins by key on host once.  A model
    whose feature-shard/entity-column layout differs from the coordinate's
    config scores through its own host path — the device caches hold the
    coordinate's shard, not the model's."""
    if (model.shard_name != coord.config.shard_name
            or model.entity_column != coord.config.entity_column):
        return model.score(coord.data)
    feats, dense = _scoring_feats(coord)
    holder = coord.device_data
    n_pad = _score_pad(coord)

    def pad_idx(idx: np.ndarray) -> np.ndarray:
        # Padding rows carry entity index -1 -> zero margins.
        return np.pad(
            idx.astype(np.int32), (0, n_pad - len(idx)), constant_values=-1
        )

    # host-sync: foreign-vocabulary key compare (warm starts from disk);
    # same-run models hit the identity check inside keys_match.
    if keys_match(model.keys, coord.dataset.keys):
        if holder._score_entity_idx is None:
            holder._score_entity_idx = put_sharded(
                pad_idx(coord.dataset.entity_idx_per_row), coord.mesh
            )
            holder._score_cache_bytes += holder._score_entity_idx.nbytes
        entity_idx = holder._score_entity_idx
    else:
        entity_idx = put_sharded(
            pad_idx(entity_index_for(
                coord.data.id_columns[coord.config.entity_column],
                # host-sync: foreign-vocabulary key join (host keys; the
                # warm-start path — not the descent steady state).
                np.asarray(model.keys),
            )),
            coord.mesh,
        )
    return model.margins_device(entity_idx, feats, dense)


@dataclasses.dataclass(frozen=True)
class FixedEffectCoordinateConfig:
    """Reference: FixedEffectDataConfiguration + per-coordinate optimization
    config inside GameOptimizationConfiguration."""

    # Coordinate kind, shared by the config and its coordinate class: the
    # checkpoint fingerprint's logical-layout component (fault.checkpoint
    # .logical_layout) — what a coordinate IS, independent of mesh shape.
    kind = "fixed"

    shard_name: str
    problem: ProblemConfig = ProblemConfig()
    downsampling_rate: float = 1.0  # <1: train on a subsample
    downsampler: str = "default"  # default (uniform) | binary (negatives only)
    seed: int = 0  # subsample seed

    @property
    def data_key(self):
        return (
            "fixed", self.shard_name, self.downsampling_rate,
            self.downsampler, self.seed,
        )


@dataclasses.dataclass(frozen=True)
class RandomEffectCoordinateConfig:
    """Reference: RandomEffectDataConfiguration (entity id column a.k.a.
    randomEffectType, feature shard, active-data upper bound)."""

    kind = "random"

    shard_name: str
    entity_column: str
    problem: ProblemConfig = ProblemConfig()
    active_row_cap: Optional[int] = None
    # Feature projection for the per-entity solves (reference: data/projectors
    # — SURVEY.md §2.2): none | index_map (per-entity active features) |
    # random (sparse-sign matrix to projected_dim).
    projection: str = "none"
    projected_dim: Optional[int] = None
    seed: int = 0
    # Row-split placement (README §scale-out): instead of sharding the ENTITY
    # axis over the mesh, every shard holds a ROW slice of every entity and
    # per-entity data terms psum — for entities whose rows exceed one
    # shard/host (the reference co-locates them with a shuffle; here no row
    # moves).  Ignored without a mesh.
    row_split: bool = False

    def __post_init__(self):
        if self.projection not in ("none", "index_map", "random"):
            raise ValueError(f"unknown projection {self.projection!r}")
        if self.projection == "random" and not self.projected_dim:
            raise ValueError("random projection needs projected_dim")
        if self.row_split and self.projection == "index_map":
            # Per-entity index-map projection picks each entity's active
            # features from its OWN rows; under row-split a shard sees only
            # a row slice, so the projection would differ per shard.
            raise ValueError("row_split does not support index_map projection")

    @property
    def data_key(self):
        return (
            "random",
            self.shard_name,
            self.entity_column,
            self.active_row_cap,
            self.projection,
            self.projected_dim,
            self.seed,
            self.row_split,
        )


@dataclasses.dataclass(frozen=True)
class FactoredRandomEffectCoordinateConfig:
    """Latent-factor random effect (reference: FactoredRandomEffectCoordinate,
    SURVEY.md §2.2 [K?]): per-entity coefficients are constrained to a shared
    ``latent_dim``-rank subspace, ``w_e = L z_e`` with ``L: [d, r]`` learned
    on pooled data and ``z_e`` per entity — regularizing entities with few
    rows far harder than a free per-entity fit."""

    kind = "factored_random"

    shard_name: str
    entity_column: str
    latent_dim: int = 4
    problem: ProblemConfig = ProblemConfig()
    # Alternations between the per-entity z solves and the pooled L solve
    # (the reference's latent-space iteration count).
    latent_iterations: int = 2
    active_row_cap: Optional[int] = None
    seed: int = 0

    def __post_init__(self):
        if self.latent_dim < 1:
            raise ValueError("latent_dim must be >= 1")
        if self.latent_iterations < 2:
            # li=1 would fit z against the random-init projection and never
            # solve L; li=0 would return an all-zero model.
            raise ValueError("latent_iterations must be >= 2 (z,L,...,z)")
        if self.problem.variance_computation != "none":
            raise ValueError(
                "variance computation is not supported for factored random "
                "effects (z-space variances do not transport to w = L z)"
            )
        if self.problem.regularization.l1_weight > 0 or (
            self.problem.optimizer.lower() not in ("lbfgs", "l-bfgs")
        ):
            raise ValueError(
                "factored random effects support lbfgs with none/l2 "
                "regularization only (the pooled projection solve is a "
                "smooth L-BFGS problem)"
            )

    @property
    def data_key(self):
        # Same device data as an unprojected random coordinate (the latent
        # projection is learned, so buckets hold raw features) — delegate so
        # the estimator's device-data cache shares entries by construction.
        return self.as_random_config().data_key

    def as_random_config(self) -> "RandomEffectCoordinateConfig":
        return RandomEffectCoordinateConfig(
            shard_name=self.shard_name,
            entity_column=self.entity_column,
            problem=self.problem,
            active_row_cap=self.active_row_cap,
            seed=self.seed,
        )


CoordinateConfig = Union[
    FixedEffectCoordinateConfig,
    RandomEffectCoordinateConfig,
    FactoredRandomEffectCoordinateConfig,
]


class Coordinate(Protocol):
    def train(self, offsets: np.ndarray, initial_model=None): ...

    def score(self, model) -> np.ndarray: ...


# ---------------------------------------------------------------------------
# Device-resident datasets (shared across sweep configurations)
# ---------------------------------------------------------------------------


def _pad_fixed_rows(shard, label, offset, weight, target_n):
    """Host-side row padding for the fixed-effect batch's row-capacity
    headroom: pad rows carry weight 0 (inert in every weighted objective),
    zero features (ids=0/vals=0 for sparse — a no-op gather), and zero
    label/offset.  Padding on HOST, before :func:`shard_to_batch` uploads,
    is what makes a capacity rebuild compile-free — the device only ever
    sees the capacity shape."""
    n = len(label)
    pad = target_n - n
    # host-sync: every input here is caller-owned host numpy (this runs
    # BEFORE the one device upload) — the asarray calls are dtype casts.
    label = np.pad(np.asarray(label, np.float32), (0, pad))
    # host-sync: host numpy offset (pre-upload).
    offset = None if offset is None else np.pad(
        np.asarray(offset, np.float32), (0, pad)
    )
    # A None weight means "all ones" — materialize it so the pad rows can
    # carry the zeros that keep them out of the loss.
    # host-sync: host numpy weight (pre-upload).
    weight = np.pad(
        np.ones(n, np.float32) if weight is None
        else np.asarray(weight, np.float32),
        (0, pad),
    )
    if isinstance(shard, DenseShard):
        # host-sync: host numpy shard rows (pre-upload).
        shard = DenseShard(
            np.pad(np.asarray(shard.x), ((0, pad), (0, 0)))
        )
    else:
        shard = SparseShard(
            # host-sync: host numpy shard rows (pre-upload).
            np.pad(np.asarray(shard.ids), ((0, pad), (0, 0))),
            np.pad(np.asarray(shard.vals), ((0, pad), (0, 0))),
            shard.dim_,
        )
    return shard, label, offset, weight


class FixedEffectDeviceData:
    """The fixed-effect training batch, resident on device (sharded over the
    mesh's data axis when a mesh is given).  Built once per (shard,
    downsampling) data config; reused across the regularization sweep."""

    def __init__(
        self,
        data: GameDataset,
        config: FixedEffectCoordinateConfig,
        mesh=None,
        build_fm: bool = True,
        row_capacity: Optional[int] = None,
    ):
        self.mesh = mesh
        shard = data.shard(config.shard_name)
        self.dim = shard.dim
        self.train_rows: Optional[np.ndarray] = None
        label, offset, weight = data.label, data.offset, data.weight
        if config.downsampling_rate < 1.0:
            # Weight-corrected subsample (the reference's DownSampler on the
            # fixed-effect dataset; `binary` keeps positives and thins
            # negatives — data.sampling).
            from photon_tpu.data.sampling import get_down_sampler

            sampler = get_down_sampler(config.downsampler, config.downsampling_rate)
            keep, corrected = sampler.down_sample(label, weight, seed=config.seed)
            self.train_rows = keep
            shard = _gather_shard_rows(shard, keep)
            label = label[keep]
            offset = offset[keep]
            weight = corrected
        self.unpadded_n = len(label)
        if row_capacity is not None and row_capacity > self.unpadded_n:
            # Row-capacity headroom (ISSUE 18 satellite): weight-0 pad rows
            # on HOST, ahead of the device upload and aux construction, so
            # a refresh that rebuilds this layout at the SAME capacity
            # reproduces the batch shape exactly — the upload lands at the
            # (unchanged) padded shape, every program compiled against it
            # stays hot, and nothing recompiles.  Pad rows are inert in the
            # solve (the loss is weight-summed) and invisible to scoring
            # (score paths read the shard, not the training batch).
            shard, label, offset, weight = _pad_fixed_rows(
                shard, label, offset, weight, row_capacity
            )
        self.batch = shard_to_batch(shard, label, offset, weight)
        self._train_rows_dev: Optional[Array] = None
        # Device scoring cache (residual engine): full-row-order shard
        # features + residency accounting, filled by _scoring_feats.
        self._score_feats: Optional[tuple] = None
        self._score_cache_bytes: int = 0
        if mesh is not None:
            # Same Pallas/xchg-kernel eligibility as single-device: the
            # per-shard aligned layouts + routes are built when the
            # selector could route to them (gated inside shard_batch —
            # VERDICT r5 item 2).
            self.batch = shard_batch(
                self.batch, mesh, build_fm=build_fm, aligned_dim=self.dim
            )
        elif build_fm and isinstance(self.batch, SparseBatch):
            from photon_tpu.data.batch import attach_feature_major
            from photon_tpu.ops.sparse_grad_select import aligned_layout_wanted

            # Single-device: the GAME fixed effect is the framework's big
            # sparse solve, so it gets the same Pallas-kernel eligibility
            # as the legacy driver (aligned layouts only when the selector
            # could route to them).
            e_total = int(self.batch.ids.size)
            self.batch = attach_feature_major(
                self.batch,
                aligned_dim=self.dim if aligned_layout_wanted(e_total) else None,
            )

    def offsets_to_device(self, offsets) -> Array:
        """Training offsets ready for the batch: accepts the residual
        engine's device vector — already padded to the mesh multiple, so the
        row gather / pad below is sized off the ACTUAL length — or a host
        numpy vector (the seed's upload path)."""
        if isinstance(offsets, jax.Array):
            dev = offsets
            if self.train_rows is not None:
                if self._train_rows_dev is None:
                    self._train_rows_dev = jnp.asarray(self.train_rows)
                dev = _gather_rows(dev, self._train_rows_dev)
        else:
            if self.train_rows is not None:
                offsets = offsets[self.train_rows]
            # host-sync: caller-owned host numpy on the seed path (this
            # branch never sees device data — jax.Array took the one above).
            offsets = np.asarray(offsets, np.float32)
            pad = self.batch.num_examples - offsets.shape[0]
            if pad:
                # Pad on HOST: the upload then always lands at the batch's
                # (capacity) shape, so a refresh at a new true row count
                # compiles nothing on the seed path.
                offsets = np.pad(offsets, (0, pad))
            dev = jnp.asarray(offsets)
        short = self.batch.num_examples - dev.shape[0]
        if short:
            # Device vectors (the residual engine's total) pad on device:
            # covers both the mesh pad-to-shard-multiple and single-device
            # row-capacity headroom (pad rows carry weight 0, so their
            # offset value never reaches the loss).
            dev = jnp.pad(dev, (0, short))
        if self.mesh is None:
            return dev
        return reshard(dev, NamedSharding(self.mesh, P(DATA_AXIS)))


class RandomEffectDeviceData:
    """Bucketed per-entity data resident on device, entity axis sharded over
    the mesh.  Holds everything except offsets, which change per descent
    iteration.

    The raw power-of-two row-capacity buckets are consolidated into SIZE
    BINS (``game.batched_solve.bin_layout``) before upload: each bin is one
    padded ``[E, R, ...]`` block solved by a single jitted program —
    ``self.buckets`` / ``self.device_buckets`` hold the binned blocks, and
    ``self.bin_stats`` records each bin's padding economics for the
    ``solves.*`` telemetry gauges.  New entities arriving between fits
    extend the layout in place via :meth:`onboard` (appended bins, remapped
    indices) instead of a full rebuild."""

    def __init__(
        self,
        data: GameDataset,
        config: RandomEffectCoordinateConfig,
        mesh=None,
    ):
        self.mesh = mesh
        self.config = config
        self.dataset: RandomEffectDataset = build_random_effect_dataset(
            data,
            entity_column=config.entity_column,
            shard_name=config.shard_name,
            active_row_cap=config.active_row_cap,
            seed=config.seed,
        )
        self.dim = self.dataset.dim
        n_shards = mesh_shards(mesh)
        self.row_split = bool(getattr(config, "row_split", False)) and n_shards > 1
        # Optional feature projection shrinks each bucket's solve dimension
        # (reference: data/projectors — see game.projection).
        self.random_matrix = None
        if config.projection == "random":
            from photon_tpu.game.projection import build_random_projection

            self.random_matrix = build_random_projection(
                self.dim, config.projected_dim, seed=config.seed
            )
        # Device scoring cache (residual engine): full-row-order shard
        # features + per-row entity index + residency accounting, filled by
        # _scoring_feats / _random_score_device.
        self._score_feats: Optional[tuple] = None
        self._score_entity_idx: Optional[Array] = None
        self._score_cache_bytes: int = 0
        # Foreign-vocabulary warm-start join cache: keys-object identity ->
        # src_idx (see _align_foreign_table) — the O(E) host key join is
        # paid once per distinct warm-start vocabulary, not once per warm
        # start.
        self._warm_join_cache: dict = {}
        # Size-binned device blocks: features / label / weight / entity idx
        # per bin.
        self.buckets: list = []
        self.device_buckets: list = []
        self.bin_stats: list = []
        # Per-entity placement index (bin / slot / used rows), built lazily
        # by _entity_locator for the in-place growth path and invalidated
        # whenever the layout changes.
        self._locator = None
        self._append_bins(self.dataset.buckets)

    def _append_bins(self, raw_buckets) -> None:
        """Bin ``raw_buckets`` (host ``EntityBucket``s over THIS dataset's
        entity indices), pad for the mesh placement, upload, and append to
        the device layout — the shared path of __init__ and onboard()."""
        from photon_tpu.game.batched_solve import bin_layout
        from photon_tpu.game.data import merge_buckets

        n_shards = mesh_shards(self.mesh)
        for group in bin_layout(raw_buckets):
            merged = merge_buckets([raw_buckets[i] for i in group])
            live_entities = merged.num_entities
            live_rows = int((merged.row_weight > 0).sum())
            if self.row_split:
                # Entities replicated, each entity's ROWS sharded over the
                # mesh (solve_entities_row_split); pad row capacity, not
                # entities.
                merged = pad_bucket_rows(merged, n_shards)
            else:
                merged = pad_bucket_entities(
                    merged, n_shards, self.dataset.num_entities
                )
            self.buckets.append(merged)
            self.bin_stats.append({
                "capacity": merged.row_capacity,
                "live_entities": live_entities,
                "total_entities": merged.num_entities,
                "live_rows": live_rows,
            })
            self.device_buckets.append(self._build_device_bucket(merged))

    def _build_device_bucket(self, bucket) -> dict:
        config = self.config
        feats = bucket.features
        proj = None
        if config.projection == "index_map":
            from photon_tpu.game.projection import build_index_map_projection

            proj = build_index_map_projection(bucket)
        elif config.projection == "random":
            proj = self.random_matrix
        if proj is not None:
            feats = proj.project(feats)
        solve_dim = self.dim if proj is None else proj.projected_dim
        if isinstance(feats, DenseShard):
            dev_feats = (self._place(jnp.asarray(feats.x)),)
        else:
            dev_feats = (
                self._place(jnp.asarray(feats.ids)),
                self._place(jnp.asarray(feats.vals)),
            )
        return {
            "feats": dev_feats,
            "dense": isinstance(feats, DenseShard),
            "label": self._place(jnp.asarray(bucket.label)),
            "weight": self._place(jnp.asarray(bucket.row_weight)),
            "entity_index": jnp.asarray(bucket.entity_index),
            "proj": proj,
            "solve_dim": solve_dim,
            "w0": self._place_w0(
                jnp.zeros((bucket.num_entities, solve_dim), jnp.float32)
            ),
        }

    def _sharding(self, ndim: int):
        # The mesh's one physical axis — the same axis the score tables
        # shard their row dimension over (parallel.mesh.first_axis_name):
        # entity blocks and score rows split across the same chips.
        axis = first_axis_name(self.mesh)
        if self.row_split:
            # [E, R, ...]: entities replicated, the row axis sharded.
            if ndim < 2:
                return NamedSharding(self.mesh, P())
            return NamedSharding(self.mesh, P(None, axis, *([None] * (ndim - 2))))
        return NamedSharding(self.mesh, P(axis, *([None] * (ndim - 1))))

    def _place(self, leaf: Array) -> Array:
        if self.mesh is None:
            return leaf
        return jax.device_put(leaf, self._sharding(leaf.ndim))

    def _place_w0(self, leaf: Array) -> Array:
        """Per-entity coefficient tables: sharded like entities normally,
        REPLICATED under row-split (every shard runs the same optimizer on
        psum-ed gradients)."""
        if self.mesh is None:
            return leaf
        if self.row_split:
            return jax.device_put(leaf, NamedSharding(self.mesh, P()))
        return jax.device_put(leaf, self._sharding(leaf.ndim))

    def restrict_device(self, i: int, table: Array) -> Array:
        """Bucket ``i``'s warm-start restriction applied on DEVICE: local
        per-entity coefficients from the globally-gathered ``[E_b, dim]``
        table.  The projection's static buffers (index-map slots + mask, or
        the random matrix + its column norms) upload on first warm start
        and stay cached — the seed fetched the whole aligned table to host
        and restricted in numpy once per bucket per warm start."""
        dev = self.device_buckets[i]
        proj = dev["proj"]
        if proj is None:
            return table
        from photon_tpu.game.projection import IndexMapBucketProjection

        if "restrict_buffers" not in dev:
            if isinstance(proj, IndexMapBucketProjection):
                ids, mask = proj.scatter_args()
                dev["restrict_buffers"] = (
                    self._place(jnp.asarray(ids)),
                    self._place(jnp.asarray(mask)),
                )
            else:
                col_norms = (proj.matrix**2).sum(axis=0)
                dev["restrict_buffers"] = (
                    jnp.asarray(proj.matrix),
                    jnp.asarray(
                        (1.0 / np.maximum(col_norms, 1e-12)).astype(np.float32)
                    ),
                )
        a, b = dev["restrict_buffers"]
        if isinstance(proj, IndexMapBucketProjection):
            return _restrict_index_map(table, a, b)
        return _restrict_random(table, a, b)

    def gather_buffers(self, i: int) -> tuple[Array, Array]:
        """Bucket ``i``'s device-resident ``row_index``/mask gather buffers
        for the residual engine, uploaded on first use (host-mode runs —
        including the automatic multi-process fallback — never pay for
        them) and cached for every later iteration."""
        dev = self.device_buckets[i]
        if "row_index" not in dev:
            bucket = self.buckets[i]
            dev["row_index"] = self._place(jnp.asarray(bucket.row_index))
            dev["row_mask"] = self._place(
                jnp.asarray(bucket.row_weight > 0, jnp.float32)
            )
        return dev["row_index"], dev["row_mask"]

    def batch_for(self, i: int, offsets_b: Array):
        dev = self.device_buckets[i]
        offsets_b = self._place(offsets_b)
        if dev["dense"]:
            return DenseBatch(dev["feats"][0], dev["label"], offsets_b, dev["weight"])
        return SparseBatch(
            dev["feats"][0], dev["feats"][1], dev["label"], offsets_b, dev["weight"]
        )

    def check_onboard(self, data: GameDataset, absent_tail=None) -> None:
        """Validate :meth:`onboard`'s preconditions WITHOUT mutating — so a
        caller onboarding several layouts (the estimator's device-data
        cache) can reject the whole batch up front instead of leaving some
        layouts grown and others not (a half-onboarded cache would mix
        grown bucket row indices with old-length offset vectors).

        Appended rows may reference BOTH new and existing entities (ISSUE
        15 blocker fix — existing-entity rows grow the layout in place).
        ``absent_tail`` is an optional bool mask over the appended rows
        marking rows that carry NO id for this coordinate (the online
        ingest's missing-column fill): they are skipped, not bucketed."""
        old = self.dataset
        n_old = len(old.entity_idx_per_row)
        if data.num_examples < n_old:
            raise ValueError(
                f"onboard() needs the GROWN dataset: got {data.num_examples} "
                f"rows, the layout was built from {n_old}"
            )
        if self.config.entity_column not in data.id_columns:
            raise KeyError(
                f"grown dataset lacks id column {self.config.entity_column!r}"
            )
        shard = data.shard(self.config.shard_name)  # raises on a missing shard
        if shard.dim != self.dim:
            raise ValueError(
                f"appended shard {self.config.shard_name!r} has dim "
                f"{shard.dim}; the layout was built at dim {self.dim}"
            )
        if self.buckets:
            built_dense = isinstance(self.buckets[0].features, DenseShard)
            if isinstance(shard, DenseShard) != built_dense:
                raise ValueError(
                    f"grown shard {self.config.shard_name!r} is "
                    f"{'dense' if not built_dense else 'sparse'} but the "
                    f"layout was built "
                    f"{'dense' if built_dense else 'sparse'}; coerce the "
                    "appended rows to the layout's storage (the online "
                    "merge does) or rebuild"
                )
        n_tail = data.num_examples - n_old
        if absent_tail is not None and len(absent_tail) != n_tail:
            raise ValueError(
                f"absent_tail mask covers {len(absent_tail)} rows, the "
                f"appended tail has {n_tail}"
            )

    def _entity_locator(self):
        """``[bin_of, slot_of, used]`` per entity over the CURRENT layout —
        which bin block holds the entity, at which slot, with how many live
        (weight > 0) rows.  The in-place growth path's placement index;
        built lazily, invalidated by :meth:`onboard`."""
        if self._locator is None:
            n_entities = self.dataset.num_entities
            bin_of = np.full(n_entities, -1, np.int32)
            slot_of = np.zeros(n_entities, np.int32)
            used = np.zeros(n_entities, np.int32)
            for i, bucket in enumerate(self.buckets):
                idx = bucket.entity_index
                live = idx < n_entities  # skip dummy/padded/migrated-away
                if not live.any():
                    continue
                slots = np.nonzero(live)[0].astype(np.int32)
                bin_of[idx[live]] = i
                slot_of[idx[live]] = slots
                used[idx[live]] = (
                    bucket.row_weight[slots] > 0
                ).sum(axis=1).astype(np.int32)
            self._locator = [bin_of, slot_of, used]
        return self._locator

    def _plan_append_buckets(self, data, entities, rows_by_entity,
                             corrections):
        """Host ``EntityBucket``s for appended entities (new arrivals and
        migrations alike): ``entities`` are MERGED-vocabulary indices,
        ``rows_by_entity[i]`` the kept global row ids, ``corrections[i]``
        the active-cap weight correction.  Row capacities are the next
        power of two past each entity's kept count — the same amortized-
        doubling headroom the original bucketing gives, so a steadily
        growing entity migrates O(log rows) times."""
        from photon_tpu.utils import pow2_at_least

        if not entities:
            return []
        shard = data.shard(self.config.shard_name)
        # host-sync: append-bucket planning — pure host numpy over the
        # delta's row lists, no device data involved.
        counts = np.asarray([len(r) for r in rows_by_entity], np.int64)
        caps = np.asarray([pow2_at_least(int(c)) for c in counts], np.int64)
        buckets = []
        for capacity in np.unique(caps):
            members = np.nonzero(caps == capacity)[0]
            n_e = len(members)
            row_index = np.zeros((n_e, capacity), np.int64)
            mask = np.zeros((n_e, capacity), np.float32)
            corr = np.ones(n_e, np.float32)
            for k, m in enumerate(members):
                rr = rows_by_entity[m]
                row_index[k, : len(rr)] = rr
                mask[k, : len(rr)] = 1.0
                corr[k] = corrections[m]
            row_weight = (
                data.weight[row_index] * mask * corr[:, None]
            ).astype(Float)
            buckets.append(
                EntityBucket(
                    row_capacity=int(capacity),
                    # host-sync: host bucket assembly (merged entity ids).
                    entity_index=np.asarray(
                        [entities[m] for m in members], np.int32
                    ),
                    row_index=row_index,
                    row_weight=row_weight,
                    label=(data.label[row_index] * mask).astype(Float),
                    features=_gather_shard_rows(shard, row_index),
                )
            )
        return buckets

    def _grow_bin_in_place(self, i: int, slots, pos, rows, data) -> None:
        """Scatter appended rows into bin ``i``'s row-capacity headroom —
        host arrays and the resident device blocks both.  No shape changes,
        so every compiled solve program over this bin stays valid (the
        serving-table capacity trick applied to training bins)."""
        bucket = self.buckets[i]
        shard = data.shard(self.config.shard_name)
        w = data.weight[rows].astype(Float)
        lab = data.label[rows].astype(Float)
        bucket.row_index[slots, pos] = rows
        bucket.row_weight[slots, pos] = w
        bucket.label[slots, pos] = lab
        feats = bucket.features
        if isinstance(feats, DenseShard):
            new_ids = new_vals = None
            feats.x[slots, pos] = shard.x[rows]
        else:
            # The plan phase routed wider-than-block rows to migration;
            # narrower rows pad up to the block's nonzero width (zero
            # ids/vals are inert, the padded-COO convention).
            k_block = feats.ids.shape[-1]
            k_shard = shard.ids.shape[1]
            new_ids, new_vals = shard.ids[rows], shard.vals[rows]
            if k_shard < k_block:
                widths = [(0, 0), (0, k_block - k_shard)]
                new_ids = np.pad(new_ids, widths)
                new_vals = np.pad(new_vals, widths)
            feats.ids[slots, pos] = new_ids
            feats.vals[slots, pos] = new_vals
        dev = self.device_buckets[i]
        sl, po = jnp.asarray(slots), jnp.asarray(pos)
        dev["label"] = self._place(
            dev["label"].at[sl, po].set(jnp.asarray(lab))
        )
        dev["weight"] = self._place(
            dev["weight"].at[sl, po].set(jnp.asarray(w))
        )
        if dev["dense"]:
            dev["feats"] = (
                self._place(
                    dev["feats"][0].at[sl, po].set(jnp.asarray(shard.x[rows]))
                ),
            )
        else:
            dev["feats"] = (
                self._place(
                    dev["feats"][0].at[sl, po].set(jnp.asarray(new_ids))
                ),
                self._place(
                    dev["feats"][1].at[sl, po].set(jnp.asarray(new_vals))
                ),
            )
        if "row_index" in dev:
            # The residual engine's cached gather buffers follow the bin.
            dev["row_index"] = self._place(
                dev["row_index"].at[sl, po].set(jnp.asarray(rows))
            )
            dev["row_mask"] = self._place(dev["row_mask"].at[sl, po].set(1.0))
        self.bin_stats[i]["live_rows"] += int(len(rows))

    def _neutralize_slot(self, i: int, slot: int, dummy: int,
                         used: int) -> None:
        """Retire a migrated-away entity's old slot: dummy entity index (its
        scatter lands on the coefficient table's absorbing row, masked out
        of the solve stats) and zero row weights (invisible to the
        objective).  The slot's feature block stays resident — dead padding,
        exactly like a bucket's built-in pad rows."""
        bucket = self.buckets[i]
        bucket.entity_index[slot] = dummy
        bucket.row_weight[slot, :] = 0.0
        dev = self.device_buckets[i]
        dev["entity_index"] = dev["entity_index"].at[slot].set(dummy)
        dev["weight"] = self._place(dev["weight"].at[slot].set(0.0))
        if "row_mask" in dev:
            dev["row_mask"] = self._place(dev["row_mask"].at[slot].set(0.0))
        self.bin_stats[i]["live_rows"] -= int(used)
        self.bin_stats[i]["live_entities"] -= 1

    def _record_headroom(self, telemetry) -> None:
        """Capacity-headroom accounting (ISSUE 15 satellite): per-bin padded
        row cells vs live rows — the room the next append lands in without
        a migration."""
        col = self.config.entity_column
        for i, st in enumerate(self.bin_stats):
            cells = st["capacity"] * st["total_entities"]
            telemetry.gauge(
                "onboard.bin_row_capacity", column=col, bin=i
            ).set(cells)
            telemetry.gauge(
                "onboard.bin_rows_live", column=col, bin=i
            ).set(st["live_rows"])
            telemetry.gauge(
                "onboard.bin_row_headroom", column=col, bin=i
            ).set(cells - st["live_rows"])

    def onboard(self, data: GameDataset, telemetry=None,
                absent_tail=None) -> None:
        """Incremental onboarding: extend this device layout with rows
        APPENDED to the training data — for BOTH new and existing entities
        — without a full rebuild (ISSUE 15: the continual-training blocker
        fix).

        ``data`` is the grown dataset — its first ``n_old`` rows must be
        the rows this layout was built from (append-only).  Work done here
        is proportional to the APPENDED rows:

        - Rows for NEW entities are bucketed, binned, and uploaded as
          appended bins; existing bins' tiny ``entity_index`` vectors are
          remapped (one device gather each) onto the merged vocabulary.
        - Rows for EXISTING entities land IN PLACE: each power-of-two bin
          block carries row-capacity headroom, and the new rows scatter
          into the owning entity's free padded slots on host AND device —
          no shapes change, no recompiles, resident feature blocks
          untouched.
        - An entity whose headroom is exhausted — or that crosses the
          active-row cap, or lives under a per-bin projection (whose
          feature transform its new rows would invalidate) — MIGRATES: its
          old slot is neutralized (dummy index, zero weights) and its full
          row set re-buckets into an appended bin at the next power-of-two
          capacity (amortized doubling).  An entity pushed past
          ``active_row_cap`` re-subsamples with a per-entity seeded draw
          (unbiased weight correction; the draw is per-entity stable, not
          byte-identical to a cold rebuild's shared-stream draws).

        ``absent_tail`` (bool mask over the appended rows) marks rows that
        carry no id for this coordinate (the online ingest's missing-
        column fill): they keep per-row entity index -1 — zero margin from
        this coordinate, no bin membership.

        A batch failing validation mutates NOTHING: every rejection happens
        in the plan phase, before the first host/device write.  Scoring-
        side caches are dropped and lazily rebuilt at the grown row count.
        """
        from photon_tpu.telemetry import NULL_SESSION

        telemetry = telemetry or NULL_SESSION
        self.check_onboard(data, absent_tail=absent_tail)
        old = self.dataset
        n_old = len(old.entity_idx_per_row)
        n_tail = data.num_examples - n_old
        if n_tail == 0:
            return
        col = self.config.entity_column
        raw_tail = data.id_columns[col][n_old:]
        present = np.ones(n_tail, bool)
        if absent_tail is not None:
            present &= ~absent_tail.astype(bool)
        sel = np.nonzero(present)[0]
        raw_present = raw_tail[sel]

        # ---- plan phase: NO mutation until every input is validated ----
        old_idx = (
            entity_index_for(raw_present, old.keys)
            if len(raw_present) else np.zeros(0, np.int32)
        )
        new_mask = old_idx < 0
        new_raw = raw_present[new_mask]
        if len(new_raw):
            merged_keys = np.unique(
                np.concatenate([old.keys, np.unique(new_raw)])
            )
        else:
            merged_keys = old.keys
        grew = len(merged_keys) != len(old.keys)
        dummy = len(merged_keys)
        if grew:
            remap = entity_index_for(old.keys, merged_keys)
            # Old index -> merged index, with the dummy padding slot
            # (old num_entities) mapped to the NEW dummy slot.
            remap_full = np.concatenate(
                [remap, [dummy]]
            ).astype(np.int32)
        else:
            remap_full = None
        # Per-row map of the appended tail in MERGED space (-1 = absent).
        tail_idx = np.full(n_tail, -1, np.int32)
        if len(raw_present):
            tail_idx[sel] = entity_index_for(raw_present, merged_keys)
        tail_global = n_old + sel

        bin_of, slot_of, used_of = self._entity_locator()  # OLD index space
        cap = self.config.active_row_cap
        shard = data.shard(self.config.shard_name)
        # Sparse shards: an in-place write must fit the bin block's
        # padded-COO nonzero width (a merged append can WIDEN the shard —
        # wider rows migrate instead, into blocks built at the new width;
        # narrower rows pad up in _grow_bin_in_place).
        shard_k = (
            None if isinstance(shard, DenseShard) else shard.ids.shape[1]
        )

        def width_fits(i: int) -> bool:
            if shard_k is None:
                return True
            feats = self.buckets[i].features
            return shard_k <= feats.ids.shape[-1]
        append_entities: list = []  # merged entity index per appended entity
        append_rows: list = []      # kept global row ids per appended entity
        append_corr: list = []      # active-cap weight correction
        in_place: dict = {}         # bin -> [(slot, used, rows)]
        neutralize: list = []       # (bin, slot, used) of migrated entities
        in_place_rows = 0
        migrated_rows = 0
        n_migrated = 0

        exist_pos = np.nonzero(~new_mask)[0]
        if len(exist_pos):
            ents_old = old_idx[exist_pos]
            order = np.argsort(ents_old, kind="stable")
            ents_sorted = ents_old[order]
            rows_sorted = tail_global[exist_pos[order]]
            uniq, starts = np.unique(ents_sorted, return_index=True)
            bounds = np.append(starts, len(ents_sorted))
            # True per-entity base row counts (the active-cap accounting):
            # the per-row map covers every base row, including rows a
            # previous subsample dropped from the bin.
            full_counts = np.bincount(
                old.entity_idx_per_row[old.entity_idx_per_row >= 0],
                minlength=len(old.keys),
            )
            migrating: list = []
            for j, e_old in enumerate(uniq):
                rr = rows_sorted[bounds[j]: bounds[j + 1]]
                i = int(bin_of[e_old])
                u = int(used_of[e_old])
                total = int(full_counts[e_old]) + len(rr)
                subsampled = int(full_counts[e_old]) > u
                fits = (
                    i >= 0
                    and not subsampled
                    and (cap is None or total <= cap)
                    and u + len(rr) <= self.buckets[i].row_capacity
                    and self.config.projection == "none"
                    and width_fits(i)
                )
                if fits:
                    in_place.setdefault(i, []).append(
                        (int(slot_of[e_old]), u, rr)
                    )
                    in_place_rows += len(rr)
                else:
                    migrating.append((int(e_old), rr, i, int(slot_of[e_old]),
                                      u))
            n_migrated = len(migrating)
            for e_old, rr, i, s, u in migrating:
                # The entity's true base row universe, from the per-row
                # map (the bin may hold only a subsample of it).
                base_rows = np.nonzero(old.entity_idx_per_row == e_old)[0]
                all_rows = np.concatenate([base_rows, rr])
                corr = 1.0
                if cap is not None and len(all_rows) > cap:
                    rng = np.random.default_rng(
                        (self.config.seed, 0x6F6E6C, int(e_old))
                    )
                    keep = rng.choice(len(all_rows), size=cap, replace=False)
                    keep.sort()
                    corr = len(all_rows) / cap
                    all_rows = all_rows[keep]
                append_entities.append(
                    int(remap_full[e_old]) if grew else int(e_old)
                )
                append_rows.append(all_rows)
                append_corr.append(corr)
                migrated_rows += len(rr)
                if i >= 0:
                    neutralize.append((i, s, u))

        n_new_entities = 0
        if new_mask.any():
            ents_new = tail_idx[sel[new_mask]]  # merged index
            rows_new = tail_global[new_mask]
            order = np.argsort(ents_new, kind="stable")
            es, rs = ents_new[order], rows_new[order]
            uniq, starts = np.unique(es, return_index=True)
            bounds = np.append(starts, len(es))
            n_new_entities = len(uniq)
            for j, e in enumerate(uniq):
                rr = rs[bounds[j]: bounds[j + 1]]
                corr = 1.0
                if cap is not None and len(rr) > cap:
                    rng = np.random.default_rng(
                        (self.config.seed, 0x6F6E6C, int(e))
                    )
                    keep = rng.choice(len(rr), size=cap, replace=False)
                    keep.sort()
                    corr = len(rr) / cap
                    rr = rr[keep]
                append_entities.append(int(e))
                append_rows.append(rr)
                append_corr.append(corr)
        append_buckets = self._plan_append_buckets(
            data, append_entities, append_rows, append_corr
        )

        # ---- apply phase: mutations only, nothing below rejects input ----
        if grew:
            remap_dev = jnp.asarray(remap_full)
            for i, bucket in enumerate(self.buckets):
                self.buckets[i] = dataclasses.replace(
                    bucket, entity_index=remap_full[bucket.entity_index]
                )
                dev = self.device_buckets[i]
                dev["entity_index"] = remap_dev[dev["entity_index"]]
            old_per_row = np.where(
                old.entity_idx_per_row >= 0,
                remap_full[np.maximum(old.entity_idx_per_row, 0)],
                -1,
            ).astype(np.int32)
        else:
            old_per_row = old.entity_idx_per_row
        for i, writes in sorted(in_place.items()):
            slots = np.concatenate(
                [np.full(len(rr), s, np.int32) for s, _, rr in writes]
            )
            pos = np.concatenate(
                [u + np.arange(len(rr), dtype=np.int32)
                 for _, u, rr in writes]
            )
            rows = np.concatenate([rr for _, _, rr in writes])
            self._grow_bin_in_place(i, slots, pos, rows, data)
        for i, s, u in neutralize:
            self._neutralize_slot(i, s, dummy, u)
        self.dataset = dataclasses.replace(
            old,
            keys=merged_keys,
            buckets=tuple(self.buckets),
            entity_idx_per_row=np.concatenate([old_per_row, tail_idx]),
        )
        if append_buckets:
            self._append_bins(append_buckets)
            self.dataset = dataclasses.replace(
                self.dataset, buckets=tuple(self.buckets)
            )
        # Row count and vocabulary changed: the scoring caches, the
        # warm-start join cache, and the placement index are stale — drop
        # them (rebuilt lazily).
        self._score_feats = None
        self._score_entity_idx = None
        self._score_cache_bytes = 0
        self._warm_join_cache.clear()
        self._locator = None
        if in_place_rows:
            telemetry.counter("onboard.rows_in_place", column=col).inc(
                in_place_rows
            )
        if migrated_rows:
            telemetry.counter("onboard.rows_migrated", column=col).inc(
                migrated_rows
            )
        if n_migrated:
            telemetry.counter("onboard.entities_migrated", column=col).inc(
                n_migrated
            )
        if n_new_entities:
            telemetry.counter("onboard.entities_new", column=col).inc(
                n_new_entities
            )
        skipped = n_tail - len(sel)
        if skipped:
            telemetry.counter("onboard.rows_absent", column=col).inc(skipped)
        self._record_headroom(telemetry)


# ---------------------------------------------------------------------------
# Coordinates
# ---------------------------------------------------------------------------


class FixedEffectCoordinate:
    """Data-parallel global GLM fit (reference: FixedEffectCoordinate)."""

    kind = "fixed"

    def __init__(
        self,
        data: GameDataset,
        config: FixedEffectCoordinateConfig,
        task_type: str,
        mesh=None,
        normalization: Optional[NormalizationContext] = None,
        device_data: Optional[FixedEffectDeviceData] = None,
    ):
        self.data = data
        self.config = config
        self.task_type = task_type
        self.mesh = mesh
        self.device_data = device_data or FixedEffectDeviceData(data, config, mesh)
        self.dim = self.device_data.dim
        # host-sync: one-time construction check of host-side factors.
        if normalization is not None and len(
            np.asarray(normalization.factors_or_ones(self.dim))
        ) != self.dim:
            raise ValueError(
                f"normalization context dim mismatch for shard "
                f"{config.shard_name!r} (expected {self.dim})"
            )
        # get_loss accepts task-type names directly (core/losses.TASK_TO_LOSS).
        obj = GlmObjective.create(
            task_type, config.problem.regularization, normalization
        )
        if mesh is None:
            self.objective = obj
        else:
            from photon_tpu.parallel.distributed import DistributedGlmObjective

            self.objective = DistributedGlmObjective(obj, mesh)
        self.problem = GlmOptimizationProblem(self.objective, config.problem)
        self.normalization = normalization

    def train(
        self, offsets: np.ndarray, initial_model: Optional[FixedEffectModel] = None
    ) -> tuple[FixedEffectModel, OptimizationStatesTracker]:
        """One GLM fit against the other coordinates' scores as offsets
        (SURVEY.md §3.1: offsets = sum of scores of other coordinates)."""
        import time

        batch = with_offset(
            self.device_data.batch, self.device_data.offsets_to_device(offsets)
        )
        w0 = None
        if initial_model is not None:
            w0 = jnp.asarray(initial_model.coefficients.means)
            if self.normalization is not None:
                w0 = self.normalization.model_to_normalized_space(w0)
        t0 = time.monotonic()
        coefficients, result = self.problem.run(batch, w0, dim=self.dim)
        jax.block_until_ready(coefficients.means)
        tracker = OptimizationStatesTracker(result, time.monotonic() - t0)
        means, variances = coefficients.means, coefficients.variances
        if self.normalization is not None:
            means = self.normalization.model_to_original_space(means)
            variances = self.normalization.variances_to_original_space(variances)
        from photon_tpu.fault.injection import consume_nan_injection

        if consume_nan_injection(getattr(self, "fault_name", None)):
            means = means.at[0].set(jnp.nan)
        # Non-finite guard (graceful degradation): a diverged/poisoned solve
        # keeps the previous iterate (the warm-start model, or zeros on the
        # first pass) instead of feeding NaN margins into the residual
        # engine.  The solve already synced above, so this check is a
        # dim-sized host reduce, not a new hot-loop transfer.
        tracker.quarantined = 0
        if not bool(jnp.all(jnp.isfinite(means))):
            tracker.quarantined = 1
            if initial_model is not None:
                prev = initial_model.coefficients
                means = jnp.asarray(prev.means)
                variances = (
                    None if prev.variances is None else jnp.asarray(prev.variances)
                )
            else:
                means, variances = jnp.zeros_like(means), None
        model = FixedEffectModel(
            model=model_for_task(self.task_type, Coefficients(means, variances)),
            shard_name=self.config.shard_name,
        )
        return model, tracker

    def score(self, model: FixedEffectModel) -> np.ndarray:
        return model.score(self.data)

    def score_device(self, model: FixedEffectModel) -> Array:
        """Training-data margins as a device array (the residual engine's
        scoring path); shard features upload once and stay cached.  A model
        trained on a different feature shard (foreign warm start) scores
        through its own host path — the cache holds this coordinate's
        shard."""
        if model.shard_name != self.config.shard_name:
            return model.score(self.data)
        feats, dense = _scoring_feats(self)
        return model.margins_device(feats, dense)


class RandomEffectCoordinate:
    """Per-entity batched GLM fits (reference: RandomEffectCoordinate).

    The reference maps ``SingleNodeOptimizationProblem.run`` over an
    ``RDD[(entityId, LocalDataset)]``; here each bucket's entities are solved
    by ONE vmapped optimizer call — per-lane line search and convergence are
    masked, so early-converging entities freeze while heavy ones iterate
    (SURVEY.md §7).
    """

    kind = "random"

    def __init__(
        self,
        data: GameDataset,
        config: RandomEffectCoordinateConfig,
        task_type: str,
        mesh=None,
        device_data: Optional[RandomEffectDeviceData] = None,
    ):
        self.data = data
        self.config = config
        self.task_type = task_type
        self.mesh = mesh
        self.device_data = device_data or RandomEffectDeviceData(data, config, mesh)
        self.dataset = self.device_data.dataset
        self.dim = self.dataset.dim
        obj = GlmObjective.create(task_type, config.problem.regularization)
        self.problem = GlmOptimizationProblem(obj, config.problem)
        # Shared vmapped solver (one traced program per static config +
        # bucket shape, module-cached): the objective rides along as a pytree
        # argument, so sweep configs differing only in reg weights reuse it.
        self._solver = functools.partial(
            self.problem.solver(vmapped=True), self.problem.objective
        )

    def _bin_routes(self) -> list:
        """Per-bin solver route (``newton``/``newton_cg``/``vmapped``/
        ``row_split``) —
        see game.batched_solve.solver_route.  Cached per coordinate (the
        descent loop calls train() every outer iteration; the routes only
        change when onboarding extends the bin layout, which the bin-count
        key detects — coordinates are rebuilt per sweep configuration, so
        the problem-config component never goes stale)."""
        from photon_tpu.game.batched_solve import solver_route

        cached = getattr(self, "_routes_cache", None)
        n_bins = len(self.device_data.device_buckets)
        if cached is not None and cached[0] == n_bins:
            return cached[1]
        routes = [
            solver_route(
                self.config.problem, dev["solve_dim"],
                row_split=self.device_data.row_split,
            )
            for dev in self.device_data.device_buckets
        ]
        self._routes_cache = (n_bins, routes)
        return routes

    def _solve_bin(self, route: str, batch, w0):
        """Dispatch one bin's batched solve along its resolved route: the
        batched-Cholesky Newton program (small-dim smooth bins), the
        matrix-free Newton-CG program (smooth bins past the dense-Hessian
        cap — no ``[B, d, d]`` materialization), the row-split psum solve,
        or the vmapped iterative solver (L1 / over-cap bins — every
        existing problem config still solves)."""
        if route == "newton":
            from photon_tpu.game.batched_solve import cached_newton_solver

            return cached_newton_solver(self.config.problem)(
                self.problem.objective, batch, w0
            )
        if route == "newton_cg":
            from photon_tpu.game.batched_solve import cached_newton_cg_solver

            return cached_newton_cg_solver(self.config.problem)(
                self.problem.objective, batch, w0
            )
        if route == "row_split":
            from photon_tpu.parallel.distributed import solve_entities_row_split

            return solve_entities_row_split(
                self.problem.objective, self.config.problem,
                batch, w0, self.mesh,
                axis_name=first_axis_name(self.mesh),
            )
        return self._solver(batch, w0)

    def _initial_table(self, initial_model: RandomEffectModel) -> Array:
        """Align a warm-start model's per-entity rows onto THIS dataset's
        vocabulary by key (the model may come from different training data —
        SURVEY.md §5 warm start); unseen entities start at zero.  The dummy
        slot at the end absorbs padded entities.

        The common case — coordinate descent re-passing the model THIS
        coordinate trained last iteration, whose ``keys`` is the dataset's
        own object — stays entirely on device: the table gets its dummy row
        appended by one device concatenate, no d2h fetch and no O(E) key
        join (the per-warm-start host path the ROADMAP flagged)."""
        if initial_model.dim != self.dim:
            raise ValueError(
                f"warm-start model dim {initial_model.dim} != coordinate dim {self.dim}"
            )
        # Only FOREIGN vocabularies (warm starts loaded from disk) pay the
        # host compare + join below; see data.keys_match.
        if keys_match(initial_model.keys, self.dataset.keys):
            table = jnp.asarray(initial_model.table, jnp.float32)
            return jnp.concatenate(
                [table, jnp.zeros((1, self.dim), table.dtype)]
            )
        # Foreign vocabulary: host key join, with the computed src_idx
        # CACHED per keys-object identity on the shared device data (the
        # sweep re-passes the same warm-start model once per configuration
        # × iteration) and its transfers counted — _align_foreign_table.
        return jnp.asarray(_align_foreign_table(self, initial_model))

    def train(
        self, offsets: np.ndarray, initial_model: Optional[RandomEffectModel] = None
    ) -> tuple[RandomEffectModel, dict]:
        """Solve every entity; returns the model + convergence summary."""
        num_entities = self.dataset.num_entities
        # Extra dummy slot absorbs padded entities' scatter writes.
        table = jnp.zeros((num_entities + 1, self.dim), jnp.float32)
        var_table = (
            jnp.zeros((num_entities + 1, self.dim), jnp.float32)
            if self.config.problem.variance_computation != "none"
            else None
        )
        init_table = (
            None if initial_model is None else self._initial_table(initial_model)
        )
        # Per-coordinate device stats accumulator: entities / converged /
        # iterations_max / quarantined / cg_iters fold in per bucket ON
        # DEVICE, and train() returns the handle — no host sync here at
        # all.  The descent loop drains every coordinate's accumulator in
        # its single per-iteration stats/quarantine sync
        # (descent.host_syncs).
        acc = jnp.zeros(6, jnp.int32)
        from photon_tpu.fault.injection import consume_nan_injection
        from photon_tpu.game.projection import (
            IndexMapBucketProjection,
            RandomProjectionMatrix,
        )

        inject_nan = consume_nan_injection(getattr(self, "fault_name", None))
        routes = self._bin_routes()
        # Gauges describe the (static) bin layout: set them once per
        # coordinate, again only if onboarding extended the layout — not
        # once per outer descent iteration.
        if getattr(self, "_bins_recorded", None) != len(routes):
            from photon_tpu.game.batched_solve import record_bin_telemetry

            record_bin_telemetry(
                getattr(self, "telemetry", NULL_SESSION),
                getattr(self, "fault_name", self.config.shard_name),
                self.device_data.bin_stats, routes,
            )
            self._bins_recorded = len(routes)
        for i, bucket in enumerate(self.device_data.buckets):
            offsets_b = _bucket_offsets(self.device_data, i, bucket, offsets)
            batch = self.device_data.batch_for(i, offsets_b)
            dev = self.device_data.device_buckets[i]
            entity_idx = dev["entity_index"]
            proj = dev["proj"]
            if init_table is not None:
                # Device gather against the bucket's entity index, then the
                # projection's device restriction (cached static buffers) —
                # the whole warm-start alignment stays on device.
                w0 = self.device_data._place_w0(
                    self.device_data.restrict_device(i, init_table[entity_idx])
                )
            else:
                w0 = dev["w0"]
            coefficients, result = self._solve_bin(routes[i], batch, w0)
            means, variances = coefficients.means, coefficients.variances
            if inject_nan and i == 0:
                # Fault injection (solve:nan): poison one entity's solve so
                # the quarantine path below is exercised end to end.
                means = means.at[0].set(jnp.nan)
            # Non-finite guard (graceful degradation): entities whose solve
            # diverged to NaN/Inf keep their previous iterate (warm-start
            # row, or zero on a cold start) instead of poisoning the table;
            # the count joins the ONE deferred host sync below.
            good = jnp.all(jnp.isfinite(means), axis=1)
            prev_rows = None if init_table is None else init_table[entity_idx]
            if proj is None:
                fallback = 0.0 if prev_rows is None else prev_rows
                table = table.at[entity_idx].set(
                    jnp.where(good[:, None], means, fallback)
                )
                if var_table is not None:
                    # Quarantined entities get zero variance: the previous
                    # model's variances are not carried through warm starts.
                    var_table = var_table.at[entity_idx].set(
                        jnp.where(good[:, None], variances, 0.0)
                    )
            elif isinstance(proj, IndexMapBucketProjection):
                # Scatter each local slot back to its global column; slots
                # are unique per entity, so add-on-zero-rows equals set, and
                # masked pad slots contribute exactly 0.  Quarantined
                # entities scatter zeros, then get their previous full row
                # added onto their (still-zero) table row.
                proj_ids, mask = proj.scatter_args()
                ids_j, mask_j = jnp.asarray(proj_ids), jnp.asarray(mask)
                safe_means = jnp.where(good[:, None], means, 0.0)
                table = table.at[entity_idx[:, None], ids_j].add(
                    safe_means * mask_j
                )
                if prev_rows is not None:
                    table = table.at[entity_idx].add(
                        jnp.where(good, 0.0, 1.0)[:, None] * prev_rows
                    )
                if var_table is not None:
                    var_table = var_table.at[entity_idx[:, None], ids_j].add(
                        jnp.where(good[:, None], variances, 0.0) * mask_j
                    )
            else:
                assert isinstance(proj, RandomProjectionMatrix)
                lifted = proj.lift(means)
                fallback = 0.0 if prev_rows is None else prev_rows
                table = table.at[entity_idx].set(
                    jnp.where(good[:, None], lifted, fallback)
                )
                if var_table is not None:
                    var_table = var_table.at[entity_idx].set(
                        jnp.where(good[:, None], proj.lift_variance(variances), 0.0)
                    )
            acc = _accumulate_solve_stats(
                acc, entity_idx, num_entities, result.converged,
                result.iterations, good,
                cg_iterations=getattr(result, "cg_iterations", None),
            )
        model = RandomEffectModel(
            table=table[:num_entities],
            keys=self.dataset.keys,
            entity_column=self.config.entity_column,
            shard_name=self.config.shard_name,
            task_type=self.task_type,
            variances=None if var_table is None else var_table[:num_entities],
        )
        return model, DeferredSolveStats(acc)

    def score(self, model: RandomEffectModel) -> np.ndarray:
        return model.score(self.data)

    def score_device(self, model: RandomEffectModel) -> Array:
        """Training-data margins as a device array (the residual engine's
        scoring path)."""
        return _random_score_device(self, model)


class FactoredRandomEffectCoordinate:
    """Latent-factor random effect: alternate vmapped per-entity latent
    solves (``z_e``, dim r, on features ``x @ L``) with one pooled L-BFGS
    solve of the shared projection ``L`` (margin linear in ``vec(L)``:
    ``x_i @ L @ z_{e(i)}``).  Exports a plain :class:`RandomEffectModel`
    with materialized ``w_e = L z_e`` so scoring, model IO, and warm start
    reuse the unfactored machinery (the reference's factored coordinate
    likewise yields per-entity GLMs)."""

    kind = "factored_random"

    def __init__(
        self,
        data: GameDataset,
        config: FactoredRandomEffectCoordinateConfig,
        task_type: str,
        mesh=None,
        device_data: Optional[RandomEffectDeviceData] = None,
    ):
        self.data = data
        self.config = config
        self.task_type = task_type
        self.mesh = mesh
        self.device_data = device_data or RandomEffectDeviceData(
            data, config.as_random_config(), mesh
        )
        self.dataset = self.device_data.dataset
        self.dim = self.dataset.dim
        self.r = config.latent_dim
        obj = GlmObjective.create(task_type, config.problem.regularization)
        self.problem = GlmOptimizationProblem(obj, config.problem)
        self._z_solver = functools.partial(
            self.problem.solver(vmapped=True), self.problem.objective
        )
        self._objective = obj
        # Device-resident pooled-solve arrays + ONE jitted objective, built
        # once: _solve_latent is called per latent iteration per sweep point,
        # and rebuilding arrays/closures there would re-upload the dataset
        # and recompile every call.  Under a mesh the per-row arrays are
        # padded (weight-0 rows) and sharded over the data axis; the jitted
        # objective then partitions via GSPMD (XLA inserts the all-reduce
        # for the scalar value and the replicated gradient automatically).
        if mesh is not None:
            n_shards = int(np.prod(list(mesh.shape.values())))
            n = self.data.num_examples
            self._pool_pad = (-n) % n_shards
        else:
            self._pool_pad = 0

        def place_rows(a):
            a = jnp.asarray(a)
            # Pad to the POOLED target length (residual-engine offsets
            # arrive pre-padded to the mesh multiple; host vectors don't).
            short = (self.data.num_examples + self._pool_pad) - a.shape[0]
            if short > 0:
                a = jnp.pad(a, [(0, short)] + [(0, 0)] * (a.ndim - 1))
            if mesh is None:
                return a
            ax = next(iter(mesh.shape))
            return reshard(
                a, NamedSharding(mesh, P(ax, *([None] * (a.ndim - 1))))
            )

        self._place_rows = place_rows
        shard = self.data.shard(config.shard_name)
        label = place_rows(jnp.asarray(self.data.label, jnp.float32))
        weight = place_rows(jnp.asarray(self.data.weight, jnp.float32))
        loss = obj.loss
        l2 = obj.l2_weight
        d, r = self.dim, self.r
        if isinstance(shard, DenseShard):
            x = place_rows(jnp.asarray(shard.x))

            def _latent_value(flat, z_rows, offsets):
                latent = flat.reshape(d, r)
                z = jnp.einsum("nd,dk,nk->n", x, latent, z_rows) + offsets
                return (
                    jnp.sum(weight * loss.value(z, label))
                    + 0.5 * l2 * jnp.dot(flat, flat)
                )
        else:
            ids = place_rows(jnp.asarray(shard.ids))
            vals = place_rows(jnp.asarray(shard.vals))

            def _latent_value(flat, z_rows, offsets):
                latent = flat.reshape(d, r)
                xl = jnp.einsum("njk,nj->nk", jnp.take(latent, ids, axis=0), vals)
                z = jnp.sum(xl * z_rows, axis=-1) + offsets
                return (
                    jnp.sum(weight * loss.value(z, label))
                    + 0.5 * l2 * jnp.dot(flat, flat)
                )

        self._latent_value_and_grad = jax.jit(jax.value_and_grad(_latent_value))

    # -- bucket features projected by the current L ---------------------------
    def _project_bucket(self, dev: dict, latent: Array) -> Array:
        if dev["dense"]:
            return jnp.einsum("erd,dk->erk", dev["feats"][0], latent)
        ids, vals = dev["feats"]
        # sum_k vals * L[ids]: [E, R, nnz, r] contracted over nnz.
        return jnp.einsum(
            "ernk,ern->erk", jnp.take(latent, ids, axis=0), vals
        )

    # -- pooled L solve -------------------------------------------------------
    def _solve_latent(self, z_rows: Array, offsets: Array, latent0: Array) -> Array:
        """Optimize ``L`` with all entities' ``z`` fixed: a GLM over
        ``vec(L)`` whose margins are ``(x_i @ L) . z_i``."""
        from photon_tpu.core.optimizers import lbfgs

        z_rows = self._place_rows(z_rows)
        offsets = self._place_rows(offsets)
        result = lbfgs(
            lambda w: self._latent_value_and_grad(w, z_rows, offsets),
            latent0.reshape(-1),
            self.config.problem.optimizer_config,
        )
        return result.w.reshape(self.dim, self.r)

    def _warm_start(self, initial_model: RandomEffectModel):
        """Recover (L, z) from a previous model's full-dim table via rank-r
        SVD (coordinate descent passes the previous iteration's model; a
        fresh random restart would discard all alternation progress).  Also
        returns the key-aligned previous table — the quarantine fallback
        rows — since the SVD fetched it to host anyway (the factored warm
        start is a known host-resident edge, see ROADMAP)."""
        # Key-aligned previous table via the shared (cached) foreign join;
        # the rank-r SVD below runs in numpy, once per warm start (not per
        # iteration) — the factored warm start is a known host-resident
        # edge, see ROADMAP.
        aligned = _align_foreign_table(self, initial_model)
        u, s, vt = np.linalg.svd(aligned, full_matrices=False)
        r = self.r
        sq = np.sqrt(s[:r])
        latent = (vt[:r].T * sq[None, :]).astype(np.float32)  # [d, r]
        z = (u[:, :r] * sq[None, :]).astype(np.float32)  # [E+1, r]
        # The aligned previous table stays HOST numpy: it is only needed
        # once, at the final quarantine-fallback where — uploading it here
        # would pin a full [E, dim] device copy through every alternation
        # of the train (the exact residency factoring exists to avoid).
        return jnp.asarray(latent), jnp.asarray(z), aligned[:-1]

    def train(
        self, offsets: np.ndarray, initial_model: Optional[RandomEffectModel] = None
    ) -> tuple[RandomEffectModel, dict]:
        num_entities = self.dataset.num_entities
        rng = np.random.default_rng(self.config.seed)
        latent = jnp.asarray(
            rng.standard_normal((self.dim, self.r)) / np.sqrt(self.dim),
            jnp.float32,
        )
        offsets_j = jnp.asarray(offsets, jnp.float32)
        entity_of_row = jnp.asarray(self.dataset.entity_idx_per_row, jnp.int32)
        z_table = jnp.zeros((num_entities + 1, self.r), jnp.float32)
        prev_table = None
        if initial_model is not None:
            latent, z_table, prev_table = self._warm_start(initial_model)
            # Warm-started L is already informed: refresh it from the new
            # offsets before the first z solve.
            latent = self._solve_latent(
                z_table[entity_of_row], offsets_j, latent
            )

        # Per-coordinate device stats accumulator (see
        # _accumulate_solve_stats): reset each latent alternation so the
        # reported counts cover the FINAL z pass, like the dict the seed
        # rebuilt per alternation; drained by the descent loop's one
        # boundary sync.
        acc = jnp.zeros(6, jnp.int32)
        for it in range(self.config.latent_iterations):
            last = it == self.config.latent_iterations - 1
            acc = jnp.zeros(6, jnp.int32)
            for i, bucket in enumerate(self.device_data.buckets):
                dev = self.device_data.device_buckets[i]
                offsets_b = self.device_data._place(
                    _bucket_offsets(self.device_data, i, bucket, offsets)
                )
                feats = self._project_bucket(dev, latent)
                batch = DenseBatch(feats, dev["label"], offsets_b, dev["weight"])
                entity_idx = dev["entity_index"]
                w0 = self.device_data._place(z_table[entity_idx])
                coefficients, result = self._z_solver(batch, w0)
                z_table = z_table.at[entity_idx].set(coefficients.means)
                acc = _accumulate_solve_stats(
                    acc, entity_idx, num_entities, result.converged,
                    result.iterations,
                    jnp.ones_like(result.converged, bool),
                    cg_iterations=getattr(result, "cg_iterations", None),
                )
            if not last:
                z_rows = z_table[entity_of_row]
                latent = self._solve_latent(z_rows, offsets_j, latent)

        # Materialize per-entity coefficients w_e = L z_e (padded slot drops).
        table = z_table[:num_entities] @ latent.T
        from photon_tpu.fault.injection import consume_nan_injection

        if consume_nan_injection(getattr(self, "fault_name", None)):
            table = table.at[0].set(jnp.nan)
        # Non-finite guard: entities whose materialized coefficients are
        # NaN/Inf (a diverged latent alternation) fall back to the
        # warm-start model's rows (aligned during the warm start's SVD
        # fetch), or zeros on a cold start — applied unconditionally on
        # device, and COUNTED into the accumulator's quarantined slot, so
        # the guard adds no host transfer at all.
        good = jnp.all(jnp.isfinite(table), axis=1)
        acc = _count_quarantined(acc, good)
        prev = (
            jnp.asarray(prev_table) if prev_table is not None
            else jnp.zeros_like(table)
        )
        table = jnp.where(good[:, None], table, prev)
        model = RandomEffectModel(
            table=table,
            keys=self.dataset.keys,
            entity_column=self.config.entity_column,
            shard_name=self.config.shard_name,
            task_type=self.task_type,
        )
        return model, DeferredSolveStats(
            acc, extra={"latent_iterations": self.config.latent_iterations}
        )

    def score(self, model: RandomEffectModel) -> np.ndarray:
        return model.score(self.data)

    def score_device(self, model: RandomEffectModel) -> Array:
        """Training-data margins as a device array (the residual engine's
        scoring path; the factored coordinate exports a plain
        :class:`RandomEffectModel`, so scoring is the same gather-join)."""
        return _random_score_device(self, model)


def build_coordinate(
    data: GameDataset,
    config: CoordinateConfig,
    task_type: str,
    mesh=None,
    normalization: Optional[NormalizationContext] = None,
    device_data=None,
):
    if isinstance(config, FixedEffectCoordinateConfig):
        return FixedEffectCoordinate(
            data, config, task_type, mesh, normalization, device_data
        )
    if isinstance(config, (RandomEffectCoordinateConfig,
                           FactoredRandomEffectCoordinateConfig)):
        if normalization is not None:
            raise ValueError(
                "normalization is not supported for random-effect coordinates "
                f"(coordinate on shard {config.shard_name!r})"
            )
        if isinstance(config, FactoredRandomEffectCoordinateConfig):
            return FactoredRandomEffectCoordinate(
                data, config, task_type, mesh, device_data
            )
        return RandomEffectCoordinate(data, config, task_type, mesh, device_data)
    raise TypeError(f"unknown coordinate config type {type(config)!r}")
