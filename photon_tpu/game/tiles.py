"""Tiled score tables + double-buffered chunk streaming (out-of-core GAME).

The resident engines (:mod:`photon_tpu.game.residuals`) hold ONE stacked
``[C, n]`` score table in device memory — correct until ``n`` outgrows HBM.
This module is the out-of-core counterpart (ISSUE 10 / the ROADMAP's
"billions of rows that never fit in HBM" wall): rows are partitioned into
fixed-size **chunks** (one per sharded part-file group), the score table
becomes per-chunk ``[C, rows_k]`` **tiles** resident at the host tier, and
per-chunk Neumaier-compensated partials ``(total_k, comp_k)`` reduce to
exactly the global compensated total the resident engine maintains — the
Neumaier scan runs over the COORDINATE axis element-wise per row, so the
chunk partition cannot change a single value.  This is Snap ML's hierarchy
argument (arXiv:1803.06333) applied one tier up: the dataset and score
state live at the host level, and only the working chunk (plus its
prefetched successor) ever occupies device memory.

:class:`ChunkStreamer` is the transport: chunk ``k+1``'s host slice +
``device_put`` runs on io-pool worker threads while chunk ``k`` computes —
the double-buffered h2d prefetch.  Overlap is measured, not assumed:
``stream.stall_s`` accumulates the wall time the consumer spent blocked on
a chunk that was not ready, ``stream.prefetch_overlap_s`` the load time
that was hidden behind compute, and the ``residuals.device_bytes`` gauge
reports the peak in-flight device residency (the chunk budget bound the
descent asserts against).

The per-chunk map + cross-chunk reduce shape — every training pass is
``reduce(map(chunk))`` with the reduction inside jit per chunk — is the
DrJAX MapReduce idiom (arXiv:2403.07128) expressed at the host loop level,
which is where it must live once the mapped axis no longer fits on device.

ISSUE 11 adds the THIRD tier: :mod:`photon_tpu.game.tile_store` part
files behind an LRU :class:`HostTileCache` (``--max-host-mb``), a
:class:`SpilledChunkSource` whose disk→host reads run one stage ahead of
the h2d window, and :class:`SpilledScoreTable` tiles written through to
disk — the full disk→host→device pipeline with per-tier
``stream.stall_s{tier}`` / ``stream.prefetch_overlap_s{tier}``
measurement, bounding the HOST working set the way PR 10 bounded device
residency.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

from photon_tpu.game.tile_store import (
    FEATURES as FEAT_KIND,
    TILES as TILE_KIND,
    codec_roundtrip,
)
from photon_tpu.telemetry import NULL_SESSION

# The residual table's on-disk tile kind (part files named
# ``tile-residuals-NNNNNN.pt``): the table's telemetry path rides the
# FILE NAME so a second spilled table sharing the store can never
# overwrite these — external readers (bench parity check, tests) import
# this instead of assuming the bare ``tile`` kind.
RESIDUAL_TILE_KIND = f"{TILE_KIND}-residuals"

# Chunks the streamer keeps in flight beyond the one being consumed: chunk
# k+1 uploads while chunk k computes (double buffering).  The device-memory
# bound every budget computation uses is (PREFETCH_DEPTH + 1) chunks.
PREFETCH_DEPTH = 2


# ---------------------------------------------------------------------------
# Chunk plan + memory budget
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ChunkPlan:
    """Fixed-size row partition: chunk ``k`` covers rows
    ``[k * chunk_rows, min(n, (k+1) * chunk_rows))``.  The last chunk may be
    partial; a ``chunk_rows >= n`` plan degenerates to one chunk (the
    resident-equivalent case the tests pin)."""

    n: int
    chunk_rows: int

    def __post_init__(self):
        if self.n < 0:
            raise ValueError(f"negative row count {self.n}")
        if self.chunk_rows < 1:
            raise ValueError(f"chunk_rows must be >= 1, got {self.chunk_rows}")

    @property
    def num_chunks(self) -> int:
        return max(1, -(-self.n // self.chunk_rows))

    def bounds(self, k: int) -> tuple[int, int]:
        if not 0 <= k < self.num_chunks:
            raise IndexError(f"chunk {k} out of range [0, {self.num_chunks})")
        lo = k * self.chunk_rows
        return lo, min(self.n, lo + self.chunk_rows)

    def rows(self, k: int) -> int:
        lo, hi = self.bounds(k)
        return hi - lo


def per_row_bytes(data) -> int:
    """Bytes one dataset row occupies across every feature shard plus the
    per-row scalars — the unit the chunk budget divides by."""
    from photon_tpu.game.data import DenseShard

    total = 12  # label + offset + weight (f32 each)
    for shard in data.shards.values():
        if isinstance(shard, DenseShard):
            total += shard.x.dtype.itemsize * shard.x.shape[1]
        else:
            total += (
                shard.ids.dtype.itemsize + shard.vals.dtype.itemsize
            ) * shard.ids.shape[1]
    return total


def resident_bytes_estimate(data, n_coordinates: int = 2) -> int:
    """Device bytes a RESIDENT GAME fit would hold for this dataset: the
    training feature blocks, the scoring-cache second copy the residual
    engine keeps (``coordinate._scoring_feats``), and the two stacked
    ``[C, n]`` float32 score tables (residual + validation) at
    ``n_coordinates`` rows each.  A lower bound — random-effect bin
    padding (≤2× per block) and optimizer workspace ride on top — which
    is the right direction for the auto-streaming gate
    (``--max-resident-mb``): an over-budget ESTIMATE always streams, and
    a dataset whose floor already exceeds the budget can never silently
    train resident."""
    n = data.num_examples
    return 2 * per_row_bytes(data) * n + 2 * max(1, n_coordinates) * n * 4


def stream_host_bytes_estimate(data, n_coordinates: int = 2) -> int:
    """HOST bytes the streamed (out-of-core) fit pins without a disk tier:
    the feature chunks (the dataset rows themselves) plus the ``[C, rows]``
    float32 residual score tiles.  The quantity ``--max-host-mb`` budgets:
    past it, the disk-backed tile store spills both and bounds the host
    working set to the LRU cache instead (ISSUE 11)."""
    n = data.num_examples
    return per_row_bytes(data) * n + max(1, n_coordinates) * n * 4


def chunk_rows_for_budget(data, max_resident_mb: float) -> int:
    """Chunk size such that the streamer's in-flight window —
    ``PREFETCH_DEPTH + 1`` chunks — fits the device budget."""
    if max_resident_mb <= 0:
        raise ValueError(f"max_resident_mb must be > 0, got {max_resident_mb}")
    budget = int(max_resident_mb * (1 << 20))
    rows = budget // ((PREFETCH_DEPTH + 1) * max(1, per_row_bytes(data)))
    return max(1, min(int(rows), max(1, data.num_examples)))


def slice_rows(data, lo: int, hi: int):
    """Contiguous row window ``[lo, hi)`` of a GameDataset as numpy VIEWS
    (no copy — the chunk loader's host side is a slice, not a gather)."""
    from photon_tpu.game.data import DenseShard, GameDataset, SparseShard

    def cut(shard):
        if isinstance(shard, DenseShard):
            return DenseShard(shard.x[lo:hi])
        return SparseShard(shard.ids[lo:hi], shard.vals[lo:hi], shard.dim_)

    return GameDataset(
        label=data.label[lo:hi],
        offset=data.offset[lo:hi],
        weight=data.weight[lo:hi],
        shards={name: cut(s) for name, s in data.shards.items()},
        id_columns={name: c[lo:hi] for name, c in data.id_columns.items()},
    )


# ---------------------------------------------------------------------------
# Double-buffered chunk streamer
# ---------------------------------------------------------------------------


def _device_nbytes(payload) -> int:
    """Device bytes of one loaded chunk (any pytree of arrays)."""
    import jax

    return sum(
        int(getattr(leaf, "nbytes", 0))
        for leaf in jax.tree.leaves(payload)
    )


class ChunkStreamer:
    """Ordered chunk iteration with h2d prefetch on io-pool worker threads.

    ``stream(load_chunk, num_chunks)`` yields ``load_chunk(k)`` results in
    order; ``load_chunk`` runs on worker threads (host slice + device_put,
    so the upload overlaps the consumer's compute).  At most
    ``prefetch`` chunks are in flight beyond the one being consumed — the
    double-buffer window that bounds device residency at
    ``(prefetch + 1) × chunk_bytes``.

    Telemetry (shared across every pass this streamer drives):
    ``stream.stall_s{tier=h2d}`` — consumer wall time blocked on an
    unready chunk; ``stream.prefetch_overlap_s{tier=h2d}`` — load seconds
    hidden behind compute (the disk tier reports the same pair under
    ``tier=disk`` from :class:`SpilledChunkSource`); ``stream.chunks`` —
    chunks delivered; ``peak_in_flight_bytes`` — the high-water in-flight
    device residency (exported by the descent as the
    ``residuals.device_bytes`` gauge, the chunk-budget assertion).
    """

    def __init__(self, telemetry=None, prefetch: int = PREFETCH_DEPTH):
        self.telemetry = telemetry or NULL_SESSION
        self.prefetch = max(1, int(prefetch))
        self.peak_in_flight_bytes = 0
        self._lock = threading.Lock()
        # One persistent worker pool per streamer: a streamed L-BFGS runs
        # one stream() pass PER OBJECTIVE EVALUATION, and spawning threads
        # per pass would churn hundreds of threads across a fit.
        self._pool: Optional[ThreadPoolExecutor] = None
        self._pool_workers = 0

    def _executor(self, workers: int) -> ThreadPoolExecutor:
        with self._lock:
            if self._pool is None or self._pool_workers < workers:
                if self._pool is not None:
                    self._pool.shutdown(wait=False)
                self._pool = ThreadPoolExecutor(
                    max_workers=workers,
                    thread_name_prefix="photon-chunk-stream",
                )
                self._pool_workers = workers
            return self._pool

    def _note_bytes(self, in_flight_chunks: int, chunk_bytes: int) -> None:
        bound = in_flight_chunks * chunk_bytes
        with self._lock:
            if bound > self.peak_in_flight_bytes:
                self.peak_in_flight_bytes = bound

    def stream(
        self, load_chunk: Callable[[int], object], num_chunks: int
    ) -> Iterator[object]:
        from photon_tpu.utils.io_pool import io_threads

        tel = self.telemetry
        # Per-tier labels (ISSUE 11): this streamer IS the host→device
        # stage; the disk→host stage (SpilledChunkSource) reports under
        # tier="disk" on the same counter names.
        stall_c = tel.counter("stream.stall_s", tier="h2d")
        overlap_c = tel.counter("stream.prefetch_overlap_s", tier="h2d")
        chunks_c = tel.counter("stream.chunks")

        def timed_load(k: int):
            t0 = time.monotonic()
            payload = load_chunk(k)
            return payload, time.monotonic() - t0, _device_nbytes(payload)

        # Single chunk: plain eager load — there is nothing to overlap,
        # and the whole load time is an honest stall.
        window = self.prefetch
        if num_chunks <= 1:
            for k in range(num_chunks):
                payload, load_s, nbytes = timed_load(k)
                stall_c.inc(load_s)
                chunks_c.inc()
                self._note_bytes(1, nbytes)
                yield payload
            return

        ex = self._executor(min(window, max(2, io_threads())))
        futs: deque = deque()
        try:
            idx = 0
            while futs or idx < num_chunks:
                while idx < num_chunks and len(futs) < window:
                    futs.append(ex.submit(timed_load, idx))
                    idx += 1
                t_wait = time.monotonic()
                payload, load_s, nbytes = futs.popleft().result()
                stall = time.monotonic() - t_wait
                stall_c.inc(stall)
                overlap_c.inc(max(0.0, load_s - stall))
                chunks_c.inc()
                # REFILL before yielding: the successor chunks must be in
                # flight WHILE the consumer computes on this one — with
                # prefetch=1 this is what makes single-buffering ahead
                # real rather than a silent no-overlap mode.
                while idx < num_chunks and len(futs) < window:
                    futs.append(ex.submit(timed_load, idx))
                    idx += 1
                # Compute-time residency: the chunk being consumed plus
                # everything in flight behind it (sized by this chunk —
                # chunks share one layout).  Steady state is window + 1
                # chunks, the (PREFETCH_DEPTH + 1) factor the budget
                # divides by.
                self._note_bytes(len(futs) + 1, nbytes)
                yield payload
        finally:
            # An abandoned pass (consumer raised / generator closed) must
            # not leave queued loads running into the next pass: cancel
            # what has not started; in-progress loads finish harmlessly
            # (their results are dropped with the futures).
            for f in futs:
                f.cancel()


# ---------------------------------------------------------------------------
# Host tier: LRU cache over the disk-backed tile store (ISSUE 11)
# ---------------------------------------------------------------------------


def _entry_nbytes(value) -> int:
    """Host bytes of a cached entry: arrays, or any dict/tuple/list nest
    of them (a feature payload is the store's ``(arrays, meta)`` pair)."""
    if isinstance(value, dict):
        return sum(_entry_nbytes(v) for v in value.values())
    if isinstance(value, (tuple, list)):
        return sum(_entry_nbytes(v) for v in value)
    return int(getattr(value, "nbytes", 0))


@dataclasses.dataclass
class _CacheEntry:
    value: object
    nbytes: int
    load_s: float
    consumed: bool = False


class HostTileCache:
    """Bounded LRU host cache keyed by ``(kind, chunk_id)`` between the
    disk tier (:class:`photon_tpu.game.tile_store.TileStore`) and the
    host→device streamer — the ``--max-host-mb`` budget, mirroring
    ``--max-resident-mb`` one tier up.

    Thread-safe with single-flight loads: concurrent misses of one key
    (an io-pool disk prefetch racing the h2d worker) share ONE disk read.
    Insertion evicts least-recently-used entries until the budget holds
    (the incoming entry is kept even when it alone exceeds the budget —
    the caller needs the data either way; the cache then simply holds
    one oversized entry until the next insert).

    Telemetry: ``tiles.cache_hits`` / ``tiles.cache_misses`` /
    ``tiles.cache_evictions`` counters and the live
    ``tiles.host_cache_bytes`` gauge (CI asserts it never exceeds the
    budget after an eviction pass).
    """

    def __init__(self, max_bytes: Optional[int] = None, telemetry=None):
        if max_bytes is not None and max_bytes <= 0:
            raise ValueError(f"max_bytes must be > 0, got {max_bytes}")
        self.max_bytes = max_bytes
        self.telemetry = telemetry or NULL_SESSION
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, _CacheEntry]" = OrderedDict()
        self._inflight: Dict[tuple, Future] = {}
        self._bytes = 0
        self._evict_listeners: List[Callable[[tuple, object], None]] = []
        self._hits = self.telemetry.counter("tiles.cache_hits")
        self._misses = self.telemetry.counter("tiles.cache_misses")
        self._evictions = self.telemetry.counter("tiles.cache_evictions")
        self._gauge = self.telemetry.gauge("tiles.host_cache_bytes")

    @property
    def nbytes(self) -> int:
        with self._lock:
            return self._bytes

    def add_evict_listener(
        self, fn: Callable[[tuple, object], None]
    ) -> None:
        """Register ``fn(key, value)`` to run after an LRU EVICTION
        (outside the cache lock — ``fn`` may do IO or re-enter the
        cache).  Deliberate drops (:meth:`invalidate`, :meth:`clear`)
        do NOT notify: the reset paths discard state on purpose, and a
        write-back hook firing there would resurrect it.  The spilled
        score table uses this to flush a still-dirty tile whose cached
        copy is being displaced (write-back, not write-through)."""
        self._evict_listeners.append(fn)

    def _notify_evicted(self, evicted) -> None:
        for key, entry in evicted:
            for fn in self._evict_listeners:
                fn(key, entry.value)

    def _evict_locked(self) -> list:
        # The entry just inserted sits at the MRU end, so the `> 1` bound
        # both protects it and implements the oversized-entry allowance
        # (a lone entry larger than the budget stays until the next
        # insert displaces it).  Returns the evicted (key, entry) pairs —
        # the caller notifies listeners AFTER releasing the lock.
        evicted = []
        while (
            self.max_bytes is not None
            and self._bytes > self.max_bytes
            and len(self._entries) > 1
        ):
            key, entry = self._entries.popitem(last=False)
            self._bytes -= entry.nbytes
            self._evictions.inc()
            evicted.append((key, entry))
        self._gauge.set(self._bytes)
        return evicted

    def _insert_locked(self, key: tuple, entry: _CacheEntry) -> list:
        old = self._entries.pop(key, None)
        if old is not None:
            self._bytes -= old.nbytes
        self._entries[key] = entry
        self._bytes += entry.nbytes
        return self._evict_locked()

    def put(self, key: tuple, value) -> None:
        """Insert/replace (write-through warm path: the tile just written
        to the store is the hottest possible entry)."""
        with self._lock:
            evicted = self._insert_locked(
                key, _CacheEntry(value, _entry_nbytes(value), 0.0, True)
            )
        self._notify_evicted(evicted)

    def invalidate(self, key: tuple) -> None:
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is not None:
                self._bytes -= entry.nbytes
            self._gauge.set(self._bytes)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0
            self._gauge.set(0)

    def _do_load(self, key, fut: Future, loader, consumed: bool):
        """Single-flight load body: loads, inserts, resolves waiters.
        ``consumed=False`` marks a prefetch — the first real consumer's
        :meth:`get` then reports the hidden read time as overlap."""
        try:
            t0 = time.monotonic()
            value = loader()
            load_s = time.monotonic() - t0
        except BaseException as e:
            with self._lock:
                self._inflight.pop(key, None)
            fut.set_exception(e)
            raise
        with self._lock:
            evicted = self._insert_locked(
                key,
                _CacheEntry(value, _entry_nbytes(value), load_s, consumed),
            )
            self._inflight.pop(key, None)
        self._misses.inc()
        fut.set_result(value)
        self._notify_evicted(evicted)
        return value, load_s

    def get(self, key: tuple, loader: Callable[[], object]):
        """``(value, hidden_load_s)``: the cached value (loading it via
        ``loader`` on a miss), plus — on the FIRST consumption of an entry
        a prefetch loaded — the disk-read seconds that consumption just
        hid (disk-tier overlap).  Hot hits and own loads return 0.0."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self._hits.inc()
                hidden = 0.0 if entry.consumed else entry.load_s
                entry.consumed = True
                return entry.value, hidden
            fut = self._inflight.get(key)
            if fut is None:
                fut = Future()
                self._inflight[key] = fut
                owner = True
            else:
                owner = False
        if not owner:
            # A prefetcher (or sibling worker) is mid-read: share its one
            # disk read.  The wall time spent here is the caller's own
            # stall measurement; mark the entry consumed so a LATER hit
            # cannot re-report the read as hidden overlap.
            value = fut.result()
            with self._lock:
                entry = self._entries.get(key)
                if entry is not None:
                    entry.consumed = True
            self._hits.inc()
            return value, 0.0
        value, _ = self._do_load(key, fut, loader, consumed=True)
        return value, 0.0

    def prefetch(self, key: tuple, loader: Callable[[], object]) -> None:
        """Warm ``key`` in the background (io-pool worker) — the
        disk→host stage that runs one step ahead of the h2d upload."""
        from photon_tpu.utils.io_pool import submit

        with self._lock:
            if key in self._entries or key in self._inflight:
                return

        def warm():
            with self._lock:
                if key in self._entries or key in self._inflight:
                    return
                fut = Future()
                self._inflight[key] = fut
            try:
                self._do_load(key, fut, loader, consumed=False)
            except BaseException:
                # Surfacing happens on the consumer's own (retried,
                # guarded) read — a failed warm must not kill the pool.
                pass

        try:
            submit(warm, pool="tile-prefetch")
        except RuntimeError:
            pass  # interpreter shutting down: prefetch is best-effort


# ---------------------------------------------------------------------------
# Chunk feature sources: resident host slices vs the spilled disk tier
# ---------------------------------------------------------------------------


class ResidentChunkSource:
    """PR 10 behavior: chunk features are numpy VIEWS over the host-
    resident dataset."""

    tier = "host"

    def __init__(self, data, plan: ChunkPlan):
        self.data = data
        self.plan = plan

    def chunk(self, k: int):
        lo, hi = self.plan.bounds(k)
        return slice_rows(self.data, lo, hi)


def _shard_schema(data) -> dict:
    from photon_tpu.game.data import DenseShard

    out = {}
    for name, shard in data.shards.items():
        if isinstance(shard, DenseShard):
            out[name] = {"kind": "dense", "dtype": shard.x.dtype.str}
        else:
            out[name] = {"kind": "sparse", "dim": int(shard.dim_)}
    return out


def dataset_fingerprint(
    data, chunk_rows: int, tile_dtype: str = "f32"
) -> dict:
    """Cheap identity of (dataset, chunk plan, storage codec) for
    spill-dir reuse: shape, schema, a content hash of the per-row scalar
    columns (one pass over 12·n bytes — features are not re-hashed; a
    dataset that changes features while keeping labels/weights/offsets
    bit-identical is out of scope and documented), and the store's
    ``tile_dtype`` — changing the precision tier MUST invalidate the
    spilled feature blocks, or a bf16 run would silently train on a
    previous run's f32 chunks (or vice versa)."""
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(data.label, np.float32).tobytes())
    h.update(np.ascontiguousarray(data.weight, np.float32).tobytes())
    h.update(np.ascontiguousarray(data.offset, np.float32).tobytes())
    return {
        "n": int(data.num_examples),
        "chunk_rows": int(chunk_rows),
        "shards": _shard_schema(data),
        "scalar_sha256": h.hexdigest(),
        "tile_dtype": str(tile_dtype),
    }


def spill_dataset(store, data, plan: ChunkPlan, telemetry=None) -> int:
    """Write every chunk's feature block into the store (skipping chunks
    already published by a previous run over the SAME dataset+plan — the
    store's ``dataset.json`` pins that identity; any mismatch resets the
    store).  Returns the number of chunks actually written."""
    from photon_tpu.game.data import DenseShard

    tel = telemetry or NULL_SESSION
    tile_dtype = getattr(store, "tile_dtype", "f32")
    fp = dataset_fingerprint(data, plan.chunk_rows, tile_dtype)
    if store.read_dataset_meta() != fp:
        # Foreign/stale spill dir: drop everything, re-publish identity
        # LAST (a kill mid-spill leaves no matching dataset.json, so the
        # next run re-spills from scratch instead of trusting a torn set).
        store.reset_all()
    written = 0
    with tel.span("tiles.spill", chunks=plan.num_chunks):
        for k in range(plan.num_chunks):
            if store.has(FEAT_KIND, k):
                continue
            lo, hi = plan.bounds(k)
            arrays = {
                "label": data.label[lo:hi],
                "offset": data.offset[lo:hi],
                "weight": data.weight[lo:hi],
            }
            # Only feature VALUES take the lossy tier: sparse column ids
            # are indices and the per-row scalars feed the objective (and
            # the fingerprint hash) directly — both stay exact.
            lossy = []
            for name, shard in data.shards.items():
                if isinstance(shard, DenseShard):
                    arrays[f"s:{name}:x"] = shard.x[lo:hi]
                    lossy.append(f"s:{name}:x")
                else:
                    arrays[f"s:{name}:ids"] = shard.ids[lo:hi]
                    arrays[f"s:{name}:vals"] = shard.vals[lo:hi]
                    lossy.append(f"s:{name}:vals")
            store.write(
                FEAT_KIND, k, arrays,
                meta={"chunk": k, "rows": hi - lo,
                      "shards": _shard_schema(data)},
                codecs=store.lossy_codecs(lossy),
            )
            written += 1
    if store.read_dataset_meta() != fp:
        store.write_dataset_meta(fp)
    tel.counter("tiles.chunks_spilled").inc(written)
    return written


class SpilledChunkSource:
    """Feature chunks served from the disk tier through the LRU host
    cache, with disk→host prefetch scheduled ONE STAGE AHEAD of the h2d
    window: when the streamer's worker loads chunk ``k`` (host→device),
    this source warms chunks ``k+1 .. k+stage_ahead`` on io-pool workers,
    so in steady state the disk read of a chunk completes while its
    predecessors upload and compute.

    Per-tier telemetry (same measured-overlap contract as the streamer):
    ``stream.stall_s{tier=disk}`` — time an h2d load spent blocked on an
    uncached disk read; ``stream.prefetch_overlap_s{tier=disk}`` — disk
    read seconds hidden behind the pipeline (prefetched reads consumed
    later).
    """

    tier = "disk"

    def __init__(
        self, store, plan: ChunkPlan, cache: HostTileCache, telemetry=None,
        stage_ahead: int = PREFETCH_DEPTH + 1,
    ):
        self.store = store
        self.plan = plan
        self.cache = cache
        self.telemetry = telemetry or NULL_SESSION
        self.stage_ahead = max(1, int(stage_ahead))
        self._stall_c = self.telemetry.counter("stream.stall_s", tier="disk")
        self._overlap_c = self.telemetry.counter(
            "stream.prefetch_overlap_s", tier="disk"
        )

    def _loader(self, k: int):
        return lambda: self.store.read(FEAT_KIND, k)

    def _rebuild(self, payload):
        from photon_tpu.game.data import DenseShard, GameDataset, SparseShard

        arrays, meta = payload
        shards = {}
        for name, schema in meta["shards"].items():
            if schema["kind"] == "dense":
                shards[name] = DenseShard(arrays[f"s:{name}:x"])
            else:
                shards[name] = SparseShard(
                    arrays[f"s:{name}:ids"], arrays[f"s:{name}:vals"],
                    schema["dim"],
                )
        return GameDataset(
            label=arrays["label"], offset=arrays["offset"],
            weight=arrays["weight"], shards=shards, id_columns={},
        )

    def chunk(self, k: int):
        # Warm the successors first: the disk stage must run ahead even
        # when THIS chunk is about to stall (first touch of the stream).
        for j in range(k + 1, min(k + 1 + self.stage_ahead,
                                  self.plan.num_chunks)):
            self.cache.prefetch((FEAT_KIND, j), self._loader(j))
        t0 = time.monotonic()
        payload, hidden_s = self.cache.get((FEAT_KIND, k), self._loader(k))
        wait = time.monotonic() - t0
        self._stall_c.inc(wait)
        self._overlap_c.inc(max(0.0, hidden_s - wait))
        return self._rebuild(payload)


@dataclasses.dataclass
class SpillContext:
    """The assembled disk tier of one spilled streamed fit: the part-file
    store, the budgeted host cache, and the chunk feature source reading
    through them — built once per estimator
    (:meth:`photon_tpu.game.estimator.GameEstimator._spill_context`) and
    threaded through the descent and every streamed coordinate."""

    store: object
    cache: HostTileCache
    source: SpilledChunkSource


# ---------------------------------------------------------------------------
# Compensated cross-chunk accumulator (ISSUE 11 satellite)
# ---------------------------------------------------------------------------


class NeumaierAccumulator:
    """Neumaier-compensated float64 accumulator for the streamed L-BFGS
    cross-chunk value+grad reduce: the per-chunk terms arrive as f32
    device results, and the compensated f64 sum makes the cross-chunk
    accumulation error independent of the chunk COUNT — a 1-chunk and a
    1000-chunk pass reduce to the same f64 total up to the per-chunk f32
    inputs themselves (the remaining streamed-vs-resident floor)."""

    def __init__(self, dim: int):
        self._v = 0.0
        self._vc = 0.0
        self._g = np.zeros(dim, np.float64)
        self._gc = np.zeros(dim, np.float64)

    def add(self, value: float, grad: np.ndarray) -> None:
        v = float(value)
        t = self._v + v
        if abs(self._v) >= abs(v):
            self._vc += (self._v - t) + v
        else:
            self._vc += (v - t) + self._v
        self._v = t
        # host-sync: per-chunk grads arrive as host numpy by construction
        # (the streamed reduce's d2h is marked at its call site).
        g = np.asarray(grad, np.float64)
        t = self._g + g
        self._gc += np.where(
            np.abs(self._g) >= np.abs(g),
            (self._g - t) + g,
            (g - t) + self._g,
        )
        self._g = t

    @property
    def value(self) -> float:
        return self._v + self._vc

    @property
    def grad(self) -> np.ndarray:
        return self._g + self._gc


# ---------------------------------------------------------------------------
# Tiled score tables
# ---------------------------------------------------------------------------


def _neumaier_rows_np(tile: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Neumaier-compensated column-wise sum of one ``[C, rows]`` tile in
    float32 numpy — the SAME arithmetic, in the same order, as the resident
    engine's jitted ``_neumaier_rows`` scan (elementwise IEEE f32 ops), so
    per-chunk partials concatenate to the resident engine's global
    total/comp pair."""
    total = np.zeros(tile.shape[1], np.float32)
    comp = np.zeros(tile.shape[1], np.float32)
    for row in tile:
        t = total + row
        lost = np.where(
            np.abs(total) >= np.abs(row),
            (total - t) + row,
            (row - t) + total,
        )
        comp = comp + lost
        total = t
    return total, comp


class TiledScoreTable:
    """Host-resident per-chunk score tiles with maintained compensated
    partials — the out-of-core form of ``_DeviceScoreTable``.

    ``tiles[k]`` is the ``[C, rows_k]`` float32 score tile of chunk ``k``
    (row ``c`` = coordinate ``c``'s scores over that chunk's rows);
    ``totals[k]``/``comps[k]`` hold the chunk's Neumaier partials,
    recomputed from the tile on every row update (never incrementally
    drifted, same rule as the resident engine).  Training offsets and
    composite margins are produced PER CHUNK — the streamed training and
    scoring passes consume them chunk by chunk and never materialize a
    device ``[C, n]`` table.

    Non-finite score vectors are rejected at update (host check — the
    tiles ARE host data), keeping the previous tile; the pending guard
    flags drain through the same ``drain_guard_flags`` /
    ``poll_quarantined`` contract as the engines.
    """

    _PATH = "residuals"

    def __init__(
        self,
        base_offset: np.ndarray,
        names: Sequence[str],
        plan: ChunkPlan,
        telemetry=None,
    ):
        if not names:
            raise ValueError(
                f"{type(self).__name__} needs at least one coordinate"
            )
        self.names = list(names)
        self._row = {name: i for i, name in enumerate(self.names)}
        if len(self._row) != len(self.names):
            raise ValueError(f"duplicate coordinate names in {self.names}")
        if len(base_offset) != plan.n:
            raise ValueError(
                f"base offset has {len(base_offset)} rows, plan covers {plan.n}"
            )
        self.plan = plan
        self.telemetry = telemetry or NULL_SESSION
        self.n = plan.n
        # host-sync: the tiled tables are host-resident BY DESIGN — the
        # out-of-core tier keeps score state at host level, streaming only
        # the working chunk to device.
        self.base = np.asarray(base_offset, np.float32)
        # The Neumaier partials stay host-RESIDENT in every mode (12
        # bytes/row beside the base offset): every per-chunk read needs
        # them, and they are two orders smaller than the tiles+features
        # the ``--max-host-mb`` budget spills.
        self.totals: List[np.ndarray] = [
            np.zeros(plan.rows(k), np.float32) for k in range(plan.num_chunks)
        ]
        self.comps: List[np.ndarray] = [
            np.zeros(plan.rows(k), np.float32) for k in range(plan.num_chunks)
        ]
        self._pending_guard: list = []
        self._init_tiles()
        self.telemetry.gauge(f"{self._PATH}.tile_chunks").set(plan.num_chunks)

    # -- tile residency hooks (overridden by the spilled subclass) ------------
    def _init_tiles(self) -> None:
        c = len(self.names)
        self.tiles: List[np.ndarray] = [
            np.zeros((c, self.plan.rows(k)), np.float32)
            for k in range(self.plan.num_chunks)
        ]

    def tile(self, k: int) -> np.ndarray:
        """Chunk ``k``'s ``[C, rows_k]`` score tile (host float32)."""
        return self.tiles[k]

    def _publish_tile(self, k: int, tile: np.ndarray) -> None:
        """Land a mutated tile: refresh the chunk's compensated partials
        (recomputed from the tile on every row update — never
        incrementally drifted, same rule as the resident engine)."""
        self.tiles[k] = tile
        self.totals[k], self.comps[k] = _neumaier_rows_np(tile)

    @property
    def num_chunks(self) -> int:
        return self.plan.num_chunks

    def row(self, name: str) -> int:
        return self._row[name]

    def update(self, name: str, new_scores) -> None:
        """Replace ``name``'s score row across every tile and refresh the
        per-chunk compensated partials.  ``new_scores`` is a host float32
        vector of length ``n`` (the streamed scoring passes assemble it
        chunk by chunk)."""
        # host-sync: streamed score vectors arrive as host numpy by
        # construction (assembled from per-chunk d2h fetches).
        host = np.asarray(new_scores, np.float32)
        if host.shape != (self.n,):
            raise ValueError(
                f"score vector for {name!r} has shape {host.shape}, "
                f"want ({self.n},)"
            )
        ok = bool(np.isfinite(host).all())
        self._pending_guard.append((name, ok))
        if ok:
            c = self._row[name]
            for k in range(self.num_chunks):
                lo, hi = self.plan.bounds(k)
                tile = self.tile(k)
                tile[c] = host[lo:hi]
                self._publish_tile(k, tile)
        self.telemetry.counter(f"{self._PATH}.updates", coordinate=name).inc()

    # -- per-chunk reads ------------------------------------------------------
    def offsets_chunk(self, name: str, k: int) -> np.ndarray:
        """Chunk ``k``'s training offsets for coordinate ``name``:
        ``base_k + (total_k - tile_k[c]) + comp_k`` — the same fused formula
        (and f32 order) as the resident ``_offsets_kernel``."""
        lo, hi = self.plan.bounds(k)
        c = self._row[name]
        return self.base[lo:hi] + (
            (self.totals[k] - self.tile(k)[c]) + self.comps[k]
        )

    def offsets_full(self, name: str) -> np.ndarray:
        """All chunks' offsets concatenated (``[n]`` f32) — the host gather
        source for random-effect bucket offsets, and exactly the
        concatenation of :meth:`offsets_chunk` (chunking never changes a
        value; see module docstring)."""
        return np.concatenate(
            [self.offsets_chunk(name, k) for k in range(self.num_chunks)]
        )

    def composite_chunk(self, k: int) -> np.ndarray:
        """Chunk ``k``'s composite margin ``base_k + (total_k + comp_k)``
        (the validation table's scoring output)."""
        lo, hi = self.plan.bounds(k)
        return self.base[lo:hi] + (self.totals[k] + self.comps[k])

    def composite_full(self) -> np.ndarray:
        return np.concatenate(
            [self.composite_chunk(k) for k in range(self.num_chunks)]
        )

    def scores_for(self, name: str) -> np.ndarray:
        """Coordinate ``name``'s current score vector (host, ``[n]``)."""
        c = self._row[name]
        return np.concatenate(
            [self.tile(k)[c] for k in range(self.num_chunks)]
        )

    # -- guard / snapshot contract (mirrors the engines) ----------------------
    def drain_guard_flags(self) -> list:
        pending, self._pending_guard = self._pending_guard, []
        return pending

    def record_rejected(self, bad: Sequence[str]) -> None:
        for name in bad:
            self.telemetry.counter(
                f"{self._PATH}.nonfinite_rows", coordinate=name
            ).inc()

    def poll_quarantined(self) -> list:
        bad = [name for name, ok in self.drain_guard_flags() if not ok]
        self.record_rejected(bad)
        return bad

    def snapshot_rows(self) -> dict:
        """All score rows as host float32 ``{name: [n]}`` — the checkpoint
        snapshot (already host: staging is a copy)."""
        return {name: self.scores_for(name).copy() for name in self.names}

    def load_rows(self, rows: dict) -> None:
        """Rebuild tiles from checkpointed rows (resume path).  Stored
        directly — checkpointed rows were guarded at write time, and
        routing them through update() would enqueue phantom guard flags."""
        loaded = {}
        for name, row in rows.items():
            if name not in self._row:
                continue
            # host-sync: checkpointed rows are host arrays by construction.
            host = np.asarray(row, np.float32)
            if host.shape != (self.n,):
                raise ValueError(
                    f"checkpointed row for {name!r} has shape {host.shape}, "
                    f"want ({self.n},)"
                )
            loaded[self._row[name]] = host
        # Chunk-outer: ONE read-modify-write per tile (the spilled table
        # publishes each tile once, not once per coordinate).
        for k in range(self.num_chunks):
            lo, hi = self.plan.bounds(k)
            tile = self.tile(k)
            for c, host in loaded.items():
                tile[c] = host[lo:hi]
            self._publish_tile(k, tile)

    def clear(self) -> None:
        """Zero every tile (the deterministic-rebuild reset of the spilled
        resume path)."""
        for k in range(self.num_chunks):
            tile = self.tile(k)
            tile[:] = 0.0
            self._publish_tile(k, tile)

    def tile_digest(self, k: int) -> str:
        """Chunk ``k``'s tile content digest — sha256/16 of the raw tile
        bytes, the PR 10 checkpoint digest contract."""
        return hashlib.sha256(self.tile(k).tobytes()).hexdigest()[:16]

    def tile_digests(self) -> List[str]:
        """Per-chunk content digests of the score tiles (sha256/16): stamped
        into mid-epoch checkpoints so a resume can verify the rebuilt tiles
        match the interrupted run's state chunk for chunk."""
        return [self.tile_digest(k) for k in range(self.num_chunks)]


class TiledResidualTable(TiledScoreTable):
    """Training-side tiled score table (the residual engine's role; the
    base class already carries the ``residuals`` telemetry path)."""


class TiledValidationTable(TiledScoreTable):
    """Validation-side tiled score table: incremental per-coordinate
    re-scoring with the composite margin from the same per-chunk partials
    (``validation.score_reuse`` counting happens in the descent loop)."""

    _PATH = "validation"


class SpilledScoreTable(TiledScoreTable):
    """Score tiles resident at the DISK tier (ISSUE 11): every read goes
    through the LRU host cache, every publish lands in a WRITE-BACK set
    that flushes to the :class:`~photon_tpu.game.tile_store.TileStore`
    part file once per descent sweep (atomic rename — a torn write-back
    keeps the previous tile), so the host working set of the score plane
    is the cache budget, not ``C × n``.

    Write-back batching (ISSUE 17 / the ROADMAP tiering edge): a sweep
    updates every coordinate's row of every tile, and the PR 11 write-
    THROUGH design republished each full ``[C, rows_k]`` tile C times per
    sweep — a C-fold disk amplification.  ``_publish_tile`` now only
    refreshes the in-memory state (partials, digest, cache) and marks
    the tile dirty; :meth:`flush` — called by the descent once per outer
    iteration and before every checkpoint — publishes each dirty tile
    ONCE.  Two hooks keep the old guarantees: an LRU evict listener
    flushes a still-dirty tile whose cached copy is being displaced (the
    dirty set never pins more than the cache budget), and kill-safety
    falls back to the existing resume ladder — a kill between sweeps
    finds disk == checkpoint digests (fast adopt), a kill mid-sweep
    finds a digest mismatch and rebuilds deterministically from the
    checkpointed models (exactly the torn-write-back path PR 11 pinned).

    Numerics per codec: at the exact tier the store roundtrip is
    bit-exact and spilled vs resident streamed runs produce
    ``np.array_equal`` tiles (pinned by tests).  At a lossy tier
    (``TileStore(tile_dtype="bf16"|"int8")``) every publish rounds the
    tile through the storage codec FIRST — partials, digests, and the
    cached copy all describe the decoded-from-disk bytes, so memory and
    disk agree bit for bit and kill→resume parity stays exact per codec.

    Checkpoint contract: :meth:`snapshot_rows` returns ``{}`` — the
    on-disk tiles are REFERENCED by the checkpoint's per-chunk digests,
    not re-saved into it; :meth:`attach_resume` adopts them at resume
    (digest-verified at read — corruption is refused loudly), and the
    descent rebuilds deterministically from the checkpointed models when
    the referenced tiles are stale (e.g. a kill tore the update sequence
    mid-write-back).
    """

    def __init__(
        self,
        base_offset: np.ndarray,
        names: Sequence[str],
        plan: ChunkPlan,
        store,
        cache: HostTileCache,
        telemetry=None,
    ):
        self._store = store
        self._cache = cache
        self._dirty_lock = threading.Lock()
        # k -> (tile, totals, comps, full_sha): everything one store
        # publish needs, captured at _publish_tile time.  Tuples are
        # immutable snapshots — a racing evict-flush and an iteration
        # flush of the same chunk write identical bytes.
        self._dirty: Dict[int, tuple] = {}
        self._publishes_since_flush = 0
        super().__init__(base_offset, names, plan, telemetry=telemetry)
        cache.add_evict_listener(self._on_cache_evict)
        self.telemetry.gauge(f"{self._PATH}.tiles_spilled").set(1)

    # -- residency hooks ------------------------------------------------------
    def _init_tiles(self) -> None:
        # No [C, rows_k] host allocation: a None digest marks the implicit
        # all-zero tile (nothing published yet).
        self._digests: List[Optional[str]] = [None] * self.plan.num_chunks

    @property
    def _tile_kind(self) -> str:
        # The _PATH rides the part-file NAME, not just the cache key: two
        # spilled tables sharing one store (e.g. a future spilled
        # validation table) must not overwrite each other's tiles.
        return f"{TILE_KIND}-{self._PATH}"

    def _key(self, k: int) -> tuple:
        return (TILE_KIND, self._PATH, k)

    def _zero_tile(self, k: int) -> np.ndarray:
        return np.zeros((len(self.names), self.plan.rows(k)), np.float32)

    def tile(self, k: int) -> np.ndarray:
        def load():
            # Dirty-first: a dirty tile evicted from the cache may not
            # have reached disk yet (its evict-flush could still be in
            # flight) — the write-back set is the authoritative copy.
            with self._dirty_lock:
                entry = self._dirty.get(k)
            if entry is not None:
                return entry[0]
            if not self._store.has(self._tile_kind, k):
                return self._zero_tile(k)
            arrays, _ = self._store.read(self._tile_kind, k)
            return arrays["tile"]

        tile, _ = self._cache.get(self._key(k), load)
        return tile

    def _publish_tile(self, k: int, tile: np.ndarray) -> None:
        # Storage-codec roundtrip FIRST (identity at the exact tier):
        # partials, digest, and the cached copy must describe the bytes
        # a reader will decode from disk, not pre-quantization values.
        tile = codec_roundtrip(tile, self._store.tile_dtype)
        totals, comps = _neumaier_rows_np(tile)
        self.totals[k], self.comps[k] = totals, comps
        # One hash serves both contracts: the full sha256 goes to the
        # part-file header (via ``digests=``, saving _pack re-hashing the
        # tile bytes at the exact tier) and its 16-char prefix is the
        # checkpoint digest — always over the roundtripped f32 bytes,
        # the same domain the resume path hashes a decoded tile in.
        full = hashlib.sha256(tile.tobytes()).hexdigest()
        self._digests[k] = full[:16]
        # Write-BACK: mark dirty (coalescing this sweep's remaining
        # coordinate updates of the same tile), keep the cache hot.  The
        # store is refreshed by flush() / the evict listener.
        with self._dirty_lock:
            self._dirty[k] = (tile, totals, comps, full)
            self._publishes_since_flush += 1
        self._cache.put(self._key(k), tile)

    def _write_entry(self, k: int, entry: tuple) -> None:
        tile, totals, comps, full = entry
        self._store.write(
            self._tile_kind, k,
            {"tile": tile, "total": totals, "comp": comps},
            meta={"chunk": k, "path": self._PATH,
                  "tile_digest": full[:16]},
            digests={"tile": full},
            codecs=self._store.lossy_codecs(("tile",)),
        )
        # Pop AFTER the publish succeeds (identity compare: a newer
        # publish of the same chunk must stay dirty).
        with self._dirty_lock:
            if self._dirty.get(k) is entry:
                del self._dirty[k]

    def _on_cache_evict(self, key: tuple, value) -> None:
        if key[:2] != (TILE_KIND, self._PATH):
            return
        k = key[2]
        with self._dirty_lock:
            entry = self._dirty.get(k)
        if entry is None:
            return
        self._write_entry(k, entry)
        self.telemetry.counter(
            "tiles.writeback_evict_flushes", path=self._PATH
        ).inc()

    def flush(self) -> int:
        """Publish every dirty tile to the store — ONE atomic write per
        touched tile per sweep, however many coordinate rows changed.
        The descent calls this at the end of each outer iteration and
        before every checkpoint (the checkpoint's digests must describe
        tiles a resume can actually read)."""
        with self._dirty_lock:
            pending = dict(self._dirty)
            publishes = self._publishes_since_flush
            self._publishes_since_flush = 0
        for k in sorted(pending):
            self._write_entry(k, pending[k])
        if pending:
            self.telemetry.counter(
                "tiles.writeback_flushes", path=self._PATH
            ).inc()
            self.telemetry.counter(
                "tiles.writeback_coalesced", path=self._PATH
            ).inc(max(0, publishes - len(pending)))
        return len(pending)

    # -- digest / checkpoint contract ----------------------------------------
    def tile_digest(self, k: int) -> str:
        d = self._digests[k]
        if d is None:
            # The implicit zero tile: sha of all-zero f32 bytes.
            nbytes = 4 * len(self.names) * self.plan.rows(k)
            d = hashlib.sha256(b"\x00" * nbytes).hexdigest()[:16]
            self._digests[k] = d
        return d

    def snapshot_rows(self) -> dict:
        """Spilled checkpoints REFERENCE the on-disk tiles (via the
        per-chunk digests in the stream payload) instead of re-saving the
        rows — the d2h+npz cost of the score plane drops out of every
        mid-epoch snapshot."""
        return {}

    def reset_store(self) -> None:
        """Fresh (non-resume) runs must not read a previous run's
        published tiles as their zero state."""
        with self._dirty_lock:
            self._dirty.clear()
            self._publishes_since_flush = 0
        self._store.reset_tiles(self.num_chunks, kind=self._tile_kind)
        for k in range(self.num_chunks):
            self._cache.invalidate(self._key(k))
        self._init_tiles()
        for k in range(self.num_chunks):
            self.totals[k][:] = 0.0
            self.comps[k][:] = 0.0

    def attach_resume(self) -> List[int]:
        """Adopt the interrupted run's on-disk tiles: loads each published
        tile's partials + digest (payload sha256-verified at read — a
        corrupted tile raises :class:`~photon_tpu.game.tile_store.
        CorruptTileError` and the resume is refused); returns the chunk
        ids with NO published tile (implicit zero — the descent's digest
        compare against the checkpoint decides whether that is the true
        state or a stale store needing a model rebuild)."""
        missing: List[int] = []
        for k in range(self.num_chunks):
            if not self._store.has(self._tile_kind, k):
                self._digests[k] = None
                self.totals[k][:] = 0.0
                self.comps[k][:] = 0.0
                missing.append(k)
                continue
            # Selective read: the partials are ~1/C the tile's size and
            # the digest lives in the header — the dominant tile payload
            # is neither decoded nor pushed through the budgeted LRU here
            # (first training access loads it lazily).
            arrays, meta = self._store.read(
                self._tile_kind, k, names=("total", "comp")
            )
            digest = meta.get("tile_digest")
            if digest is None:
                # Foreign/legacy part file without the header digest:
                # fall back to one full read.
                full, _ = self._store.read(self._tile_kind, k)
                digest = hashlib.sha256(
                    full["tile"].tobytes()
                ).hexdigest()[:16]
                self._cache.put(self._key(k), full["tile"])
            self.totals[k] = np.ascontiguousarray(
                arrays["total"], np.float32
            )
            self.comps[k] = np.ascontiguousarray(arrays["comp"], np.float32)
            self._digests[k] = digest
        self.telemetry.counter(f"{self._PATH}.tiles_attached").inc(
            self.num_chunks - len(missing)
        )
        return missing


class SpilledResidualTable(SpilledScoreTable):
    """Training-side spilled score table (the ``residuals`` telemetry
    path, like :class:`TiledResidualTable`)."""


# The exported constant and the table's own kind derivation
# (``_tile_kind`` = f"{TILE_KIND}-{_PATH}") must agree: external readers
# (bench parity check, tests) look part files up by RESIDUAL_TILE_KIND.
assert RESIDUAL_TILE_KIND == f"{TILE_KIND}-{SpilledResidualTable._PATH}"


# ---------------------------------------------------------------------------
# Chunked model scoring (shared by training re-score and validation)
# ---------------------------------------------------------------------------


def score_model_chunks(
    model,
    data,
    plan: ChunkPlan,
    streamer: ChunkStreamer,
    entity_idx: Optional[np.ndarray] = None,
    source=None,
) -> np.ndarray:
    """Score one coordinate model over ``data`` chunk by chunk: each chunk's
    features upload on the streamer's worker threads (prefetch overlapping
    the previous chunk's margin kernel + fetch), margins compute on device,
    and the per-chunk d2h fetches assemble the host ``[n]`` score vector the
    tiled tables consume.  ``entity_idx`` (random models) is the
    pre-computed per-row entity index against the MODEL's vocabulary.
    ``source`` overrides where chunk FEATURES come from (the spilled disk
    tier); default is host slices of ``data``."""
    import jax.numpy as jnp

    from photon_tpu.game.data import DenseShard
    from photon_tpu.game.model import FixedEffectModel, RandomEffectModel

    dense = isinstance(data.shard(model.shard_name), DenseShard)
    is_random = isinstance(model, RandomEffectModel)
    if is_random and entity_idx is None:
        from photon_tpu.game.data import entity_index_for

        # host-sync: the per-row entity key join is host work by nature
        # (raw keys live on host); callers cache it per vocabulary.
        entity_idx = entity_index_for(
            data.id_columns[model.entity_column], np.asarray(model.keys)
        )
    if not is_random and not isinstance(model, FixedEffectModel):
        raise TypeError(f"cannot chunk-score a {type(model).__name__}")
    src = source or ResidentChunkSource(data, plan)

    def load(k: int):
        lo, hi = plan.bounds(k)
        shard = src.chunk(k).shard(model.shard_name)
        if dense:
            feats = jnp.asarray(shard.x)
        else:
            feats = (jnp.asarray(shard.ids), jnp.asarray(shard.vals))
        if is_random:
            return feats, jnp.asarray(entity_idx[lo:hi].astype(np.int32))
        return feats, None

    out = np.empty(plan.n, np.float32)
    pos = 0
    for feats, idx in streamer.stream(load, plan.num_chunks):
        if is_random:
            margins = model.margins_device(idx, feats, dense)
        else:
            margins = model.margins_device(feats, dense)
        # host-sync: the streamed scoring pass lands each chunk's margins at
        # the host tier (that is where the tiles live — see module
        # docstring); counted as d2h transfer below.
        host = np.asarray(margins, np.float32)
        out[pos : pos + len(host)] = host
        pos += len(host)
    streamer.telemetry.counter(
        "descent.host_transfer_bytes", direction="d2h", path="stream_score"
    ).inc(out.nbytes)
    return out


def entity_index_cache() -> Dict:
    """A tiny per-descent cache for ``(column, keys-object) -> entity_idx``
    joins used by :func:`score_model_chunks` callers (same identity-first
    discipline as ``data.keys_match``)."""
    return {}


def cached_entity_index(cache: Dict, data, column: str, keys) -> np.ndarray:
    from photon_tpu.game.data import entity_index_for, keys_match

    hit = cache.get(column)
    if hit is not None and keys_match(keys, hit[0], hit[1]):
        return hit[2]
    # host-sync: entity-key vocabularies are host numpy by construction.
    arr = np.asarray(keys)
    # host-sync: foreign-vocabulary key join (host keys) — once per
    # distinct (column, vocabulary), cached after.
    idx = entity_index_for(data.id_columns[column], arr)
    cache[column] = (keys, arr, idx)
    return idx
