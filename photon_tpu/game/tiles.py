"""Tiled score tables + double-buffered chunk streaming (out-of-core GAME).

The resident engines (:mod:`photon_tpu.game.residuals`) hold ONE stacked
``[C, n]`` score table in device memory — correct until ``n`` outgrows HBM.
This module is the out-of-core counterpart (ISSUE 10 / the ROADMAP's
"billions of rows that never fit in HBM" wall): rows are partitioned into
fixed-size **chunks** (one per sharded part-file group), the score table
becomes per-chunk ``[C, rows_k]`` **tiles** resident at the host tier, and
per-chunk Neumaier-compensated partials ``(total_k, comp_k)`` reduce to
exactly the global compensated total the resident engine maintains — the
Neumaier scan runs over the COORDINATE axis element-wise per row, so the
chunk partition cannot change a single value.  This is Snap ML's hierarchy
argument (arXiv:1803.06333) applied one tier up: the dataset and score
state live at the host level, and only the working chunk (plus its
prefetched successor) ever occupies device memory.

:class:`ChunkStreamer` is the transport: chunk ``k+1``'s host slice +
``device_put`` runs on io-pool worker threads while chunk ``k`` computes —
the double-buffered h2d prefetch.  Overlap is measured, not assumed:
``stream.stall_s`` accumulates the wall time the consumer spent blocked on
a chunk that was not ready, ``stream.prefetch_overlap_s`` the load time
that was hidden behind compute, and the ``residuals.device_bytes`` gauge
reports the peak in-flight device residency (the chunk budget bound the
descent asserts against).

The per-chunk map + cross-chunk reduce shape — every training pass is
``reduce(map(chunk))`` with the reduction inside jit per chunk — is the
DrJAX MapReduce idiom (arXiv:2403.07128) expressed at the host loop level,
which is where it must live once the mapped axis no longer fits on device.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

from photon_tpu.telemetry import NULL_SESSION

# Chunks the streamer keeps in flight beyond the one being consumed: chunk
# k+1 uploads while chunk k computes (double buffering).  The device-memory
# bound every budget computation uses is (PREFETCH_DEPTH + 1) chunks.
PREFETCH_DEPTH = 2


# ---------------------------------------------------------------------------
# Chunk plan + memory budget
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ChunkPlan:
    """Fixed-size row partition: chunk ``k`` covers rows
    ``[k * chunk_rows, min(n, (k+1) * chunk_rows))``.  The last chunk may be
    partial; a ``chunk_rows >= n`` plan degenerates to one chunk (the
    resident-equivalent case the tests pin)."""

    n: int
    chunk_rows: int

    def __post_init__(self):
        if self.n < 0:
            raise ValueError(f"negative row count {self.n}")
        if self.chunk_rows < 1:
            raise ValueError(f"chunk_rows must be >= 1, got {self.chunk_rows}")

    @property
    def num_chunks(self) -> int:
        return max(1, -(-self.n // self.chunk_rows))

    def bounds(self, k: int) -> tuple[int, int]:
        if not 0 <= k < self.num_chunks:
            raise IndexError(f"chunk {k} out of range [0, {self.num_chunks})")
        lo = k * self.chunk_rows
        return lo, min(self.n, lo + self.chunk_rows)

    def rows(self, k: int) -> int:
        lo, hi = self.bounds(k)
        return hi - lo


def per_row_bytes(data) -> int:
    """Bytes one dataset row occupies across every feature shard plus the
    per-row scalars — the unit the chunk budget divides by."""
    from photon_tpu.game.data import DenseShard

    total = 12  # label + offset + weight (f32 each)
    for shard in data.shards.values():
        if isinstance(shard, DenseShard):
            total += shard.x.dtype.itemsize * shard.x.shape[1]
        else:
            total += (
                shard.ids.dtype.itemsize + shard.vals.dtype.itemsize
            ) * shard.ids.shape[1]
    return total


def resident_bytes_estimate(data, n_coordinates: int = 2) -> int:
    """Device bytes a RESIDENT GAME fit would hold for this dataset: the
    training feature blocks, the scoring-cache second copy the residual
    engine keeps (``coordinate._scoring_feats``), and the two stacked
    ``[C, n]`` float32 score tables (residual + validation) at
    ``n_coordinates`` rows each.  A lower bound — random-effect bin
    padding (≤2× per block) and optimizer workspace ride on top — which
    is the right direction for the auto-streaming gate
    (``--max-resident-mb``): an over-budget ESTIMATE always streams, and
    a dataset whose floor already exceeds the budget can never silently
    train resident."""
    n = data.num_examples
    return 2 * per_row_bytes(data) * n + 2 * max(1, n_coordinates) * n * 4


def chunk_rows_for_budget(data, max_resident_mb: float) -> int:
    """Chunk size such that the streamer's in-flight window —
    ``PREFETCH_DEPTH + 1`` chunks — fits the device budget."""
    if max_resident_mb <= 0:
        raise ValueError(f"max_resident_mb must be > 0, got {max_resident_mb}")
    budget = int(max_resident_mb * (1 << 20))
    rows = budget // ((PREFETCH_DEPTH + 1) * max(1, per_row_bytes(data)))
    return max(1, min(int(rows), max(1, data.num_examples)))


def slice_rows(data, lo: int, hi: int):
    """Contiguous row window ``[lo, hi)`` of a GameDataset as numpy VIEWS
    (no copy — the chunk loader's host side is a slice, not a gather)."""
    from photon_tpu.game.data import DenseShard, GameDataset, SparseShard

    def cut(shard):
        if isinstance(shard, DenseShard):
            return DenseShard(shard.x[lo:hi])
        return SparseShard(shard.ids[lo:hi], shard.vals[lo:hi], shard.dim_)

    return GameDataset(
        label=data.label[lo:hi],
        offset=data.offset[lo:hi],
        weight=data.weight[lo:hi],
        shards={name: cut(s) for name, s in data.shards.items()},
        id_columns={name: c[lo:hi] for name, c in data.id_columns.items()},
    )


# ---------------------------------------------------------------------------
# Double-buffered chunk streamer
# ---------------------------------------------------------------------------


def _device_nbytes(payload) -> int:
    """Device bytes of one loaded chunk (any pytree of arrays)."""
    import jax

    return sum(
        int(getattr(leaf, "nbytes", 0))
        for leaf in jax.tree.leaves(payload)
    )


class ChunkStreamer:
    """Ordered chunk iteration with h2d prefetch on io-pool worker threads.

    ``stream(load_chunk, num_chunks)`` yields ``load_chunk(k)`` results in
    order; ``load_chunk`` runs on worker threads (host slice + device_put,
    so the upload overlaps the consumer's compute).  At most
    ``prefetch`` chunks are in flight beyond the one being consumed — the
    double-buffer window that bounds device residency at
    ``(prefetch + 1) × chunk_bytes``.

    Telemetry (shared across every pass this streamer drives):
    ``stream.stall_s`` — consumer wall time blocked on an unready chunk;
    ``stream.prefetch_overlap_s`` — load seconds hidden behind compute;
    ``stream.chunks`` — chunks delivered; ``peak_in_flight_bytes`` — the
    high-water in-flight device residency (exported by the descent as the
    ``residuals.device_bytes`` gauge, the chunk-budget assertion).
    """

    def __init__(self, telemetry=None, prefetch: int = PREFETCH_DEPTH):
        self.telemetry = telemetry or NULL_SESSION
        self.prefetch = max(1, int(prefetch))
        self.peak_in_flight_bytes = 0
        self._lock = threading.Lock()
        # One persistent worker pool per streamer: a streamed L-BFGS runs
        # one stream() pass PER OBJECTIVE EVALUATION, and spawning threads
        # per pass would churn hundreds of threads across a fit.
        self._pool: Optional[ThreadPoolExecutor] = None
        self._pool_workers = 0

    def _executor(self, workers: int) -> ThreadPoolExecutor:
        with self._lock:
            if self._pool is None or self._pool_workers < workers:
                if self._pool is not None:
                    self._pool.shutdown(wait=False)
                self._pool = ThreadPoolExecutor(
                    max_workers=workers,
                    thread_name_prefix="photon-chunk-stream",
                )
                self._pool_workers = workers
            return self._pool

    def _note_bytes(self, in_flight_chunks: int, chunk_bytes: int) -> None:
        bound = in_flight_chunks * chunk_bytes
        with self._lock:
            if bound > self.peak_in_flight_bytes:
                self.peak_in_flight_bytes = bound

    def stream(
        self, load_chunk: Callable[[int], object], num_chunks: int
    ) -> Iterator[object]:
        from photon_tpu.utils.io_pool import io_threads

        tel = self.telemetry
        stall_c = tel.counter("stream.stall_s")
        overlap_c = tel.counter("stream.prefetch_overlap_s")
        chunks_c = tel.counter("stream.chunks")

        def timed_load(k: int):
            t0 = time.monotonic()
            payload = load_chunk(k)
            return payload, time.monotonic() - t0, _device_nbytes(payload)

        # Single chunk: plain eager load — there is nothing to overlap,
        # and the whole load time is an honest stall.
        window = self.prefetch
        if num_chunks <= 1:
            for k in range(num_chunks):
                payload, load_s, nbytes = timed_load(k)
                stall_c.inc(load_s)
                chunks_c.inc()
                self._note_bytes(1, nbytes)
                yield payload
            return

        ex = self._executor(min(window, max(2, io_threads())))
        futs: deque = deque()
        try:
            idx = 0
            while futs or idx < num_chunks:
                while idx < num_chunks and len(futs) < window:
                    futs.append(ex.submit(timed_load, idx))
                    idx += 1
                t_wait = time.monotonic()
                payload, load_s, nbytes = futs.popleft().result()
                stall = time.monotonic() - t_wait
                stall_c.inc(stall)
                overlap_c.inc(max(0.0, load_s - stall))
                chunks_c.inc()
                # REFILL before yielding: the successor chunks must be in
                # flight WHILE the consumer computes on this one — with
                # prefetch=1 this is what makes single-buffering ahead
                # real rather than a silent no-overlap mode.
                while idx < num_chunks and len(futs) < window:
                    futs.append(ex.submit(timed_load, idx))
                    idx += 1
                # Compute-time residency: the chunk being consumed plus
                # everything in flight behind it (sized by this chunk —
                # chunks share one layout).  Steady state is window + 1
                # chunks, the (PREFETCH_DEPTH + 1) factor the budget
                # divides by.
                self._note_bytes(len(futs) + 1, nbytes)
                yield payload
        finally:
            # An abandoned pass (consumer raised / generator closed) must
            # not leave queued loads running into the next pass: cancel
            # what has not started; in-progress loads finish harmlessly
            # (their results are dropped with the futures).
            for f in futs:
                f.cancel()


# ---------------------------------------------------------------------------
# Tiled score tables
# ---------------------------------------------------------------------------


def _neumaier_rows_np(tile: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Neumaier-compensated column-wise sum of one ``[C, rows]`` tile in
    float32 numpy — the SAME arithmetic, in the same order, as the resident
    engine's jitted ``_neumaier_rows`` scan (elementwise IEEE f32 ops), so
    per-chunk partials concatenate to the resident engine's global
    total/comp pair."""
    total = np.zeros(tile.shape[1], np.float32)
    comp = np.zeros(tile.shape[1], np.float32)
    for row in tile:
        t = total + row
        lost = np.where(
            np.abs(total) >= np.abs(row),
            (total - t) + row,
            (row - t) + total,
        )
        comp = comp + lost
        total = t
    return total, comp


class TiledScoreTable:
    """Host-resident per-chunk score tiles with maintained compensated
    partials — the out-of-core form of ``_DeviceScoreTable``.

    ``tiles[k]`` is the ``[C, rows_k]`` float32 score tile of chunk ``k``
    (row ``c`` = coordinate ``c``'s scores over that chunk's rows);
    ``totals[k]``/``comps[k]`` hold the chunk's Neumaier partials,
    recomputed from the tile on every row update (never incrementally
    drifted, same rule as the resident engine).  Training offsets and
    composite margins are produced PER CHUNK — the streamed training and
    scoring passes consume them chunk by chunk and never materialize a
    device ``[C, n]`` table.

    Non-finite score vectors are rejected at update (host check — the
    tiles ARE host data), keeping the previous tile; the pending guard
    flags drain through the same ``drain_guard_flags`` /
    ``poll_quarantined`` contract as the engines.
    """

    _PATH = "residuals"

    def __init__(
        self,
        base_offset: np.ndarray,
        names: Sequence[str],
        plan: ChunkPlan,
        telemetry=None,
    ):
        if not names:
            raise ValueError(
                f"{type(self).__name__} needs at least one coordinate"
            )
        self.names = list(names)
        self._row = {name: i for i, name in enumerate(self.names)}
        if len(self._row) != len(self.names):
            raise ValueError(f"duplicate coordinate names in {self.names}")
        if len(base_offset) != plan.n:
            raise ValueError(
                f"base offset has {len(base_offset)} rows, plan covers {plan.n}"
            )
        self.plan = plan
        self.telemetry = telemetry or NULL_SESSION
        self.n = plan.n
        # host-sync: the tiled tables are host-resident BY DESIGN — the
        # out-of-core tier keeps score state at host level, streaming only
        # the working chunk to device.
        self.base = np.asarray(base_offset, np.float32)
        c = len(self.names)
        self.tiles: List[np.ndarray] = [
            np.zeros((c, plan.rows(k)), np.float32)
            for k in range(plan.num_chunks)
        ]
        self.totals: List[np.ndarray] = [
            np.zeros(plan.rows(k), np.float32) for k in range(plan.num_chunks)
        ]
        self.comps: List[np.ndarray] = [
            np.zeros(plan.rows(k), np.float32) for k in range(plan.num_chunks)
        ]
        self._pending_guard: list = []
        self.telemetry.gauge(f"{self._PATH}.tile_chunks").set(plan.num_chunks)

    @property
    def num_chunks(self) -> int:
        return self.plan.num_chunks

    def row(self, name: str) -> int:
        return self._row[name]

    def update(self, name: str, new_scores) -> None:
        """Replace ``name``'s score row across every tile and refresh the
        per-chunk compensated partials.  ``new_scores`` is a host float32
        vector of length ``n`` (the streamed scoring passes assemble it
        chunk by chunk)."""
        # host-sync: streamed score vectors arrive as host numpy by
        # construction (assembled from per-chunk d2h fetches).
        host = np.asarray(new_scores, np.float32)
        if host.shape != (self.n,):
            raise ValueError(
                f"score vector for {name!r} has shape {host.shape}, "
                f"want ({self.n},)"
            )
        ok = bool(np.isfinite(host).all())
        self._pending_guard.append((name, ok))
        if ok:
            c = self._row[name]
            for k in range(self.num_chunks):
                lo, hi = self.plan.bounds(k)
                self.tiles[k][c] = host[lo:hi]
                self.totals[k], self.comps[k] = _neumaier_rows_np(
                    self.tiles[k]
                )
        self.telemetry.counter(f"{self._PATH}.updates", coordinate=name).inc()

    # -- per-chunk reads ------------------------------------------------------
    def offsets_chunk(self, name: str, k: int) -> np.ndarray:
        """Chunk ``k``'s training offsets for coordinate ``name``:
        ``base_k + (total_k - tile_k[c]) + comp_k`` — the same fused formula
        (and f32 order) as the resident ``_offsets_kernel``."""
        lo, hi = self.plan.bounds(k)
        c = self._row[name]
        return self.base[lo:hi] + (
            (self.totals[k] - self.tiles[k][c]) + self.comps[k]
        )

    def offsets_full(self, name: str) -> np.ndarray:
        """All chunks' offsets concatenated (``[n]`` f32) — the host gather
        source for random-effect bucket offsets, and exactly the
        concatenation of :meth:`offsets_chunk` (chunking never changes a
        value; see module docstring)."""
        return np.concatenate(
            [self.offsets_chunk(name, k) for k in range(self.num_chunks)]
        )

    def composite_chunk(self, k: int) -> np.ndarray:
        """Chunk ``k``'s composite margin ``base_k + (total_k + comp_k)``
        (the validation table's scoring output)."""
        lo, hi = self.plan.bounds(k)
        return self.base[lo:hi] + (self.totals[k] + self.comps[k])

    def composite_full(self) -> np.ndarray:
        return np.concatenate(
            [self.composite_chunk(k) for k in range(self.num_chunks)]
        )

    def scores_for(self, name: str) -> np.ndarray:
        """Coordinate ``name``'s current score vector (host, ``[n]``)."""
        c = self._row[name]
        return np.concatenate([tile[c] for tile in self.tiles])

    # -- guard / snapshot contract (mirrors the engines) ----------------------
    def drain_guard_flags(self) -> list:
        pending, self._pending_guard = self._pending_guard, []
        return pending

    def record_rejected(self, bad: Sequence[str]) -> None:
        for name in bad:
            self.telemetry.counter(
                f"{self._PATH}.nonfinite_rows", coordinate=name
            ).inc()

    def poll_quarantined(self) -> list:
        bad = [name for name, ok in self.drain_guard_flags() if not ok]
        self.record_rejected(bad)
        return bad

    def snapshot_rows(self) -> dict:
        """All score rows as host float32 ``{name: [n]}`` — the checkpoint
        snapshot (already host: staging is a copy)."""
        return {name: self.scores_for(name).copy() for name in self.names}

    def load_rows(self, rows: dict) -> None:
        """Rebuild tiles from checkpointed rows (resume path).  Stored
        directly — checkpointed rows were guarded at write time, and
        routing them through update() would enqueue phantom guard flags."""
        for name, row in rows.items():
            if name not in self._row:
                continue
            # host-sync: checkpointed rows are host arrays by construction.
            host = np.asarray(row, np.float32)
            if host.shape != (self.n,):
                raise ValueError(
                    f"checkpointed row for {name!r} has shape {host.shape}, "
                    f"want ({self.n},)"
                )
            c = self._row[name]
            for k in range(self.num_chunks):
                lo, hi = self.plan.bounds(k)
                self.tiles[k][c] = host[lo:hi]
        for k in range(self.num_chunks):
            self.totals[k], self.comps[k] = _neumaier_rows_np(self.tiles[k])

    def tile_digests(self) -> List[str]:
        """Per-chunk content digests of the score tiles (sha256/16): stamped
        into mid-epoch checkpoints so a resume can verify the rebuilt tiles
        match the interrupted run's state chunk for chunk."""
        out = []
        for k in range(self.num_chunks):
            h = hashlib.sha256()
            h.update(self.tiles[k].tobytes())
            out.append(h.hexdigest()[:16])
        return out


class TiledResidualTable(TiledScoreTable):
    """Training-side tiled score table (the residual engine's role; the
    base class already carries the ``residuals`` telemetry path)."""


class TiledValidationTable(TiledScoreTable):
    """Validation-side tiled score table: incremental per-coordinate
    re-scoring with the composite margin from the same per-chunk partials
    (``validation.score_reuse`` counting happens in the descent loop)."""

    _PATH = "validation"


# ---------------------------------------------------------------------------
# Chunked model scoring (shared by training re-score and validation)
# ---------------------------------------------------------------------------


def score_model_chunks(
    model,
    data,
    plan: ChunkPlan,
    streamer: ChunkStreamer,
    entity_idx: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Score one coordinate model over ``data`` chunk by chunk: each chunk's
    features upload on the streamer's worker threads (prefetch overlapping
    the previous chunk's margin kernel + fetch), margins compute on device,
    and the per-chunk d2h fetches assemble the host ``[n]`` score vector the
    tiled tables consume.  ``entity_idx`` (random models) is the
    pre-computed per-row entity index against the MODEL's vocabulary."""
    import jax.numpy as jnp

    from photon_tpu.game.data import DenseShard
    from photon_tpu.game.model import FixedEffectModel, RandomEffectModel

    shard = data.shard(model.shard_name)
    dense = isinstance(shard, DenseShard)
    is_random = isinstance(model, RandomEffectModel)
    if is_random and entity_idx is None:
        from photon_tpu.game.data import entity_index_for

        # host-sync: the per-row entity key join is host work by nature
        # (raw keys live on host); callers cache it per vocabulary.
        entity_idx = entity_index_for(
            data.id_columns[model.entity_column], np.asarray(model.keys)
        )
    if not is_random and not isinstance(model, FixedEffectModel):
        raise TypeError(f"cannot chunk-score a {type(model).__name__}")

    def load(k: int):
        lo, hi = plan.bounds(k)
        if dense:
            feats = jnp.asarray(shard.x[lo:hi])
        else:
            feats = (jnp.asarray(shard.ids[lo:hi]), jnp.asarray(shard.vals[lo:hi]))
        if is_random:
            return feats, jnp.asarray(entity_idx[lo:hi].astype(np.int32))
        return feats, None

    out = np.empty(plan.n, np.float32)
    pos = 0
    for feats, idx in streamer.stream(load, plan.num_chunks):
        if is_random:
            margins = model.margins_device(idx, feats, dense)
        else:
            margins = model.margins_device(feats, dense)
        # host-sync: the streamed scoring pass lands each chunk's margins at
        # the host tier (that is where the tiles live — see module
        # docstring); counted as d2h transfer below.
        host = np.asarray(margins, np.float32)
        out[pos : pos + len(host)] = host
        pos += len(host)
    streamer.telemetry.counter(
        "descent.host_transfer_bytes", direction="d2h", path="stream_score"
    ).inc(out.nbytes)
    return out


def entity_index_cache() -> Dict:
    """A tiny per-descent cache for ``(column, keys-object) -> entity_idx``
    joins used by :func:`score_model_chunks` callers (same identity-first
    discipline as ``data.keys_match``)."""
    return {}


def cached_entity_index(cache: Dict, data, column: str, keys) -> np.ndarray:
    from photon_tpu.game.data import entity_index_for, keys_match

    hit = cache.get(column)
    if hit is not None and keys_match(keys, hit[0], hit[1]):
        return hit[2]
    # host-sync: entity-key vocabularies are host numpy by construction.
    arr = np.asarray(keys)
    # host-sync: foreign-vocabulary key join (host keys) — once per
    # distinct (column, vocabulary), cached after.
    idx = entity_index_for(data.id_columns[column], arr)
    cache[column] = (keys, arr, idx)
    return idx
