"""Low-precision storage codecs + per-codec parity bounds (ISSUE 17).

Every hot path the benches measure is bytes-bound, not FLOP-bound: the
serving gathers move HBM bytes, the spilled trainer moves disk bytes.
This module is the ONE place the repo's precision tiers are defined —
the storage dtypes (``f32 | bf16 | int8``), the host-side row codecs the
disk tier encodes with, and the measured per-codec parity tolerances the
serve-time canary gate, the supervisor's known-answer probe, and the
benches all assert against.

The recipe is 2112.09017's: STORAGE drops to bf16/int8, every
multiply-accumulate stays float32.  int8 is symmetric per-row absmax
quantization — alongside each int8 row rides one f32 scale
(``absmax / 127``); a decode is ``q * scale`` in f32.  An all-zero row
has ``absmax == 0`` so its stored scale is exactly 0 and the decode is
exactly 0 — the serving zero-row / cold-entity fallback survives
quantization bit-for-bit.

The scale arithmetic runs in float64 and the canonical encoder iterates
to a quantization fixed point, so re-encoding a decoded tile is
byte-identical — what makes the tile store's read-modify-write publish
cycle drift-free and kill->resume parity exact per codec.

Residency contract (``tools/check_host_sync.py`` guards this module):
the codecs here are pure host numpy by design — they encode/decode the
DISK tier's bytes and must never touch device data (the serving tier's
on-device decode lives in ``photon_tpu/game/model.py``).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

# The storage dtypes either byte mover accepts: serving gather tables
# (``GameScorer(table_dtype=...)``) and tile-store arrays
# (``TileStore(tile_dtype=...)``).
TABLE_DTYPES = ("f32", "bf16", "int8")
TILE_DTYPES = TABLE_DTYPES

# Serve-time parity bounds vs the f32 host oracle (worst |delta| per
# request), per table dtype.  f32 keeps the historical exact-path gate;
# the lossy bounds are MEASURED: on the standard-normal serving fixtures
# (dim 8-32 tables, unit-scale features) bf16 lands ~1e-2 worst-case
# (8-bit mantissa, ~0.4% per entry, f32 accumulation) and int8 ~3e-2
# (<=0.5*scale per entry); the bounds below carry ~4x headroom and the
# serving bench asserts the measured number stays under them.
PARITY_TOL = {"f32": 1e-3, "bf16": 5e-2, "int8": 2e-1}

# Spilled-training metric bounds vs the f32 oracle fit (per validation
# metric, absolute): lossy FEATURE/score-tile storage perturbs the fit
# itself, not just a readout, so the bounds are wider than serving's.
# f32 keeps the bit-exact tier's 1e-6; the lossy numbers are measured by
# ``bench.py --mode ooc`` against the f32 host-resident oracle.
TILE_METRIC_TOL = {"f32": 1e-6, "bf16": 5e-2, "int8": 2e-1}


def check_dtype(dtype, kinds: Tuple[str, ...] = TABLE_DTYPES,
                what: str = "table dtype") -> str:
    """Validate + normalize a storage-dtype token (None -> ``"f32"``)."""
    if dtype is None:
        return "f32"
    dtype = str(dtype)
    if dtype not in kinds:
        raise ValueError(
            f"unknown {what} {dtype!r}; expected one of {kinds}"
        )
    return dtype


def parity_tol_for(dtype) -> float:
    """The serve-time canary/probe parity bound for one table dtype."""
    return PARITY_TOL[check_dtype(dtype)]


def tile_metric_tol_for(dtype) -> float:
    """The spilled-fit metric parity bound for one tile dtype."""
    return TILE_METRIC_TOL[check_dtype(dtype, TILE_DTYPES, "tile dtype")]


def bf16_dtype():
    """The numpy-visible bfloat16 dtype (ml_dtypes ships with jax)."""
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


# -- host-side row codecs (the disk tier) -------------------------------------


def quantize_int8_rows(arr) -> Tuple[np.ndarray, np.ndarray]:
    """Symmetric per-row absmax int8: ``(q int8 of arr.shape, scale f32 of
    arr.shape[:-1])``.  The last axis is the "row"; the scale arithmetic
    runs in float64 so ``absmax/127`` rounds to f32 exactly once (the
    idempotence lever — see :func:`quantize_int8_canonical`).  Rows whose
    absmax is 0 store scale 0 and decode exactly 0."""
    # host-sync: disk-tier codec — pure host numpy by design; the input is
    # caller-owned host data (tile arrays), never a device buffer.
    x = np.asarray(arr, np.float32)
    x64 = x.astype(np.float64)
    absmax = np.max(np.abs(x64), axis=-1)
    scale = (absmax / 127.0).astype(np.float32)
    div = np.where(absmax > 0.0, absmax / 127.0, 1.0)
    q = np.clip(
        np.rint(x64 / div[..., None]), -127.0, 127.0
    ).astype(np.int8)
    return q, scale


def dequantize_int8_rows(q: np.ndarray, scale: np.ndarray) -> np.ndarray:
    """f32 decode of :func:`quantize_int8_rows` output: ``q * scale``."""
    # host-sync: disk-tier codec — pure host numpy by design (see above).
    return np.asarray(q, np.float32) * np.asarray(
        scale, np.float32
    )[..., None]


def quantize_int8_canonical(
    arr, max_rounds: int = 4
) -> Tuple[np.ndarray, np.ndarray, bool]:
    """``(q, scale, converged)`` at a quantization FIXED POINT: re-encoding
    the decoded array reproduces the same bytes.  The grid indices ``q``
    are stable under sub-ulp scale wobble by construction (|q| <= 127, so
    a <=1-ulp scale perturbation moves ``round(x/scale)`` by ~1e-5 — far
    from any .5 boundary); only the stored scale can wobble by one ulp
    through the decode->absmax->scale cycle, and iterating lands it.  A
    pathological non-converging array (never observed; a tie-to-even
    oscillation would need ``127*scale`` exactly on a rounding boundary)
    returns ``converged=False`` and the tile codec stores it lossless."""
    q, scale = quantize_int8_rows(arr)
    for _ in range(max_rounds):
        q2, scale2 = quantize_int8_rows(dequantize_int8_rows(q, scale))
        if (q2.tobytes() == q.tobytes()
                and scale2.tobytes() == scale.tobytes()):
            return q2, scale2, True
        q, scale = q2, scale2
    return q, scale, False


def encode_bf16(arr) -> np.ndarray:
    """bf16 storage form of a float array (truncation is idempotent: a
    bf16->f32->bf16 roundtrip is byte-identical by construction)."""
    # host-sync: disk-tier codec — pure host numpy by design (see above).
    return np.asarray(arr, np.float32).astype(bf16_dtype())


def decode_bf16(raw: np.ndarray) -> np.ndarray:
    """f32 decode of :func:`encode_bf16` output (exact widening)."""
    # host-sync: disk-tier codec — pure host numpy by design (see above).
    return np.asarray(raw).astype(np.float32)
