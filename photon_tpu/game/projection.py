"""Feature projection for random-effect solves.

Rebuild of the reference's projector stack (photon-api ``data/projectors``:
``IndexMapProjection``, ``RandomProjection``, ``ProjectionMatrix`` —
SURVEY.md §2.2 'Feature projection'): each entity sees only a sliver of the
shard's feature space, so its local solve can run in a much smaller
dimension.  The reference projects each entity's LocalDataset before the
local optimizer and maps coefficients back.

TPU-native shape: projection happens **per bucket** at dataset-build time so
every vmapped solve keeps a static shape:

- **index_map**: per-entity active-feature sets, padded to the bucket's
  power-of-two max active count ``p``.  Features gather into local slots;
  trained local coefficients scatter-add back into the global table.  Both
  maps are exact — margins are unchanged.
- **random**: one global sparse-sign matrix ``R [dim, p]``.  Local margins
  ``(Rᵀx)ᵀ w_local`` equal global margins of the lifted model ``R w_local``,
  so lifting is exact for scoring as well (the reference instead stores the
  projected model + matrix; lifting keeps the model format uniform).

Both make the per-entity solve dimension ``p`` instead of ``dim`` — the
regularizer then acts in projected space, exactly as in the reference.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Union

import numpy as np

from photon_tpu.game.data import DenseShard, EntityBucket, Shard, SparseShard


from photon_tpu.utils import pow2_at_least as _pow2_at_least


@dataclasses.dataclass(frozen=True)
class IndexMapBucketProjection:
    """Per-entity feature subsetting for one bucket.

    ``proj_ids[e, j]`` is the global feature id behind entity ``e``'s local
    slot ``j`` (sorted; padded slots carry id 0 with ``mask == 0``).
    """

    proj_ids: np.ndarray  # [E, p] int32
    mask: np.ndarray  # [E, p] float32

    @property
    def projected_dim(self) -> int:
        return self.proj_ids.shape[1]

    def project(self, features: Shard) -> Shard:
        if isinstance(features, DenseShard):
            # x_local[e, r, j] = x[e, r, proj_ids[e, j]] (0 on padded slots).
            gathered = np.take_along_axis(
                features.x, self.proj_ids[:, None, :], axis=2
            )
            return DenseShard(gathered * self.mask[:, None, :])
        # Sparse: remap global ids to the entity's local slots.  proj_ids
        # rows are sorted and contain every id present in the entity's rows,
        # so searchsorted is exact.
        ids, vals = features.ids, features.vals
        local = np.empty_like(ids)
        for e in range(ids.shape[0]):
            local[e] = np.searchsorted(self.proj_ids[e], ids[e])
        return SparseShard(
            local.astype(np.int32), vals, self.projected_dim
        )

    def restrict_table(self, table: np.ndarray) -> np.ndarray:
        """Global per-entity coefficients [E, dim] -> local [E, p]
        (warm-start restriction; exact)."""
        return (
            np.take_along_axis(table, self.proj_ids, axis=1) * self.mask
        ).astype(np.float32)

    def scatter_args(self):
        """(proj_ids, mask) for the device-side scatter-add of local
        coefficients back into the global table."""
        return self.proj_ids, self.mask


def build_index_map_projection(bucket: EntityBucket) -> Optional[IndexMapBucketProjection]:
    """Active-feature projection for one bucket; None when it cannot shrink
    the solve (dense shards or no savings)."""
    features = bucket.features
    if isinstance(features, DenseShard):
        dim = features.x.shape[2]
        active = [np.nonzero(np.any(features.x[e] != 0, axis=0))[0]
                  for e in range(features.x.shape[0])]
    else:
        dim = features.dim
        active = [np.unique(features.ids[e]) for e in range(features.ids.shape[0])]
    max_active = max((len(a) for a in active), default=0)
    p = _pow2_at_least(max(max_active, 1))
    if p >= dim:
        return None  # projection would not shrink the solve
    n_e = len(active)
    proj_ids = np.zeros((n_e, p), np.int32)
    mask = np.zeros((n_e, p), np.float32)
    for e, ids in enumerate(active):
        s = np.sort(ids)
        proj_ids[e, : len(s)] = s
        if len(s):
            # Pad with the largest active id so the row STAYS SORTED —
            # the sparse remap searchsorts each row, and searchsorted
            # returns the first (real) slot for the duplicated id; padded
            # slots are masked out of restriction and scatter.
            proj_ids[e, len(s):] = s[-1]
        mask[e, : len(s)] = 1.0
    return IndexMapBucketProjection(proj_ids=proj_ids, mask=mask)


@dataclasses.dataclass(frozen=True)
class RandomProjectionMatrix:
    """Global sparse-sign projection ``R [dim, p]`` (Achlioptas: entries
    ``±sqrt(3/p)`` with density 1/3, so ``E[R_ij²] = 1/p`` and projected
    feature norms are preserved in expectation; the reference's
    RandomProjection).

    Methods are array-library-agnostic: they work on numpy (host build time)
    and jax arrays (device lift at train time) alike.
    """

    matrix: np.ndarray  # [dim, p] float32

    @property
    def projected_dim(self) -> int:
        return self.matrix.shape[1]

    def project(self, features: Shard) -> DenseShard:
        if isinstance(features, DenseShard):
            return DenseShard(features.x @ self.matrix)
        # Sparse rows: sum_t vals[t] * R[ids[t]] -> dense [E, R, p].
        gathered = self.matrix[features.ids]  # [E, R, k, p]
        return DenseShard(
            np.einsum("erk,erkp->erp", features.vals, gathered).astype(np.float32)
        )

    def restrict_table(self, table: np.ndarray) -> np.ndarray:
        """Warm-start restriction: column-normalized least-squares pullback
        ``w_local ≈ (diag(RᵀR))⁻¹ Rᵀ w_global``, so that
        ``restrict(lift(w)) ≈ w`` — a raw ``Rᵀ w`` would scale warm starts
        by ~dim/p and blow up every descent iteration after the first."""
        col_norms = (self.matrix**2).sum(axis=0)  # diag(RᵀR), [p]
        return ((table @ self.matrix) / np.maximum(col_norms, 1e-12)).astype(
            np.float32
        )

    def lift(self, w_local):
        """Exact margin-preserving lift: w_global = R w_local."""
        return w_local @ self.matrix.T

    def lift_variance(self, var_local):
        """Diagonal-covariance lift: Var[R w]_i = Σ_j R_ij² Var[w_j]."""
        return var_local @ (self.matrix.T**2)


def build_random_projection(
    dim: int, projected_dim: int, seed: int = 0
) -> RandomProjectionMatrix:
    if not 0 < projected_dim < dim:
        raise ValueError(
            f"projected_dim must be in (0, {dim}), got {projected_dim}"
        )
    rng = np.random.default_rng(seed)
    u = rng.random((dim, projected_dim))
    scale = np.sqrt(3.0 / projected_dim)
    # +scale w.p. 1/6, -scale w.p. 1/6, 0 w.p. 2/3  =>  E[R_ij²] = 1/p.
    matrix = np.where(
        u < 1.0 / 6.0, scale, np.where(u < 1.0 / 3.0, -scale, 0.0)
    ).astype(np.float32)
    return RandomProjectionMatrix(matrix=matrix)


BucketProjection = Union[IndexMapBucketProjection, RandomProjectionMatrix]
