"""Size-binned batched device linear algebra for random-effect solves.

The seed's ``RandomEffectCoordinate.train`` drove a Python loop over
row-count buckets — O(buckets) host dispatches and one compiled program per
bucket shape — which is what capped entity counts (ROADMAP "Random effects
at millions of entities").  This module is the routing layer that replaces
it:

- **Bin layout** — :func:`bin_layout` consolidates the power-of-two buckets
  into a few padded size bins (``game.data.plan_size_bins`` /
  ``merge_buckets``), so a million-entity coordinate dispatches a handful
  of jitted programs instead of a dozen-plus.  ``PHOTON_SOLVE_BINNING=off``
  restores the one-bucket-per-capacity loop (the escape hatch and the
  bench's bucket-loop baseline).
- **Solver routing** — :func:`solver_route` picks, per bin, between the
  batched-Cholesky damped Newton (``core.optimizers.newton`` vmapped over
  the entity axis: ``[B, dim, dim]`` Hessians, one batched ``cho_factor``/
  ``cho_solve`` per iteration — the 2112.09017 padded-factorization shape)
  for the common small-``solve_dim`` smooth case, and the existing vmapped
  L-BFGS/OWL-QN/TRON program for everything else (L1 bins, large dims,
  row-split placement) — so every existing ``problem`` config still solves.
- **Solver cache** — :func:`cached_newton_solver` mirrors
  ``core.problem.cached_solver``: one traced program per static
  (optimizer-config, variance) pair, module-cached, the objective riding
  along as a pytree argument so reg sweeps share it.

Entity-axis sharding rides the existing ``RandomEffectDeviceData``
placement: bins are padded to the mesh multiple and sharded over the mesh
axis the score tables already use (``parallel.mesh``), composing with
``solve_entities_row_split`` under multi-controller row-split configs.

Above the dense-Newton dim cap, smooth bins now route to the MATRIX-FREE
batched Newton-CG (``core.optimizers.newton_cg`` vmapped over the entity
axis: Hessian-vector products through ``objective.hvp_operator`` — two
sparse matvecs per inner iteration, never a ``[B, d, d]`` block — with a
Jacobi preconditioner from the cheap Hessian diagonal and Eisenstat-Walker
adaptive inner tolerances), lifting the per-entity solve-dimension ceiling
from ``PHOTON_NEWTON_MAX_DIM`` (64) to ``PHOTON_NEWTON_CG_MAX_DIM``
(default 1024) — the ROADMAP "lift the solver ceilings" edge (ISSUE 14).

Knobs (env): ``PHOTON_SOLVE_BINNING`` (``on``/``off``),
``PHOTON_SOLVE_MAX_BINS`` (default 4), ``PHOTON_SOLVE_BIN_WASTE`` (default
2.0 — padded row cells allowed per live row cell before a capacity starts
its own bin), ``PHOTON_SOLVE_NEWTON`` (``on``/``off``),
``PHOTON_NEWTON_MAX_DIM`` (default 64 — above it the dense ``[B, d, d]``
Hessian stops paying and bins route to Newton-CG),
``PHOTON_SOLVE_NEWTON_CG`` (``on``/``off``), ``PHOTON_NEWTON_CG_MAX_DIM``
(default 1024 — above it bins route to the vmapped iterative solvers).
"""

from __future__ import annotations

import functools
import os

import jax

from photon_tpu.core.optimizers import OptimizerConfig
from photon_tpu.core.optimizers.newton import newton
from photon_tpu.core.optimizers.newton_cg import newton_cg
from photon_tpu.core.problem import ProblemConfig, _compute_variances, hvp_at_for
from photon_tpu.models.glm import Coefficients


def binning_enabled() -> bool:
    return os.environ.get("PHOTON_SOLVE_BINNING", "on").strip().lower() not in (
        "off", "0", "false",
    )


def newton_enabled() -> bool:
    return os.environ.get("PHOTON_SOLVE_NEWTON", "on").strip().lower() not in (
        "off", "0", "false",
    )


def max_bins() -> int:
    return int(os.environ.get("PHOTON_SOLVE_MAX_BINS", "4"))


def bin_waste_cap() -> float:
    return float(os.environ.get("PHOTON_SOLVE_BIN_WASTE", "2.0"))


def newton_max_dim() -> int:
    return int(os.environ.get("PHOTON_NEWTON_MAX_DIM", "64"))


def newton_cg_enabled() -> bool:
    return os.environ.get("PHOTON_SOLVE_NEWTON_CG", "on").strip().lower() not in (
        "off", "0", "false",
    )


def newton_cg_max_dim() -> int:
    return int(os.environ.get("PHOTON_NEWTON_CG_MAX_DIM", "1024"))


def bin_layout(buckets: tuple) -> list:
    """Bucket-index groups for the operative bin policy: the planned size
    bins, or one bucket per bin when binning is off (the seed's loop)."""
    if not binning_enabled() or len(buckets) <= 1:
        return [[i] for i in range(len(buckets))]
    from photon_tpu.game.data import plan_size_bins

    return plan_size_bins(buckets, max_bins=max_bins(),
                          waste_cap=bin_waste_cap())


def solver_route(problem: ProblemConfig, solve_dim: int,
                 row_split: bool = False) -> str:
    """Which solver a bin runs: ``newton`` (batched Cholesky) for smooth
    small-dim problems, ``newton_cg`` (matrix-free Hessian-vector CG) for
    smooth bins past the dense-Hessian cap up to ``newton_cg_max_dim``,
    ``row_split`` under row-split placement, else ``vmapped`` (the
    existing L-BFGS/OWL-QN/TRON program — L1 bins and over-cap dims keep
    their iterative solve)."""
    if row_split:
        return "row_split"
    smooth = (
        problem.regularization.l1_weight == 0
        and problem.optimizer.lower() not in ("owlqn", "owl-qn")
    )
    if problem.optimizer.lower() in ("newton_cg", "newton-cg"):
        # An explicitly requested Newton-CG problem routes there at ANY
        # dim — the route label must not silently rename the user's
        # solver choice.
        return "newton_cg"
    if smooth and newton_enabled() and solve_dim <= newton_max_dim():
        return "newton"
    if (
        smooth
        and newton_cg_enabled()
        and newton_max_dim() < solve_dim <= newton_cg_max_dim()
    ):
        return "newton_cg"
    return "vmapped"


def _run_newton_fit(objective, batch, w0, *, cfg: OptimizerConfig,
                    variance: str):
    """One damped-Newton GLM fit, pure in (objective, batch, w0) — the body
    :func:`cached_newton_solver` vmaps and compiles.  Mirrors
    ``core.problem._run_fit``: the objective is a pytree argument, and the
    variance computation is the SAME ``_compute_variances`` formula the
    iterative path runs, so means AND variances agree at convergence."""
    fun = lambda w: objective.value_and_grad(w, batch)  # noqa: E731
    result = newton(
        fun, w0, cfg, hess=lambda w: objective.hessian_matrix(w, batch)
    )
    coefficients = Coefficients(
        means=result.w,
        variances=_compute_variances(objective, variance, result.w, batch),
    )
    return coefficients, result


def cached_newton_solver(problem: ProblemConfig):
    """The jit-compiled batched-Newton solver for one static problem
    configuration: ``(objective, batch, w0) -> (Coefficients,
    OptimizerResult)`` mapped over a leading entity axis.  Module-cached
    like ``core.problem.cached_solver`` — every coordinate and sweep config
    with the same static (optimizer config, variance) shares one traced
    program, and jit's own cache keys on bin shapes."""
    return _cached_newton_solver(
        problem.optimizer_config, problem.variance_computation
    )


@functools.lru_cache(maxsize=32)
def _cached_newton_solver(cfg: OptimizerConfig, variance: str):
    run = functools.partial(_run_newton_fit, cfg=cfg, variance=variance)
    return jax.jit(jax.vmap(run, in_axes=(None, 0, 0)))


def _run_newton_cg_fit(objective, batch, w0, *, cfg: OptimizerConfig,
                       variance: str):
    """One matrix-free Newton-CG GLM fit, pure in (objective, batch, w0) —
    the body :func:`cached_newton_cg_solver` vmaps and compiles.  The
    curvature rides ``objective.hvp_operator`` (per-row ``D(w)`` computed
    once per outer iteration, each CG step two matvecs — never a ``[d, d]``
    block), the Jacobi preconditioner is the cheap Hessian diagonal, and
    the variance computation is the SAME ``_compute_variances`` formula as
    every other route, so means AND variances stay on the existing parity
    contract."""
    fun = lambda w: objective.value_and_grad(w, batch)  # noqa: E731
    result = newton_cg(
        fun, w0, cfg,
        hvp_at=hvp_at_for(objective, batch),
        diag=lambda w: objective.hessian_diagonal(w, batch),
    )
    coefficients = Coefficients(
        means=result.w,
        variances=_compute_variances(objective, variance, result.w, batch),
    )
    return coefficients, result


def cached_newton_cg_solver(problem: ProblemConfig):
    """The jit-compiled batched Newton-CG solver for one static problem
    configuration — same caching contract as :func:`cached_newton_solver`:
    ``(objective, batch, w0) -> (Coefficients, OptimizerResult)`` mapped
    over a leading entity axis, one traced program per static (optimizer
    config, variance) pair."""
    return _cached_newton_cg_solver(
        problem.optimizer_config, problem.variance_computation
    )


@functools.lru_cache(maxsize=32)
def _cached_newton_cg_solver(cfg: OptimizerConfig, variance: str):
    run = functools.partial(_run_newton_cg_fit, cfg=cfg, variance=variance)
    return jax.jit(jax.vmap(run, in_axes=(None, 0, 0)))


def record_bin_telemetry(telemetry, coordinate: str, bin_stats: list,
                         routes: list) -> None:
    """Export the bin layout's padding economics as gauges — the ISSUE 8
    observability satellite: ``solves.bin_occupancy`` (LIVE entities per
    bin), ``solves.bin_entities_padded`` (mesh-padding slots), and
    ``solves.padded_fraction`` (padded fraction of the bin's entity×row
    cells — bin merging pads rows, mesh padding pads entities), so the bin
    policy's waste is observable instead of guessed.  Labels carry the
    coordinate, bin index, row capacity, and the routed solver.  The
    ``solves.routed{route}`` counter (ISSUE 14 satellite) counts the LIVE
    entities each route received — a silently-downgraded bin (L1,
    over-cap dim falling back to ``vmapped``) shows up in the run report
    instead of being inferred from timings."""
    for b, (stats, route) in enumerate(zip(bin_stats, routes)):
        labels = dict(
            coordinate=coordinate, bin=str(b),
            capacity=str(stats["capacity"]), route=route,
        )
        telemetry.counter(
            "solves.routed", coordinate=coordinate, route=route
        ).inc(stats["live_entities"])
        telemetry.gauge("solves.bin_occupancy", **labels).set(
            stats["live_entities"]
        )
        telemetry.gauge("solves.bin_entities_padded", **labels).set(
            stats["total_entities"] - stats["live_entities"]
        )
        cells = stats["total_entities"] * stats["capacity"]
        telemetry.gauge("solves.padded_fraction", **labels).set(
            0.0 if cells == 0 else 1.0 - stats["live_rows"] / cells
        )
