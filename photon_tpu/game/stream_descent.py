"""Epoch-style streamed GAME coordinate descent (out-of-core training).

The resident :class:`~photon_tpu.game.descent.CoordinateDescent` requires
every coordinate's training data AND the ``[C, n]`` score tables on device.
This module is the out-of-core mode (ISSUE 10): the dataset and the score
state stay at the host tier (:mod:`photon_tpu.game.tiles`), and each
coordinate's train / re-score / validate loop **maps over fixed-size row
chunks** streamed through a double-buffered h2d prefetch:

- **Fixed effect** — the whole-dataset GLM fit becomes a streamed L-BFGS
  (:func:`photon_tpu.data.streaming.streaming_lbfgs`): every objective
  evaluation is one pass over the chunks, each chunk's value+grad computed
  by the jitted per-chunk kernel (``_chunk_value_and_grad`` — the existing
  ``_fast_data_value_and_grad`` routing unchanged per chunk) and
  accumulated across chunks.  Chunk ``k+1``'s slice + upload runs on the
  io pool while chunk ``k``'s kernel executes.
- **Random effect** — each size bin's entities are split into
  **sub-blocks** sized to the chunk budget; blocks upload through the same
  prefetch pipeline and fold into the size-binned batched solves
  (``game.batched_solve`` routes — vmapped/Newton — are per-entity
  independent, so block composition cannot change any entity's solve).
- **Re-score / validate** — per-chunk device margins land back in the host
  score tiles; validation evaluates the tiled composite on host.

The descent keeps the one-host-sync-per-outer-iteration contract for
SOLVE STATS: per-coordinate device accumulators drain in ONE batched
``device_get`` at the iteration boundary (the chunk-cursor drain).  Score
data itself moves host<->device per chunk by design — that is the
out-of-core tier working as intended, and it is all bulk streaming
transfer, never a blocking scalar sync inside a chunk.

With a :class:`~photon_tpu.game.tiles.SpillContext` attached (ISSUE 11),
the residual tiles and feature chunks live one tier lower — disk part
files behind the LRU host cache — and the loop's shape is unchanged: the
chunk loads read disk→host→device, and every residual update writes the
dirty tiles back through the store (write-through, atomic per chunk).

Mid-epoch restartability: after EVERY coordinate the full restart state —
models, residual tiles, the **chunk cursor** (how far into the epoch's
update sequence the run got) and per-chunk **score-tile digests** — is
handed to the checkpointer, so a multi-hour streamed fit killed mid-epoch
resumes at the exact coordinate boundary with bit-identical state (the
digests are verified at load).  The ``descent:kill`` fault site fires both
at the iteration boundary (resident parity) and before each coordinate
(``coord=<name>`` scoping) to exercise the mid-epoch path.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Dict, Optional, Sequence

import numpy as np

from photon_tpu.evaluation.evaluators import MultiEvaluator
from photon_tpu.fault import QuarantineBudgetError
from photon_tpu.fault.checkpoint import DescentState, descent_fingerprint
from photon_tpu.fault.injection import fault_point
from photon_tpu.game.coordinate import (
    DeferredSolveStats,
    _accumulate_solve_stats,
    _align_foreign_table,
)
from photon_tpu.game.data import (
    DenseShard,
    EntityBucket,
    GameDataset,
    SparseShard,
    build_random_effect_dataset,
    entity_index_for,
    keys_match,
    merge_buckets,
    pad_bucket_entities,
)
from photon_tpu.game.descent import (
    DescentResult,
    _quarantine_count,
    _record_coordinate_info,
)
from photon_tpu.game.model import FixedEffectModel, GameModel, RandomEffectModel
from photon_tpu.game.tiles import (
    ChunkPlan,
    ChunkStreamer,
    NeumaierAccumulator,
    SpilledResidualTable,
    SpillContext,
    TiledResidualTable,
    TiledValidationTable,
    cached_entity_index,
    entity_index_cache,
    per_row_bytes,
    score_model_chunks,
)
from photon_tpu.telemetry import NULL_SESSION
from photon_tpu.utils.logging import PhotonLogger

# The streamed-mode marker in checkpoint fingerprints: a streamed fit's
# numerics depend on the chunked accumulation order, so its checkpoints are
# compatible only with streamed runs of the SAME chunk size — never with a
# resident fit (and vice versa).
STREAM_RESIDUAL_MODE = "stream"


def stream_fingerprint(
    task_type,
    coordinate_names,
    num_examples: int,
    chunk_rows: int,
    config_key=None,
    validation_key=None,
    locked=(),
    warm_start: bool = False,
    coordinate_kinds=None,
) -> dict:
    """The streamed descent's checkpoint fingerprint: the resident
    fingerprint with ``residual_mode == "stream"`` plus the chunk size
    (chunk boundaries fix the fixed-effect accumulation order, so resuming
    under a different ``chunk_rows`` would silently change numerics —
    refuse instead)."""
    fp = descent_fingerprint(
        task_type, coordinate_names, num_examples, STREAM_RESIDUAL_MODE,
        config_key=config_key, validation_key=validation_key, locked=locked,
        warm_start=warm_start, coordinate_kinds=coordinate_kinds,
    )
    fp["stream"] = {"chunk_rows": int(chunk_rows)}
    return fp


def _require_streamable_problem(config, what: str) -> None:
    """The streamed coordinate gates: fail LOUDLY at build time for
    configurations whose resident-only features have no streamed
    counterpart yet (rather than silently training something else)."""
    if config.problem.variance_computation != "none":
        raise ValueError(
            f"{what}: variance computation is not supported under "
            "--stream-chunks (the streamed solvers return means only)"
        )


# ---------------------------------------------------------------------------
# Streamed fixed-effect coordinate
# ---------------------------------------------------------------------------


class StreamedFixedEffectCoordinate:
    """Whole-dataset GLM fit that never holds the dataset on device: a
    streamed L-BFGS whose every objective evaluation maps the jitted
    per-chunk value+grad kernel over the chunk stream and reduces across
    chunks (DrJAX's MapReduce shape at the host-loop level)."""

    kind = "fixed"

    def __init__(
        self,
        data: GameDataset,
        config,
        task_type: str,
        plan: ChunkPlan,
        streamer: ChunkStreamer,
        normalization=None,
        source=None,
    ):
        from photon_tpu.core.objective import GlmObjective

        if config.downsampling_rate < 1.0:
            raise ValueError(
                "streamed GAME does not support fixed-effect downsampling "
                "(chunk layouts are contiguous row windows); train resident "
                "or drop downsample="
            )
        if config.problem.optimizer.lower() not in ("lbfgs", "l-bfgs"):
            raise ValueError(
                "streamed GAME fixed effect supports the lbfgs optimizer "
                f"(got {config.problem.optimizer!r}); OWL-QN/TRON have no "
                "streamed host loop yet"
            )
        if normalization is not None:
            raise ValueError(
                "streamed GAME does not support fixed-effect normalization "
                "(the per-chunk kernel cache requires a hashable objective)"
            )
        _require_streamable_problem(config, "streamed fixed effect")
        self.data = data
        self.config = config
        self.task_type = task_type
        self.plan = plan
        self.streamer = streamer
        self.mesh = None
        shard = data.shard(config.shard_name)
        self.dim = shard.dim
        self._dense = isinstance(shard, DenseShard)
        self.source = source  # None = host-resident slices (PR 10)
        self.objective = GlmObjective.create(
            task_type, config.problem.regularization
        )

    def _chunk_batch(self, k: int, offsets: list):
        """Worker-side chunk load: chunk ``k``'s feature rows, labels and
        weights (host slices, or the spilled disk tier through the host
        cache when a ``source`` is attached) + this coordinate's tiled
        training offsets, placed on device."""
        import jax.numpy as jnp

        from photon_tpu.data.batch import DenseBatch, SparseBatch

        if self.source is not None:
            sub = self.source.chunk(k)
            shard = sub.shard(self.config.shard_name)
            label_np, weight_np = sub.label, sub.weight
            feats = shard.x if self._dense else (shard.ids, shard.vals)
        else:
            lo, hi = self.plan.bounds(k)
            shard = self.data.shard(self.config.shard_name)
            label_np = self.data.label[lo:hi]
            weight_np = self.data.weight[lo:hi]
            feats = (
                shard.x[lo:hi] if self._dense
                else (shard.ids[lo:hi], shard.vals[lo:hi])
            )
        label = jnp.asarray(label_np)
        weight = jnp.asarray(weight_np)
        off = jnp.asarray(offsets[k])
        if self._dense:
            return DenseBatch(jnp.asarray(feats), label, off, weight)
        return SparseBatch(
            jnp.asarray(feats[0]), jnp.asarray(feats[1]),
            label, off, weight,
        )

    def _streamed_value_and_grad(self, w, offs):
        """One pass over the chunk stream: the jitted per-chunk kernel
        (``_chunk_value_and_grad`` — the existing
        ``_fast_data_value_and_grad`` routing unchanged per chunk) computes
        each chunk's data value+grad on device, and the CROSS-CHUNK reduce
        is a Neumaier-COMPENSATED float64 accumulation on host (ISSUE 11
        satellite) — the fixed-effect analog of the tiles' partials: the
        cross-chunk accumulation error is independent of the chunk count
        (a 1-chunk and a 1000-chunk pass reduce identically up to the
        per-chunk f32 inputs themselves), which keeps streamed-vs-resident
        parity at the two-solver f32 plateau floor instead of drifting
        with the chunk count."""
        import jax.numpy as jnp

        from photon_tpu.data.streaming import _chunk_value_and_grad

        data_obj = dataclasses.replace(
            self.objective, l2_weight=0.0, l1_weight=0.0
        )
        acc = NeumaierAccumulator(self.dim)
        for chunk in self.streamer.stream(
            lambda k: self._chunk_batch(k, offs), self.plan.num_chunks
        ):
            kernel = data_obj._sparse_kernel(chunk, self.dim)
            v, g = _chunk_value_and_grad(data_obj, kernel, w, chunk)
            # host-sync: the cross-chunk reduce — each chunk's scalar
            # value + [dim] gradient land on host and fold into the
            # compensated f64 accumulator (bulk dim-sized transfer).
            acc.add(float(v), np.asarray(g, np.float64))
        total_v, total_g = acc.value, acc.grad
        l2 = self.objective.l2_weight
        if l2:
            # host-sync: dim-sized regularization terms of the f64 reduce.
            w_host = np.asarray(w, np.float64)
            total_v += 0.5 * l2 * float(w_host @ w_host)
            total_g = total_g + l2 * w_host
        return (
            jnp.asarray(np.float32(total_v)),
            jnp.asarray(total_g.astype(np.float32)),
        )

    def train(self, offsets, initial_model: Optional[FixedEffectModel] = None):
        """One streamed GLM fit against the tiled offsets.  ``offsets`` is
        the tiled residual table's view for this coordinate (``chunk(k)``
        per-chunk host vectors, frozen for the duration of the train)."""
        import jax
        import jax.numpy as jnp

        from photon_tpu.core.optimizers import OptimizationStatesTracker
        from photon_tpu.data.streaming import streaming_lbfgs

        # The tiles cannot change during this train: materialize every
        # chunk's offsets once, then every streamed pass re-reads them.
        offs = [offsets.chunk(k) for k in range(self.plan.num_chunks)]
        coord = self

        class _Objective:
            """The streaming_lbfgs-facing surface: every evaluation is one
            streamed pass with the f64 cross-chunk reduce above."""

            def value_and_grad(self, w):
                return coord._streamed_value_and_grad(w, offs)

        sobj = _Objective()
        w0 = jnp.zeros(self.dim, jnp.float32)
        if initial_model is not None:
            w0 = jnp.asarray(initial_model.coefficients.means)
        t0 = time.monotonic()
        result = streaming_lbfgs(
            sobj, w0, self.config.problem.optimizer_config
        )
        jax.block_until_ready(result.w)
        tracker = OptimizationStatesTracker(result, time.monotonic() - t0)
        means = result.w
        from photon_tpu.fault.injection import consume_nan_injection
        from photon_tpu.models.glm import Coefficients, model_for_task

        if consume_nan_injection(getattr(self, "fault_name", None)):
            means = means.at[0].set(jnp.nan)
        # Non-finite guard, mirroring the resident coordinate: a poisoned
        # solve keeps the previous iterate (the streamed loop already
        # synced per pass, so this check costs one dim-sized host reduce).
        tracker.quarantined = 0
        if not bool(jnp.all(jnp.isfinite(means))):
            tracker.quarantined = 1
            means = (
                jnp.asarray(initial_model.coefficients.means)
                if initial_model is not None else jnp.zeros_like(means)
            )
        model = FixedEffectModel(
            model=model_for_task(self.task_type, Coefficients(means, None)),
            shard_name=self.config.shard_name,
        )
        return model, tracker

    def score_stream(self, model: FixedEffectModel) -> np.ndarray:
        """Training-data margins assembled chunk by chunk (host ``[n]``)."""
        if model.shard_name != self.config.shard_name:
            # host-sync: foreign-shard warm starts score through the
            # model's own host path (no chunk layout for that shard here).
            return np.asarray(model.score(self.data), np.float32)
        return score_model_chunks(
            model, self.data, self.plan, self.streamer, source=self.source
        )


# ---------------------------------------------------------------------------
# Streamed random-effect coordinate
# ---------------------------------------------------------------------------


class StreamedRandomEffectHostData:
    """Host-side bucketed layout of one random-effect coordinate: the same
    entity grouping + size-binned merge as the resident
    ``RandomEffectDeviceData``, but the padded ``[E, R, ...]`` bin blocks
    stay in HOST memory — the training pass uploads entity sub-blocks
    through the chunk streamer instead of pinning whole bins in HBM.
    Shared across sweep configurations by the estimator (the grouping is
    the expensive one-time host pass)."""

    def __init__(self, data: GameDataset, config):
        from photon_tpu.game.batched_solve import bin_layout

        self.config = config
        self.dataset = build_random_effect_dataset(
            data,
            entity_column=config.entity_column,
            shard_name=config.shard_name,
            active_row_cap=config.active_row_cap,
            seed=config.seed,
        )
        self.dim = self.dataset.dim
        raw = self.dataset.buckets
        self.bins = [
            merge_buckets([raw[i] for i in group])
            for group in bin_layout(raw)
        ]
        # Foreign-vocabulary warm-start join cache — same contract as the
        # resident device data (coordinate._foreign_src_idx reads it).
        self._warm_join_cache: dict = {}

    def entity_bytes(self, bucket: EntityBucket) -> int:
        """Approximate host/device bytes ONE entity of ``bucket`` occupies
        (feature block + labels/weights/offsets) — the sub-block sizing
        unit."""
        feats = bucket.features
        if isinstance(feats, DenseShard):
            per = feats.x.dtype.itemsize * feats.x.shape[2]
        else:
            per = (
                feats.ids.dtype.itemsize + feats.vals.dtype.itemsize
            ) * feats.ids.shape[2]
        # label + weight + offsets, f32 each.
        return bucket.row_capacity * (per + 12)


def _slice_bucket(bucket: EntityBucket, e0: int, e1: int) -> EntityBucket:
    """Entity-axis window ``[e0, e1)`` of a host bucket (numpy views)."""
    feats = bucket.features
    if isinstance(feats, DenseShard):
        feats = DenseShard(feats.x[e0:e1])
    else:
        feats = SparseShard(feats.ids[e0:e1], feats.vals[e0:e1], feats.dim_)
    return EntityBucket(
        row_capacity=bucket.row_capacity,
        entity_index=bucket.entity_index[e0:e1],
        row_index=bucket.row_index[e0:e1],
        row_weight=bucket.row_weight[e0:e1],
        label=bucket.label[e0:e1],
        features=feats,
    )


class StreamedRandomEffectCoordinate:
    """Per-entity batched GLM fits whose bin blocks stream through the
    chunk budget: each size bin's entities are solved in fixed-size
    sub-blocks (padded to one shape per bin — one compiled program per
    bin, like resident), uploaded double-buffered while the previous
    block's vmapped/Newton solve runs.  Per-entity independence of the
    batched solvers makes the block split numerically invisible."""

    kind = "random"

    def __init__(
        self,
        data: GameDataset,
        config,
        task_type: str,
        plan: ChunkPlan,
        streamer: ChunkStreamer,
        host_data: Optional[StreamedRandomEffectHostData] = None,
        source=None,
    ):
        from photon_tpu.core.objective import GlmObjective
        from photon_tpu.core.problem import GlmOptimizationProblem

        if config.projection != "none":
            raise ValueError(
                "streamed GAME random effects support projection=none only "
                f"(got {config.projection!r}); projected solves are a "
                "resident-mode feature"
            )
        if getattr(config, "row_split", False):
            raise ValueError(
                "row_split is a mesh feature; streamed GAME runs "
                "single-controller (see README §Out-of-core GAME)"
            )
        _require_streamable_problem(config, "streamed random effect")
        self.data = data
        self.config = config
        self.task_type = task_type
        self.plan = plan
        self.streamer = streamer
        self.mesh = None
        self.source = source  # spilled chunk features for re-scoring
        self.device_data = host_data or StreamedRandomEffectHostData(
            data, config
        )
        self.dataset = self.device_data.dataset
        self.dim = self.dataset.dim
        # The chunk budget in bytes bounds each in-flight entity block the
        # same way it bounds a row chunk.
        self._block_budget = max(
            1, plan.chunk_rows * per_row_bytes(data)
        )
        obj = GlmObjective.create(task_type, config.problem.regularization)
        self.problem = GlmOptimizationProblem(obj, config.problem)
        self._solver = functools.partial(
            self.problem.solver(vmapped=True), self.problem.objective
        )

    def _bin_blocks(self) -> list:
        """Flat block schedule ``[(bin_index, e0, e1, block_entities)]``:
        every bin's entity axis cut into budget-sized windows; the LAST
        window of a bin pads up to ``block_entities`` (one compiled shape
        per bin)."""
        blocks = []
        for i, bucket in enumerate(self.device_data.bins):
            e_bytes = self.device_data.entity_bytes(bucket)
            e_sub = max(1, min(
                bucket.num_entities, self._block_budget // max(1, e_bytes)
            ))
            for e0 in range(0, bucket.num_entities, e_sub):
                blocks.append(
                    (i, e0, min(bucket.num_entities, e0 + e_sub), e_sub)
                )
        return blocks

    def _routes(self) -> dict:
        from photon_tpu.game.batched_solve import solver_route

        return {
            i: solver_route(self.config.problem, self.dim, row_split=False)
            for i in range(len(self.device_data.bins))
        }

    def _load_block(self, block, offsets_full: np.ndarray):
        """Worker-side sub-block load: slice + pad the host bin, gather the
        block's training offsets from the tiled offsets vector, and place
        everything on device."""
        import jax.numpy as jnp

        from photon_tpu.data.batch import DenseBatch, SparseBatch

        i, e0, e1, e_sub = block
        sub = _slice_bucket(self.device_data.bins[i], e0, e1)
        if sub.num_entities < e_sub:
            sub = pad_bucket_entities(sub, e_sub, self.dataset.num_entities)
        off = offsets_full[sub.row_index] * (sub.row_weight > 0)
        label = jnp.asarray(sub.label)
        weight = jnp.asarray(sub.row_weight)
        off_dev = jnp.asarray(off.astype(np.float32))
        feats = sub.features
        if isinstance(feats, DenseShard):
            batch = DenseBatch(jnp.asarray(feats.x), label, off_dev, weight)
        else:
            batch = SparseBatch(
                jnp.asarray(feats.ids), jnp.asarray(feats.vals),
                label, off_dev, weight,
            )
        return i, batch, jnp.asarray(sub.entity_index.astype(np.int32))

    def _solve_block(self, route: str, batch, w0):
        if route == "newton":
            from photon_tpu.game.batched_solve import cached_newton_solver

            return cached_newton_solver(self.config.problem)(
                self.problem.objective, batch, w0
            )
        if route == "newton_cg":
            # Matrix-free large-dim route (ISSUE 14): streamed high-dim
            # bins get the same Hessian-vector-product CG program as
            # resident ones — no [B, d, d] block competes with the chunk
            # window for device memory.
            from photon_tpu.game.batched_solve import cached_newton_cg_solver

            return cached_newton_cg_solver(self.config.problem)(
                self.problem.objective, batch, w0
            )
        return self._solver(batch, w0)

    def _initial_table(self, initial_model: RandomEffectModel):
        """Key-aligned warm-start table with the trailing dummy slot —
        same-vocabulary models stay on device; foreign vocabularies go
        through the shared (cached, io-pool-prefetchable) host join."""
        import jax.numpy as jnp

        if initial_model.dim != self.dim:
            raise ValueError(
                f"warm-start model dim {initial_model.dim} != coordinate "
                f"dim {self.dim}"
            )
        if keys_match(initial_model.keys, self.dataset.keys):
            table = jnp.asarray(initial_model.table, jnp.float32)
            return jnp.concatenate(
                [table, jnp.zeros((1, self.dim), table.dtype)]
            )
        return jnp.asarray(_align_foreign_table(self, initial_model))

    def train(self, offsets, initial_model: Optional[RandomEffectModel] = None):
        """Solve every entity, streaming bin sub-blocks through the chunk
        budget; returns (model, DeferredSolveStats) — the stats accumulator
        stays on device for the descent boundary drain."""
        import jax.numpy as jnp

        from photon_tpu.fault.injection import consume_nan_injection

        num_entities = self.dataset.num_entities
        offsets_full = offsets.full()
        table = jnp.zeros((num_entities + 1, self.dim), jnp.float32)
        init_table = (
            None if initial_model is None
            else self._initial_table(initial_model)
        )
        acc = jnp.zeros(6, jnp.int32)
        inject_nan = consume_nan_injection(getattr(self, "fault_name", None))
        routes = self._routes()
        blocks = self._bin_blocks()
        first = True
        for i, batch, entity_idx in self.streamer.stream(
            lambda j: self._load_block(blocks[j], offsets_full), len(blocks)
        ):
            if init_table is not None:
                w0 = init_table[entity_idx]
            else:
                w0 = jnp.zeros((entity_idx.shape[0], self.dim), jnp.float32)
            coefficients, result = self._solve_block(routes[i], batch, w0)
            means = coefficients.means
            if inject_nan and first:
                means = means.at[0].set(jnp.nan)
            first = False
            good = jnp.all(jnp.isfinite(means), axis=1)
            prev_rows = (
                init_table[entity_idx] if init_table is not None else 0.0
            )
            table = table.at[entity_idx].set(
                jnp.where(good[:, None], means, prev_rows)
            )
            acc = _accumulate_solve_stats(
                acc, entity_idx, num_entities, result.converged,
                result.iterations, good,
                cg_iterations=getattr(result, "cg_iterations", None),
            )
        model = RandomEffectModel(
            table=table[:num_entities],
            keys=self.dataset.keys,
            entity_column=self.config.entity_column,
            shard_name=self.config.shard_name,
            task_type=self.task_type,
        )
        return model, DeferredSolveStats(acc)

    def score_stream(self, model: RandomEffectModel) -> np.ndarray:
        """Training-data margins assembled chunk by chunk (host ``[n]``)."""
        if (model.shard_name != self.config.shard_name
                or model.entity_column != self.config.entity_column):
            # host-sync: foreign-layout warm starts score through the
            # model's own host path.
            return np.asarray(model.score(self.data), np.float32)
        # host-sync: foreign-vocabulary key compare/join (warm starts from
        # disk); same-run models hit the identity check.
        if keys_match(model.keys, self.dataset.keys):
            idx = self.dataset.entity_idx_per_row
        else:
            idx = entity_index_for(
                self.data.id_columns[self.config.entity_column],
                # host-sync: foreign vocabularies are host numpy keys.
                np.asarray(model.keys),
            )
        return score_model_chunks(
            model, self.data, self.plan, self.streamer, entity_idx=idx,
            source=self.source,
        )


# ---------------------------------------------------------------------------
# The streamed descent loop
# ---------------------------------------------------------------------------


class StreamedCoordinateDescent:
    """Coordinate descent whose data plane is the chunk stream: same outer
    contract as :class:`~photon_tpu.game.descent.CoordinateDescent` (update
    order, residual passing, incremental validation, quarantine budget,
    preemption, checkpoint/resume), different residency — see module
    docstring.  Built by :class:`~photon_tpu.game.estimator.GameEstimator`
    when ``stream_chunks`` is set."""

    def __init__(
        self,
        coordinates: Dict[str, object],
        task_type: str,
        training_data: GameDataset,
        validation_data: Optional[GameDataset] = None,
        evaluators: Optional[MultiEvaluator] = None,
        plan: Optional[ChunkPlan] = None,
        streamer: Optional[ChunkStreamer] = None,
        logger: Optional[PhotonLogger] = None,
        telemetry=None,
        spill: Optional[SpillContext] = None,
    ):
        if not coordinates:
            raise ValueError(
                "StreamedCoordinateDescent needs at least one coordinate"
            )
        self.coordinates = dict(coordinates)
        self.task_type = task_type
        self.training_data = training_data
        self.validation_data = validation_data
        self.evaluators = evaluators
        self.logger = logger or PhotonLogger("photon_tpu.game.stream")
        self.telemetry = telemetry or NULL_SESSION
        self.plan = plan or ChunkPlan(
            training_data.num_examples, training_data.num_examples
        )
        self.streamer = streamer or ChunkStreamer(self.telemetry)
        self.spill = spill
        self._val_idx_cache = entity_index_cache()

    # -- helpers -------------------------------------------------------------
    def _fingerprint(self, config_key=None, locked=(), warm_start=False):
        has_validation = (
            self.validation_data is not None and self.evaluators is not None
        )
        return stream_fingerprint(
            self.task_type, self.coordinates,
            self.training_data.num_examples, self.plan.chunk_rows,
            config_key=config_key,
            validation_key=(
                self.evaluators.primary.name if has_validation else None
            ),
            locked=locked, warm_start=warm_start,
            coordinate_kinds={
                name: getattr(c, "kind", type(c).__name__)
                for name, c in self.coordinates.items()
            },
        )

    def _val_plan(self) -> ChunkPlan:
        return ChunkPlan(
            self.validation_data.num_examples, self.plan.chunk_rows
        )

    def _score_validation(self, model) -> np.ndarray:
        """One coordinate model's margins over the validation rows,
        streamed per chunk (entity joins cached per vocabulary)."""
        idx = None
        if isinstance(model, RandomEffectModel):
            idx = cached_entity_index(
                self._val_idx_cache, self.validation_data,
                model.entity_column, model.keys,
            )
        return score_model_chunks(
            model, self.validation_data, self._val_plan(), self.streamer,
            entity_idx=idx,
        )

    def _evaluate(self, val_table: TiledValidationTable) -> Dict[str, float]:
        """Host evaluation of the tiled composite margin (the compensated
        per-chunk partials carry host-f64-equivalent precision)."""
        composite = val_table.composite_full()
        data = self.validation_data
        entity_ids = dict(data.id_columns)
        return self.evaluators.evaluate(
            composite, data.label, data.weight, entity_ids
        )

    def _snapshot(
        self, iteration: int, cursor: int, num_iterations: int,
        models, best_model, best_metrics, best_iteration, history,
        residuals, quarantined: int, fp: dict,
    ) -> DescentState:
        # Monotonic checkpoint sequence across epoch/cursor positions:
        # mid-epoch snapshots of iteration i+1 (cursor 1..C) sort after the
        # end-of-iteration-i snapshot (cursor 0) and before i+1's.
        n_pos = len(self.coordinates) + 1
        seq = (iteration + 1) * n_pos + cursor
        return DescentState(
            iteration=iteration,
            num_iterations=num_iterations,
            task_type=self.task_type,
            models=dict(models),
            best_models=(
                dict(best_model.coordinates) if best_model is not None else {}
            ),
            best_metrics=dict(best_metrics),
            best_iteration=best_iteration,
            history=list(history),
            residual_rows=residuals.snapshot_rows(),
            quarantined=quarantined,
            fingerprint=fp,
            stream={
                "chunk_rows": int(self.plan.chunk_rows),
                "cursor": int(cursor),
                "seq": int(seq),
                "tile_digests": residuals.tile_digests(),
                # Informational: spilled snapshots carry EMPTY residual
                # rows — the on-disk tiles are referenced by the digests
                # above, not re-saved (resume re-adopts or rebuilds; the
                # spill residency itself is deliberately NOT fingerprinted
                # because spilled and host-resident tiles are bit-equal).
                "spilled": self.spill is not None,
            },
        )

    # -- run -----------------------------------------------------------------
    def run(
        self,
        num_iterations: int,
        initial_model: Optional[GameModel] = None,
        locked_coordinates: Sequence[str] = (),
        checkpoint_fn=None,
        checkpointer=None,
        resume_state: Optional[DescentState] = None,
        max_quarantined: Optional[int] = None,
        config_key: Optional[str] = None,
    ) -> DescentResult:
        try:
            result = self._run(
                num_iterations, initial_model=initial_model,
                locked_coordinates=locked_coordinates,
                checkpoint_fn=checkpoint_fn, checkpointer=checkpointer,
                resume_state=resume_state, max_quarantined=max_quarantined,
                config_key=config_key,
            )
        except BaseException:
            if checkpointer is not None and hasattr(checkpointer, "drain"):
                checkpointer.drain(reraise=False)
            raise
        finally:
            from photon_tpu.fault.watchdog import complete

            complete("descent.iteration")
        if checkpointer is not None and hasattr(checkpointer, "drain"):
            with self.telemetry.span("descent.checkpoint.drain"):
                checkpointer.drain()
        return result

    def _run(
        self,
        num_iterations: int,
        initial_model: Optional[GameModel] = None,
        locked_coordinates: Sequence[str] = (),
        checkpoint_fn=None,
        checkpointer=None,
        resume_state: Optional[DescentState] = None,
        max_quarantined: Optional[int] = None,
        config_key: Optional[str] = None,
    ) -> DescentResult:
        locked = set(locked_coordinates)
        unknown = locked - set(self.coordinates)
        if unknown:
            raise KeyError(
                f"locked coordinates not in update sequence: {sorted(unknown)}"
            )
        if locked and initial_model is None:
            raise ValueError("locked coordinates require an initial model")
        for name in locked:
            if initial_model is not None and name not in initial_model.coordinates:
                raise KeyError(
                    f"locked coordinate {name!r} missing from initial model"
                )

        telemetry = self.telemetry
        fp = self._fingerprint(
            config_key, locked=locked, warm_start=initial_model is not None
        )
        models: Dict[str, object] = {}
        with telemetry.span(
            "descent.residuals.init", mode=STREAM_RESIDUAL_MODE,
            spilled=self.spill is not None,
        ):
            if self.spill is not None:
                residuals = SpilledResidualTable(
                    self.training_data.offset, names=list(self.coordinates),
                    plan=self.plan, store=self.spill.store,
                    cache=self.spill.cache, telemetry=telemetry,
                )
                if resume_state is None:
                    # A fresh fit must not read a previous run's published
                    # tiles as its zero state.
                    residuals.reset_store()
            else:
                residuals = TiledResidualTable(
                    self.training_data.offset, names=list(self.coordinates),
                    plan=self.plan, telemetry=telemetry,
                )
        val_table = None
        if self.validation_data is not None and self.evaluators is not None:
            with telemetry.span("descent.validation.init"):
                val_table = TiledValidationTable(
                    self.validation_data.offset,
                    names=list(self.coordinates),
                    plan=self._val_plan(), telemetry=telemetry,
                )

        best_model: Optional[GameModel] = None
        best_metrics: Dict[str, float] = {}
        best_iteration = -1
        history: list = []
        start_iteration = 0
        resume_cursor = 0
        quarantined_total = 0

        if resume_state is not None:
            from photon_tpu.fault.checkpoint import (
                CheckpointError,
                require_fingerprint,
            )

            require_fingerprint(resume_state, fp, "this streamed descent")
            with telemetry.span(
                "descent.resume", iteration=resume_state.iteration
            ):
                models = dict(resume_state.models)
                stream_meta = resume_state.stream or {}
                saved_digests = stream_meta.get("tile_digests")
                rows = resume_state.residual_rows
                if rows:
                    residuals.load_rows(rows)
                elif hasattr(residuals, "attach_resume"):
                    # Spilled checkpoint: the tiles were REFERENCED, not
                    # re-saved — adopt the on-disk part files (reads are
                    # digest-verified; corruption is refused loudly).
                    residuals.attach_resume()
                if saved_digests is not None:
                    rebuilt = residuals.tile_digests()
                    if rebuilt != list(saved_digests) and not rows:
                        # Referenced tiles are stale (a kill tore the
                        # update sequence mid-write-back, or the spill
                        # residency changed between runs).  The tiles are
                        # a pure function of the checkpointed models over
                        # the fingerprinted data+plan: rebuild them
                        # deterministically and re-verify.
                        telemetry.counter("tiles.rebuilt").inc()
                        self.logger.info(
                            "on-disk tiles do not match the checkpoint; "
                            "rebuilding from the checkpointed models"
                        )
                        if hasattr(residuals, "reset_store"):
                            # Spilled table: dropping the part files IS
                            # the zero state — no stale-tile reads, no
                            # zero-tile publishes that the model rebuild
                            # below would immediately overwrite.
                            residuals.reset_store()
                        else:
                            residuals.clear()
                        for name, coord_model in models.items():
                            residuals.update(
                                name,
                                self.coordinates[name].score_stream(
                                    coord_model
                                ),
                            )
                        residuals.drain_guard_flags()  # checkpointed = guarded
                        rebuilt = residuals.tile_digests()
                    if rebuilt != list(saved_digests):
                        raise CheckpointError(
                            "score-tile digests do not match the "
                            "checkpoint's (per-chunk state diverged); "
                            "refusing to resume"
                        )
                if val_table is not None:
                    for name, model in models.items():
                        val_table.update(
                            name, self._score_validation(model)
                        )
                    val_table.drain_guard_flags()  # checkpointed = guarded
                if resume_state.best_models:
                    best_model = GameModel(
                        dict(resume_state.best_models), self.task_type
                    )
                best_metrics = dict(resume_state.best_metrics)
                best_iteration = resume_state.best_iteration
                history = list(resume_state.history)
                quarantined_total = resume_state.quarantined
                start_iteration = resume_state.iteration + 1
                resume_cursor = int(stream_meta.get("cursor", 0))
            telemetry.counter("descent.resumes").inc()
            self.logger.info(
                "resumed streamed descent at iteration %d coordinate cursor "
                "%d", start_iteration, resume_cursor,
            )
        elif initial_model is not None:
            for name, coord_model in initial_model.coordinates.items():
                if name not in self.coordinates:
                    continue
                models[name] = coord_model
                residuals.update(
                    name,
                    self.coordinates[name].score_stream(coord_model),
                )
                if val_table is not None:
                    val_table.update(
                        name, self._score_validation(coord_model)
                    )
            # Overlap the remaining host-resident warm-start work (the
            # foreign-vocabulary key joins) with the first coordinate's
            # training — ISSUE 10 satellite; shared with the resident loop.
            from photon_tpu.game.coordinate import prefetch_warm_joins

            prefetch_warm_joins(
                self.coordinates, initial_model, telemetry=telemetry
            )

        # Seed-guard drain: rejected seed rows belong to the initial model
        # (same semantics as the resident loop).
        seed_rejected = set(residuals.poll_quarantined())
        if val_table is not None:
            seed_rejected |= set(val_table.poll_quarantined())
        bad_locked = sorted(seed_rejected & locked)
        if bad_locked:
            raise ValueError(
                f"locked coordinate(s) {bad_locked} produced non-finite "
                "scores from the initial model; a locked coordinate cannot "
                "be quarantined"
            )
        for name in sorted(seed_rejected):
            telemetry.counter(
                "descent.quarantined", coordinate=name, stage="seed"
            ).inc()
            quarantined_total += 1
            models.pop(name, None)
            self.logger.info(
                "coordinate %s: non-finite scores from the initial model "
                "quarantined (cold start instead)", name,
            )
        if max_quarantined is not None and quarantined_total > max_quarantined:
            raise QuarantineBudgetError(
                f"{quarantined_total} quarantined solves/score rows "
                f"exceed --max-quarantined {max_quarantined}"
            )

        if start_iteration >= num_iterations:
            last = GameModel(dict(models), self.task_type)
            return DescentResult(
                best_model=best_model if best_model is not None else last,
                last_model=last,
                best_metrics=best_metrics,
                history=history,
            )

        from photon_tpu.fault.preemption import (
            PreemptedError,
            consume_preempt_injection,
            preemption_requested,
            preemption_reason,
        )
        from photon_tpu.fault.watchdog import heartbeat

        def preempt_exit(where: str):
            telemetry.counter("descent.preempted").inc()
            if checkpointer is not None and hasattr(checkpointer, "drain"):
                with telemetry.span("descent.checkpoint.drain"):
                    checkpointer.drain()
                hint = "resume with --resume auto"
            else:
                hint = ("no checkpointer configured — a restart begins "
                        "from scratch (set --checkpoint-dir)")
            raise PreemptedError(
                f"preempted ({preemption_reason()}) {where}; {hint}"
            )

        order = list(self.coordinates)
        game_model = GameModel(dict(models), self.task_type)
        for it in range(start_iteration, num_iterations):
            fault_point("descent:kill", iteration=it)
            consume_preempt_injection(it)
            if preemption_requested():
                preempt_exit(f"before iteration {it}")
            heartbeat("descent.iteration")
            coord_logs: Dict[str, str] = {}
            trained = 0
            deferred: Dict[str, object] = {}
            skip = resume_cursor if it == start_iteration else 0
            with telemetry.span(
                "descent.iteration", iteration=it, mode=STREAM_RESIDUAL_MODE
            ) as iter_span:
                for pos, name in enumerate(order):
                    if name in locked or pos < skip:
                        continue
                    coord = self.coordinates[name]
                    # Mid-epoch kill/preempt points: the chunk-cursor
                    # checkpoint below makes a coordinate boundary a safe
                    # restart line, so both fire here too.
                    fault_point(
                        "descent:kill", iteration=it, coordinate=name
                    )
                    if preemption_requested():
                        preempt_exit(
                            f"mid-epoch before coordinate {name!r} of "
                            f"iteration {it}"
                        )
                    prev = models.get(name)
                    offsets = _TiledOffsets(residuals, name)
                    with self.logger.timed(f"iter{it}-{name}"):
                        model, info = coord.train(
                            offsets, initial_model=models.get(name)
                        )
                    models[name] = model
                    residuals.update(name, coord.score_stream(model))
                    rejected = set(residuals.poll_quarantined())
                    if val_table is not None and name not in rejected:
                        val_table.update(
                            name, self._score_validation(model)
                        )
                        rejected |= set(val_table.poll_quarantined())
                    if name in rejected:
                        # Non-finite score row: roll the model back to the
                        # previous iterate (drop it entirely on a cold
                        # start) and re-sync BOTH tables — same semantics,
                        # handled immediately because the tiled guard is a
                        # host check.
                        telemetry.counter(
                            "descent.quarantined", coordinate=name,
                            stage="score_row",
                        ).inc()
                        quarantined_total += 1
                        if prev is not None:
                            models[name] = prev
                            residuals.update(
                                name, coord.score_stream(prev)
                            )
                            if val_table is not None:
                                val_table.update(
                                    name, self._score_validation(prev)
                                )
                        else:
                            models.pop(name, None)
                            residuals.update(
                                name, np.zeros(self.plan.n, np.float32)
                            )
                            if val_table is not None:
                                val_table.update(
                                    name,
                                    np.zeros(val_table.n, np.float32),
                                )
                        residuals.drain_guard_flags()
                        if val_table is not None:
                            val_table.drain_guard_flags()
                        self.logger.info(
                            "iter %d coordinate %s: non-finite scores "
                            "quarantined (previous iterate kept)", it, name,
                        )
                    trained += 1
                    if isinstance(info, DeferredSolveStats):
                        if checkpointer is not None:
                            # Checkpointed runs resolve each coordinate's
                            # stats NOW (one [6]-int32 fetch): the mid-epoch
                            # snapshot below must carry this coordinate's
                            # solve-stage quarantine count, or a kill+resume
                            # that skips past it would permanently lose the
                            # count — and with it --max-quarantined
                            # enforcement parity.  Unchecked runs keep the
                            # strict one-drain-per-iteration path.
                            info = info.resolve()
                        else:
                            deferred[name] = info
                    if not isinstance(info, DeferredSolveStats):
                        q = _quarantine_count(info)
                        if q:
                            telemetry.counter(
                                "descent.quarantined", coordinate=name,
                                stage="solve",
                            ).inc(q)
                            quarantined_total += q
                        _record_coordinate_info(telemetry, name, info)
                        summary = (
                            info.summary().splitlines()[0]
                            if hasattr(info, "summary") else str(info)
                        )
                        coord_logs[name] = summary
                        self.logger.info(
                            "iter %d coordinate %s: %s", it, name, summary
                        )
                    telemetry.counter(
                        "descent.coordinate_updates", coordinate=name
                    ).inc()
                    if max_quarantined is not None and (
                        quarantined_total > max_quarantined
                    ):
                        raise QuarantineBudgetError(
                            f"{quarantined_total} quarantined solves/score "
                            f"rows exceed --max-quarantined {max_quarantined}"
                        )
                    if checkpointer is not None:
                        # The chunk-cursor checkpoint: models + tiles +
                        # cursor after EVERY coordinate, so a mid-epoch
                        # kill resumes at this exact boundary.
                        state = self._snapshot(
                            it - 1, pos + 1, num_iterations, models,
                            best_model, best_metrics, best_iteration,
                            history, residuals, quarantined_total, fp,
                        )
                        with telemetry.span(
                            "descent.checkpoint.save", iteration=it,
                            cursor=pos + 1,
                        ):
                            checkpointer.save(state)

                # THE one stats host sync of the iteration (the
                # chunk-cursor drain): every coordinate's device stats
                # accumulator comes to host in a single batched device_get.
                import jax as _jax

                # host-sync: the sanctioned once-per-iteration stats drain
                # (descent.host_syncs counts it), same as resident.
                stats_host = _jax.device_get(
                    {name: ds.device for name, ds in deferred.items()}
                )
                telemetry.counter("descent.host_syncs", kind="stats").inc()
                for name, ds in deferred.items():
                    info = ds.resolve(stats_host[name])
                    q = int(info.get("quarantined", 0))
                    if q:
                        telemetry.counter(
                            "descent.quarantined", coordinate=name,
                            stage="solve",
                        ).inc(q)
                        quarantined_total += q
                    _record_coordinate_info(telemetry, name, info)
                    coord_logs[name] = str(info)
                    self.logger.info(
                        "iter %d coordinate %s: %s", it, name, info
                    )
                if max_quarantined is not None and (
                    quarantined_total > max_quarantined
                ):
                    raise QuarantineBudgetError(
                        f"{quarantined_total} quarantined solves/score rows "
                        f"exceed --max-quarantined {max_quarantined}"
                    )

                game_model = GameModel(dict(models), self.task_type)
                if checkpoint_fn is not None:
                    with telemetry.span("descent.checkpoint", iteration=it):
                        checkpoint_fn(it, game_model)
                metrics: Dict[str, float] = {}
                with telemetry.span("descent.validate", iteration=it):
                    if val_table is not None:
                        telemetry.counter("validation.score_reuse").inc(
                            (len(self.coordinates) - trained)
                            * self.validation_data.num_examples
                        )
                        metrics = self._evaluate(val_table)
                if metrics:
                    self.logger.info("iter %d validation %s", it, metrics)
                    iter_span.set_attribute("metrics", metrics)
                    for k, v in metrics.items():
                        telemetry.gauge(
                            "descent.validation_metric", metric=k
                        ).set(v)
            # Sweep-end write-back flush: every tile this iteration's C
            # coordinate updates dirtied publishes ONCE (the ISSUE 17
            # batching — the PR 11 write-through design republished each
            # full tile C times per sweep).  Runs before the end-of-
            # iteration checkpoint so its digests describe on-disk tiles
            # a resume can adopt directly.
            if hasattr(residuals, "flush"):
                with telemetry.span("tiles.writeback_flush", iteration=it):
                    residuals.flush()
            telemetry.counter("descent.iterations").inc()
            # The chunk-budget residency gauge: the streamer's measured
            # in-flight peak IS the device footprint of the streamed score
            # plane (there is no resident [C, n] table to account for).
            telemetry.gauge("residuals.device_bytes").set(
                self.streamer.peak_in_flight_bytes
            )
            history.append(
                {"iteration": it, "metrics": metrics,
                 "coordinates": coord_logs}
            )

            if not metrics:
                best_model, best_metrics, best_iteration = (
                    game_model, metrics, it
                )
            else:
                primary = self.evaluators.primary
                if best_model is None or primary.better_than(
                    metrics[primary.name], best_metrics[primary.name]
                ):
                    best_model, best_metrics, best_iteration = (
                        game_model, metrics, it
                    )

            if checkpointer is not None:
                state = self._snapshot(
                    it, 0, num_iterations, models, best_model, best_metrics,
                    best_iteration, history, residuals, quarantined_total,
                    fp,
                )
                with telemetry.span(
                    "descent.checkpoint.save", iteration=it
                ):
                    checkpointer.save(state)

        assert best_model is not None
        return DescentResult(
            best_model=best_model,
            last_model=game_model,
            best_metrics=best_metrics,
            history=history,
        )


@dataclasses.dataclass(frozen=True)
class _TiledOffsets:
    """A coordinate's view of its tiled training offsets: ``chunk(k)``
    feeds the streamed fixed-effect chunks, ``full()`` the random-effect
    host row gather.  Values are identical either way (see tiles.py)."""

    table: TiledResidualTable
    name: str

    def chunk(self, k: int) -> np.ndarray:
        return self.table.offsets_chunk(self.name, k)

    def full(self) -> np.ndarray:
        return self.table.offsets_full(self.name)
