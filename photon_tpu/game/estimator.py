"""GameEstimator: sweep over GAME optimization configurations.

Rebuild of the reference's ``estimators.GameEstimator`` (SURVEY.md §2.2):
``fit()`` runs CoordinateDescent once per :class:`GameOptimizationConfiguration`
in the sweep (the reference's per-coordinate regularization-weight grid),
evaluates each resulting model on validation data, and selects the best
(model, configuration) pair by the primary evaluator — the reference's
model-selection component.

Warm start / partial retraining (SURVEY.md §5 'Checkpoint'): an
``initial_model`` seeds every coordinate's first fit, and
``locked_coordinates`` keep their initial model entirely (scored, never
retrained).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Sequence

from photon_tpu.core.normalization import NormalizationContext
from photon_tpu.evaluation.evaluators import MultiEvaluator, default_evaluators_for_task
from photon_tpu.game.coordinate import CoordinateConfig, build_coordinate
from photon_tpu.game.data import GameDataset
from photon_tpu.game.descent import CoordinateDescent, DescentResult
from photon_tpu.game.model import GameModel
from photon_tpu.telemetry import NULL_SESSION
from photon_tpu.utils.logging import PhotonLogger


@dataclasses.dataclass(frozen=True)
class GameOptimizationConfiguration:
    """One point of the sweep: per-coordinate configs in update order +
    number of outer coordinate-descent iterations (the reference's
    GameOptimizationConfiguration + coordinateDescentIterations)."""

    coordinates: Dict[str, CoordinateConfig]
    descent_iterations: int = 1
    name: str = ""

    def __post_init__(self):
        if not self.coordinates:
            raise ValueError("configuration needs at least one coordinate")
        if self.descent_iterations < 1:
            raise ValueError("descent_iterations must be >= 1")


@dataclasses.dataclass
class GameResult:
    """One fitted sweep entry: (model, evaluation, configuration) — the
    reference's GameEstimator.fit return triple."""

    model: GameModel
    metrics: Dict[str, float]
    configuration: GameOptimizationConfiguration
    descent: DescentResult


class GameEstimator:
    """Builds coordinates per configuration and runs the descent sweep."""

    def __init__(
        self,
        task_type: str,
        training_data: GameDataset,
        validation_data: Optional[GameDataset] = None,
        evaluators: Optional[MultiEvaluator] = None,
        mesh=None,
        normalization: Optional[Dict[str, NormalizationContext]] = None,
        logger: Optional[PhotonLogger] = None,
        telemetry=None,
        residual_mode: Optional[str] = None,
        validation_mode: Optional[str] = None,
        stream_chunks: Optional[int] = None,
        spill_dir: Optional[str] = None,
        max_host_mb: Optional[float] = None,
        tile_dtype: Optional[str] = None,
    ):
        """``normalization`` is keyed by feature-shard name and applies to
        fixed-effect coordinates on that shard (the reference normalizes the
        fixed-effect objective only).  ``residual_mode`` selects how descent
        passes residuals between coordinates, ``validation_mode`` how it
        scores/evaluates validation data (``auto``/``device``/``host`` —
        see :mod:`photon_tpu.game.residuals`).

        ``stream_chunks`` (rows per chunk, > 0) switches every fit to the
        OUT-OF-CORE streamed descent (:mod:`photon_tpu.game.stream_descent`):
        training data and score state stay host-resident as fixed-size row
        chunks / score tiles, streamed through a double-buffered h2d
        prefetch — device residency is bounded by the chunk window instead
        of the dataset size.  Streamed mode is single-controller (no mesh)
        and replaces the residual/validation mode machinery.

        ``spill_dir`` (requires ``stream_chunks``) adds the DISK tier
        behind the stream (:mod:`photon_tpu.game.tile_store`): feature
        chunks and residual score tiles live in per-chunk part files, an
        LRU host cache bounded by ``max_host_mb`` (MB; ``None`` =
        unbounded cache, still disk-backed) serves them, and the prefetch
        pipeline becomes disk→host→device — the score plane and the
        fixed-effect feature stream are bounded by the cache budget
        instead of the dataset.  (The caller-provided ``training_data``
        itself and the random-effect bin layouts are still host-resident
        — the ROADMAP tiering item's remaining edges.)

        ``tile_dtype`` (requires ``spill_dir``) picks the disk tier's
        storage codec for feature blocks and score tiles —
        ``f32 | bf16 | int8`` (:mod:`photon_tpu.game.lowp`; default f32,
        the bit-exact tier).  Lossy tiers trade a bounded, measured fit-
        metric perturbation (``lowp.TILE_METRIC_TOL``) for 2-4× less
        disk traffic; all accumulation stays f32 and kill→resume parity
        stays exact per codec."""
        self.task_type = task_type
        self.training_data = training_data
        self.validation_data = validation_data
        if evaluators is None and validation_data is not None:
            evaluators = MultiEvaluator(default_evaluators_for_task(task_type))
        self.evaluators = evaluators
        self.mesh = mesh
        if isinstance(normalization, NormalizationContext):
            raise TypeError(
                "pass normalization as {shard_name: NormalizationContext}"
            )
        self.normalization = normalization or {}
        self.logger = logger or PhotonLogger("photon_tpu.game")
        self.telemetry = telemetry or NULL_SESSION
        self.residual_mode = residual_mode
        self.validation_mode = validation_mode
        self.stream_chunks = None
        if stream_chunks is not None:
            if int(stream_chunks) < 1:
                raise ValueError(
                    f"stream_chunks must be >= 1, got {stream_chunks}"
                )
            if mesh is not None:
                raise ValueError(
                    "stream_chunks (out-of-core GAME) runs single-controller"
                    " — drop the mesh or train resident"
                )
            if residual_mode not in (None, "auto") or (
                validation_mode not in (None, "auto")
            ):
                # Same refuse-loudly policy as every other unsupported
                # streamed configuration: an explicitly requested resident
                # engine must not be silently replaced by the tiled tables
                # (the CLI driver strips the flags itself and logs).
                raise ValueError(
                    "stream_chunks replaces the residual/validation "
                    "engines; drop the explicit residual_mode/"
                    "validation_mode (got "
                    f"{residual_mode!r}/{validation_mode!r})"
                )
            self.stream_chunks = int(stream_chunks)
        self.spill_dir = spill_dir
        self.max_host_mb = max_host_mb
        if spill_dir is not None and not self.stream_chunks:
            raise ValueError(
                "spill_dir (the disk-backed tile store) requires "
                "stream_chunks — the disk tier spills the STREAMED fit's "
                "host working set"
            )
        if max_host_mb is not None:
            if max_host_mb <= 0:
                raise ValueError(
                    f"max_host_mb must be > 0, got {max_host_mb}"
                )
            if spill_dir is None:
                raise ValueError(
                    "max_host_mb bounds the spill host cache; set "
                    "spill_dir (or let the driver derive one)"
                )
        from photon_tpu.game.lowp import TILE_DTYPES, check_dtype

        self.tile_dtype = check_dtype(tile_dtype, TILE_DTYPES, "tile dtype")
        if self.tile_dtype != "f32" and spill_dir is None:
            raise ValueError(
                "tile_dtype selects the DISK tier's storage codec; set "
                "spill_dir (host-resident tiles are always f32)"
            )
        # Device-resident data shared across sweep configurations: building
        # the bucketed random-effect datasets (the reference's shuffle) and
        # uploading feature blocks happens once per distinct data config.
        self._device_data_cache: Dict[tuple, object] = {}
        # Fixed-effect batch row-capacity headroom (ISSUE 18 satellite):
        # per data config, the amortized-doubling padded row count the next
        # FixedEffectDeviceData rebuild targets.  A refresh whose grown row
        # count still fits rebuilds at the SAME shape, so every solve
        # program compiled against the batch stays hot (zero recompiles
        # across online refreshes — the test_online pin).
        self._fixed_row_capacity: Dict[tuple, int] = {}
        # Streamed mode: host-side bucketed layouts + the shared chunk
        # streamer (overlap/stall telemetry accumulates across the sweep).
        self._stream_data_cache: Dict[tuple, object] = {}
        self._streamer = None
        self._spill = None
        # Validation scoring cache shared across the whole sweep: one upload
        # of the validation feature shards for ALL configurations.
        self._validation_cache = None

    def _validation_scoring_cache(self):
        """The shared device validation cache, when the resolved modes call
        for one (host-mode runs never pay the upload)."""
        from photon_tpu.game.model import DeviceScoringCache
        from photon_tpu.game.residuals import (
            resolve_residual_mode,
            resolve_validation_mode,
        )

        if self.validation_data is None or self.evaluators is None:
            return None
        mode = resolve_validation_mode(
            self.validation_mode, resolve_residual_mode(self.residual_mode)
        )
        if mode != "device":
            return None
        if self._validation_cache is None:
            self._validation_cache = DeviceScoringCache(
                self.validation_data, mesh=self.mesh, telemetry=self.telemetry
            )
        return self._validation_cache

    def _device_data(self, coord_config):
        from photon_tpu.game.coordinate import (
            FixedEffectCoordinateConfig,
            FixedEffectDeviceData,
            RandomEffectDeviceData,
        )

        key = coord_config.data_key
        if key not in self._device_data_cache:
            if isinstance(coord_config, FixedEffectCoordinateConfig):
                self._device_data_cache[key] = FixedEffectDeviceData(
                    self.training_data, coord_config, self.mesh,
                    row_capacity=self._fixed_row_capacity.get(key),
                )
            else:
                from photon_tpu.game.coordinate import (
                    FactoredRandomEffectCoordinateConfig,
                )

                rc = (
                    coord_config.as_random_config()
                    if isinstance(coord_config, FactoredRandomEffectCoordinateConfig)
                    else coord_config
                )
                self._device_data_cache[key] = RandomEffectDeviceData(
                    self.training_data, rc, self.mesh
                )
        return self._device_data_cache[key]

    def device_layout(self, coord_config):
        """The cached device-resident layout for one coordinate config
        (``FixedEffectDeviceData`` / ``RandomEffectDeviceData``), built on
        first use — the PUBLIC handle the online-learning loop grows
        vocabularies/warm starts against (reaching into the private cache
        would couple callers to its key structure)."""
        return self._device_data(coord_config)

    def entity_vocabularies(self) -> Dict[str, object]:
        """Current entity vocabulary per id column, from the LIVE
        random-effect device layouts (the onboarded state, which may be
        ahead of any saved model's keys)."""
        from photon_tpu.game.coordinate import RandomEffectDeviceData

        return {
            dd.config.entity_column: dd.dataset.keys
            for dd in self._device_data_cache.values()
            if isinstance(dd, RandomEffectDeviceData)
        }

    def _build_coordinates(self, config: GameOptimizationConfiguration):
        coords = {
            name: build_coordinate(
                self.training_data,
                coord_config,
                self.task_type,
                mesh=self.mesh,
                normalization=self.normalization.get(coord_config.shard_name),
                device_data=self._device_data(coord_config),
            )
            for name, coord_config in config.coordinates.items()
        }
        for name, coord in coords.items():
            # The coordinate's update-sequence name, so named fault-injection
            # sites (solve:nan:coord=<name>) and quarantine telemetry can
            # address it.
            coord.fault_name = name
            # The coordinates' own telemetry (bin-occupancy gauges,
            # warm-start transfer counters) lands in the run's session.
            coord.telemetry = self.telemetry
        return coords

    # -- streamed (out-of-core) mode -----------------------------------------
    def _stream_plan(self):
        from photon_tpu.game.tiles import ChunkPlan

        return ChunkPlan(self.training_data.num_examples, self.stream_chunks)

    def _stream_streamer(self):
        from photon_tpu.game.tiles import ChunkStreamer

        if self._streamer is None:
            self._streamer = ChunkStreamer(self.telemetry)
        return self._streamer

    def _spill_context(self):
        """The disk tier of a spilled streamed fit, built ONCE per
        estimator: the part-file store, the ``max_host_mb``-bounded LRU
        host cache, and the chunk feature source reading through them.
        Building it spills the training dataset's feature chunks (skipped
        when a previous run over the same dataset+plan already published
        them — mid-epoch resume reuses the store)."""
        if self.spill_dir is None:
            return None
        if self._spill is None:
            from photon_tpu.game.tile_store import TileStore
            from photon_tpu.game.tiles import (
                HostTileCache,
                SpillContext,
                SpilledChunkSource,
                spill_dataset,
            )

            store = TileStore(
                self.spill_dir, telemetry=self.telemetry,
                tile_dtype=self.tile_dtype,
            )
            cache = HostTileCache(
                max_bytes=(
                    None if self.max_host_mb is None
                    else int(self.max_host_mb * (1 << 20))
                ),
                telemetry=self.telemetry,
            )
            plan = self._stream_plan()
            spill_dataset(
                store, self.training_data, plan, telemetry=self.telemetry
            )
            self._spill = SpillContext(
                store=store, cache=cache,
                source=SpilledChunkSource(
                    store, plan, cache, telemetry=self.telemetry,
                ),
            )
        return self._spill

    def _build_stream_coordinates(self, config: GameOptimizationConfiguration):
        """Streamed counterparts of :meth:`_build_coordinates`: no device
        data is uploaded at build time — fixed coordinates stream row
        chunks, random coordinates stream entity sub-blocks from HOST bin
        layouts cached across sweep configurations."""
        from photon_tpu.game.coordinate import (
            FactoredRandomEffectCoordinateConfig,
            FixedEffectCoordinateConfig,
            RandomEffectCoordinateConfig,
        )
        from photon_tpu.game.stream_descent import (
            StreamedFixedEffectCoordinate,
            StreamedRandomEffectCoordinate,
            StreamedRandomEffectHostData,
        )

        plan, streamer = self._stream_plan(), self._stream_streamer()
        spill = self._spill_context()
        source = spill.source if spill is not None else None
        coords = {}
        for name, cc in config.coordinates.items():
            if isinstance(cc, FixedEffectCoordinateConfig):
                coords[name] = StreamedFixedEffectCoordinate(
                    self.training_data, cc, self.task_type, plan, streamer,
                    normalization=self.normalization.get(cc.shard_name),
                    source=source,
                )
            elif isinstance(cc, FactoredRandomEffectCoordinateConfig):
                raise ValueError(
                    f"coordinate {name!r}: factored_random coordinates have "
                    "no streamed path (the pooled latent solve is "
                    "whole-dataset); train resident"
                )
            elif isinstance(cc, RandomEffectCoordinateConfig):
                key = cc.data_key
                if key not in self._stream_data_cache:
                    self._stream_data_cache[key] = (
                        StreamedRandomEffectHostData(self.training_data, cc)
                    )
                coords[name] = StreamedRandomEffectCoordinate(
                    self.training_data, cc, self.task_type, plan, streamer,
                    host_data=self._stream_data_cache[key],
                    source=source,
                )
            else:
                raise TypeError(f"unknown coordinate config {type(cc)!r}")
        for name, coord in coords.items():
            coord.fault_name = name
            coord.telemetry = self.telemetry
        return coords

    def onboard_training_data(self, data: GameDataset,
                              absent_tail=None) -> None:
        """Incremental onboarding between fits: swap in a GROWN training
        dataset whose appended rows may reference BOTH new and existing
        random-effect entities (ISSUE 15: the continual-training loop's
        data-growth edge).

        The cached random-effect device layouts extend in place
        (:meth:`~photon_tpu.game.coordinate.RandomEffectDeviceData.onboard`
        — new entities as appended bins, existing entities' rows scattered
        into per-bin row-capacity headroom, migration past exhausted
        capacity; resident feature blocks untouched, ZERO full layout
        rebuilds — the contract the online service asserts via the
        ``estimator.device_data_rebuilds{kind}`` counter).  Fixed-effect
        device data is whole-dataset and is dropped for a lazy rebuild on
        the next fit, counted as ``kind="fixed"`` — but the rebuild pads to
        an amortized-doubling ROW CAPACITY (weight-0 pad rows), so while
        growth fits the previous capacity the batch shape is unchanged and
        the compiled solve programs stay hot; the ``kind="random"`` count
        stays 0 by construction.  Warm-start models from the previous fit can be grown
        to the merged vocabulary on device with
        :meth:`~photon_tpu.game.model.RandomEffectModel.with_entities`.

        ``absent_tail`` maps an id column to a bool mask over the appended
        rows marking rows that carry no id for that column (the online
        ingest's missing-column fill — those rows join no entity of the
        column's coordinates).
        """
        from photon_tpu.game.coordinate import RandomEffectDeviceData

        if data.num_examples < self.training_data.num_examples:
            raise ValueError(
                "onboard_training_data() needs the grown dataset (rows are "
                "append-only)"
            )
        absent_tail = absent_tail or {}
        with self.telemetry.span(
            "estimator.onboard", rows=data.num_examples
        ):
            # Validate EVERY layout's preconditions before mutating any:
            # one layout rejecting mid-loop must not leave the cache
            # half-onboarded (grown per-user bins against an old-length
            # offsets vector).
            for dd in self._device_data_cache.values():
                if isinstance(dd, RandomEffectDeviceData):
                    dd.check_onboard(
                        data,
                        absent_tail=absent_tail.get(dd.config.entity_column),
                    )
            for key, dd in list(self._device_data_cache.items()):
                if isinstance(dd, RandomEffectDeviceData):
                    before = dd.dataset.num_entities
                    dd.onboard(
                        data, telemetry=self.telemetry,
                        absent_tail=absent_tail.get(dd.config.entity_column),
                    )
                    self.telemetry.counter("estimator.entities_onboarded").inc(
                        dd.dataset.num_entities - before
                    )
                else:
                    # Record the amortized-doubling row capacity the lazy
                    # rebuild will pad to: while the grown row count still
                    # fits the previous capacity the rebuilt batch keeps
                    # its exact shape (weight-0 pad rows), so the solve
                    # programs compiled against it stay hot; past capacity,
                    # double (at least) so growth pays a recompile only
                    # O(log n) times.
                    from photon_tpu.utils import pow2_at_least

                    need = int(data.num_examples)
                    prev = self._fixed_row_capacity.get(
                        key, int(dd.batch.num_examples)
                    )
                    if need > prev:
                        prev = max(pow2_at_least(need), 2 * prev)
                    self._fixed_row_capacity[key] = prev
                    del self._device_data_cache[key]
                    self.telemetry.counter(
                        "estimator.device_data_rebuilds", kind="fixed"
                    ).inc()
        # Streamed host layouts have no incremental-onboard path (they are
        # cheap host structures): drop them for a lazy rebuild at the
        # grown row count.  The spill context follows — the grown dataset
        # re-spills under its new fingerprint on the next fit.
        self._stream_data_cache.clear()
        self._spill = None
        self.training_data = data

    def fit(
        self,
        configurations: Sequence[GameOptimizationConfiguration],
        initial_model: Optional[GameModel] = None,
        locked_coordinates: Sequence[str] = (),
        checkpoint_fn=None,
        checkpoint_dir: Optional[str] = None,
        resume: Optional[str] = None,
        max_quarantined: Optional[int] = None,
        checkpoint_async=None,
        checkpoint_max_staged_mb: Optional[float] = None,
    ) -> List[GameResult]:
        """``checkpoint_fn(iteration, model)`` is forwarded to each descent
        run (per-iteration intermediate model output — SURVEY.md §5).

        ``checkpoint_dir`` turns on preemption-safe descent checkpointing
        (one ``cfg-NNN`` subdirectory per configuration in this call);
        ``resume`` restores from it: ``auto`` resumes whatever is
        checkpointed (fresh start otherwise), ``latest`` requires a
        checkpoint, an explicit path names one checkpoint version (single-
        configuration fits only).  A configuration whose checkpoint already
        covers its final iteration is rebuilt from the snapshot without
        re-running — mid-sweep resume skips finished work.
        ``max_quarantined`` is the descent quarantine budget (None =
        unlimited; see :meth:`CoordinateDescent.run`).  ``checkpoint_async``
        gates the background checkpoint publisher (``'on'``/``'off'``/bool;
        None defers to ``PHOTON_CHECKPOINT_ASYNC``, default on — see
        :func:`photon_tpu.fault.checkpoint.resolve_checkpoint_async`).
        ``checkpoint_max_staged_mb`` bounds the async publisher's staged
        host copies (over the cap a snapshot publishes blocking — see
        :class:`~photon_tpu.fault.checkpoint.CheckpointPublisherBase`).

        Checkpoints are MESH-SHAPE PORTABLE: resume accepts a checkpoint
        written under a different device/process count — restored model
        tables are placed for THIS estimator's mesh and the engines re-pad/
        re-shard score rows onto it (the fingerprint pins the logical
        layout, never the mesh).
        """
        if not configurations:
            raise ValueError("fit() needs at least one configuration")
        if resume and checkpoint_dir is None and resume in ("auto", "latest"):
            raise ValueError(f"resume={resume!r} needs checkpoint_dir")
        if resume and resume not in ("auto", "latest") and len(configurations) > 1:
            raise ValueError(
                "an explicit checkpoint path resumes a single-configuration "
                "fit; use resume='auto' for sweeps"
            )
        from photon_tpu.fault.checkpoint import (
            DescentCheckpointer,
            configuration_key,
            descent_fingerprint,
            require_fingerprint,
        )
        from photon_tpu.game.residuals import resolve_residual_mode

        results = []
        for i, config in enumerate(configurations):
            label = config.name or f"config-{i}"
            config_key = configuration_key(config.coordinates)
            checkpointer = None
            resume_state = None
            if checkpoint_dir is not None:
                checkpointer = DescentCheckpointer(
                    os.path.join(checkpoint_dir, f"cfg-{i:03d}"),
                    telemetry=self.telemetry, logger=self.logger,
                    async_publish=checkpoint_async,
                    max_staged_mb=checkpoint_max_staged_mb,
                )
            if resume:
                # The load places restored model state for THIS run's mesh
                # — whatever shape it is (elastic resume).
                if resume in ("auto", "latest"):
                    resume_state = checkpointer.load(resume, mesh=self.mesh)
                else:
                    resume_state = DescentCheckpointer.load_path(
                        resume, mesh=self.mesh
                    )
            if resume_state is not None:
                # Validate compatibility HERE, before the completed
                # short-circuit below can return a foreign checkpoint's
                # model as this configuration's result.  The config key
                # digests the per-coordinate optimization configs, so a
                # sweep point with different regularization can never
                # adopt this checkpoint.
                has_validation = (
                    self.validation_data is not None
                    and self.evaluators is not None
                )
                kinds = {
                    name: getattr(cc, "kind", type(cc).__name__)
                    for name, cc in config.coordinates.items()
                }
                validation_key = (
                    self.evaluators.primary.name if has_validation else None
                )
                if self.stream_chunks:
                    from photon_tpu.game.stream_descent import (
                        stream_fingerprint,
                    )

                    expected = stream_fingerprint(
                        self.task_type, config.coordinates,
                        self.training_data.num_examples, self.stream_chunks,
                        config_key=config_key,
                        validation_key=validation_key,
                        locked=locked_coordinates,
                        warm_start=initial_model is not None,
                        coordinate_kinds=kinds,
                    )
                else:
                    expected = descent_fingerprint(
                        self.task_type, config.coordinates,
                        self.training_data.num_examples,
                        resolve_residual_mode(self.residual_mode),
                        config_key=config_key,
                        validation_key=validation_key,
                        locked=locked_coordinates,
                        warm_start=initial_model is not None,
                        coordinate_kinds=kinds,
                    )
                require_fingerprint(
                    resume_state, expected, f"configuration {label!r}"
                )
            # Completed means: covers THIS run's requested iterations (a
            # raised descent_iterations resumes and runs the extra passes).
            if (resume_state is not None
                    and resume_state.iteration + 1 >= config.descent_iterations):
                # This configuration already finished before the
                # interruption: rebuild its result from the snapshot.
                best = GameModel(dict(resume_state.best_models), self.task_type)
                descent = DescentResult(
                    best_model=best,
                    last_model=GameModel(
                        dict(resume_state.models), self.task_type
                    ),
                    best_metrics=dict(resume_state.best_metrics),
                    history=list(resume_state.history),
                )
                self.telemetry.counter("estimator.configurations_resumed").inc()
                self.logger.info(
                    "fit-%s restored from completed checkpoint", label
                )
                results.append(
                    GameResult(
                        model=best,
                        metrics=descent.best_metrics,
                        configuration=config,
                        descent=descent,
                    )
                )
                continue
            with self.telemetry.span("estimator.fit", configuration=label), \
                    self.logger.timed(f"fit-{label}"):
                if self.stream_chunks:
                    from photon_tpu.game.stream_descent import (
                        StreamedCoordinateDescent,
                    )

                    loop = StreamedCoordinateDescent(
                        self._build_stream_coordinates(config),
                        self.task_type,
                        self.training_data,
                        self.validation_data,
                        self.evaluators,
                        plan=self._stream_plan(),
                        streamer=self._stream_streamer(),
                        logger=self.logger,
                        telemetry=self.telemetry,
                        spill=self._spill_context(),
                    )
                else:
                    loop = CoordinateDescent(
                        self._build_coordinates(config),
                        self.task_type,
                        self.training_data,
                        self.validation_data,
                        self.evaluators,
                        logger=self.logger,
                        telemetry=self.telemetry,
                        residual_mode=self.residual_mode,
                        validation_mode=self.validation_mode,
                        validation_cache=self._validation_scoring_cache(),
                    )
                descent = loop.run(
                    config.descent_iterations,
                    initial_model=initial_model,
                    locked_coordinates=locked_coordinates,
                    checkpoint_fn=checkpoint_fn,
                    checkpointer=checkpointer,
                    resume_state=resume_state,
                    max_quarantined=max_quarantined,
                    config_key=config_key,
                )
            self.telemetry.counter("estimator.configurations").inc()
            results.append(
                GameResult(
                    model=descent.best_model,
                    metrics=descent.best_metrics,
                    configuration=config,
                    descent=descent,
                )
            )
        return results

    def select_best(self, results: Sequence[GameResult]) -> GameResult:
        """Best sweep entry by the primary evaluator; without validation the
        first entry wins (reference behavior: selection needs a validation
        set)."""
        if self.evaluators is None or not any(r.metrics for r in results):
            return results[0]
        primary = self.evaluators.primary
        best = results[0]
        for r in results[1:]:
            if r.metrics and primary.better_than(
                r.metrics.get(primary.name, float("nan")),
                best.metrics.get(primary.name, float("nan")),
            ):
                best = r
        return best
