"""CoordinateDescent: the GAME outer loop.

Rebuild of the reference's ``algorithm.CoordinateDescent``
(``descend``/``optimize`` — SURVEY.md §2.2, §3.1): cycle the coordinates in
update order for a fixed number of outer iterations; each coordinate trains
against the **residuals** of the others — its training offsets are the
dataset offset plus the sum of every other coordinate's current scores — then
re-scores the data.  After each full pass the composite model is evaluated on
validation data and the best model (by the primary evaluator) is tracked.

Locked coordinates (the reference's partial-retraining lock list) keep their
initial model: they are scored but never retrained.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence

import numpy as np

from photon_tpu.evaluation.evaluators import MultiEvaluator
from photon_tpu.fault import QuarantineBudgetError
from photon_tpu.fault.checkpoint import DescentState
from photon_tpu.fault.injection import fault_point
from photon_tpu.game.coordinate import DeferredSolveStats
from photon_tpu.game.data import GameDataset
from photon_tpu.game.model import DeviceScoringCache, GameModel
from photon_tpu.game.residuals import (
    HostResiduals,
    ResidualEngine,
    ValidationEngine,
    resolve_residual_mode,
    resolve_validation_mode,
)
from photon_tpu.telemetry import NULL_SESSION
from photon_tpu.utils.logging import PhotonLogger


@dataclasses.dataclass
class DescentResult:
    """Outcome of one CoordinateDescent run."""

    best_model: GameModel
    last_model: GameModel
    best_metrics: Dict[str, float]
    history: list  # per outer iteration: {"iteration", "metrics", "coordinates"}

    @property
    def models_match(self) -> bool:
        return self.best_model is self.last_model


def _quarantine_count(info) -> int:
    """Quarantined-solve count reported by a coordinate's train() — dict key
    for random-effect stats, attribute for the fixed-effect tracker."""
    if isinstance(info, dict):
        return int(info.get("quarantined", 0))
    return int(getattr(info, "quarantined", 0))


def _record_coordinate_info(telemetry, name: str, info) -> None:
    """Record a coordinate's convergence info into the telemetry registry.

    Fixed-effect coordinates return an OptimizationStatesTracker (which
    knows how to record itself); random-effect coordinates return a stats
    dict over their per-entity vmapped solves."""
    if hasattr(info, "record_to"):
        info.record_to(telemetry.registry, coordinate=name)
    elif isinstance(info, dict) and "entities" in info:
        telemetry.counter("re_solver.entities", coordinate=name).inc(
            info["entities"]
        )
        telemetry.counter("re_solver.converged_entities", coordinate=name).inc(
            info.get("converged", 0)
        )
        telemetry.gauge("re_solver.iterations_max", coordinate=name).set(
            info.get("iterations_max", 0)
        )
        cg = info.get("cg_iters", 0)
        if cg:
            # Newton-CG bins only (ISSUE 14): mean inner-CG iterations per
            # CG-ROUTED entity solve this outer iteration — the knob that
            # tells whether the Eisenstat-Walker tolerance and the Jacobi
            # preconditioner are doing their jobs.  The denominator is the
            # CG bins' own entity count, so a coordinate mixing CG and
            # dense/vmapped bins cannot dilute the mean.
            telemetry.histogram("solves.cg_iters", coordinate=name).observe(
                cg / max(info.get("cg_entities", 0), 1)
            )


class CoordinateDescent:
    """Cycles coordinate training with residual (offset) passing.

    ``coordinates`` maps name -> built Coordinate object; iteration order is
    the update order (the reference's coordinateUpdateSequence).

    Residual passing runs in one of two modes (``game.residuals``):
    ``device`` keeps every coordinate's score vector in a device-resident
    table and computes each coordinate's training offsets with one jitted
    kernel; ``host`` is the float64 numpy accumulate the seed shipped with
    (``PHOTON_RESIDUALS=host`` / ``--residuals host``).

    Validation runs in one of two modes too (``validation_mode``):
    ``device`` keeps a second score table over the validation rows
    (:class:`ValidationEngine` + a shared :class:`DeviceScoringCache`),
    re-scores ONLY the coordinates that retrained each outer iteration, and
    evaluates the jitted device metrics — the per-iteration host traffic is
    the per-metric scalars; ``host`` is the seed's full
    ``GameModel.score`` fetch + numpy evaluator pass.
    """

    def __init__(
        self,
        coordinates: Dict[str, object],
        task_type: str,
        training_data: GameDataset,
        validation_data: Optional[GameDataset] = None,
        evaluators: Optional[MultiEvaluator] = None,
        logger: Optional[PhotonLogger] = None,
        telemetry=None,
        residual_mode: Optional[str] = None,
        validation_mode: Optional[str] = None,
        validation_cache: Optional[DeviceScoringCache] = None,
    ):
        if not coordinates:
            raise ValueError("CoordinateDescent needs at least one coordinate")
        self.coordinates = dict(coordinates)
        self.task_type = task_type
        self.training_data = training_data
        self.validation_data = validation_data
        self.evaluators = evaluators
        self.logger = logger or PhotonLogger("photon_tpu.game")
        self.telemetry = telemetry or NULL_SESSION
        self.residual_mode = resolve_residual_mode(residual_mode)
        self.validation_mode = resolve_validation_mode(
            validation_mode, self.residual_mode
        )
        # Scoring-side device data for the validation rows, shared across
        # descent runs by the estimator (feature uploads happen once per
        # shard, not once per sweep configuration).
        self._validation_cache = validation_cache

    def _mesh(self):
        return next(
            (c.mesh for c in self.coordinates.values()
             if getattr(c, "mesh", None) is not None),
            None,
        )

    def _build_residuals(self):
        """The residual state for this run: the device engine, or the host
        float64 path (escape hatch)."""
        cls = ResidualEngine if self.residual_mode == "device" else HostResiduals
        with self.telemetry.span(
            "descent.residuals.init", mode=self.residual_mode
        ):
            return cls(
                self.training_data.offset,
                names=list(self.coordinates),
                mesh=self._mesh(),
                telemetry=self.telemetry,
            )

    def _build_validation(self):
        """The validation engine + scoring cache for a device-mode run (the
        cache is reused across runs when the estimator supplied one)."""
        cache = self._validation_cache
        if cache is None or cache.data is not self.validation_data:
            cache = DeviceScoringCache(
                self.validation_data, mesh=self._mesh(),
                telemetry=self.telemetry,
            )
            self._validation_cache = cache
        with self.telemetry.span("descent.validation.init"):
            engine = ValidationEngine(
                self.validation_data.offset,
                names=list(self.coordinates),
                mesh=self._mesh(),
                telemetry=self.telemetry,
            )
        return engine, cache

    def _score(self, coord, model):
        """Score a coordinate's model over the training data: device path
        returns a device array (no host round-trip); host path returns the
        numpy vector the seed produced."""
        if self.residual_mode == "device" and hasattr(coord, "score_device"):
            return coord.score_device(model)
        return coord.score(model)

    def _evaluate(self, model: GameModel) -> Dict[str, float]:
        if self.validation_data is None or self.evaluators is None:
            return {}
        data = self.validation_data
        # host-sync: the HOST validation path (escape hatch) — every
        # coordinate's margins come to host and evaluators run in numpy.
        scores = model.score(data)
        entity_ids = dict(data.id_columns)
        return self.evaluators.evaluate(scores, data.label, data.weight, entity_ids)

    def _evaluate_device(self, engine: ValidationEngine,
                         cache: DeviceScoringCache) -> Dict[str, float]:
        """Device-resident validation: composite margin from the score
        table, jitted metrics over the cached labels/weights/entity codes.
        The per-metric ``float()`` scalars are the only d2h traffic."""
        composite = engine.composite()
        entity_ids = {
            ev.entity_column: cache.entity_codes(ev.entity_column)
            for ev in self.evaluators.evaluators
            if ev.entity_column is not None and ev.device_kind is not None
        }
        metrics = self.evaluators.evaluate(
            composite, cache.label, cache.weight, entity_ids
        )
        # host-sync: the per-metric scalars — the ONE host sync the device
        # validation pipeline performs per outer iteration.
        self.telemetry.counter(
            "descent.host_transfer_bytes", direction="d2h", path="validation"
        ).inc(4 * len(metrics))
        self.telemetry.gauge("validation.scoring_cache_bytes").set(
            cache.device_bytes
        )
        return metrics

    def _fingerprint(
        self, config_key: Optional[str] = None, locked=(),
        warm_start: bool = False,
    ) -> dict:
        from photon_tpu.fault.checkpoint import descent_fingerprint

        has_validation = (
            self.validation_data is not None and self.evaluators is not None
        )
        return descent_fingerprint(
            self.task_type, self.coordinates,
            self.training_data.num_examples, self.residual_mode,
            config_key=config_key,
            validation_key=(
                self.evaluators.primary.name if has_validation else None
            ),
            locked=locked,
            warm_start=warm_start,
            coordinate_kinds={
                name: getattr(c, "kind", type(c).__name__)
                for name, c in self.coordinates.items()
            },
        )

    def run(
        self,
        num_iterations: int,
        initial_model: Optional[GameModel] = None,
        locked_coordinates: Sequence[str] = (),
        checkpoint_fn=None,
        checkpointer=None,
        resume_state: Optional[DescentState] = None,
        max_quarantined: Optional[int] = None,
        config_key: Optional[str] = None,
    ) -> DescentResult:
        """``checkpoint_fn(iteration, model)``, when given, is called after
        every full coordinate pass with the current composite model — the
        reference's per-iteration intermediate model output (SURVEY.md §5
        'Failure detection': restart-from-checkpoint is the recovery story).

        ``checkpointer`` (a :class:`~photon_tpu.fault.checkpoint.
        DescentCheckpointer`) snapshots the FULL restart state — models,
        residual score rows, best-model tracking, history — after every
        outer iteration; with its async publisher (the default) the loop
        only stages the d2h copies and the serialize+fsync+rename runs
        behind the next iteration's compute.  ``resume_state`` restores a
        snapshot mid-sweep (device tables rebuilt from the saved rows), so
        a resumed fit matches an uninterrupted one.  ``max_quarantined``
        bounds how many non-finite solves/score rows may be quarantined
        (previous iterate kept) before the run fails with
        :class:`QuarantineBudgetError` (None = unlimited).
        """
        try:
            result = self._run(
                num_iterations,
                initial_model=initial_model,
                locked_coordinates=locked_coordinates,
                checkpoint_fn=checkpoint_fn,
                checkpointer=checkpointer,
                resume_state=resume_state,
                max_quarantined=max_quarantined,
                config_key=config_key,
            )
        except BaseException:
            # Quiesce the async publisher without masking the real error
            # (an InjectedKillError must surface as itself; the in-flight
            # publish is allowed to land — a checkpoint more is strictly
            # better than one fewer).
            if checkpointer is not None and hasattr(checkpointer, "drain"):
                checkpointer.drain(reraise=False)
            raise
        finally:
            # Retire the iteration heartbeat: a finished (or dead) descent
            # going quiet is not a stall the watchdog should flag.
            from photon_tpu.fault.watchdog import complete

            complete("descent.iteration")
        if checkpointer is not None and hasattr(checkpointer, "drain"):
            # The final iteration drains: a completed fit returns only
            # after its last checkpoint is PUBLISHED, and a publish failure
            # from the tail iteration surfaces here, never silently.
            with self.telemetry.span("descent.checkpoint.drain"):
                checkpointer.drain()
        return result

    def _run(
        self,
        num_iterations: int,
        initial_model: Optional[GameModel] = None,
        locked_coordinates: Sequence[str] = (),
        checkpoint_fn=None,
        checkpointer=None,
        resume_state: Optional[DescentState] = None,
        max_quarantined: Optional[int] = None,
        config_key: Optional[str] = None,
    ) -> DescentResult:
        locked = set(locked_coordinates)
        unknown = locked - set(self.coordinates)
        if unknown:
            raise KeyError(f"locked coordinates not in update sequence: {sorted(unknown)}")
        if locked and initial_model is None:
            raise ValueError("locked coordinates require an initial model")
        for name in locked:
            if initial_model is not None and name not in initial_model.coordinates:
                raise KeyError(f"locked coordinate {name!r} missing from initial model")

        models: Dict[str, object] = {}
        residuals = self._build_residuals()
        val_engine = val_cache = None
        if (self.validation_data is not None and self.evaluators is not None
                and self.validation_mode == "device"):
            val_engine, val_cache = self._build_validation()

        best_model: Optional[GameModel] = None
        best_metrics: Dict[str, float] = {}
        best_iteration = -1
        history = []
        start_iteration = 0
        quarantined_total = 0

        if resume_state is not None:
            from photon_tpu.fault.checkpoint import require_fingerprint

            require_fingerprint(
                resume_state,
                self._fingerprint(
                    config_key, locked=locked,
                    warm_start=initial_model is not None,
                ),
                "this descent",
            )
            with self.telemetry.span(
                "descent.resume", iteration=resume_state.iteration
            ):
                models = dict(resume_state.models)
                residuals.load_rows(resume_state.residual_rows)
                if val_engine is not None:
                    # The validation table is NOT snapshotted: re-scoring
                    # the restored models against the cached features is
                    # the same deterministic kernel an uninterrupted run
                    # used to fill these rows.
                    for name, model in models.items():
                        val_engine.update(name, val_cache.score(model))
                best_model = GameModel(
                    dict(resume_state.best_models), self.task_type
                )
                best_metrics = dict(resume_state.best_metrics)
                best_iteration = resume_state.best_iteration
                history = list(resume_state.history)
                quarantined_total = resume_state.quarantined
                start_iteration = resume_state.iteration + 1
            self.telemetry.counter("descent.resumes").inc()
            self.logger.info(
                "resumed descent after iteration %d", resume_state.iteration
            )
        elif initial_model is not None:
            for name, coord_model in initial_model.coordinates.items():
                if name not in self.coordinates:
                    continue
                models[name] = coord_model
                residuals.update(
                    name, self._score(self.coordinates[name], coord_model)
                )
                if val_engine is not None:
                    # Seed the validation score table: locked coordinates
                    # are never re-scored again (their rows are reused every
                    # iteration — validation.score_reuse counts them).
                    val_engine.update(name, val_cache.score(coord_model))
            # Kick the foreign-vocabulary warm-start key joins onto the io
            # pool NOW: the fixed effect usually trains first, and by the
            # time a random coordinate's train() needs its aligned table
            # the join has run beside that compute instead of blocking it.
            from photon_tpu.game.coordinate import prefetch_warm_joins

            prefetch_warm_joins(
                self.coordinates, initial_model, telemetry=self.telemetry
            )

        # Drain guard flags from the seeding/resume updates BEFORE the loop:
        # a rejected seed row belongs to the INITIAL model, not to whatever
        # trains first in iteration 0 (misattributing it would roll a good
        # trained iterate back to the bad initial model).  The rejected
        # row already kept its zero state, so dropping the model is the
        # whole fix-up.
        seed_rejected = set(residuals.poll_quarantined())
        if val_engine is not None:
            seed_rejected |= set(val_engine.poll_quarantined())
        bad_locked = sorted(seed_rejected & locked)
        if bad_locked:
            raise ValueError(
                f"locked coordinate(s) {bad_locked} produced non-finite "
                "scores from the initial model; a locked coordinate cannot "
                "be quarantined"
            )
        for name in sorted(seed_rejected):
            self.telemetry.counter(
                "descent.quarantined", coordinate=name, stage="seed"
            ).inc()
            quarantined_total += 1
            models.pop(name, None)
            self.logger.info(
                "coordinate %s: non-finite scores from the initial model "
                "quarantined (cold start instead)", name,
            )
        if max_quarantined is not None and quarantined_total > max_quarantined:
            raise QuarantineBudgetError(
                f"{quarantined_total} quarantined solves/score rows "
                f"exceed --max-quarantined {max_quarantined}"
            )

        if start_iteration >= num_iterations:
            # Resumed a completed descent: nothing left to run.
            last = GameModel(dict(models), self.task_type)
            return DescentResult(
                best_model=best_model if best_model is not None else last,
                last_model=last,
                best_metrics=best_metrics,
                history=history,
            )

        from photon_tpu.fault.preemption import (
            PreemptedError,
            consume_preempt_injection,
            preemption_requested,
            preemption_reason,
        )
        from photon_tpu.fault.watchdog import heartbeat

        telemetry = self.telemetry
        for it in range(start_iteration, num_iterations):
            # The preemption site fault injection exercises: between outer
            # iterations, where a killed run must restart from the last
            # published checkpoint.
            fault_point("descent:kill", iteration=it)
            # Preemption-aware shutdown: SIGTERM (or the injected `preempt`
            # site) lands here, at the iteration boundary where the
            # checkpoint state is consistent.  The previous iteration's
            # snapshot was already handed to the checkpointer — draining
            # forces that final save through synchronously, so the process
            # exits with its last completed iteration PUBLISHED (losing
            # zero completed work), then the driver maps PreemptedError to
            # the distinct preemption exit code.
            consume_preempt_injection(it)
            if preemption_requested():
                telemetry.counter("descent.preempted").inc()
                if checkpointer is not None and hasattr(checkpointer, "drain"):
                    with telemetry.span("descent.checkpoint.drain"):
                        checkpointer.drain()
                    self.logger.info(
                        "preempted (%s) before iteration %d: last completed "
                        "iteration's checkpoint published; exiting",
                        preemption_reason(), it,
                    )
                    hint = "resume with --resume auto"
                else:
                    # Be honest with the operator: nothing was saved, so
                    # the advertised recovery cannot be a resume.
                    hint = ("no checkpointer configured — a restart begins "
                            "from scratch (set --checkpoint-dir)")
                raise PreemptedError(
                    f"preempted ({preemption_reason()}) before iteration "
                    f"{it}; {hint}"
                )
            # Watchdog progress mark: one heartbeat per outer iteration
            # (a stalled heartbeat is how a hung run becomes visible).
            heartbeat("descent.iteration")
            coord_logs = {}
            trained = 0
            prev_iterates: Dict[str, object] = {}
            # Coordinates whose train() returned a device stats accumulator
            # (DeferredSolveStats): their telemetry/log/quarantine
            # accounting waits for the ONE boundary drain below.
            deferred: Dict[str, object] = {}
            with telemetry.span("descent.iteration", iteration=it) as iter_span:
                for name, coord in self.coordinates.items():
                    if name in locked:
                        continue
                    prev_iterates[name] = models.get(name)
                    offsets = residuals.offsets_for(name)
                    with self.logger.timed(f"iter{it}-{name}"):
                        model, info = coord.train(
                            offsets, initial_model=models.get(name)
                        )
                    models[name] = model
                    residuals.update(name, self._score(coord, model))
                    if val_engine is not None:
                        # Incremental re-score: ONLY the coordinate that
                        # just trained touches its validation score row.
                        val_engine.update(name, val_cache.score(model))
                    trained += 1
                    if isinstance(info, DeferredSolveStats):
                        deferred[name] = info
                    else:
                        q = _quarantine_count(info)
                        if q:
                            # Non-finite solves quarantined inside train():
                            # those buckets kept their previous iterate.
                            telemetry.counter(
                                "descent.quarantined", coordinate=name,
                                stage="solve",
                            ).inc(q)
                            quarantined_total += q
                    cache_bytes = getattr(
                        getattr(coord, "device_data", None),
                        "_score_cache_bytes", 0,
                    )
                    if cache_bytes:
                        # The device scoring path's cached feature/index
                        # residency (a second, replicated copy of the shard
                        # — see coordinate._scoring_feats): the memory side
                        # of the transfer trade, next to the engine's
                        # residuals.device_bytes.
                        telemetry.gauge(
                            "residuals.scoring_cache_bytes", coordinate=name
                        ).set(cache_bytes)
                    telemetry.counter(
                        "descent.coordinate_updates", coordinate=name
                    ).inc()
                    if name not in deferred:
                        _record_coordinate_info(telemetry, name, info)
                        summary = (
                            info.summary().splitlines()[0]
                            if hasattr(info, "summary")
                            else str(info)
                        )
                        coord_logs[name] = summary
                        self.logger.info(
                            "iter %d coordinate %s: %s", it, name, summary
                        )

                # THE one stats/quarantine host sync of the iteration: the
                # per-coordinate device stats accumulators and BOTH score
                # tables' non-finite guard flags come to host in a single
                # batched device_get (the seed paid one deferred sync per
                # coordinate train instead).  A rejected row means the
                # coordinate's fresh scores were poisoned even though its
                # solve looked fine: roll the model back to the previous
                # iterate (drop it entirely on a cold start) and re-sync
                # BOTH engines' rows to the rolled-back model, so
                # composite, residual offsets, validation rows, and any
                # checkpoint stay consistent.  A coordinate rejected by
                # both engines is ONE quarantine event.
                import jax as _jax

                res_flags = residuals.drain_guard_flags()
                val_flags = (
                    val_engine.drain_guard_flags()
                    if val_engine is not None else []
                )
                # host-sync: the sanctioned once-per-iteration stats/
                # quarantine drain (descent.host_syncs counts it).
                stats_host, res_ok, val_ok = _jax.device_get((
                    {name: ds.device for name, ds in deferred.items()},
                    [ok for _, ok in res_flags],
                    [ok for _, ok in val_flags],
                ))
                telemetry.counter("descent.host_syncs", kind="stats").inc()
                for name, ds in deferred.items():
                    info = ds.resolve(stats_host[name])
                    q = int(info.get("quarantined", 0))
                    if q:
                        telemetry.counter(
                            "descent.quarantined", coordinate=name,
                            stage="solve",
                        ).inc(q)
                        quarantined_total += q
                    _record_coordinate_info(telemetry, name, info)
                    coord_logs[name] = str(info)
                    self.logger.info(
                        "iter %d coordinate %s: %s", it, name, info
                    )
                rejected = {
                    name for (name, _), ok in zip(res_flags, res_ok)
                    if not bool(ok)
                }
                residuals.record_rejected(sorted(rejected))
                if val_engine is not None:
                    val_rejected = {
                        name for (name, _), ok in zip(val_flags, val_ok)
                        if not bool(ok)
                    }
                    val_engine.record_rejected(sorted(val_rejected))
                    rejected |= val_rejected
                bad_locked = sorted(rejected & locked)
                if bad_locked:
                    # A locked coordinate's scores come straight from the
                    # caller's initial model: quarantining it would silently
                    # drop the one coordinate the caller pinned.  Fail.
                    raise ValueError(
                        f"locked coordinate(s) {bad_locked} produced "
                        "non-finite scores from the initial model; a locked "
                        "coordinate cannot be quarantined"
                    )
                for name in sorted(rejected):
                    telemetry.counter(
                        "descent.quarantined", coordinate=name,
                        stage="score_row",
                    ).inc()
                    quarantined_total += 1
                    prev = prev_iterates.get(name)
                    if prev is not None:
                        models[name] = prev
                        residuals.update(
                            name, self._score(self.coordinates[name], prev)
                        )
                        if val_engine is not None:
                            val_engine.update(name, val_cache.score(prev))
                    else:
                        # No previous iterate: the coordinate leaves the
                        # composite entirely this iteration (zero rows ==
                        # absent coordinate), instead of keeping a model
                        # whose scores are non-finite.
                        models.pop(name, None)
                        residuals.update(
                            name,
                            np.zeros(
                                self.training_data.num_examples, np.float32
                            ),
                        )
                        if val_engine is not None:
                            val_engine.update(
                                name, np.zeros(val_cache.n, np.float32)
                            )
                    self.logger.info(
                        "iter %d coordinate %s: non-finite scores "
                        "quarantined (previous iterate kept)", it, name,
                    )
                if max_quarantined is not None and quarantined_total > max_quarantined:
                    raise QuarantineBudgetError(
                        f"{quarantined_total} quarantined solves/score rows "
                        f"exceed --max-quarantined {max_quarantined}"
                    )

                game_model = GameModel(dict(models), self.task_type)
                if checkpoint_fn is not None:
                    with telemetry.span("descent.checkpoint", iteration=it):
                        checkpoint_fn(it, game_model)
                with telemetry.span("descent.validate", iteration=it):
                    if val_engine is not None:
                        # Rows whose device scores were REUSED this
                        # iteration (locked / not-retrained coordinates):
                        # the host path re-scored every coordinate's margins
                        # each iteration regardless.
                        telemetry.counter("validation.score_reuse").inc(
                            (len(self.coordinates) - trained) * val_cache.n
                        )
                        metrics = self._evaluate_device(val_engine, val_cache)
                    else:
                        metrics = self._evaluate(game_model)
                if metrics:
                    self.logger.info("iter %d validation %s", it, metrics)
                    iter_span.set_attribute("metrics", metrics)
                    for k, v in metrics.items():
                        telemetry.gauge("descent.validation_metric", metric=k).set(v)
            telemetry.counter("descent.iterations").inc()
            history.append(
                {"iteration": it, "metrics": metrics, "coordinates": coord_logs}
            )

            if not metrics:
                best_model, best_metrics, best_iteration = game_model, metrics, it
            else:
                primary = self.evaluators.primary
                if best_model is None or primary.better_than(
                    metrics[primary.name], best_metrics[primary.name]
                ):
                    best_model, best_metrics, best_iteration = game_model, metrics, it

            if checkpointer is not None:
                # Async publishing: hand the checkpointer DEVICE row
                # handles — its staging step starts copy_to_host_async on
                # rows and model tables together and gathers once, instead
                # of the blocking per-table fetches the sync path keeps.
                rows = (
                    residuals.snapshot_rows_async()
                    if getattr(checkpointer, "async_publish", False)
                    else residuals.snapshot_rows()
                )
                state = DescentState(
                    iteration=it,
                    num_iterations=num_iterations,
                    task_type=self.task_type,
                    models=dict(models),
                    best_models=dict(best_model.coordinates),
                    best_metrics=dict(best_metrics),
                    best_iteration=best_iteration,
                    history=list(history),
                    residual_rows=rows,
                    quarantined=quarantined_total,
                    fingerprint=self._fingerprint(
                        config_key, locked=locked,
                        warm_start=initial_model is not None,
                    ),
                )
                with telemetry.span("descent.checkpoint.save", iteration=it):
                    checkpointer.save(state)

        assert best_model is not None
        return DescentResult(
            best_model=best_model,
            last_model=game_model,
            best_metrics=best_metrics,
            history=history,
        )
