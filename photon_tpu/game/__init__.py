"""GAME (Generalized Additive Mixed Effect) training engine.

TPU-native rebuild of the reference's photon-api layer: the GAME data
pipeline (``data.GameDatum``/``FixedEffectDataset``/``RandomEffectDataset``),
coordinates (``FixedEffectCoordinate``/``RandomEffectCoordinate``),
``CoordinateDescent``, GAME models, and ``GameEstimator`` — SURVEY.md §2.2.
"""

from photon_tpu.game.data import (
    DenseShard,
    EntityBucket,
    GameDataset,
    RandomEffectDataset,
    SparseShard,
    build_random_effect_dataset,
)
from photon_tpu.game.model import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_tpu.game.coordinate import (
    CoordinateConfig,
    FixedEffectCoordinate,
    FixedEffectCoordinateConfig,
    RandomEffectCoordinate,
    RandomEffectCoordinateConfig,
    build_coordinate,
)
from photon_tpu.game.descent import CoordinateDescent, DescentResult
from photon_tpu.game.estimator import GameEstimator, GameOptimizationConfiguration
from photon_tpu.game.tiles import (
    ChunkPlan,
    ChunkStreamer,
    TiledResidualTable,
    TiledValidationTable,
    chunk_rows_for_budget,
    resident_bytes_estimate,
)

__all__ = [
    "ChunkPlan",
    "ChunkStreamer",
    "TiledResidualTable",
    "TiledValidationTable",
    "chunk_rows_for_budget",
    "resident_bytes_estimate",
    "DenseShard",
    "SparseShard",
    "GameDataset",
    "EntityBucket",
    "RandomEffectDataset",
    "build_random_effect_dataset",
    "FixedEffectModel",
    "RandomEffectModel",
    "GameModel",
    "CoordinateConfig",
    "FixedEffectCoordinateConfig",
    "RandomEffectCoordinateConfig",
    "FixedEffectCoordinate",
    "RandomEffectCoordinate",
    "build_coordinate",
    "CoordinateDescent",
    "DescentResult",
    "GameEstimator",
    "GameOptimizationConfiguration",
]
