"""Disk-backed tile store: the third tier of the out-of-core hierarchy.

PR 10's out-of-core descent (:mod:`photon_tpu.game.tiles`) bounds DEVICE
memory but still pins every score tile and feature chunk in host RAM — one
tier short of the full memory hierarchy.  This module adds the disk tier
(Snap ML's argument, arXiv:1803.06333: the headline speed of out-of-core
GLM training comes from pipelining data across *all* tiers so the slowest
link is always overlapped): per-chunk **part files** hold a chunk's feature
block and its ``[C, rows_k]`` score tile + Neumaier partials, an LRU host
cache (:class:`photon_tpu.game.tiles.HostTileCache`) bounds the host-RAM
working set to ``--max-host-mb``, and the prefetch pipeline becomes
disk→host→device.

Part-file format (one self-describing container per chunk per role —
``feat-NNNNNN.pt`` is the immutable feature block written once at spill
time, ``tile-NNNNNN.pt`` the score tile + partials republished on every
dirty write-back; splitting the roles keeps a tile update from rewriting
the much larger feature payload):

    8 bytes   magic ``PHTILE01``
    8 bytes   header length (uint64 LE)
    header    JSON: per-array name/dtype/shape/encoding/offset +
              sha256 of the RAW (decoded) bytes, plus caller meta
    payload   concatenated encoded array bytes

Durability follows the PR 4 checkpoint contract: writes build a temp file
in the store directory, fsync, then publish with ONE atomic rename — a
kill at any instant leaves either the previous complete part file or the
new one, never a torn hybrid.  Reads verify every array's sha256 digest
after decode and refuse corruption loudly (:class:`CorruptTileError`,
deliberately NOT an ``OSError`` so the retry layer does not burn its
budget re-reading bit-rot).  All IO routes through
:func:`photon_tpu.fault.retry.retry_call` (sites ``tile:read`` /
``tile:write``): transient failures back off and retry, every attempt
heartbeats the run watchdog, and a configured ``--stall-timeout`` bounds
each attempt — the retry/timeout/backoff triangle covers the disk edge.

Optional compression (``PHOTON_TILE_COMPRESS=1``) trades CPU for disk
bandwidth: multi-byte arrays are delta-coded at their item width
(wraparound integer subtraction — exactly invertible), byte-shuffled so
high-order bytes group into runs, and zlib-deflated; an encoding that
fails to shrink falls back to raw per array.  Either way the roundtrip is
bit-exact — spilled and host-resident streamed runs produce identical
tiles, which the tests pin with ``np.array_equal``.

ISSUE 17 adds per-array LOSSY storage codecs next to the lossless
encodings: ``bf16`` (truncate f32 payloads to bfloat16) and ``int8``
(symmetric per-row absmax quantization — an f32 scale row rides beside
the int8 grid; see :mod:`photon_tpu.game.lowp`).  Three contracts keep
the lossy tiers as kill-safe as the exact one:

- **digests cover the ENCODED payload** (pre-zlib bytes — the bf16
  stream, or scale row + int8 grid), so a flipped bit in a scale row is
  caught BEFORE a decode could silently rescale a whole row
  (:class:`CorruptTileError`), and verify cost shrinks with the payload;
- **encoding is idempotent**: both codecs re-encode their own decode to
  identical bytes (bf16 by construction, int8 via
  :func:`~photon_tpu.game.lowp.quantize_int8_canonical`'s fixed point),
  so the write-through read-modify-write cycle never drifts and a
  kill→resume digest compare is exact per codec;
- **lossless fallback**: arrays a lossy codec cannot represent
  faithfully (non-f32, NaN/infinity payloads, a non-convergent int8
  quantization) are stored exact under the ``f32`` codec — per array,
  recorded in the header, transparent at read.
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
import threading
import zlib
from typing import Dict, Optional, Tuple

import numpy as np

from photon_tpu.telemetry import NULL_SESSION

MAGIC = b"PHTILE01"
COMPRESS_VAR = "PHOTON_TILE_COMPRESS"

# Store roles: one immutable feature block + one mutable score tile per
# chunk (see module docstring for why they are separate part files).
FEATURES = "feat"
TILES = "tile"


class CorruptTileError(RuntimeError):
    """A part file failed digest verification (or is structurally torn).

    NOT an ``OSError``: retrying a read cannot heal bit-rot, so the retry
    layer must surface this immediately instead of spending its budget."""


def _dtype_token(dtype: np.dtype) -> str:
    """Serializable dtype identity.  ``dtype.str`` alone loses extension
    dtypes — ml_dtypes.bfloat16 stringifies as the opaque void ``'<V2'``
    (and ``np.dtype('<V2')`` round-trips to a JAX-rejected void array) —
    so extension dtypes are stored by NAME and resolved through
    ml_dtypes at read."""
    s = np.dtype(dtype).str
    if s.endswith(("V2", "V1")) or s.startswith(("|V", "<V", ">V")):
        return f"name:{np.dtype(dtype).name}"
    return s


def _resolve_dtype(token: str) -> np.dtype:
    if token.startswith("name:"):
        name = token[5:]
        try:
            return np.dtype(name)
        except TypeError:
            import ml_dtypes

            return np.dtype(getattr(ml_dtypes, name))
    return np.dtype(token)


def compress_enabled(override: Optional[bool] = None) -> bool:
    """Resolve the ``PHOTON_TILE_COMPRESS`` gate (default off: score tiles
    and feature chunks are usually incompressible-ish f32 noise on CPU
    fixtures; real column streams with locality are where the CPU-for-
    bandwidth trade wins)."""
    if override is not None:
        return bool(override)
    return os.environ.get(COMPRESS_VAR, "").strip().lower() in (
        "1", "on", "true", "shuffle", "delta",
    )


# ---------------------------------------------------------------------------
# Array codec: raw | dsz (delta + byte-shuffle + zlib), bit-exact roundtrip,
# plus the lossy bf16 / int8+scale storage codecs layered above (ISSUE 17)
# ---------------------------------------------------------------------------

LOSSY_CODECS = ("bf16", "int8")


def _lossy_payload(arr: np.ndarray, codec: str) -> Optional[bytes]:
    """Encoded-domain payload of one array under a lossy codec, or
    ``None`` when the array must fall back to lossless storage: non-f32
    or 0-d arrays, NaN/infinity payloads (neither codec represents them
    — bf16 keeps NaN but absmax quantization cannot, and the fallback
    keeps the two codecs' contracts identical), or an int8 quantization
    that failed to reach its re-encode fixed point (never observed; the
    guard exists so idempotence is a checked property, not a hope)."""
    if arr.dtype != np.float32 or arr.ndim == 0 or arr.size == 0:
        return None
    if not np.isfinite(arr).all():
        return None
    if codec == "bf16":
        from photon_tpu.game.lowp import encode_bf16

        return np.ascontiguousarray(encode_bf16(arr)).tobytes()
    if codec == "int8":
        from photon_tpu.game.lowp import quantize_int8_canonical

        q, scale, converged = quantize_int8_canonical(arr)
        if not converged:
            return None
        # Scale row first: the decoder's split point is computable from
        # the header shape alone (float32 scale of shape[:-1], then the
        # int8 grid of the full shape).
        return np.ascontiguousarray(scale).tobytes() + q.tobytes()
    raise ValueError(f"unknown lossy codec {codec!r}")


def _lossy_decode(
    raw: bytes, codec: str, dtype: np.dtype, shape: tuple
) -> np.ndarray:
    """f32 decode of a lossy payload.  Size/shape disagreements are
    corruption (same contract as a digest mismatch)."""
    if np.dtype(dtype) != np.float32:
        raise CorruptTileError(
            f"lossy codec {codec!r} on non-f32 dtype {dtype!r}"
        )
    n = int(np.prod(shape, dtype=np.int64)) if shape else 1
    if codec == "bf16":
        from photon_tpu.game.lowp import bf16_dtype, decode_bf16

        if len(raw) != 2 * n:
            raise CorruptTileError(
                f"bf16 payload is {len(raw)} bytes, want {2 * n}"
            )
        return decode_bf16(
            np.frombuffer(raw, dtype=bf16_dtype()).reshape(shape)
        )
    if codec == "int8":
        from photon_tpu.game.lowp import dequantize_int8_rows

        scale_shape = tuple(shape[:-1])
        scale_n = int(np.prod(scale_shape, dtype=np.int64)) if scale_shape else 1
        if len(raw) != 4 * scale_n + n:
            raise CorruptTileError(
                f"int8 payload is {len(raw)} bytes, want {4 * scale_n + n}"
            )
        scale = np.frombuffer(
            raw[: 4 * scale_n], np.float32
        ).reshape(scale_shape)
        q = np.frombuffer(raw[4 * scale_n:], np.int8).reshape(shape)
        # dequantize allocates fresh f32 output — writable, like every
        # other decode path (cached tiles are mutated in place).
        return dequantize_int8_rows(q, scale)
    raise CorruptTileError(f"unknown array codec {codec!r}")


def codec_roundtrip(arr: np.ndarray, codec: Optional[str]) -> np.ndarray:
    """``arr`` as it will decode back from disk under ``codec`` — what the
    write-through publish path rounds a tile through BEFORE deriving
    partials, digests, and the cached copy, so memory and disk agree bit
    for bit (including when the codec falls back to lossless)."""
    arr = np.ascontiguousarray(arr)
    if codec in (None, "f32"):
        return arr
    payload = _lossy_payload(arr, codec)
    if payload is None:
        return arr  # lossless fallback: disk stores the exact bytes
    return _lossy_decode(payload, codec, arr.dtype, arr.shape)


def _encode(arr: np.ndarray, compress: bool) -> Tuple[bytes, str]:
    raw = arr.tobytes()  # C-order flat item stream
    if not compress or arr.size == 0:
        return raw, "raw"
    itemsize = arr.dtype.itemsize
    if itemsize in (2, 4, 8):
        flat = np.frombuffer(raw, dtype=np.dtype(f"<u{itemsize}"))
        delta = np.empty_like(flat)
        delta[0] = flat[0]
        # Wraparound unsigned subtraction: exactly invertible by cumsum
        # at the same width, no overflow UB.
        np.subtract(flat[1:], flat[:-1], out=delta[1:])
        shuffled = (
            delta.view(np.uint8).reshape(-1, itemsize).T.copy().tobytes()
        )
        encoding = "dsz"
    else:
        shuffled = raw
        encoding = "z"
    packed = zlib.compress(shuffled, 1)
    if len(packed) >= len(raw):
        return raw, "raw"  # incompressible: raw is strictly better
    return packed, encoding


def _decode(
    buf: bytes, dtype: np.dtype, shape: tuple, encoding: str
) -> np.ndarray:
    if encoding == "raw":
        # frombuffer is read-only; copy so cached arrays are writable
        # (score tiles are mutated in place by row updates).
        return np.frombuffer(buf, dtype=dtype).reshape(shape).copy()
    raw = zlib.decompress(buf)
    if encoding == "z":
        return np.frombuffer(raw, dtype=dtype).reshape(shape).copy()
    if encoding != "dsz":
        raise CorruptTileError(f"unknown array encoding {encoding!r}")
    itemsize = np.dtype(dtype).itemsize
    width = np.dtype(f"<u{itemsize}")
    shuffled = np.frombuffer(raw, dtype=np.uint8)
    delta = np.ascontiguousarray(
        shuffled.reshape(itemsize, -1).T
    ).view(width)
    flat = np.cumsum(delta, dtype=width)  # wraparound inverse of the delta
    return flat.view(np.uint8).view(dtype).reshape(shape).copy()


# ---------------------------------------------------------------------------
# Part-file container
# ---------------------------------------------------------------------------


def _pack(
    arrays: Dict[str, np.ndarray],
    meta: dict,
    compress: bool,
    digests: Optional[Dict[str, str]] = None,
    codecs: Optional[Dict[str, str]] = None,
) -> bytes:
    """``digests`` lets a caller that already hashed an array's raw bytes
    (sha256 of ``arr.tobytes()``) pass the hex digest in instead of
    paying a second tile-sized hash here — the write-through publish path
    hashes every tile for its checkpoint digest anyway.  ``codecs`` maps
    array names to a lossy storage codec (``bf16``/``int8``); lossy
    entries hash the ENCODED payload instead (the header's ``codec``
    field doubles as the digest-domain marker) and ignore caller
    digests, which are raw-domain by contract."""
    entries = []
    payloads = []
    offset = 0
    digests = digests or {}
    codecs = codecs or {}
    for name, arr in arrays.items():
        arr = np.ascontiguousarray(arr)
        codec = codecs.get(name) or "f32"
        payload = _lossy_payload(arr, codec) if codec != "f32" else None
        if payload is None:
            codec = "f32"  # lossless (or fell back to it)
            buf, encoding = _encode(arr, compress)
            sha = (
                digests.get(name)
                or hashlib.sha256(arr.tobytes()).hexdigest()
            )
        else:
            sha = hashlib.sha256(payload).hexdigest()
            buf, encoding = payload, "raw"
            if compress:
                # Lossy payloads skip the delta/shuffle stage (a mixed
                # scale+grid byte stream has no single item width) —
                # plain zlib or nothing.
                packed = zlib.compress(payload, 1)
                if len(packed) < len(payload):
                    buf, encoding = packed, "z"
        entries.append({
            "name": name,
            "dtype": _dtype_token(arr.dtype),
            "shape": list(arr.shape),
            "encoding": encoding,
            "codec": codec,
            "offset": offset,
            "nbytes": len(buf),
            "sha256": sha,
        })
        payloads.append(buf)
        offset += len(buf)
    header = json.dumps(
        {"version": 1, "arrays": entries, "meta": meta or {}}
    ).encode()
    return b"".join(
        [MAGIC, struct.pack("<Q", len(header)), header, *payloads]
    )


def _read_header(f) -> dict:
    magic = f.read(len(MAGIC))
    if magic != MAGIC:
        raise CorruptTileError(
            f"bad part-file magic {magic!r} (torn or foreign file)"
        )
    raw_len = f.read(8)
    if len(raw_len) != 8:
        raise CorruptTileError(
            "part file truncated inside the header length field"
        )
    (hlen,) = struct.unpack("<Q", raw_len)
    try:
        return json.loads(f.read(hlen).decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise CorruptTileError(f"unreadable part-file header: {e}") from None


def _unpack(
    path: str, verify: bool = True, names=None
) -> Tuple[Dict[str, np.ndarray], dict]:
    """Decode a part file (optionally only the arrays in ``names`` — the
    header carries per-array offsets, so a selective read never touches
    the skipped payloads' bytes)."""
    with open(path, "rb") as f:
        header = _read_header(f)
        base = f.tell()
        arrays: Dict[str, np.ndarray] = {}
        for entry in header["arrays"]:
            if names is not None and entry["name"] not in names:
                continue
            f.seek(base + entry["offset"])
            buf = f.read(entry["nbytes"])
            if len(buf) != entry["nbytes"]:
                raise CorruptTileError(
                    f"{path}: truncated payload for {entry['name']!r}"
                )
            codec = entry.get("codec", "f32")
            if codec != "f32":
                # Lossy entry: unwrap optional zlib, verify the digest
                # over the ENCODED payload BEFORE decoding — a corrupt
                # scale row is refused before it could rescale anything.
                try:
                    raw = (
                        zlib.decompress(buf)
                        if entry["encoding"] == "z" else buf
                    )
                    if entry["encoding"] not in ("raw", "z"):
                        raise ValueError(
                            f"encoding {entry['encoding']!r} invalid "
                            f"for codec {codec!r}"
                        )
                except (zlib.error, ValueError) as e:
                    raise CorruptTileError(
                        f"{path}: undecodable payload for "
                        f"{entry['name']!r} ({e}); on-disk tile corrupted"
                    ) from None
                if verify:
                    digest = hashlib.sha256(raw).hexdigest()
                    if digest != entry["sha256"]:
                        raise CorruptTileError(
                            f"{path}: content digest mismatch in "
                            f"{entry['name']!r} ({codec} payload — e.g. "
                            "a corrupt scale row); refusing the read"
                        )
                arrays[entry["name"]] = _lossy_decode(
                    raw, codec, _resolve_dtype(entry["dtype"]),
                    tuple(entry["shape"]),
                )
                continue
            try:
                arr = _decode(
                    buf, _resolve_dtype(entry["dtype"]),
                    tuple(entry["shape"]), entry["encoding"],
                )
            except (zlib.error, ValueError, TypeError) as e:
                # A flipped bit in a compressed payload surfaces as
                # zlib.error, a header/payload size disagreement as
                # ValueError — corruption either way, same contract as a
                # digest mismatch (NOT retriable).
                raise CorruptTileError(
                    f"{path}: undecodable payload for {entry['name']!r} "
                    f"({e}); on-disk tile corrupted"
                ) from None
            if verify:
                digest = hashlib.sha256(arr.tobytes()).hexdigest()
                if digest != entry["sha256"]:
                    raise CorruptTileError(
                        f"{path}: content digest mismatch in "
                        f"{entry['name']!r} (on-disk tile corrupted); "
                        "refusing the read"
                    )
            arrays[entry["name"]] = arr
    return arrays, header.get("meta", {})


class TileStore:
    """The disk tier: per-chunk part files under one directory, with
    atomic publish, digest-verified reads, guarded/retried IO, and
    ``tiles.disk_bytes`` accounting.

    Thread safety: reads and writes of DISTINCT (kind, chunk) part files
    may run concurrently (io-pool prefetch workers vs the write-back on
    the descent thread); the byte accounting is lock-protected.  Two
    concurrent writers of the SAME part file are last-publish-wins — the
    streamed descent never does that (tile write-back is serial on the
    descent thread).
    """

    def __init__(
        self, root: str, telemetry=None, compress: Optional[bool] = None,
        tile_dtype: Optional[str] = None,
    ):
        from photon_tpu.game.lowp import TILE_DTYPES, check_dtype

        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.telemetry = telemetry or NULL_SESSION
        self.compress = compress_enabled(compress)
        # The store's default storage codec for lossy-eligible arrays
        # (feature blocks, score tiles).  Callers choose WHICH arrays are
        # eligible per write (indices, labels, and partials always stay
        # exact); the store only carries the tier choice.
        self.tile_dtype = check_dtype(tile_dtype, TILE_DTYPES, "tile dtype")
        self._lock = threading.Lock()
        self._file_bytes: Dict[str, int] = {}
        for name in os.listdir(self.root):
            if name.endswith(".pt"):
                try:
                    self._file_bytes[name] = os.path.getsize(
                        os.path.join(self.root, name)
                    )
                except OSError:
                    continue
        self._publish_bytes_gauge()

    # -- paths / accounting ---------------------------------------------------
    def path(self, kind: str, k: int) -> str:
        return os.path.join(self.root, f"{kind}-{int(k):06d}.pt")

    def has(self, kind: str, k: int) -> bool:
        return os.path.isfile(self.path(kind, k))

    @property
    def disk_bytes(self) -> int:
        with self._lock:
            return sum(self._file_bytes.values())

    def _note_file(self, name: str, nbytes: Optional[int]) -> None:
        with self._lock:
            if nbytes is None:
                self._file_bytes.pop(name, None)
            else:
                self._file_bytes[name] = nbytes
        self._publish_bytes_gauge()

    def _publish_bytes_gauge(self) -> None:
        self.telemetry.gauge("tiles.disk_bytes").set(self.disk_bytes)

    def lossy_codecs(self, names) -> Dict[str, str]:
        """Per-array ``codecs`` dict applying the store's tier to
        ``names`` (empty at f32 — the exact tier's writes are unchanged
        byte for byte)."""
        if self.tile_dtype == "f32":
            return {}
        return {str(name): self.tile_dtype for name in names}

    # -- guarded IO -----------------------------------------------------------
    def write(
        self, kind: str, k: int, arrays: Dict[str, np.ndarray],
        meta: Optional[dict] = None,
        digests: Optional[Dict[str, str]] = None,
        codecs: Optional[Dict[str, str]] = None,
    ) -> None:
        """Publish one part file atomically (temp + fsync + rename).  The
        whole attempt — serialize, write, publish — retries as a unit
        under the ``tile:write`` site, so an injected/transient failure
        anywhere in the sequence costs backoff, not the run.  ``digests``
        forwards caller-precomputed raw-byte sha256 hexes to the header;
        ``codecs`` maps array names to a lossy storage codec (see
        :func:`_pack`)."""
        from photon_tpu.fault.atomic import atomic_write_bytes
        from photon_tpu.fault.injection import fault_point
        from photon_tpu.fault.retry import retry_call

        final = self.path(kind, k)
        blob = _pack(arrays, meta, self.compress, digests=digests,
                     codecs=codecs)

        def attempt():
            fault_point("tile:write", kind=kind, chunk=k)
            # The PR 4 publication protocol verbatim (temp + fsync +
            # rename + parent-dir fsync), so a completed tile publish
            # survives power loss exactly like a checkpoint does.
            atomic_write_bytes(final, blob)

        retry_call(attempt, site="tile:write", telemetry=self.telemetry)
        self.telemetry.counter("tiles.store_writes", kind=kind).inc()
        self.telemetry.counter(
            "tiles.store_write_bytes", kind=kind
        ).inc(len(blob))
        self._note_file(os.path.basename(final), len(blob))

    def read(
        self, kind: str, k: int, verify: bool = True, names=None
    ) -> Tuple[Dict[str, np.ndarray], dict]:
        """Load one part file's arrays + meta, digest-verified.  With
        ``names``, decode only those arrays (the header's per-array
        offsets make the skipped payloads free).  Transient failures
        retry (``tile:read``); corruption raises
        :class:`CorruptTileError` immediately."""
        from photon_tpu.fault.injection import fault_point
        from photon_tpu.fault.retry import retry_call

        path = self.path(kind, k)

        def attempt():
            fault_point("tile:read", kind=kind, chunk=k)
            return _unpack(path, verify=verify, names=names)

        arrays, meta = retry_call(
            attempt, site="tile:read", telemetry=self.telemetry
        )
        self.telemetry.counter("tiles.store_reads", kind=kind).inc()
        return arrays, meta

    def read_meta(self, kind: str, k: int) -> dict:
        """Header-only read (no payload decode) — the cheap digest probe
        the resume path uses to adopt on-disk tiles."""
        from photon_tpu.fault.injection import fault_point
        from photon_tpu.fault.retry import retry_call

        path = self.path(kind, k)

        def attempt():
            fault_point("tile:read", kind=kind, chunk=k)
            with open(path, "rb") as f:
                return _read_header(f).get("meta", {})

        return retry_call(attempt, site="tile:read", telemetry=self.telemetry)

    def delete(self, kind: str, k: int) -> None:
        path = self.path(kind, k)
        try:
            os.remove(path)
        except FileNotFoundError:
            pass
        self._note_file(os.path.basename(path), None)

    def reset_tiles(self, num_chunks: int, kind: str = TILES) -> None:
        """Drop every score-tile part file of ``kind`` (fresh, non-resume
        runs must not read a previous run's tiles as their zero state)."""
        for k in range(num_chunks):
            self.delete(kind, k)

    def reset_all(self) -> None:
        """Drop EVERY part file + the dataset identity — the foreign/
        stale-spill-dir reset (a different dataset or chunk plan may have
        published under chunk ids the new plan never touches)."""
        for name in list(os.listdir(self.root)):
            if name.endswith(".pt"):
                try:
                    os.remove(os.path.join(self.root, name))
                except OSError:
                    pass
                self._note_file(name, None)
        try:
            os.remove(self.dataset_meta_path())
        except OSError:
            pass

    # -- dataset identity -----------------------------------------------------
    _DATASET_META = "dataset.json"

    def dataset_meta_path(self) -> str:
        return os.path.join(self.root, self._DATASET_META)

    def read_dataset_meta(self) -> Optional[dict]:
        # Deliberately lenient: a missing/unreadable identity file simply
        # means "not this dataset" and triggers a fresh spill.
        try:
            with open(self.dataset_meta_path()) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def write_dataset_meta(self, meta: dict) -> None:
        from photon_tpu.fault.atomic import atomic_write_json
        from photon_tpu.fault.retry import retry_call

        retry_call(
            lambda: atomic_write_json(self.dataset_meta_path(), meta),
            site="tile:write", telemetry=self.telemetry,
        )
