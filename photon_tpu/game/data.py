"""GAME data pipeline: feature shards, entity grouping, bucketed datasets.

Rebuild of the reference's GAME data layer (photon-api .../data:
``GameDatum``, ``FixedEffectDataset``, ``RandomEffectDataset``,
``LocalDataset``, ``RandomEffectDatasetPartitioner`` — SURVEY.md §2.2).  The
reference builds an ``RDD[(UniqueSampleId, GameDatum)]`` then, per random
effect, SHUFFLES rows into per-entity groups spread over executors; each
entity's rows become a ``LocalDataset`` solved independently.

On TPU the same structure becomes static arrays (SURVEY.md §2.6: "the
entity-grouping shuffle becomes a one-time host-side bucketing"):

- A :class:`GameDataset` is columnar host-side storage — one row per example
  (the unique-sample-id order IS the row index), per-coordinate **feature
  shards** (dense ``[n, d]`` or padded-sparse ``[n, k]`` blocks), and raw
  entity-id columns.
- A :class:`RandomEffectDataset` groups rows by entity **once** and packs
  entities into power-of-two row-count **buckets**: each bucket is a dense
  ``[E, R, ...]`` block where every entity has exactly ``R`` (padded) rows.
  Buckets keep XLA shapes static while bounding padding waste to 2x on the
  skewed per-entity row-count distribution (SURVEY.md §7 'hard parts':
  ragged per-entity data under vmap).
- The reference's active/passive split (``numActiveDataPointsUpperBound``)
  becomes an ``active_row_cap``: entities over the cap train on a seeded
  subsample with weights scaled by ``count/cap`` (unbiased objective), while
  scoring still covers every row via :meth:`RandomEffectDataset.entity_index_for`.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, NamedTuple, Optional, Union

import numpy as np

Float = np.float32


class DenseShard(NamedTuple):
    """A feature shard stored dense: ``x[i]`` is row i's feature vector."""

    x: np.ndarray  # [n, d] float32

    @property
    def dim(self) -> int:
        return self.x.shape[1]


class SparseShard(NamedTuple):
    """A feature shard in padded-COO layout (see data.batch.SparseBatch)."""

    ids: np.ndarray  # [n, k] int32
    vals: np.ndarray  # [n, k] float32
    dim_: int

    @property
    def dim(self) -> int:
        return self.dim_


Shard = Union[DenseShard, SparseShard]


def _gather_shard_rows(shard: Shard, row_index: np.ndarray) -> Shard:
    """Index a shard's per-row arrays with an arbitrary-shape row index."""
    if isinstance(shard, DenseShard):
        return DenseShard(shard.x[row_index])
    return SparseShard(shard.ids[row_index], shard.vals[row_index], shard.dim_)


@dataclasses.dataclass(frozen=True)
class GameDataset:
    """Columnar GAME training/scoring data (host side).

    The row index plays the reference's ``UniqueSampleId`` role: scores,
    offsets, and labels all align on it.
    """

    label: np.ndarray  # [n] float32
    offset: np.ndarray  # [n] float32
    weight: np.ndarray  # [n] float32
    shards: Dict[str, Shard]
    id_columns: Dict[str, np.ndarray]  # raw per-row entity keys

    def __post_init__(self):
        n = self.num_examples
        for name, col in self.id_columns.items():
            if len(col) != n:
                raise ValueError(f"id column {name!r} has {len(col)} rows, want {n}")
        for name, shard in self.shards.items():
            rows = shard.x.shape[0] if isinstance(shard, DenseShard) else shard.ids.shape[0]
            if rows != n:
                raise ValueError(f"feature shard {name!r} has {rows} rows, want {n}")

    @property
    def num_examples(self) -> int:
        return len(self.label)

    def shard(self, name: str) -> Shard:
        if name not in self.shards:
            raise KeyError(
                f"unknown feature shard {name!r}; available: {sorted(self.shards)}"
            )
        return self.shards[name]

    @classmethod
    def create(
        cls,
        label: np.ndarray,
        shards: Dict[str, Shard],
        id_columns: Optional[Dict[str, np.ndarray]] = None,
        offset: Optional[np.ndarray] = None,
        weight: Optional[np.ndarray] = None,
    ) -> "GameDataset":
        n = len(label)
        return cls(
            label=np.asarray(label, Float),
            offset=np.zeros(n, Float) if offset is None else np.asarray(offset, Float),
            weight=np.ones(n, Float) if weight is None else np.asarray(weight, Float),
            shards=dict(shards),
            id_columns={} if id_columns is None else dict(id_columns),
        )


def dataset_astype(data: GameDataset, dtype) -> GameDataset:
    """Re-store every shard's FEATURE VALUES in ``dtype`` (e.g. bfloat16).

    The GAME counterpart of :func:`photon_tpu.data.batch.batch_astype`:
    labels, offsets, weights, and all arithmetic stay float32 (JAX type
    promotion); only the stored value stream shrinks, halving the HBM
    traffic of every per-coordinate gather on TPU.
    """
    import ml_dtypes  # noqa: F401 — registers bfloat16 with numpy

    np_dtype = np.dtype(dtype)
    shards = {}
    for name, shard in data.shards.items():
        if isinstance(shard, DenseShard):
            shards[name] = DenseShard(shard.x.astype(np_dtype))
        else:
            shards[name] = SparseShard(
                shard.ids, shard.vals.astype(np_dtype), shard.dim_
            )
    return dataclasses.replace(data, shards=shards)


def take_rows(data: GameDataset, rows: np.ndarray) -> GameDataset:
    """Row-subset view of a GameDataset (train/validation splits)."""
    return GameDataset(
        label=data.label[rows],
        offset=data.offset[rows],
        weight=data.weight[rows],
        shards={n: _gather_shard_rows(s, rows) for n, s in data.shards.items()},
        id_columns={n: c[rows] for n, c in data.id_columns.items()},
    )


def split_game_dataset(
    data: GameDataset, validation_fraction: float, seed: int = 0
) -> tuple[GameDataset, GameDataset]:
    """Random train/validation row split (the reference takes a separate
    validation path; a fraction split covers single-file workflows)."""
    if not 0.0 < validation_fraction < 1.0:
        raise ValueError("validation_fraction must be in (0, 1)")
    n = data.num_examples
    if n < 2:
        raise ValueError("cannot split a dataset with fewer than 2 rows")
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n)
    n_val = min(n - 1, max(1, int(round(n * validation_fraction))))
    val_rows = np.sort(perm[:n_val])
    train_rows = np.sort(perm[n_val:])
    return take_rows(data, train_rows), take_rows(data, val_rows)


@dataclasses.dataclass(frozen=True)
class EntityBucket:
    """One row-capacity cohort of a random-effect dataset.

    Every entity in the bucket owns exactly ``row_capacity`` (padded) rows.
    Padded rows carry ``weight == 0`` (invisible to objectives); their
    ``row_index`` points at row 0, which is safe because weight masks them.
    """

    row_capacity: int
    entity_index: np.ndarray  # [E] int32 — global entity index
    row_index: np.ndarray  # [E, R] int64 — original dataset row
    row_weight: np.ndarray  # [E, R] float32 — 0 on padding; includes cap correction
    label: np.ndarray  # [E, R] float32
    features: Shard  # x: [E, R, d]  or  ids/vals: [E, R, k]

    @property
    def num_entities(self) -> int:
        return len(self.entity_index)


@dataclasses.dataclass(frozen=True)
class RandomEffectDataset:
    """Per-entity training data for one random-effect coordinate.

    ``keys`` is the sorted entity vocabulary; a global entity index is its
    position in ``keys``.  ``entity_idx_per_row`` maps every dataset row to
    its entity index (the scoring-side join the reference does with a
    shuffle).
    """

    entity_column: str
    shard_name: str
    dim: int
    keys: np.ndarray  # [num_entities] sorted unique entity keys
    buckets: tuple[EntityBucket, ...]
    entity_idx_per_row: np.ndarray  # [n] int32

    @property
    def num_entities(self) -> int:
        return len(self.keys)

    def entity_index_for(self, raw_keys: np.ndarray) -> np.ndarray:
        """Map raw entity keys to this dataset's entity indices (-1 = unseen).

        The scoring-time equivalent of the reference's data-model JOIN by
        entity id (SURVEY.md §3.3): unseen entities score zero from this
        coordinate.
        """
        return entity_index_for(raw_keys, self.keys)


def entity_index_for(raw_keys: np.ndarray, vocab_keys: np.ndarray) -> np.ndarray:
    """Vectorized key→index lookup against a sorted vocabulary; -1 = missing.

    Raw keys are coerced to the vocabulary's dtype kind first: Avro id
    columns arrive as strings while a saved model's entity keys may have been
    restored as integers (game.model_io), and comparing across kinds would
    silently match nothing.
    """
    raw = np.asarray(raw_keys)
    if len(vocab_keys) and len(raw) and raw.dtype.kind != vocab_keys.dtype.kind:
        if vocab_keys.dtype.kind in "iu" and raw.dtype.kind in "US":
            try:
                raw = raw.astype(np.int64)
            except (ValueError, OverflowError) as e:
                raise ValueError(
                    "entity id column holds strings that are not valid int64 "
                    "values but the vocabulary is integer-typed"
                ) from e
        else:
            # astype(str) keeps each value's natural width; casting to the
            # vocabulary's fixed-width dtype would truncate longer keys into
            # false matches.
            raw = raw.astype(str)
    pos = np.searchsorted(vocab_keys, raw)
    pos = np.clip(pos, 0, len(vocab_keys) - 1)
    found = vocab_keys[pos] == raw if len(vocab_keys) else np.zeros(len(raw), bool)
    return np.where(found, pos, -1).astype(np.int32)


#: Missing-id marker for int64 entity columns (the common Avro id dtype;
#: string columns use "", narrower int columns use their OWN dtype's min —
#: ``missing_key`` resolves per dtype, so the marker can never wrap to a
#: valid id on a narrow column).
MISSING_INT64 = np.int64(np.iinfo(np.int64).min)


def missing_key(dtype):
    """The missing-id fill value for an entity column of ``dtype``: the
    dtype's OWN minimum for signed ints (int64 -> :data:`MISSING_INT64`),
    its maximum for unsigned ints (0 is a real id), "" for strings."""
    dt = np.dtype(dtype)
    if dt.kind == "i":
        return dt.type(np.iinfo(dt).min)
    if dt.kind == "u":
        return dt.type(np.iinfo(dt).max)
    return ""


def missing_mask(values: np.ndarray) -> np.ndarray:
    """Bool mask of rows carrying the missing-id marker (the marker is
    dtype-relative — see :func:`missing_key`)."""
    # host-sync: id columns are host numpy by construction (ingest side).
    v = np.asarray(values)
    if len(v) == 0:
        return np.zeros(0, bool)
    if v.dtype.kind in "iu":
        return v == missing_key(v.dtype)
    return v == ""


def keys_match(keys, ref, ref_array: Optional[np.ndarray] = None) -> bool:
    """Is ``keys`` the same vocabulary as ``ref``?  Identity first — a model
    trained in THIS run carries the dataset's own keys object, so the O(E)
    host value compare runs only for foreign vocabularies (warm starts
    loaded from disk).  ``ref_array`` is ``ref`` pre-coerced to numpy when
    the caller caches it."""
    if keys is ref:
        return True
    return np.array_equal(
        np.asarray(keys), ref if ref_array is None else ref_array
    )


def build_random_effect_dataset(
    data: GameDataset,
    entity_column: str,
    shard_name: str,
    active_row_cap: Optional[int] = None,
    seed: int = 0,
    vocab: Optional[np.ndarray] = None,
    missing_marker="auto",
) -> RandomEffectDataset:
    """Group rows by entity and pack them into row-capacity buckets.

    This is the one-time host-side replacement for the reference's
    ``RandomEffectDataset`` build (groupByKey + partitionBy shuffle —
    SURVEY.md §2.6).  ``vocab`` pins the entity vocabulary (e.g. when
    bucketing validation data against a training vocabulary); by default the
    vocabulary is the sorted unique keys present in ``data``.

    ``missing_marker`` keeps missing-id rows OUT of the vocabulary: rows
    carrying the marker map to per-row entity index -1 (zero margin, no
    bin membership) instead of materializing a marker "entity" that trains
    its own random effect.  ``"auto"`` resolves the dtype-relative marker
    via :func:`missing_key` — the value ``merge_append`` fills when an
    append batch omits the id column — so a cold rebuild over a merged
    dataset reproduces the incremental path's semantics.  Pass ``None``
    to disable, or an explicit value to override.
    """
    if entity_column not in data.id_columns:
        raise KeyError(
            f"unknown id column {entity_column!r}; available: "
            f"{sorted(data.id_columns)}"
        )
    shard = data.shard(shard_name)
    raw = data.id_columns[entity_column]

    if isinstance(missing_marker, str) and missing_marker == "auto":
        marker = missing_key(raw.dtype) if raw.dtype.kind in "iuUS" else None
    else:
        marker = missing_marker

    if vocab is None:
        keys = np.unique(raw)
        if marker is not None:
            try:
                keys = keys[keys != keys.dtype.type(marker)]
            except (ValueError, OverflowError, TypeError):
                pass  # marker not representable in this dtype: nothing to drop
    else:
        # entity_index_for requires a sorted unique vocabulary; normalize the
        # caller's array (index = position in the SORTED keys, everywhere).
        keys = np.unique(np.asarray(vocab))
    entity_idx_per_row = entity_index_for(raw, keys)

    # Group row indices by entity (stable order = original row order).
    present = entity_idx_per_row >= 0
    order = np.argsort(entity_idx_per_row[present], kind="stable")
    rows_in_order = np.nonzero(present)[0][order]
    counts = np.bincount(entity_idx_per_row[present], minlength=len(keys))
    starts = np.concatenate([[0], np.cumsum(counts)])

    rng = np.random.default_rng(seed)
    # Per-entity kept rows: an index into rows_in_order for the common
    # (uncapped) case, so the cohort assembly below can gather VECTORIZED
    # over all entities of a capacity at once — the Python-loop-per-entity
    # build capped entity counts in the tens of thousands.  Only entities
    # OVER the active-row cap take the per-entity subsample path (seeded
    # draws in entity order, byte-identical to the historical loop).
    kept_counts = counts.copy()
    capped_rows: Dict[int, np.ndarray] = {}
    if active_row_cap is not None:
        for e in np.nonzero(counts > active_row_cap)[0]:
            entity_rows = rows_in_order[starts[e] : starts[e + 1]]
            # Active-set subsample with unbiased weight correction (the
            # reference's numActiveDataPointsUpperBound down-sampling).
            entity_rows = rng.choice(
                entity_rows, size=active_row_cap, replace=False
            )
            entity_rows.sort()
            capped_rows[int(e)] = entity_rows
            kept_counts[e] = active_row_cap

    present_entities = np.nonzero(counts > 0)[0]
    # Padded power-of-two row capacity per entity.
    kept = kept_counts[present_entities]
    capacities = 1 << np.maximum(
        0, np.ceil(np.log2(np.maximum(kept, 1))).astype(np.int64)
    )

    buckets = []
    for capacity in np.unique(capacities):
        members = present_entities[capacities == capacity]
        n_e = len(members)
        entity_index = members.astype(np.int32)
        row_index = np.zeros((n_e, capacity), np.int64)
        mask = (
            np.arange(capacity)[None, :] < kept_counts[members][:, None]
        ).astype(Float)
        corrections = np.ones(n_e, Float)
        uncapped = np.nonzero(counts[members] <= kept_counts[members])[0]
        if len(uncapped):
            m = members[uncapped]
            # Gather each uncapped entity's contiguous rows_in_order slice:
            # clamp keeps the index in range; mask zeroes the padding.
            idx = starts[m][:, None] + np.arange(capacity)[None, :]
            row_index[uncapped] = np.where(
                mask[uncapped] > 0,
                rows_in_order[np.minimum(idx, len(rows_in_order) - 1)],
                0,
            )
        for i in np.nonzero(counts[members] > kept_counts[members])[0]:
            e = int(members[i])
            row_index[i, : kept_counts[e]] = capped_rows[e]
            corrections[i] = counts[e] / kept_counts[e]
        row_weight = data.weight[row_index] * mask * corrections[:, None]
        buckets.append(
            EntityBucket(
                row_capacity=int(capacity),
                entity_index=entity_index,
                row_index=row_index,
                row_weight=row_weight.astype(Float),
                label=(data.label[row_index] * mask).astype(Float),
                features=_gather_shard_rows(shard, row_index),
            )
        )

    return RandomEffectDataset(
        entity_column=entity_column,
        shard_name=shard_name,
        dim=shard.dim,
        keys=keys,
        buckets=tuple(buckets),
        entity_idx_per_row=entity_idx_per_row,
    )


def plan_size_bins(
    buckets: tuple,
    max_bins: int = 4,
    waste_cap: float = 2.0,
) -> list:
    """Group row-capacity buckets into at most ``max_bins`` SIZE BINS.

    The power-of-two buckets bound per-entity padding to 2x, but each bucket
    is a separately-dispatched, separately-compiled solve: at production
    entity counts the O(buckets) host dispatches and compiled programs are
    the scaling cap (ISSUE 8).  A size bin merges adjacent capacities into
    ONE padded block solved by a single jitted program — entities of a
    smaller bucket get their row axis padded (weight-0 rows) up to the
    bin's capacity.

    Policy: walk capacities from LARGEST to smallest, greedily absorbing a
    smaller bucket into the current bin while the bin's padded row cells
    stay within ``waste_cap`` × its live (bucket-padded) row cells; then, if
    more than ``max_bins`` bins remain, merge the adjacent pair that adds
    the fewest padded cells until the count fits.  Deterministic in the
    bucket list alone.

    Returns a list of bucket-index groups, each ascending, ordered by
    ascending capacity — ``merge_buckets`` turns a group into the padded
    block.
    """
    if max_bins < 1:
        raise ValueError("max_bins must be >= 1")
    stats = [
        (i, bucket.row_capacity, bucket.num_entities)
        for i, bucket in enumerate(buckets)
    ]

    def padded(members, cap):
        return cap * sum(n for _, _, n in members)

    def base(members):
        return sum(c * n for _, c, n in members)

    bins: list = []  # descending capacity; each a list of (idx, cap, n)
    for entry in sorted(stats, key=lambda t: -t[1]):
        if bins:
            members = bins[-1] + [entry]
            cap = members[0][1]
            if padded(members, cap) <= waste_cap * base(members):
                bins[-1] = members
                continue
        bins.append([entry])
    while len(bins) > max_bins:
        costs = []
        for j in range(len(bins) - 1):
            members = bins[j] + bins[j + 1]
            cap = members[0][1]
            grown = padded(members, cap)
            costs.append(
                grown - padded(bins[j], bins[j][0][1])
                - padded(bins[j + 1], bins[j + 1][0][1])
            )
        j = int(np.argmin(costs))
        bins[j : j + 2] = [bins[j] + bins[j + 1]]
    return [sorted(i for i, _, _ in members) for members in reversed(bins)]


def merge_buckets(buckets: list) -> EntityBucket:
    """Merge one size bin's buckets into a single padded ``EntityBucket``.

    Every member's row axis is padded (weight-0 rows, ``row_index`` 0 — the
    bucket convention) up to the bin capacity, then the entity axes
    concatenate; member order is the given order (ascending capacity from
    :func:`plan_size_bins`), entities keeping their within-bucket order.
    """
    if len(buckets) == 1:
        return buckets[0]
    capacity = max(b.row_capacity for b in buckets)
    padded = [pad_bucket_rows(b, capacity) for b in buckets]

    def cat(field):
        return np.concatenate([getattr(b, field) for b in padded])

    features = [b.features for b in padded]
    if isinstance(features[0], DenseShard):
        merged_features: Shard = DenseShard(
            np.concatenate([f.x for f in features])
        )
    else:
        merged_features = SparseShard(
            np.concatenate([f.ids for f in features]),
            np.concatenate([f.vals for f in features]),
            features[0].dim_,
        )
    return EntityBucket(
        row_capacity=capacity,
        entity_index=cat("entity_index"),
        row_index=cat("row_index"),
        row_weight=cat("row_weight"),
        label=cat("label"),
        features=merged_features,
    )


def pad_bucket_rows(bucket: EntityBucket, multiple: int) -> EntityBucket:
    """Pad a bucket's per-entity ROW capacity to a multiple (for row-split
    sharding: each mesh shard takes ``row_capacity / multiple`` rows of every
    entity — parallel/distributed.solve_entities_row_split).  Padded rows
    carry zero weight and row_index 0, the bucket's usual convention."""
    r = bucket.row_capacity
    target = ((r + multiple - 1) // multiple) * multiple
    if target == r:
        return bucket
    pad = target - r

    def pad1(a: np.ndarray) -> np.ndarray:
        widths = [(0, 0), (0, pad)] + [(0, 0)] * (a.ndim - 2)
        return np.pad(a, widths)

    features = bucket.features
    if isinstance(features, DenseShard):
        features = DenseShard(pad1(features.x))
    else:
        features = SparseShard(pad1(features.ids), pad1(features.vals), features.dim_)
    return EntityBucket(
        row_capacity=target,
        entity_index=bucket.entity_index,
        row_index=pad1(bucket.row_index),
        row_weight=pad1(bucket.row_weight),
        label=pad1(bucket.label),
        features=features,
    )


def pad_bucket_entities(bucket: EntityBucket, multiple: int, num_entities: int) -> EntityBucket:
    """Pad a bucket's entity axis to a multiple (for even mesh sharding).

    Padded entities carry zero row weights and ``entity_index ==
    num_entities`` — a scatter into the coefficient table's dummy slot (the
    table is allocated with ``num_entities + 1`` rows; see
    RandomEffectCoordinate).
    """
    n_e = bucket.num_entities
    target = ((n_e + multiple - 1) // multiple) * multiple
    if target == n_e:
        return bucket
    pad = target - n_e

    def pad0(a: np.ndarray) -> np.ndarray:
        widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
        return np.pad(a, widths)

    features = bucket.features
    if isinstance(features, DenseShard):
        features = DenseShard(pad0(features.x))
    else:
        features = SparseShard(pad0(features.ids), pad0(features.vals), features.dim_)
    return EntityBucket(
        row_capacity=bucket.row_capacity,
        entity_index=np.concatenate(
            [bucket.entity_index, np.full(pad, num_entities, np.int32)]
        ),
        row_index=pad0(bucket.row_index),
        row_weight=pad0(bucket.row_weight),
        label=pad0(bucket.label),
        features=features,
    )
