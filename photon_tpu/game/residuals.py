"""Device-resident score engines for GAME coordinate descent.

The reference's CoordinateDescent passes residuals between coordinates via
RDD shuffles; the seed rebuilt that as HOST float64 accumulation — every
coordinate of every outer iteration summed the other coordinates' score
vectors in numpy, uploaded the result, and fetched the fresh scores back to
host after rescoring.  That is an O(n · coordinates · iterations) host
round-trip on the hottest loop of GAME training (Snap ML's hierarchy
argument, PAPERS.md: keep hot state at the fastest tier).

Two engines keep score state on device, both built on one stacked table:

- :class:`ResidualEngine` — training-side residual passing.  Training
  offsets for coordinate ``c`` are ``base + (total - scores[c]) + comp`` —
  one O(n) jitted kernel per coordinate instead of a host O(C·n) float64
  accumulate + upload.
- :class:`ValidationEngine` — validation-side incremental scoring.  The
  same table over the validation rows; only the coordinate that just
  trained is re-scored each outer iteration, and the composite margin is
  ``base + total + comp`` from the same compensated-total kernel.  The
  descent loop's one remaining host sync per iteration is the per-metric
  scalars (see ``game.descent``).

Shared table mechanics:

- ``scores`` — ONE stacked ``[C, n_pad]`` float32 table, row ``c`` holding
  coordinate ``c``'s current score vector.  Under a mesh the row length is
  padded to a multiple of the mesh size and SHARDED over the data axis
  (``PartitionSpec(None, "data")``) — each device holds only its column
  slice, one copy of the score state across the mesh instead of the
  replicated copy per device earlier rounds paid for.
- ``total``/``comp`` — a Neumaier-compensated sum of the score rows,
  refreshed by the same jitted kernel that writes an updated row.  The
  compensation term holds the summation parity the host float64 path
  provided.  The scan over rows is element-wise per column, so the sharded
  table needs NO collectives for updates or offsets; reductions that do
  cross shards (validation metrics) get their psums from GSPMD inside the
  jitted metric kernels — the DrJAX shape (arXiv:2403.07128): express the
  map-reduce as sharded collectives and let the partitioner place them.
  Because every rank of a multi-process run executes the same jitted
  programs over globally-sharded arrays, the engine is multi-controller
  safe: ``--residuals device`` is legal under ``jax.process_count() > 1``
  (the PR-2 engine was single-controller and fell back to host).
- Row updates **donate** the score table (and the total/comp pair), so
  rescoring a coordinate recycles its row's buffer instead of allocating a
  second ``[C, n_pad]`` table per update.

Hosts see score data only where the algorithm genuinely needs host values:
per-metric validation scalars once per outer iteration, and model export at
the end.

``PHOTON_RESIDUALS=host`` (or the GAME driver's ``--residuals host``)
restores the seed's host-resident float64 path end to end — the escape
hatch if a backend misbehaves under donation or long async dispatch chains.
``PHOTON_VALIDATION=host`` (``--validation-pipeline host``) does the same
for the validation side alone.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.parallel.mesh import (
    DATA_AXIS,
    axis_sharding,
    mesh_shards,
    pad_to_multiple,
    reshard,
    reshard_to_mesh,
    to_host,
)
from photon_tpu.telemetry import NULL_SESSION

Array = jax.Array


def resolve_residual_mode(mode: Optional[str] = None) -> str:
    """Resolve the operative residual mode: ``device`` | ``host``.

    Precedence: explicit ``mode`` argument (driver flag) over the
    ``PHOTON_RESIDUALS`` env var over the default (``auto`` == device).
    The device engine runs as sharded SPMD programs over globally-sharded
    score rows, so ``auto`` resolves to ``device`` under multi-process runs
    too (the PR-2 single-controller engine used to fall back to host
    there); ``host`` remains the explicit escape hatch.
    """
    resolved = mode or os.environ.get("PHOTON_RESIDUALS", "").strip().lower() \
        or "auto"
    if resolved not in ("auto", "device", "host"):
        raise ValueError(
            f"residual mode must be 'auto', 'device' or 'host', got {resolved!r}"
        )
    return "device" if resolved == "auto" else resolved


def resolve_validation_mode(
    mode: Optional[str] = None, residual_mode: str = "device"
) -> str:
    """Resolve the validation-pipeline mode: ``device`` | ``host``.

    ``auto`` (default) follows the residual mode: a device-resident descent
    run scores and evaluates validation on device too; a host-mode run
    (escape hatch) keeps the seed's host evaluation end to end.  Explicit
    ``device``/``host`` (driver flag or ``PHOTON_VALIDATION``) overrides.
    """
    resolved = mode or os.environ.get("PHOTON_VALIDATION", "").strip().lower() \
        or "auto"
    if resolved not in ("auto", "device", "host"):
        raise ValueError(
            f"validation mode must be 'auto', 'device' or 'host', "
            f"got {resolved!r}"
        )
    if resolved == "auto":
        return "device" if residual_mode == "device" else "host"
    return resolved


def _neumaier_rows(scores: Array) -> tuple[Array, Array]:
    """Compensated column-wise sum of the ``[C, n]`` table -> (total, comp).

    Neumaier's variant of Kahan summation: ``total + comp`` carries the row
    sum to roughly twice f32 precision, which is what lets the f32 engine
    match the host float64 accumulate within validation-metric tolerance.
    Element-wise per column — sharded tables sum shard-locally.
    """
    zero = jnp.zeros_like(scores[0])

    def step(carry, row):
        total, comp = carry
        t = total + row
        lost = jnp.where(
            jnp.abs(total) >= jnp.abs(row),
            (total - t) + row,
            (row - t) + total,
        )
        return (t, comp + lost), None

    (total, comp), _ = jax.lax.scan(step, (zero, zero), scores)
    return total, comp


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def _set_row_and_resum(
    scores: Array, total: Array, comp: Array, c, new_row: Array
) -> tuple[Array, Array, Array, Array]:
    """Write row ``c`` and refresh the compensated total in one program.

    The table and the old total/comp are donated: the update recycles their
    buffers (XLA aliases the output table onto the input) instead of holding
    two ``[C, n]`` tables live.  ``total``/``comp`` are recomputed from the
    full table — never incrementally drifted — so compensation error cannot
    accumulate across descent iterations.

    Non-finite guard: a row containing any NaN/Inf is REJECTED on device —
    the previous row is kept, so one poisoned solve cannot contaminate the
    compensated total (NaN + anything = NaN forever).  The returned ``ok``
    scalar stays on device; the descent loop drains the flags once per
    outer iteration and quarantines the offending coordinate.
    """
    del total, comp  # recomputed below; parameters exist to donate buffers
    ok = jnp.all(jnp.isfinite(new_row))
    scores = scores.at[c].set(jnp.where(ok, new_row, scores[c]))
    new_total, new_comp = _neumaier_rows(scores)
    return scores, new_total, new_comp, ok


@jax.jit
def _resum_rows(scores: Array) -> tuple[Array, Array]:
    """Fresh compensated total of a (non-donated) table — the table-growth
    path rebuilds total/comp after appending rows."""
    return _neumaier_rows(scores)


@jax.jit
def _offsets_kernel(base: Array, total: Array, comp: Array,
                    scores: Array, c) -> Array:
    """Training offsets for coordinate ``c``: ``base + Σ_{k≠c} scores[k]``
    as ``base + (total - scores[c]) + comp`` — one fused O(n) program."""
    return base + ((total - scores[c]) + comp)


@jax.jit
def _composite_kernel(base: Array, total: Array, comp: Array) -> Array:
    """Composite margin over ALL coordinates: ``base + Σ_k scores[k]`` as
    ``base + (total + comp)`` — the validation engine's scoring output."""
    return base + (total + comp)


class _DeviceScoreTable:
    """Shared table state of the residual and validation engines: a stacked
    ``[C, n_pad]`` score table with a maintained Neumaier-compensated total,
    sharded over the mesh data axis (see module docstring).

    ``names`` fixes the row order; ``base_offset`` is the dataset offset
    (``[n]``, uploaded once, zero-padded to ``n_pad``).  ``path`` labels the
    telemetry transfer counters (``residuals`` / ``validation``).
    """

    _PATH = "table"
    _BYTES_GAUGE: Optional[str] = None

    def __init__(
        self,
        base_offset: np.ndarray,
        names: Sequence[str],
        mesh=None,
        telemetry=None,
    ):
        if not names:
            raise ValueError(
                f"{type(self).__name__} needs at least one coordinate"
            )
        self.names = list(names)
        self._row = {name: i for i, name in enumerate(self.names)}
        if len(self._row) != len(self.names):
            raise ValueError(f"duplicate coordinate names in {self.names}")
        self.mesh = mesh
        self.telemetry = telemetry or NULL_SESSION
        # Device-resident ok-flags of recent row updates, drained (ONE tiny
        # host sync) by poll_quarantined once per outer iteration.
        self._pending_guard: list = []
        self.n = int(len(base_offset))
        self.n_pad = pad_to_multiple(self.n, mesh_shards(mesh))
        base = np.zeros(self.n_pad, np.float32)
        # host-sync: one-time base-offset staging (host numpy in; the upload
        # below is the table's entire steady-state h2d cost).
        base[: self.n] = np.asarray(base_offset, np.float32)
        self.base = self._put(base)
        # The table and its running total are the DONATED buffers
        # (_set_row_and_resum recycles them): build them XLA-born via
        # jnp.zeros, never from host numpy memory — a zero-copy host upload
        # entering a donating kernel would be freed out from under numpy.
        self.scores = self._device(
            jnp.zeros((len(self.names), self.n_pad), jnp.float32), axis=1
        )
        self.total = self._device(jnp.zeros(self.n_pad, jnp.float32))
        self.comp = self._device(jnp.zeros(self.n_pad, jnp.float32))
        # The one-time upload is the device path's entire steady-state h2d
        # cost for this table; the host path pays ~2 vectors per coordinate
        # per iteration (see game.descent counters).
        self.telemetry.counter(
            "descent.host_transfer_bytes", direction="h2d", path=self._PATH
        ).inc(self.base.nbytes)
        if self._BYTES_GAUGE:
            self.telemetry.gauge(self._BYTES_GAUGE).set(self.device_bytes)

    def _put(self, host: np.ndarray, axis: int = 0) -> Array:
        if self.mesh is None:
            return jnp.asarray(host)
        return jax.device_put(
            host, axis_sharding(self.mesh, host.ndim, axis, DATA_AXIS)
        )

    def _device(self, dev: Array, axis: int = 0) -> Array:
        """Place an already-device array onto the table's row sharding."""
        if self.mesh is None:
            return dev
        return reshard(dev, axis_sharding(self.mesh, dev.ndim, axis, DATA_AXIS))

    @property
    def device_bytes(self) -> int:
        """Global bytes of the table state (per-device residency is this
        divided by the mesh size — the rows are sharded, not replicated)."""
        return (
            self.scores.nbytes + self.base.nbytes
            + self.total.nbytes + self.comp.nbytes
        )

    def row(self, name: str) -> int:
        return self._row[name]

    def update(self, name: str, new_scores) -> None:
        """Replace ``name``'s score row and refresh the compensated total.
        Donates the previous table buffers.

        Accepts a device row of length ``n_pad`` (the device scoring paths
        emit padded, sharded rows) or a host/device vector of length ``n``
        (host-scored fallbacks; padded and counted as an h2d transfer).
        """
        if isinstance(new_scores, np.ndarray):
            # A host score vector entering the device table is a real h2d
            # transfer (warm-start models scored on host, or a coordinate
            # without a device scoring path) — count it.
            self.telemetry.counter(
                "descent.host_transfer_bytes", direction="h2d", path=self._PATH
            ).inc(new_scores.size * 4)
        new_row = jnp.asarray(new_scores, jnp.float32)
        if new_row.shape not in ((self.n,), (self.n_pad,)):
            raise ValueError(
                f"score vector for {name!r} has shape {new_row.shape}, "
                f"want ({self.n},) or padded ({self.n_pad},)"
            )
        # Logical [n] rows — host fallbacks AND checkpointed rows written
        # under any other mesh shape — are re-padded and re-sharded onto
        # THIS table's mesh here (the elastic-resume placement path);
        # already-padded device rows just re-place (a sharding no-op in
        # the steady state).
        new_row = reshard_to_mesh(new_row, self.mesh)
        with self.telemetry.span(f"{self._PATH}.update", coordinate=name):
            self.scores, self.total, self.comp, ok = _set_row_and_resum(
                self.scores, self.total, self.comp, self._row[name], new_row
            )
        # The ok flag stays a device scalar here (no sync in the hot loop);
        # descent drains it via poll_quarantined at the iteration boundary.
        # Bounded: callers that never poll (benches, direct engine use) cap
        # the backlog instead of growing it per update.
        self._pending_guard.append((name, ok))
        if len(self._pending_guard) > 4096:
            del self._pending_guard[:-4096]
        self.telemetry.counter(
            f"{self._PATH}.updates", coordinate=name
        ).inc()

    def scores_for(self, name: str) -> Array:
        """Coordinate ``name``'s current score row (device view, ``[n]`` —
        padding trimmed)."""
        return self.scores[self._row[name], : self.n]

    def drain_guard_flags(self) -> list:
        """Hand the pending ``(name, ok)`` guard flags to the caller and
        clear them — NO host access: the ok values are device bool scalars
        the descent loop batches into its single per-iteration stats/
        quarantine drain (``jax.device_get`` over everything at once)
        instead of one blocking ``bool()`` per flag."""
        pending, self._pending_guard = self._pending_guard, []
        return pending

    def record_rejected(self, bad: Sequence[str]) -> None:
        """Count rejected row updates (called by whoever drained the
        flags — poll_quarantined below, or the descent boundary drain)."""
        for name in bad:
            self.telemetry.counter(
                f"{self._PATH}.nonfinite_rows", coordinate=name
            ).inc()

    def poll_quarantined(self) -> list:
        """Names whose row updates were rejected (non-finite) since the
        last poll — the standalone-caller form of the guard drain (the
        descent loop batches drain_guard_flags into its one boundary
        sync instead)."""
        pending = self.drain_guard_flags()
        # host-sync: draining the per-update ok flags — bool scalars, the
        # sanctioned quarantine-accounting sync for direct callers.
        bad = [name for name, ok in pending if not bool(ok)]
        self.record_rejected(bad)
        return bad

    def snapshot_rows_async(self) -> dict:
        """Device row handles ``{name: [n]}`` for the ASYNC checkpoint
        staging path: the checkpointer starts ``copy_to_host_async`` on
        them together with the model tables and gathers once — no blocking
        per-row fetch here.  The handles must be materialized before the
        next ``update`` donates the table (the checkpointer stages them
        synchronously inside ``save``, before the loop resumes)."""
        return {
            name: self.scores[self._row[name], : self.n] for name in self.names
        }

    def snapshot_rows(self) -> dict:
        """All score rows as host float32 arrays ``{name: [n]}`` — the
        checkpoint snapshot, fetched ONCE per outer iteration off the hot
        path (to_host gathers across processes under multi-controller)."""
        # host-sync: checkpoint snapshot — the sanctioned off-hot-path
        # fetch of the score table.
        table = to_host(self.scores)
        self.telemetry.counter(
            "descent.host_transfer_bytes", direction="d2h", path="checkpoint"
        ).inc(table.nbytes)
        return {
            name: np.array(table[self._row[name], : self.n])
            for name in self.names
        }

    def load_rows(self, rows: dict) -> None:
        """Rebuild the device table from checkpointed rows (resume path):
        one guarded update per coordinate, exactly the state an
        uninterrupted run would hold after the same iterations.

        Checkpointed rows are LOGICAL (unpadded, length ``n``): update()
        re-pads them to THIS run's mesh multiple and re-shards — so a
        checkpoint written under any device/process count restores onto
        whatever mesh this engine was built with (elastic resume)."""
        for name, row in rows.items():
            if name in self._row:
                # host-sync: resume-path upload of checkpointed HOST rows
                # (asarray normalizes dtype; no device fetch happens here).
                self.update(name, np.asarray(row, np.float32))

    def grow(self, base_offset: np.ndarray) -> None:
        """Extend the table to cover APPENDED training rows (incremental
        entity onboarding — ISSUE 8): existing score rows keep their values
        on device (one pad + re-shard, no d2h round-trip), appended rows
        start at zero until the next update()/re-score fills them, and the
        base offset is replaced by the grown vector.  The compensated
        total rebuilds from the grown table, so compensation error cannot
        leak across the growth."""
        new_n = int(len(base_offset))
        if new_n < self.n:
            raise ValueError(
                f"grow() only appends rows: table holds {self.n}, got {new_n}"
            )
        old_scores, old_n = self.scores, self.n
        self.n = new_n
        self.n_pad = pad_to_multiple(new_n, mesh_shards(self.mesh))
        base = np.zeros(self.n_pad, np.float32)
        # host-sync: one-time base-offset staging of the grown vector (an
        # upload, same as __init__ — no device fetch happens here).
        base[: self.n] = np.asarray(base_offset, np.float32)
        self.base = self._put(base)
        self.telemetry.counter(
            "descent.host_transfer_bytes", direction="h2d", path=self._PATH
        ).inc(self.base.nbytes)
        grown = jnp.pad(
            old_scores[:, :old_n], ((0, 0), (0, self.n_pad - old_n))
        )
        self.scores = self._device(grown, axis=1)
        total, comp = _resum_rows(self.scores)
        self.total = self._device(total)
        self.comp = self._device(comp)
        if self._BYTES_GAUGE:
            self.telemetry.gauge(self._BYTES_GAUGE).set(self.device_bytes)


class ResidualEngine(_DeviceScoreTable):
    """Training-side per-coordinate score vectors resident on device with a
    maintained compensated total (see module docstring).

    The fixed effect re-shards the emitted offsets over the data axis (a
    no-op: they already are) and the random-effect bucket gathers pull the
    rows they need across shards — GSPMD inserts the gather.
    """

    _PATH = "residuals"
    _BYTES_GAUGE = "residuals.device_bytes"

    def offsets_for(self, name: str) -> Array:
        """Training offsets for ``name``: ``base + Σ_{other} scores`` as one
        jitted device kernel; float32, shape ``[n_pad]``, sharded over the
        data axis (padding rows carry whatever the base padding holds —
        weight-0 rows never read them)."""
        with self.telemetry.span("residuals.offsets", coordinate=name):
            return _offsets_kernel(
                self.base, self.total, self.comp, self.scores, self._row[name]
            )


class ValidationEngine(_DeviceScoreTable):
    """Validation-side score table: incremental per-coordinate re-scoring
    with a composite margin from the same compensated-total kernel.

    The descent loop updates only the rows whose coordinate just retrained
    (``validation.score_reuse`` counts the rows it did NOT have to touch)
    and evaluates metrics on :meth:`composite` without fetching scores to
    host — see ``game.descent``.
    """

    _PATH = "validation"
    _BYTES_GAUGE = "validation.device_bytes"

    def composite(self) -> Array:
        """Composite validation margin ``base + Σ_k scores[k]`` — float32,
        ``[n_pad]``, sharded; padded rows carry weight 0 for every metric."""
        with self.telemetry.span("validation.composite"):
            return _composite_kernel(self.base, self.total, self.comp)


class HostResiduals:
    """The seed's host-resident float64 residual path — the escape hatch.

    Scores live on host as float64 numpy vectors; offsets for a coordinate
    are accumulated in float64 and cast to float32, bit-for-bit the
    pre-engine behavior.  Every coordinate of every outer iteration pays one
    O(C·n) host accumulate, one h2d offsets upload, and one d2h score fetch;
    the same telemetry counters the device engine emits make that recurring
    cost visible next to the engine's one-time upload.
    """

    def __init__(
        self,
        base_offset: np.ndarray,
        names: Sequence[str] = (),
        mesh=None,
        telemetry=None,
    ):
        del names, mesh  # same signature as ResidualEngine; state is host-only
        # host-sync: the escape hatch keeps ALL residual state on host.
        self.base = np.asarray(base_offset, np.float64)
        self.scores: dict = {}
        self._pending_guard: list = []
        self.telemetry = telemetry or NULL_SESSION

    def update(self, name: str, new_scores) -> None:
        """Store ``name``'s score vector on host (fetching it if needed).
        Non-finite vectors are rejected — the previous iterate is kept and
        the coordinate reported via :meth:`poll_quarantined`, mirroring the
        device engine's guarded row writes."""
        # host-sync: the host escape hatch IS the host path — every update
        # fetches one score vector, counted below.
        host = np.asarray(new_scores, np.float64)
        if host.shape != self.base.shape:
            raise ValueError(
                f"score vector for {name!r} has shape {host.shape}, "
                f"want {self.base.shape}"
            )
        if not np.isfinite(host).all():
            self._pending_guard.append(name)
        else:
            self.scores[name] = host
        # The fetch moved one f32 score vector device→host.
        self.telemetry.counter(
            "descent.host_transfer_bytes", direction="d2h", path="residuals"
        ).inc(host.size * 4)
        self.telemetry.counter("residuals.updates", coordinate=name).inc()

    def offsets_for(self, name: str) -> np.ndarray:
        """float32 host offsets; the coordinate's train() uploads them."""
        offsets = self.base.copy()
        for other, s in self.scores.items():
            if other != name:
                offsets += s
        out = offsets.astype(np.float32)
        self.telemetry.counter(
            "descent.host_transfer_bytes", direction="h2d", path="residuals"
        ).inc(out.nbytes)
        return out

    def drain_guard_flags(self) -> list:
        """Pending ``(name, ok)`` flags (host bools here — the escape hatch
        rejected on host at update time); same batching contract as the
        device engines'."""
        bad, self._pending_guard = self._pending_guard, []
        return [(name, False) for name in bad]

    def record_rejected(self, bad) -> None:
        for name in bad:
            self.telemetry.counter(
                "residuals.nonfinite_rows", coordinate=name
            ).inc()

    def poll_quarantined(self) -> list:
        """Names whose updates were rejected (non-finite) since last poll —
        same contract as the device engines' guarded rows."""
        bad = [name for name, _ok in self.drain_guard_flags()]
        self.record_rejected(bad)
        return bad

    def snapshot_rows(self) -> dict:
        """All score rows (host float64 copies) — the checkpoint snapshot.
        Saved at the path's native dtype so a resumed host-mode fit is
        bit-identical to an uninterrupted one."""
        return {name: s.copy() for name, s in self.scores.items()}

    def snapshot_rows_async(self) -> dict:
        """Host engine: rows already live on host — staging is a copy."""
        return self.snapshot_rows()

    def load_rows(self, rows: dict) -> None:
        """Restore checkpointed rows (resume path).  Stored directly —
        checkpointed rows never crossed the device boundary, so routing
        them through update() would count phantom d2h transfer bytes."""
        for name, row in rows.items():
            # host-sync: the host engine restores HOST float64 rows.
            host = np.asarray(row, np.float64)
            if host.shape != self.base.shape:
                raise ValueError(
                    f"checkpointed row for {name!r} has shape {host.shape}, "
                    f"want {self.base.shape}"
                )
            self.scores[name] = host

    def grow(self, base_offset: np.ndarray) -> None:
        """Append-rows growth (entity onboarding), mirroring the device
        engines: existing rows keep their values, appended rows are zero
        until re-scored."""
        # host-sync: the escape hatch keeps ALL residual state on host.
        new_base = np.asarray(base_offset, np.float64)
        old_n = len(self.base)
        if len(new_base) < old_n:
            raise ValueError(
                f"grow() only appends rows: table holds {old_n}, got "
                f"{len(new_base)}"
            )
        self.base = new_base
        self.scores = {
            name: np.pad(s, (0, len(new_base) - old_n))
            for name, s in self.scores.items()
        }
