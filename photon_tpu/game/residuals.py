"""Device-resident residual engine for GAME coordinate descent.

The reference's CoordinateDescent passes residuals between coordinates via
RDD shuffles; the seed rebuilt that as HOST float64 accumulation — every
coordinate of every outer iteration summed the other coordinates' score
vectors in numpy, uploaded the result, and fetched the fresh scores back to
host after rescoring.  That is an O(n · coordinates · iterations) host
round-trip on the hottest loop of GAME training (Snap ML's hierarchy
argument, PAPERS.md: keep hot state at the fastest tier).

This engine keeps the residual state on device:

- ``scores`` — ONE stacked ``[C, n]`` float32 table, row ``c`` holding
  coordinate ``c``'s current score vector, replicated over the mesh when one
  is given (every shard reads whole score rows).
- ``total``/``comp`` — a Neumaier-compensated sum of the score rows,
  refreshed by the same jitted kernel that writes an updated row.  Training
  offsets for coordinate ``c`` are ``base + (total - scores[c]) + comp`` —
  one O(n) jitted kernel per coordinate instead of a host O(C·n) float64
  accumulate + upload.  The compensation term holds the summation parity the
  host float64 path provided (the f32 table stores exactly what scoring
  produced; only the cross-coordinate sum ever needed the extra precision).
- Row updates **donate** the score table (and the total/comp pair), so
  rescoring a coordinate recycles its row's buffer instead of allocating a
  second ``[C, n]`` table per update.

Hosts see score data only where the algorithm genuinely needs host values:
validation metrics once per outer iteration, and model export at the end.

``PHOTON_RESIDUALS=host`` (or the GAME driver's ``--residuals host``)
restores the seed's host-resident float64 path end to end — the escape
hatch if a backend misbehaves under donation or long async dispatch chains.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.parallel.mesh import put_replicated
from photon_tpu.telemetry import NULL_SESSION

Array = jax.Array


def resolve_residual_mode(mode: Optional[str] = None) -> str:
    """Resolve the operative residual mode: ``device`` | ``host``.

    Precedence: explicit ``mode`` argument (driver flag) over the
    ``PHOTON_RESIDUALS`` env var over the default (``auto`` == device).
    ``auto`` falls back to ``host`` under multi-process runs — the device
    engine is single-controller for now (ROADMAP open item) and the host
    path is known-correct under ``jax.distributed``.  An EXPLICIT
    ``device`` request on a multi-process run raises instead of silently
    downgrading: a benchmark that asked for the engine must not quietly
    measure the host path.
    """
    resolved = mode or os.environ.get("PHOTON_RESIDUALS", "").strip().lower() \
        or "auto"
    if resolved not in ("auto", "device", "host"):
        raise ValueError(
            f"residual mode must be 'auto', 'device' or 'host', got {resolved!r}"
        )
    if resolved == "auto":
        return "host" if jax.process_count() > 1 else "device"
    if resolved == "device" and jax.process_count() > 1:
        raise ValueError(
            "residual mode 'device' was requested explicitly, but the device "
            "engine is single-controller and this is a multi-process run; "
            "use 'auto' (falls back to host automatically) or 'host'"
        )
    return resolved


def _neumaier_rows(scores: Array) -> tuple[Array, Array]:
    """Compensated column-wise sum of the ``[C, n]`` table -> (total, comp).

    Neumaier's variant of Kahan summation: ``total + comp`` carries the row
    sum to roughly twice f32 precision, which is what lets the f32 engine
    match the host float64 accumulate within validation-metric tolerance.
    """
    zero = jnp.zeros_like(scores[0])

    def step(carry, row):
        total, comp = carry
        t = total + row
        lost = jnp.where(
            jnp.abs(total) >= jnp.abs(row),
            (total - t) + row,
            (row - t) + total,
        )
        return (t, comp + lost), None

    (total, comp), _ = jax.lax.scan(step, (zero, zero), scores)
    return total, comp


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def _set_row_and_resum(
    scores: Array, total: Array, comp: Array, c, new_row: Array
) -> tuple[Array, Array, Array]:
    """Write row ``c`` and refresh the compensated total in one program.

    The table and the old total/comp are donated: the update recycles their
    buffers (XLA aliases the output table onto the input) instead of holding
    two ``[C, n]`` tables live.  ``total``/``comp`` are recomputed from the
    full table — never incrementally drifted — so compensation error cannot
    accumulate across descent iterations.
    """
    del total, comp  # recomputed below; parameters exist to donate buffers
    scores = scores.at[c].set(new_row)
    new_total, new_comp = _neumaier_rows(scores)
    return scores, new_total, new_comp


@jax.jit
def _offsets_kernel(base: Array, total: Array, comp: Array,
                    scores: Array, c) -> Array:
    """Training offsets for coordinate ``c``: ``base + Σ_{k≠c} scores[k]``
    as ``base + (total - scores[c]) + comp`` — one fused O(n) program."""
    return base + ((total - scores[c]) + comp)


class ResidualEngine:
    """Per-coordinate score vectors resident on device with a maintained
    compensated total (see module docstring).

    ``names`` fixes the row order; ``base_offset`` is the dataset offset
    (uploaded once).  All arrays are replicated over ``mesh`` when given —
    the fixed effect re-shards its offsets over the data axis and the
    random-effect bucket gathers emit entity-sharded blocks, both from the
    replicated row vectors.
    """

    def __init__(
        self,
        base_offset: np.ndarray,
        names: Sequence[str],
        mesh=None,
        telemetry=None,
    ):
        if not names:
            raise ValueError("ResidualEngine needs at least one coordinate")
        self.names = list(names)
        self._row = {name: i for i, name in enumerate(self.names)}
        if len(self._row) != len(self.names):
            raise ValueError(f"duplicate coordinate names in {self.names}")
        self.mesh = mesh
        self.telemetry = telemetry or NULL_SESSION
        self.n = int(len(base_offset))
        base = jnp.asarray(base_offset, jnp.float32)
        self.base = put_replicated(base, mesh)
        zeros = jnp.zeros((len(self.names), self.n), jnp.float32)
        self.scores = put_replicated(zeros, mesh)
        self.total = put_replicated(jnp.zeros(self.n, jnp.float32), mesh)
        self.comp = put_replicated(jnp.zeros(self.n, jnp.float32), mesh)
        # The one-time upload is the device path's entire steady-state h2d
        # cost for residuals; the host path pays ~2 vectors per coordinate
        # per iteration (see game.descent counters).
        self.telemetry.counter(
            "descent.host_transfer_bytes", direction="h2d", path="residuals"
        ).inc(self.base.nbytes)
        self.telemetry.gauge("residuals.device_bytes").set(
            self.scores.nbytes + self.base.nbytes
            + self.total.nbytes + self.comp.nbytes
        )

    def row(self, name: str) -> int:
        return self._row[name]

    def update(self, name: str, new_scores: Array) -> None:
        """Replace ``name``'s score row (device array, ``[n]``) and refresh
        the compensated total.  Donates the previous table buffers."""
        if isinstance(new_scores, np.ndarray):
            # A host score vector entering the device table is a real h2d
            # transfer (warm-start models scored on host, or a coordinate
            # without a device scoring path) — count it.
            self.telemetry.counter(
                "descent.host_transfer_bytes", direction="h2d", path="residuals"
            ).inc(new_scores.size * 4)
        new_row = jnp.asarray(new_scores, jnp.float32)
        if new_row.shape != (self.n,):
            raise ValueError(
                f"score vector for {name!r} has shape {new_row.shape}, "
                f"want ({self.n},)"
            )
        with self.telemetry.span("residuals.update", coordinate=name):
            self.scores, self.total, self.comp = _set_row_and_resum(
                self.scores, self.total, self.comp, self._row[name], new_row
            )
        self.telemetry.counter("residuals.updates", coordinate=name).inc()

    def offsets_for(self, name: str) -> Array:
        """Training offsets for ``name``: ``base + Σ_{other} scores`` as one
        jitted device kernel; float32, shape ``[n]``, replicated."""
        with self.telemetry.span("residuals.offsets", coordinate=name):
            return _offsets_kernel(
                self.base, self.total, self.comp, self.scores, self._row[name]
            )

    def scores_for(self, name: str) -> Array:
        """Coordinate ``name``'s current score row (device view)."""
        return self.scores[self._row[name]]


class HostResiduals:
    """The seed's host-resident float64 residual path — the escape hatch.

    Scores live on host as float64 numpy vectors; offsets for a coordinate
    are accumulated in float64 and cast to float32, bit-for-bit the
    pre-engine behavior.  Every coordinate of every outer iteration pays one
    O(C·n) host accumulate, one h2d offsets upload, and one d2h score fetch;
    the same telemetry counters the device engine emits make that recurring
    cost visible next to the engine's one-time upload.
    """

    def __init__(
        self,
        base_offset: np.ndarray,
        names: Sequence[str] = (),
        mesh=None,
        telemetry=None,
    ):
        del names, mesh  # same signature as ResidualEngine; state is host-only
        self.base = np.asarray(base_offset, np.float64)
        self.scores: dict = {}
        self.telemetry = telemetry or NULL_SESSION

    def update(self, name: str, new_scores) -> None:
        """Store ``name``'s score vector on host (fetching it if needed)."""
        host = np.asarray(new_scores, np.float64)
        if host.shape != self.base.shape:
            raise ValueError(
                f"score vector for {name!r} has shape {host.shape}, "
                f"want {self.base.shape}"
            )
        self.scores[name] = host
        # The fetch moved one f32 score vector device→host.
        self.telemetry.counter(
            "descent.host_transfer_bytes", direction="d2h", path="residuals"
        ).inc(host.size * 4)
        self.telemetry.counter("residuals.updates", coordinate=name).inc()

    def offsets_for(self, name: str) -> np.ndarray:
        """float32 host offsets; the coordinate's train() uploads them."""
        offsets = self.base.copy()
        for other, s in self.scores.items():
            if other != name:
                offsets += s
        out = offsets.astype(np.float32)
        self.telemetry.counter(
            "descent.host_transfer_bytes", direction="h2d", path="residuals"
        ).inc(out.nbytes)
        return out
