"""GAME model persistence: per-coordinate name/term-keyed Avro export.

Rebuild of the reference's ``ModelProcessingUtils.saveGameModelToHDFS`` /
``loadGameModelFromHDFS`` (photon-client .../data/avro — SURVEY.md §5
'Checkpoint / resume'): a GAME model is a directory with one subdirectory per
coordinate — ``fixed-effect/<name>/`` holding a single coefficient record,
``random-effect/<name>/`` holding one record **per entity** (the reference's
``RDD[(entityId, model)]`` written as BayesianLinearModelAvro keyed by
modelId).  Coefficients are keyed by (name, term) feature strings so models
survive feature-index rebuilds; each coordinate directory carries its own
feature index map.

Layout:
    <dir>/metadata.json                        task type, coordinate order
    <dir>/fixed-effect/<coord>/coefficients.avro
    <dir>/fixed-effect/<coord>/feature_index.json
    <dir>/random-effect/<coord>/coefficients.avro   (one record per entity)
    <dir>/random-effect/<coord>/feature_index.json
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from photon_tpu.data import avro_codec
from photon_tpu.data.index_map import IndexMap
from photon_tpu.data.model_io import (
    GLM_MODEL_SCHEMA,
    NAME_TERM_VALUE_SCHEMA,
    _ntv_list,
    load_glm_model,
    save_glm_model,
)
from photon_tpu.game.model import FixedEffectModel, GameModel, RandomEffectModel
from photon_tpu.models.glm import model_for_task

RANDOM_EFFECT_SCHEMA = {
    "type": "record",
    "name": "RandomEffectModelAvro",
    "namespace": "photon_tpu.generated",
    "fields": [
        {"name": "modelId", "type": "string"},
        {"name": "means", "type": {"type": "array", "items": NAME_TERM_VALUE_SCHEMA}},
        {
            "name": "variances",
            "type": ["null", {"type": "array", "items": "NameTermValueAvro"}],
            "default": None,
        },
    ],
}


def save_game_model(
    dir_path: str,
    model: GameModel,
    index_maps: Dict[str, IndexMap],
    fmt: str = "avro",
    telemetry=None,
) -> None:
    """``index_maps`` is keyed by feature-shard name (each coordinate stores
    the map for its shard).

    The export is ATOMIC: the whole directory is built in a hidden temp
    sibling and renamed into place (photon_tpu.fault.atomic), so a kill
    mid-export can never leave a torn model directory — readers see the
    previous complete model or the new one, nothing in between."""
    from photon_tpu.fault.atomic import atomic_dir

    with atomic_dir(dir_path) as tmp:
        _write_game_model(tmp, model, index_maps, fmt, telemetry=telemetry)


def _fetch_model_tables(model: GameModel, telemetry=None) -> Dict[str, dict]:
    """ALL per-coordinate device tables in ONE ``jax.device_get``.

    The export used to fetch each coordinate's table/variances/means with
    its own d2h round-trip; batching them into one gather (the same shape
    as the descent loop's once-per-iteration drain, PR 5) dispatches every
    copy together and is counted under
    ``descent.host_transfer_bytes{path=export}``."""
    import jax

    pending: Dict[str, dict] = {}
    for name, coord in model.coordinates.items():
        if isinstance(coord, FixedEffectModel):
            c = coord.coefficients
            pending[name] = {"means": c.means}
            if c.variances is not None:
                pending[name]["variances"] = c.variances
        elif isinstance(coord, RandomEffectModel):
            pending[name] = {"means": coord.table}
            if coord.variances is not None:
                pending[name]["variances"] = coord.variances
    fetched = jax.device_get(pending)
    host = {
        name: {k: np.asarray(v) for k, v in arrays.items()}
        for name, arrays in fetched.items()
    }
    if telemetry is not None:
        telemetry.counter(
            "descent.host_transfer_bytes", direction="d2h", path="export"
        ).inc(sum(
            a.nbytes for arrays in host.values() for a in arrays.values()
        ))
    return host


def _write_game_model(
    dir_path: str,
    model: GameModel,
    index_maps: Dict[str, IndexMap],
    fmt: str = "avro",
    telemetry=None,
) -> None:
    os.makedirs(dir_path, exist_ok=True)
    meta = {"version": 1, "task_type": model.task_type, "coordinates": []}
    ext = "avro" if fmt == "avro" else "json"
    tables = _fetch_model_tables(model, telemetry=telemetry)
    for name, coord in model.coordinates.items():
        host = tables[name]
        if isinstance(coord, FixedEffectModel):
            coord_dir = os.path.join(dir_path, "fixed-effect", name)
            os.makedirs(coord_dir, exist_ok=True)
            imap = index_maps[coord.shard_name]
            from photon_tpu.models.glm import Coefficients

            save_glm_model(
                os.path.join(coord_dir, f"coefficients.{ext}"),
                coord.model.with_coefficients(Coefficients(
                    host["means"], host.get("variances")
                )),
                imap,
                fmt=fmt,
            )
            imap.save(os.path.join(coord_dir, "feature_index.json"))
            meta["coordinates"].append(
                {"name": name, "type": "fixed", "shard_name": coord.shard_name}
            )
        elif isinstance(coord, RandomEffectModel):
            coord_dir = os.path.join(dir_path, "random-effect", name)
            os.makedirs(coord_dir, exist_ok=True)
            imap = index_maps[coord.shard_name]
            _save_random_effect(
                coord_dir, coord, imap, ext,
                table=host["means"], variances=host.get("variances"),
            )
            imap.save(os.path.join(coord_dir, "feature_index.json"))
            meta["coordinates"].append(
                {
                    "name": name,
                    "type": "random",
                    "shard_name": coord.shard_name,
                    "entity_column": coord.entity_column,
                }
            )
        else:
            raise TypeError(f"unknown coordinate model type {type(coord)!r}")
    from photon_tpu.fault.injection import fault_point

    # The mid-export window fault injection targets: coordinate files are
    # written, metadata is not — an injected failure here must leave the
    # previously-published model untouched (atomic_dir discards the temp).
    fault_point("io:write", path=dir_path)
    with open(os.path.join(dir_path, "metadata.json"), "w") as f:
        json.dump(meta, f, indent=1)


def _save_random_effect(
    coord_dir: str, coord: RandomEffectModel, imap: IndexMap, ext: str,
    table: Optional[np.ndarray] = None,
    variances: Optional[np.ndarray] = None,
) -> None:
    """``table``/``variances`` arrive pre-fetched from the batched export
    d2h (:func:`_fetch_model_tables`); the fallback fetch keeps direct
    callers working."""
    if table is None:
        table = np.asarray(coord.table)
        variances = (
            None if coord.variances is None else np.asarray(coord.variances)
        )
    records = []
    for i, key in enumerate(coord.keys):
        records.append(
            {
                "modelId": str(key),
                "means": _ntv_list(table[i], imap),
                "variances": None if variances is None else _ntv_list(variances[i], imap),
            }
        )
    path = os.path.join(coord_dir, f"coefficients.{ext}")
    if ext == "avro":
        avro_codec.write_container(path, RANDOM_EFFECT_SCHEMA, records)
    else:
        with open(path, "w") as f:
            json.dump(records, f, indent=1)


def _coeff_file(coord_dir: str) -> tuple[str, str]:
    for ext in ("avro", "json"):
        p = os.path.join(coord_dir, f"coefficients.{ext}")
        if os.path.exists(p):
            return p, ext
    raise FileNotFoundError(f"no coefficients file under {coord_dir}")


def _load_random_effect(
    coord_dir: str,
    meta: dict,
    imap: IndexMap,
    task_type: str,
    keys_dtype=None,
) -> RandomEffectModel:
    path, ext = _coeff_file(coord_dir)
    if ext == "avro":
        _, records = avro_codec.read_container(path)
    else:
        with open(path) as f:
            records = json.load(f)

    def to_vec(ntvs) -> np.ndarray:
        vec = np.zeros(len(imap), np.float32)
        for ntv in ntvs:
            from photon_tpu.data.index_map import feature_key

            idx = imap.get_id(feature_key(ntv["name"], ntv["term"]))
            if idx >= 0:
                vec[idx] = ntv["value"]
        return vec

    raw_keys = [r["modelId"] for r in records]
    # Entity keys were stringified on save; restore a numeric dtype when every
    # key parses (so vocab joins against int id columns keep working) AND the
    # parse is injective — '01' and '1' must stay distinct strings.
    try:
        ints = [int(k) for k in raw_keys]
        parsed = (
            np.asarray(ints, dtype=np.int64)
            if len(set(ints)) == len(ints)
            else np.asarray(raw_keys)
        )
    except (ValueError, OverflowError):  # non-numeric or beyond-int64 ids
        parsed = np.asarray(raw_keys)
    order = np.argsort(parsed, kind="stable")
    keys = parsed[order]
    has_var = any(r.get("variances") is not None for r in records)
    table = np.zeros((len(records), len(imap)), np.float32)
    variances = np.zeros_like(table) if has_var else None
    for out_i, rec_i in enumerate(order):
        rec = records[rec_i]
        table[out_i] = to_vec(rec["means"])
        if has_var and rec.get("variances") is not None:
            variances[out_i] = to_vec(rec["variances"])
    return RandomEffectModel(
        table=jnp.asarray(table),
        keys=keys,
        entity_column=meta["entity_column"],
        shard_name=meta["shard_name"],
        task_type=task_type,
        variances=None if variances is None else jnp.asarray(variances),
    )


def load_game_model(
    dir_path: str, index_maps: Optional[Dict[str, IndexMap]] = None
) -> tuple[GameModel, Dict[str, IndexMap]]:
    """Load a GAME model directory.  By default each coordinate's saved
    feature index is used (self-contained model); passing ``index_maps``
    re-keys coefficients onto the caller's maps (feature-index rebuild
    semantics, as the reference's loader does)."""
    with open(os.path.join(dir_path, "metadata.json")) as f:
        meta = json.load(f)
    task_type = meta["task_type"]
    coordinates = {}
    maps_out: Dict[str, IndexMap] = {}
    for cmeta in meta["coordinates"]:
        name, ctype = cmeta["name"], cmeta["type"]
        sub = "fixed-effect" if ctype == "fixed" else "random-effect"
        coord_dir = os.path.join(dir_path, sub, name)
        shard = cmeta["shard_name"]
        if index_maps is not None and shard in index_maps:
            imap = index_maps[shard]
        else:
            imap = IndexMap.load(os.path.join(coord_dir, "feature_index.json"))
        maps_out[shard] = imap
        if ctype == "fixed":
            path, fmt = _coeff_file(coord_dir)
            glm = load_glm_model(path, imap, fmt=fmt)
            # The task's link governs GAME prediction; per-coordinate loss is
            # irrelevant post-training, so rebuild on the model's task.
            glm = model_for_task(task_type, glm.coefficients)
            coordinates[name] = FixedEffectModel(model=glm, shard_name=shard)
        else:
            coordinates[name] = _load_random_effect(coord_dir, cmeta, imap, task_type)
    return GameModel(coordinates=coordinates, task_type=task_type), maps_out
