"""L-BFGS as a single jit-compiled ``lax.while_loop``.

Rebuild of the reference's ``LBFGS`` (photon-lib .../optimization/LBFGS.scala),
which wraps ``breeze.optimize.LBFGS`` — SURVEY.md §2.1.  Here the two-loop
recursion runs over a fixed ring buffer of (s, y) pairs and the backtracking
line search is an inner ``lax.while_loop``, so the whole optimize() call is
one XLA program: no host round-trips between iterations (the reference pays a
driver↔executor broadcast + treeAggregate per function evaluation).

Every state update is masked on an ``active`` flag, which makes the loop
vmap-correct for GAME's batched per-entity solves: converged lanes freeze
while the rest keep iterating (SURVEY.md §7 'hard parts').
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from photon_tpu.core.optimizers.base import (
    ConvergenceReason,
    OptimizerConfig,
    OptimizerResult,
    check_convergence,
    init_history,
    reason_is_converged,
    record_history,
    tree_where,
)

Array = jax.Array

_ARMIJO_C1 = 1e-4
_PAIR_EPS = 1e-10


class _LineSearchState(NamedTuple):
    t: Array
    f: Array
    g: Array
    ok: Array  # current trial satisfies Armijo
    it: Array
    halt: Array  # stop without success (out of steps / inactive lane)


def _backtracking_line_search(fun, w, d, f0, dir_deriv, t0, max_steps, active):
    """Armijo backtracking from step ``t0``, halving on failure.

    Returns (t, f_t, g_t, success).  The acceptance test lives in the loop
    condition, so exactly one (value, grad) evaluation happens per trial —
    an accepted first step costs a single evaluation.  Inert when ``active``
    is False.
    """

    def trial(t):
        f, g = fun(w + t * d)
        # NaN/Inf trial values (e.g. Poisson exp overflow) never pass Armijo.
        ok = (f <= f0 + _ARMIJO_C1 * t * dir_deriv) & jnp.isfinite(f)
        return f, g, ok

    f_i, g_i, ok_i = trial(t0)

    def cond(s: _LineSearchState):
        return ~(s.ok | s.halt)

    def body(s: _LineSearchState):
        t_new = s.t * 0.5
        f_new, g_new, ok_new = trial(t_new)
        return _LineSearchState(
            t=t_new, f=f_new, g=g_new, ok=ok_new, it=s.it + 1,
            halt=s.it + 1 >= max_steps,
        )

    init = _LineSearchState(
        t=jnp.asarray(t0), f=f_i, g=g_i, ok=ok_i,
        it=jnp.asarray(0, jnp.int32), halt=~active,
    )
    final = lax.while_loop(cond, body, init)
    return final.t, final.f, final.g, final.ok


def _two_loop_direction(g, S, Y, rho, num_pairs, insert_pos, gamma, m):
    """Classic L-BFGS two-loop recursion over a ring buffer.

    Slots are valid for j < num_pairs; newest pair sits at (insert_pos-1) % m.
    """

    def body1(j, carry):
        q, alphas = carry
        idx = (insert_pos - 1 - j) % m
        valid = j < num_pairs
        alpha = jnp.where(valid, rho[idx] * jnp.dot(S[idx], q), 0.0)
        q = q - alpha * Y[idx]
        alphas = alphas.at[idx].set(alpha)
        return q, alphas

    q, alphas = lax.fori_loop(0, m, body1, (g, jnp.zeros(m, g.dtype)))
    r = gamma * q

    def body2(j, r):
        idx = (insert_pos - num_pairs + j) % m
        valid = j < num_pairs
        beta = jnp.where(valid, rho[idx] * jnp.dot(Y[idx], r), 0.0)
        return r + jnp.where(valid, alphas[idx] - beta, 0.0) * S[idx]

    r = lax.fori_loop(0, m, body2, r)
    return -r


class _State(NamedTuple):
    w: Array
    f: Array
    g: Array
    S: Array  # [m, d]
    Y: Array  # [m, d]
    rho: Array  # [m]
    num_pairs: Array
    insert_pos: Array
    gamma: Array
    it: Array
    active: Array
    reason: Array
    hv: Array
    hg: Array
    hvalid: Array


def lbfgs(
    fun: Callable[[Array], tuple[Array, Array]],
    w0: Array,
    config: OptimizerConfig = OptimizerConfig(),
) -> OptimizerResult:
    """Minimize ``fun`` (returning (value, grad)) starting from ``w0``.

    Pure JAX: safe under jit, vmap (batched entity solves), and shard_map
    (the function may psum internally; the optimizer only sees full
    gradients).
    """
    m = config.history_length
    d = w0.shape[0]
    f0, g0 = fun(w0)
    gnorm0 = jnp.linalg.norm(g0)
    # The gradient test is relative to ||g0||, so at the initial point it
    # only fires for an exactly-zero gradient.
    conv0 = gnorm0 == 0.0
    hv, hg, hvalid = init_history(config.max_iterations, f0, gnorm0)

    init = _State(
        w=w0, f=f0, g=g0,
        S=jnp.zeros((m, d), w0.dtype),
        Y=jnp.zeros((m, d), w0.dtype),
        rho=jnp.zeros(m, w0.dtype),
        num_pairs=jnp.asarray(0, jnp.int32),
        insert_pos=jnp.asarray(0, jnp.int32),
        gamma=jnp.asarray(1.0, w0.dtype),
        it=jnp.asarray(0, jnp.int32),
        active=~conv0,
        reason=jnp.where(
            conv0, ConvergenceReason.GRADIENT_TOLERANCE, ConvergenceReason.NOT_CONVERGED
        ).astype(jnp.int32),
        hv=hv, hg=hg, hvalid=hvalid,
    )

    def cond(s: _State):
        return s.active

    def body(s: _State):
        dvec = _two_loop_direction(
            s.g, s.S, s.Y, s.rho, s.num_pairs, s.insert_pos, s.gamma, m
        )
        dir_deriv = jnp.dot(s.g, dvec)
        # Fall back to steepest descent if the direction is not a descent one.
        bad = dir_deriv >= 0.0
        dvec = jnp.where(bad, -s.g, dvec)
        dir_deriv = jnp.where(bad, -jnp.dot(s.g, s.g), dir_deriv)
        gnorm = jnp.linalg.norm(s.g)
        t0 = jnp.where(s.num_pairs == 0, 1.0 / jnp.maximum(gnorm, 1.0), 1.0)

        t, f_new, g_new, ls_ok = _backtracking_line_search(
            fun, s.w, dvec, s.f, dir_deriv, t0, config.max_line_search, s.active
        )

        w_new = s.w + t * dvec
        svec = w_new - s.w
        yvec = g_new - s.g
        sy = jnp.dot(svec, yvec)
        # Cautious update: only store pairs with positive curvature.
        pair_ok = ls_ok & (sy > _PAIR_EPS)
        S_new = s.S.at[s.insert_pos].set(jnp.where(pair_ok, svec, s.S[s.insert_pos]))
        Y_new = s.Y.at[s.insert_pos].set(jnp.where(pair_ok, yvec, s.Y[s.insert_pos]))
        rho_new = s.rho.at[s.insert_pos].set(
            jnp.where(pair_ok, 1.0 / jnp.where(pair_ok, sy, 1.0), s.rho[s.insert_pos])
        )
        num_pairs = jnp.where(pair_ok, jnp.minimum(s.num_pairs + 1, m), s.num_pairs)
        insert_pos = jnp.where(pair_ok, (s.insert_pos + 1) % m, s.insert_pos)
        gamma = jnp.where(pair_ok, sy / jnp.maximum(jnp.dot(yvec, yvec), 1e-30), s.gamma)

        gnorm_new = jnp.linalg.norm(g_new)
        converged, reason = check_convergence(f_new, s.f, gnorm_new, gnorm0, config)
        stop_ls = ~ls_ok
        reason = jnp.where(stop_ls, ConvergenceReason.OBJECTIVE_NOT_IMPROVING, reason)
        it_new = s.it + 1
        hit_max = it_new >= config.max_iterations
        reason = jnp.where(
            hit_max & ~(converged | stop_ls), ConvergenceReason.MAX_ITERATIONS, reason
        )
        still_active = s.active & ~(converged | stop_ls | hit_max)

        # On line-search failure keep the old iterate.
        w_out = jnp.where(ls_ok, w_new, s.w)
        f_out = jnp.where(ls_ok, f_new, s.f)
        g_out = jnp.where(ls_ok, g_new, s.g)
        hv, hg, hvalid = record_history(
            s.hv, s.hg, s.hvalid, it_new, f_out, jnp.linalg.norm(g_out), s.active & ls_ok
        )

        new = _State(
            w=w_out, f=f_out, g=g_out,
            S=S_new, Y=Y_new, rho=rho_new,
            num_pairs=num_pairs, insert_pos=insert_pos, gamma=gamma,
            it=it_new, active=still_active,
            reason=reason.astype(jnp.int32),
            hv=hv, hg=hg, hvalid=hvalid,
        )
        return tree_where(s.active, new, s)

    final = lax.while_loop(cond, body, init)

    # Full-step polish (the Newton-solver trick, grafted): the line-
    # searched loop stops where f32 FUNCTION differences round to zero —
    # a basin ~1e-4 wide around the true optimum.  The quasi-Newton map
    # built from the final ring buffer keeps contracting on the f32
    # GRADIENT's zero well past that, so two unconditional full steps
    # tighten the iterate at the cost of two extra evaluations.  Guards
    # (all vmap-safe, per lane): the step must be small relative to the
    # iterate (a lane stopped far from its optimum — max_iterations,
    # degenerate curvature — must not take an unsearched full step), the
    # stepped point must stay finite, AND — unlike Newton, whose exact
    # Hessian certifies the step — the gradient norm must not grow (a
    # stale ring buffer's direction carries no such certificate).
    def polish(carry, _):
        w, f, g = carry
        step = _two_loop_direction(
            g, final.S, final.Y, final.rho, final.num_pairs,
            final.insert_pos, final.gamma, m,
        )
        near = jnp.all(jnp.isfinite(step)) & (
            jnp.linalg.norm(step)
            <= 1e-3 * jnp.maximum(jnp.linalg.norm(w), 1.0)
        )
        w_new = jnp.where(near, w + step, w)
        f_new, g_new = fun(w_new)
        keep = (
            near & jnp.isfinite(f_new) & jnp.all(jnp.isfinite(g_new))
            & (jnp.linalg.norm(g_new) <= jnp.linalg.norm(g))
        )
        return (
            jnp.where(keep, w_new, w),
            jnp.where(keep, f_new, f),
            jnp.where(keep, g_new, g),
        ), None

    (w_out, f_out, g_out), _ = lax.scan(
        polish, (final.w, final.f, final.g), None, length=2
    )
    return OptimizerResult(
        w=w_out,
        value=f_out,
        grad_norm=jnp.linalg.norm(g_out),
        iterations=final.it,
        converged=reason_is_converged(final.reason),
        reason=final.reason,
        history_value=final.hv,
        history_grad_norm=final.hg,
        history_valid=final.hvalid,
    )
