"""Damped Newton with a batched Cholesky solve — the small-dim direct method.

The per-entity GAME solves are tiny strongly-convex GLMs (``dim`` in the
tens): exactly the regime where a direct second-order method beats the
quasi-Newton loops — Snap ML (PAPERS.md, 1803.06333) solves the same
hierarchical-GLM subproblems with direct second-order methods, and "Large
Scale Distributed Linear Algebra With TPUs" (PAPERS.md, 2112.09017) grounds
the padded batched-factorization shape this vmaps into: under ``jax.vmap``
the Hessians stack to ``[B, dim, dim]`` and the factorization becomes one
batched Cholesky (``cho_factor``/``cho_solve``) per Newton iteration.

Same contract as :func:`~photon_tpu.core.optimizers.lbfgs.lbfgs`: a single
``lax.while_loop`` machine whose state updates are all masked on an
``active`` flag, so converged lanes FREEZE under vmap while heavy entities
keep iterating (masked convergence — finished entities stop contributing
work beyond the lockstep evaluation).  Tolerance semantics, history arrays,
and convergence reasons match the shared base exactly; a fit that converges
here lands on the same optimum as the L-BFGS/TRON path (the objective is
identical), which is what the batched-vs-vmapped parity tests pin.

Robustness: the Hessian gets a tiny relative ridge before factorization
(flat directions — e.g. an entity whose rows never touch a feature — keep
the factorization defined, matching core/problem.py's full-variance
jitter), a non-finite or non-descent Newton step falls back to steepest
descent for that iteration, and an Armijo backtracking line search (shared
with L-BFGS) guards against overshoot far from the optimum (Poisson's exp
margins).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from typing import NamedTuple

from photon_tpu.core.optimizers.base import (
    ConvergenceReason,
    OptimizerConfig,
    OptimizerResult,
    check_convergence,
    init_history,
    reason_is_converged,
    record_history,
    tree_where,
)
from photon_tpu.core.optimizers.lbfgs import _backtracking_line_search

Array = jax.Array

# Relative ridge added to the Hessian diagonal before factorization: large
# enough to keep Cholesky defined on flat directions, orders of magnitude
# below any curvature that moves the solution at the 1e-5 parity tolerance.
_RIDGE = 1e-9


class _State(NamedTuple):
    w: Array
    f: Array
    g: Array
    it: Array
    active: Array
    reason: Array
    hv: Array
    hg: Array
    hvalid: Array


def newton(
    fun: Callable[[Array], tuple[Array, Array]],
    w0: Array,
    config: OptimizerConfig = OptimizerConfig(),
    hess: Callable[[Array], Array] | None = None,
) -> OptimizerResult:
    """Minimize ``fun`` (returning (value, grad)) with full Newton steps.

    ``hess(w) -> [d, d]`` supplies the dense Hessian (for GLM objectives,
    ``objective.hessian_matrix``); if None it is derived from ``fun`` by
    forward-mode differentiation of the gradient (exact, d jvp passes).
    Pure JAX: safe under jit and vmap (the GAME batched entity solves).
    """
    if hess is None:
        def hess(w):  # noqa: ANN001
            return jax.jacfwd(lambda u: fun(u)[1])(w)

    d = w0.shape[0]
    eye = jnp.eye(d, dtype=w0.dtype)
    f0, g0 = fun(w0)
    gnorm0 = jnp.linalg.norm(g0)
    conv0 = gnorm0 == 0.0
    hv, hg, hvalid = init_history(config.max_iterations, f0, gnorm0)

    init = _State(
        w=w0, f=f0, g=g0,
        it=jnp.asarray(0, jnp.int32),
        active=~conv0,
        reason=jnp.where(
            conv0, ConvergenceReason.GRADIENT_TOLERANCE,
            ConvergenceReason.NOT_CONVERGED,
        ).astype(jnp.int32),
        hv=hv, hg=hg, hvalid=hvalid,
    )

    def cond(s: _State):
        return s.active

    def body(s: _State):
        h = hess(s.w)
        ridge = _RIDGE * (1.0 + jnp.max(jnp.abs(jnp.diagonal(h))))
        chol = jax.scipy.linalg.cho_factor(h + ridge * eye)
        step = -jax.scipy.linalg.cho_solve(chol, s.g)
        dir_deriv = jnp.dot(s.g, step)
        # A failed factorization (non-PD curvature -> NaN) or a non-descent
        # step falls back to steepest descent for this iteration.
        bad = ~jnp.all(jnp.isfinite(step)) | (dir_deriv >= 0.0)
        step = jnp.where(bad, -s.g, step)
        dir_deriv = jnp.where(bad, -jnp.dot(s.g, s.g), dir_deriv)
        t0 = jnp.where(bad, 1.0 / jnp.maximum(jnp.linalg.norm(s.g), 1.0), 1.0)

        t, f_new, g_new, ls_ok = _backtracking_line_search(
            fun, s.w, step, s.f, dir_deriv, t0, config.max_line_search,
            s.active,
        )
        w_new = s.w + t * step

        gnorm_new = jnp.linalg.norm(g_new)
        converged, reason = check_convergence(
            f_new, s.f, gnorm_new, gnorm0, config
        )
        stop_ls = ~ls_ok
        reason = jnp.where(
            stop_ls, ConvergenceReason.OBJECTIVE_NOT_IMPROVING, reason
        )
        it_new = s.it + 1
        hit_max = it_new >= config.max_iterations
        reason = jnp.where(
            hit_max & ~(converged | stop_ls),
            ConvergenceReason.MAX_ITERATIONS, reason,
        )
        still_active = s.active & ~(converged | stop_ls | hit_max)

        # On line-search failure keep the old iterate (matching lbfgs).
        w_out = jnp.where(ls_ok, w_new, s.w)
        f_out = jnp.where(ls_ok, f_new, s.f)
        g_out = jnp.where(ls_ok, g_new, s.g)
        hv, hg, hvalid = record_history(
            s.hv, s.hg, s.hvalid, it_new, f_out, jnp.linalg.norm(g_out),
            s.active & ls_ok,
        )

        new = _State(
            w=w_out, f=f_out, g=g_out,
            it=it_new, active=still_active,
            reason=reason.astype(jnp.int32),
            hv=hv, hg=hg, hvalid=hvalid,
        )
        return tree_where(s.active, new, s)

    final = lax.while_loop(cond, body, init)

    # Full-step polish: the line-searched loop above stops where f32
    # FUNCTION differences round to zero — a basin ~1e-4 wide around the
    # true optimum (any value-criterion f32 solver stalls there, the seed's
    # L-BFGS included).  The Newton map ``w -> w - H(w)^{-1} g(w)`` keeps
    # contracting on the f32 GRADIENT's zero well past that, so two
    # unconditional full steps land within ~1e-6 of the true optimum —
    # what makes the batched path's ≤1e-5 ground-truth parity hold.
    # Guarded: a step is only taken when it is small relative to the
    # iterate (a lane that stopped far from its optimum — max_iterations,
    # degenerate curvature — must not take an unsearched full step) and
    # the stepped point stays finite.
    def polish(carry, _):
        w, f, g = carry
        h = hess(w)
        ridge = _RIDGE * (1.0 + jnp.max(jnp.abs(jnp.diagonal(h))))
        chol = jax.scipy.linalg.cho_factor(h + ridge * eye)
        step = -jax.scipy.linalg.cho_solve(chol, g)
        near = jnp.all(jnp.isfinite(step)) & (
            jnp.linalg.norm(step)
            <= 1e-3 * jnp.maximum(jnp.linalg.norm(w), 1.0)
        )
        w_new = jnp.where(near, w + step, w)
        f_new, g_new = fun(w_new)
        keep = near & jnp.isfinite(f_new) & jnp.all(jnp.isfinite(g_new))
        return (
            jnp.where(keep, w_new, w),
            jnp.where(keep, f_new, f),
            jnp.where(keep, g_new, g),
        ), None

    (w_out, f_out, g_out), _ = lax.scan(
        polish, (final.w, final.f, final.g), None, length=2
    )
    return OptimizerResult(
        w=w_out,
        value=f_out,
        grad_norm=jnp.linalg.norm(g_out),
        iterations=final.it,
        converged=reason_is_converged(final.reason),
        reason=final.reason,
        history_value=final.hv,
        history_grad_norm=final.hg,
        history_valid=final.hvalid,
    )
