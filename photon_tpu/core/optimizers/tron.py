"""TRON: trust-region Newton with a conjugate-gradient inner loop.

Rebuild of the reference's ``TRON`` (photon-lib .../optimization/TRON.scala,
itself a port of LIBLINEAR's tron.cpp — SURVEY.md §2.1): an outer trust-region
loop whose step comes from CG on Hessian-vector products, truncated at the
trust boundary.  Constants (eta0/1/2, sigma1/2/3, CG tolerance xi = 0.1)
follow LIBLINEAR so convergence behavior matches the reference closely
(SURVEY.md §7 'TRON parity').

Hessian-vector products are exact via ``jax.jvp`` of the gradient — the
reference's ``HessianVectorAggregator`` treeAggregate collapsed into the same
XLA program as the outer loop.  Both loops are masked ``lax.while_loop``s, so
TRON vmaps for batched per-entity GAME solves.

Departure from liblinear noted for reviewers: rejected trust-region trials
count against ``max_iterations`` here (the loop must be bounded for XLA);
liblinear only counts accepted steps.  With the standard radius-shrink logic
the difference shows up only on pathological problems.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from photon_tpu.core.optimizers.base import (
    ConvergenceReason,
    OptimizerConfig,
    OptimizerResult,
    check_convergence,
    init_history,
    reason_is_converged,
    record_history,
    tree_where,
)

Array = jax.Array

_ETA0, _ETA1, _ETA2 = 1e-4, 0.25, 0.75
_SIGMA1, _SIGMA2, _SIGMA3 = 0.25, 0.5, 4.0


class _CGState(NamedTuple):
    s: Array
    r: Array
    d: Array
    rtr: Array
    it: Array
    done: Array
    at_boundary: Array


def _trcg(hvp, g, delta, max_cg, active, cg_tolerance=0.1):
    """LIBLINEAR trcg: approximately solve H s = -g with ||s|| <= delta.

    Returns (s, r, at_boundary) where r = -g - H s is the residual."""
    cg_tol = cg_tolerance * jnp.linalg.norm(g)

    def cond(c: _CGState):
        return ~c.done

    def body(c: _CGState):
        hd = hvp(c.d)
        dhd = jnp.dot(c.d, hd)
        # Guard: curvature can be ~0 for flat directions; stop there.
        alpha = c.rtr / jnp.where(dhd > 1e-30, dhd, 1.0)
        bad_curv = dhd <= 1e-30
        s_try = c.s + alpha * c.d
        over = jnp.linalg.norm(s_try) > delta

        # Truncate to the trust boundary along d from the previous s.
        std = jnp.dot(c.s, c.d)
        sts = jnp.dot(c.s, c.s)
        dtd = jnp.dot(c.d, c.d)
        dsq = delta * delta
        rad = jnp.sqrt(jnp.maximum(std * std + dtd * (dsq - sts), 0.0))
        alpha_b = jnp.where(
            std >= 0.0,
            (dsq - sts) / jnp.maximum(std + rad, 1e-30),
            (rad - std) / jnp.maximum(dtd, 1e-30),
        )
        s_bound = c.s + alpha_b * c.d
        r_bound = c.r - alpha_b * hd

        s_in = s_try
        r_in = c.r - alpha * hd
        rtr_new = jnp.dot(r_in, r_in)
        beta = rtr_new / jnp.maximum(c.rtr, 1e-30)
        d_new = r_in + beta * c.d

        small_res = jnp.sqrt(rtr_new) <= cg_tol
        out_of_iters = c.it + 1 >= max_cg
        stop_boundary = over | bad_curv

        nxt = _CGState(
            s=jnp.where(stop_boundary, s_bound, s_in),
            r=jnp.where(stop_boundary, r_bound, r_in),
            d=d_new,
            rtr=rtr_new,
            it=c.it + 1,
            done=stop_boundary | small_res | out_of_iters,
            at_boundary=stop_boundary,
        )
        return tree_where(c.done, c, nxt)

    z = jnp.zeros_like(g)
    init = _CGState(
        s=z, r=-g, d=-g,
        rtr=jnp.dot(g, g),
        it=jnp.asarray(0, jnp.int32),
        done=~active | (jnp.sqrt(jnp.dot(g, g)) <= cg_tol),
        at_boundary=jnp.asarray(False),
    )
    final = lax.while_loop(cond, body, init)
    return final.s, final.r, final.at_boundary


class _State(NamedTuple):
    w: Array
    f: Array
    g: Array
    delta: Array
    it: Array
    accepted_iters: Array
    active: Array
    reason: Array
    hv: Array
    hg: Array
    hvalid: Array


def tron(
    fun: Callable[[Array], tuple[Array, Array]],
    w0: Array,
    config: OptimizerConfig = OptimizerConfig(),
    hvp: Callable[[Array, Array], Array] | None = None,
    hvp_at: Callable[[Array], Callable[[Array], Array]] | None = None,
) -> OptimizerResult:
    """Minimize ``fun`` (value, grad) with Hessian-vector products.

    ``hvp_at(w) -> (v -> H(w) v)`` is the preferred form (ISSUE 15
    satellite / ROADMAP solver edge (e)): the operator is built ONCE per
    outer trust-region iteration, so a curvature-closure operator
    (``GlmObjective.hvp_operator`` — per-row curvature ``D(w)`` precomputed
    from the margins) pays the margin pass once and each inner CG iteration
    costs two matvecs, instead of recomputing margins per product as the
    per-call form does.  ``hvp(w, v) -> H(w) v`` is the legacy per-call
    form (wrapped); with neither, the product derives from ``fun`` by jvp
    of the gradient component (exact, one extra forward-over-reverse pass
    per product — unchanged math, since jvp re-linearizes at the same
    ``w`` every call).
    """
    if hvp_at is None:
        if hvp is not None:
            def hvp_at(w):  # noqa: ANN001 — legacy per-call wrapper
                return lambda v: hvp(w, v)
        else:
            def hvp_at(w):  # noqa: ANN001 — jvp-of-grad fallback
                return lambda v: jax.jvp(
                    lambda u: fun(u)[1], (w,), (v,)
                )[1]

    d = w0.shape[0]
    max_cg = config.cg_max_iterations or min(d, 100)

    f0, g0 = fun(w0)
    gnorm0 = jnp.linalg.norm(g0)
    conv0 = gnorm0 == 0.0
    hv0, hg0, hvalid0 = init_history(config.max_iterations, f0, gnorm0)

    init = _State(
        w=w0, f=f0, g=g0,
        delta=gnorm0,
        it=jnp.asarray(0, jnp.int32),
        accepted_iters=jnp.asarray(0, jnp.int32),
        active=~conv0,
        reason=jnp.where(
            conv0, ConvergenceReason.GRADIENT_TOLERANCE, ConvergenceReason.NOT_CONVERGED
        ).astype(jnp.int32),
        hv=hv0, hg=hg0, hvalid=hvalid0,
    )

    def cond(s: _State):
        return s.active

    def body(s: _State):
        # ONE curvature operator per outer iteration: the precomputed-
        # curvature closure's margin pass runs here, not per CG product.
        step, resid, _ = _trcg(
            hvp_at(s.w), s.g, s.delta, max_cg, s.active,
            cg_tolerance=config.cg_tolerance,
        )
        w_new = s.w + step
        f_new, g_new = fun(w_new)

        gs = jnp.dot(s.g, step)
        prered = -0.5 * (gs - jnp.dot(step, resid))
        actred = s.f - f_new
        snorm = jnp.linalg.norm(step)

        # First successful iteration clamps the radius to the step size.
        delta = jnp.where(s.accepted_iters == 0, jnp.minimum(s.delta, snorm), s.delta)

        denom = f_new - s.f - gs
        alpha = jnp.where(denom <= 0.0, _SIGMA3, jnp.maximum(_SIGMA1, -0.5 * (gs / jnp.where(denom <= 0.0, 1.0, denom))))

        delta = jnp.where(
            actred < _ETA0 * prered,
            jnp.minimum(jnp.maximum(alpha, _SIGMA1) * snorm, _SIGMA2 * delta),
            jnp.where(
                actred < _ETA1 * prered,
                jnp.maximum(_SIGMA1 * delta, jnp.minimum(alpha * snorm, _SIGMA2 * delta)),
                jnp.where(
                    actred < _ETA2 * prered,
                    jnp.maximum(_SIGMA1 * delta, jnp.minimum(alpha * snorm, _SIGMA3 * delta)),
                    jnp.maximum(delta, jnp.minimum(alpha * snorm, _SIGMA3 * delta)),
                ),
            ),
        )

        accept = (actred > _ETA0 * prered) & jnp.isfinite(f_new)
        w_out = jnp.where(accept, w_new, s.w)
        f_out = jnp.where(accept, f_new, s.f)
        g_out = jnp.where(accept, g_new, s.g)
        gnorm_new = jnp.linalg.norm(g_out)

        converged, reason = check_convergence(f_out, s.f, gnorm_new, gnorm0, config)
        converged = converged & accept  # only test after accepted steps
        reason = jnp.where(accept, reason, ConvergenceReason.NOT_CONVERGED)
        # Degenerate model: no predicted reduction possible.
        degenerate = (prered <= 0.0) & (actred <= 0.0)
        reason = jnp.where(
            degenerate, ConvergenceReason.OBJECTIVE_NOT_IMPROVING, reason
        )
        it_new = s.it + 1
        hit_max = it_new >= config.max_iterations
        reason = jnp.where(
            hit_max & ~(converged | degenerate), ConvergenceReason.MAX_ITERATIONS, reason
        )
        still_active = s.active & ~(converged | degenerate | hit_max)

        hv, hg, hvalid = record_history(
            s.hv, s.hg, s.hvalid, it_new, f_out, gnorm_new, s.active & accept
        )

        new = _State(
            w=w_out, f=f_out, g=g_out,
            delta=delta,
            it=it_new,
            accepted_iters=s.accepted_iters + accept.astype(jnp.int32),
            active=still_active,
            reason=reason.astype(jnp.int32),
            hv=hv, hg=hg, hvalid=hvalid,
        )
        return tree_where(s.active, new, s)

    final = lax.while_loop(cond, body, init)
    return OptimizerResult(
        w=final.w,
        value=final.f,
        grad_norm=jnp.linalg.norm(final.g),
        iterations=final.it,
        converged=reason_is_converged(final.reason),
        reason=final.reason,
        history_value=final.hv,
        history_grad_norm=final.hg,
        history_valid=final.hvalid,
    )
