"""OWL-QN: orthant-wise limited-memory quasi-Newton for L1/elastic-net.

Rebuild of the reference's ``OWLQN`` (photon-lib .../optimization/OWLQN.scala,
wrapping ``breeze.optimize.OWLQN`` — SURVEY.md §2.1), re-expressed as a jitted
``lax.while_loop`` following Andrew & Gao (2007):

- the *pseudo-gradient* replaces the gradient of the (non-differentiable)
  L1 term,
- the L-BFGS two-loop direction (built from smooth-gradient (s, y) pairs) is
  *projected* onto the pseudo-gradient's descent orthant,
- each line-search trial point is *orthant-projected*: coordinates that cross
  zero are clamped to zero, which is what produces exact sparsity.

The smooth part of the objective (including any L2 term for elastic net) comes
from ``fun``; ``l1_weight`` is applied here, matching the reference's split
where L2 folds into the objective and L1 lives in the optimizer.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from photon_tpu.core.optimizers.base import (
    ConvergenceReason,
    OptimizerConfig,
    OptimizerResult,
    check_convergence,
    init_history,
    reason_is_converged,
    record_history,
    tree_where,
)
from photon_tpu.core.optimizers.lbfgs import _two_loop_direction

Array = jax.Array

_ARMIJO_C1 = 1e-4
_PAIR_EPS = 1e-10


def _pseudo_gradient(w: Array, g: Array, l1: Array) -> Array:
    """Andrew & Gao eq. (4): subgradient choice minimizing the norm."""
    left = g - l1
    right = g + l1
    at_zero = jnp.where(right < 0.0, right, jnp.where(left > 0.0, left, 0.0))
    return jnp.where(w > 0.0, right, jnp.where(w < 0.0, left, at_zero))


def _project_direction(d: Array, pg: Array) -> Array:
    """Zero out components of d not aligned with the steepest-descent
    direction -pg (orthant-wise projection of the quasi-Newton direction)."""
    return jnp.where(d * pg < 0.0, d, 0.0)


def _orthant_project(w_new: Array, xi: Array) -> Array:
    """Clamp coordinates that left the chosen orthant xi to zero."""
    return jnp.where(w_new * xi > 0.0, w_new, 0.0)


class _LineSearchState(NamedTuple):
    t: Array
    w: Array
    f: Array  # smooth value at w
    g: Array  # smooth grad at w
    ok: Array  # current trial satisfies the projected Armijo test
    it: Array
    halt: Array  # stop without success


class _State(NamedTuple):
    w: Array
    f: Array  # smooth value
    g: Array  # smooth grad
    S: Array
    Y: Array
    rho: Array
    num_pairs: Array
    insert_pos: Array
    gamma: Array
    it: Array
    active: Array
    reason: Array
    hv: Array
    hg: Array
    hvalid: Array


def owlqn(
    fun: Callable[[Array], tuple[Array, Array]],
    w0: Array,
    config: OptimizerConfig = OptimizerConfig(),
    l1_weight: float | Array = 0.0,
) -> OptimizerResult:
    """Minimize ``fun(w) + l1_weight * ||w||_1``.

    ``fun`` returns (smooth value, smooth grad).  With ``l1_weight == 0`` this
    degenerates to L-BFGS with a projected line search that never projects.
    History/tolerances are on the *total* (smooth + L1) objective, matching
    the reference's convergence semantics.
    """
    m = config.history_length
    d = w0.shape[0]
    l1 = jnp.asarray(l1_weight, w0.dtype)

    def total(w, f_smooth):
        return f_smooth + l1 * jnp.sum(jnp.abs(w))

    f0s, g0 = fun(w0)
    f0 = total(w0, f0s)
    pg0 = _pseudo_gradient(w0, g0, l1)
    gnorm0 = jnp.linalg.norm(pg0)
    conv0 = gnorm0 == 0.0
    hv, hg, hvalid = init_history(config.max_iterations, f0, gnorm0)

    init = _State(
        w=w0, f=f0s, g=g0,
        S=jnp.zeros((m, d), w0.dtype),
        Y=jnp.zeros((m, d), w0.dtype),
        rho=jnp.zeros(m, w0.dtype),
        num_pairs=jnp.asarray(0, jnp.int32),
        insert_pos=jnp.asarray(0, jnp.int32),
        gamma=jnp.asarray(1.0, w0.dtype),
        it=jnp.asarray(0, jnp.int32),
        active=~conv0,
        reason=jnp.where(
            conv0, ConvergenceReason.GRADIENT_TOLERANCE, ConvergenceReason.NOT_CONVERGED
        ).astype(jnp.int32),
        hv=hv, hg=hg, hvalid=hvalid,
    )

    def cond(s: _State):
        return s.active

    def body(s: _State):
        pg = _pseudo_gradient(s.w, s.g, l1)
        dvec = _two_loop_direction(
            pg, s.S, s.Y, s.rho, s.num_pairs, s.insert_pos, s.gamma, m
        )
        dvec = _project_direction(dvec, pg)
        dir_deriv = jnp.dot(pg, dvec)
        bad = dir_deriv >= 0.0
        dvec = jnp.where(bad, -pg, dvec)
        dir_deriv = jnp.where(bad, -jnp.dot(pg, pg), dir_deriv)
        # Orthant choice: sign(w), or sign(-pg) where w == 0.
        xi = jnp.where(s.w != 0.0, jnp.sign(s.w), -jnp.sign(pg))

        f_total_old = total(s.w, s.f)
        pgnorm = jnp.linalg.norm(pg)
        t0 = jnp.where(s.num_pairs == 0, 1.0 / jnp.maximum(pgnorm, 1.0), 1.0)

        def trial(t):
            w_t = _orthant_project(s.w + t * dvec, xi)
            f_s, g_s = fun(w_t)
            # Armijo on the total objective with the projected step:
            # f(w_t) <= f(w) + c1 * pg . (w_t - w)   (Andrew & Gao).
            descent = jnp.dot(pg, w_t - s.w)
            ok = (
                total(w_t, f_s) <= f_total_old + _ARMIJO_C1 * descent
            ) & jnp.isfinite(f_s)
            return w_t, f_s, g_s, ok

        w_i, f_i, g_i, ok_i = trial(t0)

        def ls_cond(ls: _LineSearchState):
            return ~(ls.ok | ls.halt)

        def ls_body(ls: _LineSearchState):
            t_new = ls.t * 0.5
            w_n, f_n, g_n, ok_n = trial(t_new)
            return _LineSearchState(
                t=t_new, w=w_n, f=f_n, g=g_n, ok=ok_n, it=ls.it + 1,
                halt=ls.it + 1 >= config.max_line_search,
            )

        ls0 = _LineSearchState(
            t=jnp.asarray(t0), w=w_i, f=f_i, g=g_i, ok=ok_i,
            it=jnp.asarray(0, jnp.int32), halt=~s.active,
        )
        ls = lax.while_loop(ls_cond, ls_body, ls0)

        svec = ls.w - s.w
        yvec = ls.g - s.g
        sy = jnp.dot(svec, yvec)
        pair_ok = ls.ok & (sy > _PAIR_EPS)
        S_new = s.S.at[s.insert_pos].set(jnp.where(pair_ok, svec, s.S[s.insert_pos]))
        Y_new = s.Y.at[s.insert_pos].set(jnp.where(pair_ok, yvec, s.Y[s.insert_pos]))
        rho_new = s.rho.at[s.insert_pos].set(
            jnp.where(pair_ok, 1.0 / jnp.where(pair_ok, sy, 1.0), s.rho[s.insert_pos])
        )
        num_pairs = jnp.where(pair_ok, jnp.minimum(s.num_pairs + 1, m), s.num_pairs)
        insert_pos = jnp.where(pair_ok, (s.insert_pos + 1) % m, s.insert_pos)
        gamma = jnp.where(pair_ok, sy / jnp.maximum(jnp.dot(yvec, yvec), 1e-30), s.gamma)

        pg_new = _pseudo_gradient(ls.w, ls.g, l1)
        pgnorm_new = jnp.linalg.norm(pg_new)
        f_total_new = total(ls.w, ls.f)
        converged, reason = check_convergence(
            f_total_new, f_total_old, pgnorm_new, gnorm0, config
        )
        stop_ls = ~ls.ok
        reason = jnp.where(stop_ls, ConvergenceReason.OBJECTIVE_NOT_IMPROVING, reason)
        it_new = s.it + 1
        hit_max = it_new >= config.max_iterations
        reason = jnp.where(
            hit_max & ~(converged | stop_ls), ConvergenceReason.MAX_ITERATIONS, reason
        )
        still_active = s.active & ~(converged | stop_ls | hit_max)

        w_out = jnp.where(ls.ok, ls.w, s.w)
        f_out = jnp.where(ls.ok, ls.f, s.f)
        g_out = jnp.where(ls.ok, ls.g, s.g)
        hv, hg, hvalid = record_history(
            s.hv, s.hg, s.hvalid, it_new,
            total(w_out, f_out), pgnorm_new, s.active & ls.ok,
        )

        new = _State(
            w=w_out, f=f_out, g=g_out,
            S=S_new, Y=Y_new, rho=rho_new,
            num_pairs=num_pairs, insert_pos=insert_pos, gamma=gamma,
            it=it_new, active=still_active,
            reason=reason.astype(jnp.int32),
            hv=hv, hg=hg, hvalid=hvalid,
        )
        return tree_where(s.active, new, s)

    final = lax.while_loop(cond, body, init)

    # Full-step polish (same graft as lbfgs.py): two unsearched steps of
    # the final quasi-Newton map, run through OWL-QN's machinery — the
    # direction is built from the PSEUDO-gradient, projected onto its
    # descent orthant, and the stepped point is orthant-projected, so
    # polish can only sharpen coordinates inside the orthant the loop
    # settled in (exact zeros stay exactly zero).  Kept per lane only if
    # the step is small relative to the iterate, everything stays
    # finite, and the pseudo-gradient norm does not grow.
    def polish(carry, _):
        w, f, g = carry
        pg = _pseudo_gradient(w, g, l1)
        step = _project_direction(
            _two_loop_direction(
                pg, final.S, final.Y, final.rho, final.num_pairs,
                final.insert_pos, final.gamma, m,
            ),
            pg,
        )
        near = jnp.all(jnp.isfinite(step)) & (
            jnp.linalg.norm(step)
            <= 1e-3 * jnp.maximum(jnp.linalg.norm(w), 1.0)
        )
        xi = jnp.where(w != 0.0, jnp.sign(w), -jnp.sign(pg))
        w_new = jnp.where(near, _orthant_project(w + step, xi), w)
        f_new, g_new = fun(w_new)
        pg_new = _pseudo_gradient(w_new, g_new, l1)
        keep = (
            near & jnp.isfinite(f_new) & jnp.all(jnp.isfinite(g_new))
            & (jnp.linalg.norm(pg_new) <= jnp.linalg.norm(pg))
        )
        return (
            jnp.where(keep, w_new, w),
            jnp.where(keep, f_new, f),
            jnp.where(keep, g_new, g),
        ), None

    (w_out, f_out, g_out), _ = lax.scan(
        polish, (final.w, final.f, final.g), None, length=2
    )
    pg_final = _pseudo_gradient(w_out, g_out, l1)
    return OptimizerResult(
        w=w_out,
        value=total(w_out, f_out),
        grad_norm=jnp.linalg.norm(pg_final),
        iterations=final.it,
        converged=reason_is_converged(final.reason),
        reason=final.reason,
        history_value=final.hv,
        history_grad_norm=final.hg,
        history_valid=final.hvalid,
    )
