"""Batch second-order optimizers as jit-compiled ``lax.while_loop`` machines.

Rebuild of the reference's optimizer framework (photon-lib .../optimization:
``Optimizer``, ``LBFGS``, ``OWLQN``, ``TRON``, ``OptimizerConfig``,
``OptimizationStatesTracker`` — SURVEY.md §2.1).  Where the reference
delegates L-BFGS/OWL-QN internals to Breeze and runs one driver↔executor
round-trip per function evaluation, these optimizers are single fused XLA
programs: the entire optimize() loop — line searches, two-loop recursion,
CG inner loops — compiles once and runs on-device.  All state updates are
masked on an ``active`` flag so the loops vmap correctly for GAME's batched
per-entity solves (converged lanes freeze while others continue).
"""

from photon_tpu.core.optimizers.base import (  # noqa: F401
    ConvergenceReason,
    OptimizationStatesTracker,
    OptimizerConfig,
    OptimizerResult,
)
from photon_tpu.core.optimizers.lbfgs import lbfgs  # noqa: F401
from photon_tpu.core.optimizers.newton import newton  # noqa: F401
from photon_tpu.core.optimizers.newton_cg import newton_cg  # noqa: F401
from photon_tpu.core.optimizers.owlqn import owlqn  # noqa: F401
from photon_tpu.core.optimizers.tron import tron  # noqa: F401


def get_optimizer(name: str):
    name = name.lower()
    if name in ("lbfgs", "l-bfgs"):
        return lbfgs
    if name in ("owlqn", "owl-qn"):
        return owlqn
    if name == "tron":
        return tron
    if name in ("newton_cg", "newton-cg"):
        return newton_cg
    raise KeyError(
        f"unknown optimizer {name!r}; available: lbfgs, owlqn, tron, "
        "newton_cg"
    )
