"""Shared optimizer types: config, result, convergence reasons, states tracker.

Mirrors the reference's ``OptimizerConfig`` and ``OptimizationStatesTracker``
(photon-lib .../optimization — SURVEY.md §2.1, §5 'Tracing'): the tracker's
per-iteration (value, gradient-norm, convergence-reason) history is the main
observable of a training run and part of the public API surface.  Because the
loop runs inside jit, history is recorded into fixed-size device arrays and
materialized host-side afterwards.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


class ConvergenceReason:
    """Integer codes for why optimization stopped (jit-friendly enum).

    Matches the reference's convergence-reason semantics: max iterations,
    function-value tolerance, gradient tolerance.
    """

    NOT_CONVERGED = 0
    MAX_ITERATIONS = 1
    FUNCTION_VALUES_TOLERANCE = 2
    GRADIENT_TOLERANCE = 3
    OBJECTIVE_NOT_IMPROVING = 4  # line search failed to find descent

    NAMES = {
        0: "NOT_CONVERGED",
        1: "MAX_ITERATIONS",
        2: "FUNCTION_VALUES_TOLERANCE",
        3: "GRADIENT_TOLERANCE",
        4: "OBJECTIVE_NOT_IMPROVING",
    }


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    """Static (trace-time) optimizer configuration.

    ``tolerance`` is the relative function-value tolerance and
    ``gradient_tolerance`` the relative gradient-norm tolerance
    (``||g|| <= gtol * max(1, ||g0||)``), both checked each iteration as in
    the reference.  ``history_length`` is the L-BFGS memory; ``max_line_search``
    bounds the inner line-search loop (static for XLA).
    """

    max_iterations: int = 100
    tolerance: float = 1e-7
    gradient_tolerance: float = 1e-6
    history_length: int = 10
    max_line_search: int = 25
    # Inner-CG bounds (TRON and newton_cg).  0 -> a dimension-capped
    # per-solver default: min(dim, 100) for TRON (LIBLINEAR's constant),
    # min(dim, 256) for newton_cg (whose dims run past 100 by design).
    cg_max_iterations: int = 0
    cg_tolerance: float = 0.1

    def replace(self, **kw) -> "OptimizerConfig":
        return dataclasses.replace(self, **kw)


class OptimizerResult(NamedTuple):
    """Final state plus fixed-size per-iteration history (device arrays).

    ``history_*`` arrays have length ``max_iterations + 1`` (entry 0 is the
    initial point); entries at index > iterations are garbage — mask with
    ``history_valid``.
    """

    w: Array
    value: Array
    grad_norm: Array
    iterations: Array  # int32: number of outer iterations performed
    converged: Array  # bool
    reason: Array  # int32 ConvergenceReason code
    history_value: Array  # [max_iter+1]
    history_grad_norm: Array  # [max_iter+1]
    history_valid: Array  # [max_iter+1] bool
    # int32 total inner-CG iterations, set only by solvers with a CG inner
    # loop (newton_cg); None elsewhere — a None leaf is an empty pytree
    # subtree, so existing jit/vmap programs are unchanged.
    cg_iterations: Array | None = None


class OptimizationStatesTracker:
    """Host-side view of an optimization run's per-iteration history.

    API-parity object for the reference's OptimizationStatesTracker: iterate
    to get (iteration, value, gradient norm), query the convergence reason.
    """

    def __init__(self, result: OptimizerResult, wall_time_s: float | None = None):
        valid = np.asarray(result.history_valid)
        self.values = np.asarray(result.history_value)[valid]
        self.grad_norms = np.asarray(result.history_grad_norm)[valid]
        self.iterations = int(result.iterations)
        self.converged = bool(result.converged)
        self.reason_code = int(result.reason)
        self.wall_time_s = wall_time_s

    @property
    def convergence_reason(self) -> str:
        return ConvergenceReason.NAMES.get(self.reason_code, "UNKNOWN")

    def __iter__(self):
        return iter(zip(range(len(self.values)), self.values, self.grad_norms))

    def states(self) -> list:
        """JSON-ready per-iteration trace ``[[value, |grad|], ...]`` — the
        reference dumps this tracker to logs; drivers keep it in
        training_summary.json so convergence curves survive the run
        (SURVEY.md §5 tracing)."""
        return [[float(v), float(g)] for _, v, g in self]

    def record_to(self, registry, **labels) -> None:
        """Push this run's summary into a telemetry metrics registry
        (photon_tpu.telemetry; duck-typed so the optimizer layer stays
        import-free of it): solve counts, iteration totals, a stop-reason
        breakdown, solve-seconds distribution, and final value/|grad|."""
        labels = {k: str(v) for k, v in labels.items()}
        registry.counter("optimizer.solves", **labels).inc()
        registry.counter("optimizer.iterations", **labels).inc(self.iterations)
        if self.converged:
            registry.counter("optimizer.converged_solves", **labels).inc()
        registry.counter(
            "optimizer.stop_reason", reason=self.convergence_reason, **labels
        ).inc()
        if self.wall_time_s is not None:
            registry.histogram("optimizer.solve_seconds", **labels).observe(
                self.wall_time_s
            )
        if len(self.values):
            registry.gauge("optimizer.final_value", **labels).set(
                float(self.values[-1])
            )
            registry.gauge("optimizer.final_grad_norm", **labels).set(
                float(self.grad_norms[-1])
            )

    def summary(self) -> str:
        lines = [
            f"iterations={self.iterations} converged={self.converged} "
            f"reason={self.convergence_reason}"
            + (f" wall={self.wall_time_s:.3f}s" if self.wall_time_s is not None else "")
        ]
        for i, v, g in self:
            lines.append(f"  iter {i:4d}  f={v:.10g}  |g|={g:.6g}")
        return "\n".join(lines)


def init_history(max_iterations: int, f0: Array, gnorm0: Array):
    """History arrays with slot 0 holding the initial point."""
    n = max_iterations + 1
    hv = jnp.zeros(n, dtype=f0.dtype).at[0].set(f0)
    hg = jnp.zeros(n, dtype=gnorm0.dtype).at[0].set(gnorm0)
    valid = jnp.zeros(n, dtype=bool).at[0].set(True)
    return hv, hg, valid


def record_history(hv, hg, valid, idx, f, gnorm, active):
    """Write (f, |g|) into slot ``idx`` when ``active`` (masked for vmap)."""
    hv = hv.at[idx].set(jnp.where(active, f, hv[idx]))
    hg = hg.at[idx].set(jnp.where(active, gnorm, hg[idx]))
    valid = valid.at[idx].set(valid[idx] | active)
    return hv, hg, valid


def reason_is_converged(reason: Array) -> Array:
    """True only for genuine convergence (tolerance met) — not for running
    out of iterations or a failed line search."""
    return (reason == ConvergenceReason.FUNCTION_VALUES_TOLERANCE) | (
        reason == ConvergenceReason.GRADIENT_TOLERANCE
    )


def tree_where(pred: Array, a, b):
    """Elementwise select over a pytree (per-lane freeze for vmapped loops)."""
    return jax.tree.map(lambda x, y: jnp.where(pred, x, y), a, b)


def check_convergence(
    f_new: Array,
    f_old: Array,
    gnorm: Array,
    gnorm0: Array,
    config: OptimizerConfig,
):
    """Return (converged, reason) per the reference's tolerance semantics."""
    rel_improve = jnp.abs(f_old - f_new) / jnp.maximum(jnp.abs(f_old), 1e-12)
    f_conv = rel_improve <= config.tolerance
    g_conv = gnorm <= config.gradient_tolerance * jnp.maximum(gnorm0, 1.0)
    reason = jnp.where(
        g_conv,
        ConvergenceReason.GRADIENT_TOLERANCE,
        jnp.where(
            f_conv,
            ConvergenceReason.FUNCTION_VALUES_TOLERANCE,
            ConvergenceReason.NOT_CONVERGED,
        ),
    )
    return f_conv | g_conv, reason
