"""Matrix-free damped Newton with a preconditioned-CG inner solve.

The batched Cholesky Newton (``newton.py``) materializes a dense
``[dim, dim]`` Hessian per iteration — under ``jax.vmap`` that is a
``[B, dim, dim]`` block whose memory and factorization cost cap the GAME
entity solves at ``PHOTON_NEWTON_MAX_DIM`` (ISSUE 14).  This solver keeps
the SAME outer structure (masked ``lax.while_loop`` damped Newton, the
shared Armijo backtracking, the guarded full-step gradient polish) but
computes each Newton step by conjugate gradients on Hessian-VECTOR
products: for GLM objectives ``H v = Xᵀ(D(w)·(X v)) + λ₂ v`` — two sparse
matvecs, never a matrix (Snap ML, PAPERS.md 1803.06333, solves the same
hierarchical per-partition GLM subproblems second-order; the dense
factorizations this route avoids are exactly the shapes 2112.09017
distributes when a single one no longer fits).

Design points:

- **Curvature operator per outer iteration** — ``hvp_at(w)`` returns a
  closure evaluating ``H(w)·v``; the GLM objective's ``hvp_operator``
  precomputes the per-row curvature ``D(w)`` once, so each CG iteration
  costs two matvecs, not a margin recomputation.
- **Jacobi preconditioner** — ``diag(w)`` (the cheap
  ``objective.hessian_diagonal``) scales the CG residual; for the skewed
  per-entity feature scales of random-effect bins this is the difference
  between O(rank) and O(κ) inner iterations.
- **Eisenstat-Walker forcing** — the inner tolerance is per-lane adaptive,
  ``η_k = min(0.5, sqrt(‖g_k‖/‖g_0‖))``: early outer iterations solve the
  Newton system loosely (a handful of CG steps), late ones tightly enough
  to keep the quadratic contraction — the classic inexact-Newton rule.
- **Negative-curvature fallback** — GLM+L2 Hessians are PD, but a flat or
  injected direction with ``dᵀHd ≤ 0`` stops CG at the current iterate;
  a first-iteration hit falls back to the preconditioned steepest-descent
  direction, which the Armijo search then damps (same guard philosophy as
  ``newton.py``'s non-PD Cholesky fallback).

Same contract as the other optimizers: every state update is masked on
``active`` so converged lanes FREEZE under vmap, tolerance semantics match
``base.check_convergence``, and the result's ``cg_iterations`` field
carries the total inner-CG work for the ``solves.cg_iters`` telemetry.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from photon_tpu.core.optimizers.base import (
    ConvergenceReason,
    OptimizerConfig,
    OptimizerResult,
    check_convergence,
    init_history,
    reason_is_converged,
    record_history,
    tree_where,
)
from photon_tpu.core.optimizers.lbfgs import _backtracking_line_search

Array = jax.Array

# Floor on the Jacobi preconditioner diagonal: keeps the scaling defined on
# flat directions (an entity whose rows never touch a feature) without
# moving the preconditioned system for any live curvature.
_DIAG_FLOOR = 1e-12
# Relative CG tolerance of the two polish steps: loose enough to stay
# O(rank) iterations, tight enough that the Newton contraction still lands
# ~1e-6 from the optimum after two steps (see the polish note below).
_POLISH_ETA = 1e-2


class _CGState(NamedTuple):
    p: Array
    r: Array
    z: Array
    dvec: Array
    rz: Array
    it: Array
    done: Array


def _pcg(hv, g: Array, mdiag: Array, tol: Array, max_cg: int, active):
    """Jacobi-preconditioned CG on ``H p = -g``; returns ``(p, iters)``.

    Stops on ``‖r‖ ≤ tol``, ``max_cg`` iterations, or negative curvature
    (``dᵀHd ≤ 0`` — the current iterate is returned; on the FIRST
    iteration that is the preconditioned steepest-descent direction, the
    documented fallback).  Inert when ``active`` is False (vmap freeze).
    """
    b = -g
    z0 = b / mdiag
    rz0 = jnp.dot(b, z0)
    init = _CGState(
        p=jnp.zeros_like(g), r=b, z=z0, dvec=z0, rz=rz0,
        it=jnp.asarray(0, jnp.int32),
        done=~active | (jnp.linalg.norm(b) <= tol) | ~jnp.isfinite(rz0),
    )

    def cond(c: _CGState):
        return ~c.done

    def body(c: _CGState):
        hd = hv(c.dvec)
        dhd = jnp.dot(c.dvec, hd)
        neg = dhd <= 0.0
        alpha = c.rz / jnp.where(neg, 1.0, dhd)
        p_new = c.p + alpha * c.dvec
        r_new = c.r - alpha * hd
        z_new = r_new / mdiag
        rz_new = jnp.dot(r_new, z_new)
        beta = rz_new / jnp.where(c.rz > 0.0, c.rz, 1.0)
        d_new = z_new + beta * c.dvec
        # Negative curvature keeps the best iterate so far: the current p,
        # or the preconditioned gradient on a first-iteration hit (c.z is
        # still z0 there) — always a descent direction for the outer
        # Armijo search to damp.
        p_out = jnp.where(
            neg, jnp.where(c.it == 0, c.z, c.p), p_new
        )
        it_new = c.it + 1
        done_new = (
            neg
            | (jnp.linalg.norm(r_new) <= tol)
            | (it_new >= max_cg)
            | ~jnp.isfinite(rz_new)
        )
        nxt = _CGState(
            p=p_out, r=r_new, z=z_new, dvec=d_new, rz=rz_new,
            it=it_new, done=done_new,
        )
        return tree_where(c.done, c, nxt)

    final = lax.while_loop(cond, body, init)
    return final.p, final.it


def newton_cg(
    fun: Callable[[Array], tuple[Array, Array]],
    w0: Array,
    config: OptimizerConfig = OptimizerConfig(),
    hvp_at: Optional[Callable[[Array], Callable[[Array], Array]]] = None,
    diag: Optional[Callable[[Array], Array]] = None,
) -> OptimizerResult:
    """Minimize ``fun`` (returning (value, grad)) by inexact Newton-CG.

    ``hvp_at(w)`` returns the curvature operator ``v -> H(w)·v`` (for GLM
    objectives, ``objective.hvp_operator(w, batch)`` — the per-row
    curvature is precomputed once per outer iteration); if None it is
    derived from ``fun`` by jvp of the gradient (exact, matrix-free).
    ``diag(w)`` supplies the Jacobi-preconditioner diagonal (for GLMs,
    ``objective.hessian_diagonal``); if None the identity is used.
    ``config.cg_max_iterations`` bounds the inner loop (0 → ``min(dim,
    256)``).  Pure JAX: safe under jit and vmap (the GAME batched
    large-dim entity solves).
    """
    if hvp_at is None:
        def hvp_at(w):  # noqa: ANN001 — jvp-of-grad fallback
            return lambda v: jax.jvp(lambda u: fun(u)[1], (w,), (v,))[1]
    if diag is None:
        def diag(w):  # noqa: ANN001
            return jnp.ones_like(w)

    d = w0.shape[0]
    max_cg = (
        config.cg_max_iterations
        if config.cg_max_iterations > 0
        else min(int(d), 256)
    )
    f0, g0 = fun(w0)
    gnorm0 = jnp.linalg.norm(g0)
    conv0 = gnorm0 == 0.0
    hv0, hg0, hvalid0 = init_history(config.max_iterations, f0, gnorm0)

    class _State(NamedTuple):
        w: Array
        f: Array
        g: Array
        it: Array
        active: Array
        reason: Array
        cg: Array
        hv: Array
        hg: Array
        hvalid: Array

    init = _State(
        w=w0, f=f0, g=g0,
        it=jnp.asarray(0, jnp.int32),
        active=~conv0,
        reason=jnp.where(
            conv0, ConvergenceReason.GRADIENT_TOLERANCE,
            ConvergenceReason.NOT_CONVERGED,
        ).astype(jnp.int32),
        cg=jnp.asarray(0, jnp.int32),
        hv=hv0, hg=hg0, hvalid=hvalid0,
    )

    def cond(s: _State):
        return s.active

    def body(s: _State):
        hv = hvp_at(s.w)
        mdiag = jnp.maximum(diag(s.w), _DIAG_FLOOR)
        gnorm = jnp.linalg.norm(s.g)
        # Eisenstat-Walker forcing term (sqrt variant): loose early, tight
        # near the optimum — superlinear outer convergence at O(rank)
        # inner iterations per step.
        eta = jnp.minimum(0.5, jnp.sqrt(gnorm / jnp.maximum(gnorm0, 1e-30)))
        step, cg_it = _pcg(hv, s.g, mdiag, eta * gnorm, max_cg, s.active)
        dir_deriv = jnp.dot(s.g, step)
        # A non-finite or non-descent CG result falls back to steepest
        # descent for this iteration (same guard as newton.py).
        bad = ~jnp.all(jnp.isfinite(step)) | (dir_deriv >= 0.0)
        step = jnp.where(bad, -s.g, step)
        dir_deriv = jnp.where(bad, -jnp.dot(s.g, s.g), dir_deriv)
        t0 = jnp.where(bad, 1.0 / jnp.maximum(gnorm, 1.0), 1.0)

        t, f_new, g_new, ls_ok = _backtracking_line_search(
            fun, s.w, step, s.f, dir_deriv, t0, config.max_line_search,
            s.active,
        )
        w_new = s.w + t * step

        gnorm_new = jnp.linalg.norm(g_new)
        converged, reason = check_convergence(
            f_new, s.f, gnorm_new, gnorm0, config
        )
        stop_ls = ~ls_ok
        reason = jnp.where(
            stop_ls, ConvergenceReason.OBJECTIVE_NOT_IMPROVING, reason
        )
        it_new = s.it + 1
        hit_max = it_new >= config.max_iterations
        reason = jnp.where(
            hit_max & ~(converged | stop_ls),
            ConvergenceReason.MAX_ITERATIONS, reason,
        )
        still_active = s.active & ~(converged | stop_ls | hit_max)

        # On line-search failure keep the old iterate (matching lbfgs).
        w_out = jnp.where(ls_ok, w_new, s.w)
        f_out = jnp.where(ls_ok, f_new, s.f)
        g_out = jnp.where(ls_ok, g_new, s.g)
        hv_h, hg_h, hvalid_h = record_history(
            s.hv, s.hg, s.hvalid, it_new, f_out, jnp.linalg.norm(g_out),
            s.active & ls_ok,
        )

        new = _State(
            w=w_out, f=f_out, g=g_out,
            it=it_new, active=still_active,
            reason=reason.astype(jnp.int32),
            cg=s.cg + cg_it,
            hv=hv_h, hg=hg_h, hvalid=hvalid_h,
        )
        return tree_where(s.active, new, s)

    final = lax.while_loop(cond, body, init)

    # Full-step polish — the same contraction-on-the-f32-gradient trick as
    # newton.py (its docstring carries the full argument): the line-searched
    # loop stalls where f32 FUNCTION differences round to zero, ~1e-4 from
    # the true optimum; two guarded full Newton steps (here: CG solves at a
    # tight relative tolerance) keep contracting on the f32 GRADIENT's zero
    # and land ~1e-6 away — what the ≤1e-5 ground-truth parity pins.
    # Guarded identically: only near-steps (small relative to the iterate)
    # with finite outcomes are kept.
    def polish(carry, _):
        w, f, g, cg = carry
        hv = hvp_at(w)
        mdiag = jnp.maximum(diag(w), _DIAG_FLOOR)
        gnorm = jnp.linalg.norm(g)
        step, cg_it = _pcg(
            hv, g, mdiag, _POLISH_ETA * gnorm, max_cg, jnp.asarray(True)
        )
        near = jnp.all(jnp.isfinite(step)) & (
            jnp.linalg.norm(step)
            <= 1e-3 * jnp.maximum(jnp.linalg.norm(w), 1.0)
        )
        w_new = jnp.where(near, w + step, w)
        f_new, g_new = fun(w_new)
        keep = near & jnp.isfinite(f_new) & jnp.all(jnp.isfinite(g_new))
        return (
            jnp.where(keep, w_new, w),
            jnp.where(keep, f_new, f),
            jnp.where(keep, g_new, g),
            cg + cg_it,
        ), None

    (w_out, f_out, g_out, cg_out), _ = lax.scan(
        polish, (final.w, final.f, final.g, final.cg), None, length=2
    )
    return OptimizerResult(
        w=w_out,
        value=f_out,
        grad_norm=jnp.linalg.norm(g_out),
        iterations=final.it,
        converged=reason_is_converged(final.reason),
        reason=final.reason,
        history_value=final.hv,
        history_grad_norm=final.hg,
        history_valid=final.hvalid,
        cg_iterations=cg_out,
    )
