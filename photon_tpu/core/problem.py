"""Optimization problems: optimizer + objective + variance, bound together.

Rebuild of the reference's ``DistributedOptimizationProblem`` /
``SingleNodeOptimizationProblem`` (photon-api .../optimization — SURVEY.md
§2.2): a problem owns an objective (local or distributed), an optimizer
choice, regularization, and optional per-coefficient variance computation
(``VarianceComputationType`` NONE/SIMPLE — diagonal-Hessian inverse, the
GLMix posterior approximation).

One class serves both roles: the objective it is built with decides whether
gradients psum over a mesh (DistributedGlmObjective) or stay local
(GlmObjective) — the optimizer code cannot tell the difference.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Union

import jax
import jax.numpy as jnp

from photon_tpu.core.objective import GlmObjective, RegularizationContext
from photon_tpu.core.optimizers import (
    OptimizerConfig,
    get_optimizer,
    lbfgs,
    newton_cg,
    owlqn,
    tron,
)
from photon_tpu.data.batch import Batch
from photon_tpu.models.glm import Coefficients

Array = jax.Array

VARIANCE_TYPES = ("none", "simple", "full")


@dataclasses.dataclass(frozen=True)
class ProblemConfig:
    """Per-coordinate training configuration (optimizer + regularization +
    tolerances), the analog of the reference's optimization configs."""

    optimizer: str = "lbfgs"
    regularization: RegularizationContext = RegularizationContext()
    optimizer_config: OptimizerConfig = OptimizerConfig()
    variance_computation: str = "none"

    def __post_init__(self):
        get_optimizer(self.optimizer)  # validate early
        if self.variance_computation not in VARIANCE_TYPES:
            raise ValueError(
                f"unknown variance computation {self.variance_computation!r}"
            )
        if self.regularization.l1_weight > 0 and self.optimizer.lower() not in (
            "owlqn",
            "owl-qn",
        ):
            raise ValueError(
                "L1/elastic-net regularization requires the OWL-QN optimizer "
                "(the reference enforces the same pairing)"
            )

    def replace(self, **kw) -> "ProblemConfig":
        return dataclasses.replace(self, **kw)


def hvp_at_for(objective, batch: Batch):
    """Curvature-operator factory for Newton-CG: ``w -> (v -> H(w)·v)``.

    Plain :class:`GlmObjective`s expose ``hvp_operator`` (per-row curvature
    precomputed once per outer iteration — each CG step is two matvecs);
    objectives without it (the distributed/row-split wrappers) fall back
    to a per-call ``hessian_vector``, which is still matrix-free."""
    op = getattr(objective, "hvp_operator", None)
    if op is not None:
        return lambda w: op(w, batch)
    return lambda w: (lambda v: objective.hessian_vector(w, v, batch))


def _run_fit(objective, batch: Batch, w0: Array, *, optimizer: str,
             cfg: OptimizerConfig, variance: str):
    """One GLM fit, pure in (objective, batch, w0) — the body every cached
    solver compiles.  The objective is a PYTREE ARGUMENT (reg weights and
    normalization arrays are dynamic leaves), so one compiled program serves
    an entire lambda sweep / hyperparameter search; only shapes, the loss,
    the optimizer, and its static config retrace."""
    fun = lambda w: objective.value_and_grad(w, batch)  # noqa: E731
    if optimizer in ("owlqn", "owl-qn"):
        result = owlqn(fun, w0, cfg, l1_weight=objective.l1_weight)
    elif optimizer == "tron":
        # The precomputed-curvature operator (hvp_operator): margins/D(w)
        # once per trust-region iteration, two matvecs per CG product —
        # TRON stops recomputing margins per product (ROADMAP solver
        # edge (e); objectives without hvp_operator fall back to per-call
        # hessian_vector inside hvp_at_for, still matrix-free).
        result = tron(fun, w0, cfg, hvp_at=hvp_at_for(objective, batch))
    elif optimizer in ("newton_cg", "newton-cg"):
        result = newton_cg(
            fun, w0, cfg,
            hvp_at=hvp_at_for(objective, batch),
            diag=lambda w: objective.hessian_diagonal(w, batch),
        )
    else:
        result = lbfgs(fun, w0, cfg)
    coefficients = Coefficients(
        means=result.w,
        variances=_compute_variances(objective, variance, result.w, batch),
    )
    return coefficients, result


def cached_solver(optimizer: str, cfg: OptimizerConfig, variance: str,
                  vmapped: bool = False):
    """The jit-compiled solver for one static problem configuration.

    Signature of the returned callable: ``(objective, batch, w0)`` —
    ``vmapped=True`` maps (batch, w0) over a leading entity axis with the
    objective held constant (the GAME random-effect bucket solve).  Cached at
    module level so every coordinate, sweep config, and tuning trial with the
    same static configuration shares one traced program (jit's own cache then
    keys on shapes + objective pytree structure).  The cache is BOUNDED: each
    entry pins its compiled executables for the process lifetime (the hazard
    core/variance.py documents), so a search varying static keys (tolerances,
    max_iterations) evicts old solvers instead of growing without limit —
    eviction only costs a retrace on reuse."""
    # Normalize + reject typos BEFORE the lru_cache key is formed: _run_fit
    # dispatches on exact lowercase names and its else-branch is lbfgs, and
    # lowercasing outside the cache keeps 'TRON'/'tron' from occupying two
    # cache slots.
    optimizer = optimizer.lower()
    get_optimizer(optimizer)
    if variance not in VARIANCE_TYPES:
        raise ValueError(f"unknown variance computation {variance!r}")
    return _cached_solver(optimizer, cfg, variance, vmapped)


@functools.lru_cache(maxsize=32)
def _cached_solver(optimizer: str, cfg: OptimizerConfig, variance: str,
                   vmapped: bool):
    run = functools.partial(_run_fit, optimizer=optimizer, cfg=cfg,
                            variance=variance)
    if vmapped:
        run = jax.vmap(run, in_axes=(None, 0, 0))
    return jax.jit(run)


class GlmOptimizationProblem:
    """Runs one GLM fit: ``run(batch, w0) -> (Coefficients, OptimizerResult)``.

    ``objective`` may be a plain :class:`GlmObjective` (single-node path) or a
    :class:`~photon_tpu.parallel.distributed.DistributedGlmObjective`
    (mesh path); both expose the same evaluation methods.
    """

    def __init__(self, objective, config: ProblemConfig):
        self.objective = objective
        self.config = config

    def solver(self, vmapped: bool = False):
        """This problem's shared jitted solver (see :func:`cached_solver`)."""
        return cached_solver(
            self.config.optimizer.lower(),
            self.config.optimizer_config,
            self.config.variance_computation,
            vmapped,
        )

    def run(
        self, batch: Batch, w0: Optional[Array] = None, dim: Optional[int] = None
    ):
        if w0 is None:
            if dim is None:
                raise ValueError("need w0 or dim")
            w0 = jnp.zeros(dim, jnp.float32)
        return self.solver()(self.objective, batch, w0)

    def compute_variances(self, w: Array, batch: Batch) -> Optional[Array]:
        return _compute_variances(
            self.objective, self.config.variance_computation, w, batch
        )


def _compute_variances(objective, kind: str, w: Array, batch: Batch) -> Optional[Array]:
    """Per-coefficient posterior variances at the optimum (SURVEY.md
    §2.2 'L2 + variance'): SIMPLE = 1/diag(H); FULL = diag(H⁻¹) — a
    Cholesky solve of the dense Hessian up to FULL_DENSE_MAX_DIM, a
    matrix-free CG/Hutchinson estimate above it (the dense ``[d, d]``
    materialization is a 256 GB allocation at the bench dimension —
    see core/variance.py)."""
    if kind == "none":
        return None
    if kind == "full":
        from photon_tpu.core.variance import (
            FULL_DENSE_MAX_DIM,
            hutchinson_diag_inverse,
        )

        d = int(w.shape[0])
        if d > FULL_DENSE_MAX_DIM:
            return hutchinson_diag_inverse(
                lambda v: objective.hessian_vector(w, v, batch),
                dim=d,
            )
        h = objective.hessian_matrix(w, batch)
        # Tiny jitter keeps the factorization defined for flat
        # directions (e.g. unreached features with zero curvature).
        chol = jax.scipy.linalg.cho_factor(h + 1e-9 * jnp.eye(d, dtype=h.dtype))
        inv = jax.scipy.linalg.cho_solve(chol, jnp.eye(d, dtype=h.dtype))
        return jnp.maximum(jnp.diagonal(inv), 0.0)
    diag = objective.hessian_diagonal(w, batch)
    return 1.0 / jnp.maximum(diag, 1e-12)
