"""Optimization problems: optimizer + objective + variance, bound together.

Rebuild of the reference's ``DistributedOptimizationProblem`` /
``SingleNodeOptimizationProblem`` (photon-api .../optimization — SURVEY.md
§2.2): a problem owns an objective (local or distributed), an optimizer
choice, regularization, and optional per-coefficient variance computation
(``VarianceComputationType`` NONE/SIMPLE — diagonal-Hessian inverse, the
GLMix posterior approximation).

One class serves both roles: the objective it is built with decides whether
gradients psum over a mesh (DistributedGlmObjective) or stay local
(GlmObjective) — the optimizer code cannot tell the difference.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Union

import jax
import jax.numpy as jnp

from photon_tpu.core.objective import GlmObjective, RegularizationContext
from photon_tpu.core.optimizers import OptimizerConfig, get_optimizer, lbfgs, owlqn, tron
from photon_tpu.data.batch import Batch
from photon_tpu.models.glm import Coefficients

Array = jax.Array

VARIANCE_TYPES = ("none", "simple", "full")


@dataclasses.dataclass(frozen=True)
class ProblemConfig:
    """Per-coordinate training configuration (optimizer + regularization +
    tolerances), the analog of the reference's optimization configs."""

    optimizer: str = "lbfgs"
    regularization: RegularizationContext = RegularizationContext()
    optimizer_config: OptimizerConfig = OptimizerConfig()
    variance_computation: str = "none"

    def __post_init__(self):
        get_optimizer(self.optimizer)  # validate early
        if self.variance_computation not in VARIANCE_TYPES:
            raise ValueError(
                f"unknown variance computation {self.variance_computation!r}"
            )
        if self.regularization.l1_weight > 0 and self.optimizer.lower() not in (
            "owlqn",
            "owl-qn",
        ):
            raise ValueError(
                "L1/elastic-net regularization requires the OWL-QN optimizer "
                "(the reference enforces the same pairing)"
            )

    def replace(self, **kw) -> "ProblemConfig":
        return dataclasses.replace(self, **kw)


class GlmOptimizationProblem:
    """Runs one GLM fit: ``run(batch, w0) -> (Coefficients, OptimizerResult)``.

    ``objective`` may be a plain :class:`GlmObjective` (single-node path) or a
    :class:`~photon_tpu.parallel.distributed.DistributedGlmObjective`
    (mesh path); both expose the same evaluation methods.
    """

    def __init__(self, objective, config: ProblemConfig):
        self.objective = objective
        self.config = config

    def _l1_weight(self) -> float:
        return self.config.regularization.l1_weight

    def run(
        self, batch: Batch, w0: Optional[Array] = None, dim: Optional[int] = None
    ):
        if w0 is None:
            if dim is None:
                raise ValueError("need w0 or dim")
            w0 = jnp.zeros(dim, jnp.float32)
        fun = lambda w: self.objective.value_and_grad(w, batch)  # noqa: E731
        name = self.config.optimizer.lower()
        cfg = self.config.optimizer_config
        if name in ("owlqn", "owl-qn"):
            result = owlqn(fun, w0, cfg, l1_weight=self._l1_weight())
        elif name == "tron":
            result = tron(
                fun, w0, cfg, hvp=lambda w, v: self.objective.hessian_vector(w, v, batch)
            )
        else:
            result = lbfgs(fun, w0, cfg)
        coefficients = Coefficients(
            means=result.w, variances=self.compute_variances(result.w, batch)
        )
        return coefficients, result

    def compute_variances(self, w: Array, batch: Batch) -> Optional[Array]:
        """Per-coefficient posterior variances at the optimum (SURVEY.md
        §2.2 'L2 + variance'): SIMPLE = 1/diag(H); FULL = diag(H⁻¹) — a
        Cholesky solve of the dense Hessian up to FULL_DENSE_MAX_DIM, a
        matrix-free CG/Hutchinson estimate above it (the dense ``[d, d]``
        materialization is a 256 GB allocation at the bench dimension —
        see core/variance.py)."""
        kind = self.config.variance_computation
        if kind == "none":
            return None
        if kind == "full":
            from photon_tpu.core.variance import (
                FULL_DENSE_MAX_DIM,
                hutchinson_diag_inverse,
            )

            d = int(w.shape[0])
            if d > FULL_DENSE_MAX_DIM:
                return hutchinson_diag_inverse(
                    lambda v: self.objective.hessian_vector(w, v, batch),
                    dim=d,
                )
            h = self.objective.hessian_matrix(w, batch)
            # Tiny jitter keeps the factorization defined for flat
            # directions (e.g. unreached features with zero curvature).
            chol = jax.scipy.linalg.cho_factor(h + 1e-9 * jnp.eye(d, dtype=h.dtype))
            inv = jax.scipy.linalg.cho_solve(chol, jnp.eye(d, dtype=h.dtype))
            return jnp.maximum(jnp.diagonal(inv), 0.0)
        diag = self.objective.hessian_diagonal(w, batch)
        return 1.0 / jnp.maximum(diag, 1e-12)
