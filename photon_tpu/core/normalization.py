"""Feature normalization applied inside the objective.

Rebuild of the reference's ``NormalizationContext`` / ``NormalizationType``
(photon-lib .../normalization — SURVEY.md §2.1): optimizers work in the
normalized feature space while data and the stored model stay in the original
space.  The identity used is

    (x - shift) * factor . w  ==  x . (factor * w) - (shift * factor) . w

so sparse batches never densify: normalization costs one elementwise product
on the coefficient vector plus one scalar correction per example.

Types supported (matching the reference enum):
  NONE, SCALE_WITH_STANDARD_DEVIATION, SCALE_WITH_MAX_MAGNITUDE,
  STANDARDIZATION (scale with std + shift by mean; requires an intercept).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

Array = jax.Array

NORMALIZATION_TYPES = (
    "none",
    "scale_with_standard_deviation",
    "scale_with_max_magnitude",
    "standardization",
)


@dataclasses.dataclass(frozen=True)
class NormalizationContext:
    """factors/shifts in the original feature space; either may be None.

    ``intercept_id``: index of the intercept pseudo-feature.  The intercept is
    never scaled or shifted (factor 1, shift 0), and shift-based normalization
    requires it (the margin correction lands there on denormalization).
    """

    factors: Optional[Array] = None  # [d] multiplicative
    shifts: Optional[Array] = None  # [d] subtractive
    intercept_id: Optional[int] = None

    def factors_or_ones(self, dim: int) -> Array:
        if self.factors is None:
            return jnp.ones(dim)
        return self.factors

    def effective_coefficients(self, w: Array) -> tuple[Array, Array]:
        """Return (factor * w, (shift * factor) . w) for the margin identity."""
        w_eff = w if self.factors is None else w * self.factors
        if self.shifts is None:
            correction = jnp.zeros((), dtype=w.dtype)
        else:
            correction = jnp.dot(self.shifts, w_eff)
        return w_eff, correction

    def model_to_original_space(self, w: Array) -> Array:
        """Convert coefficients learned in normalized space to the original
        feature space: w_orig = factor * w, intercept -= (shift*factor) . w."""
        w_eff, correction = self.effective_coefficients(w)
        if self.shifts is not None:
            if self.intercept_id is None:
                raise ValueError("shift-based normalization requires an intercept")
            w_eff = w_eff.at[self.intercept_id].add(-correction)
        return w_eff

    def model_to_normalized_space(self, w_orig: Array) -> Array:
        """Inverse of :meth:`model_to_original_space` (warm starts: a stored
        original-space model re-enters an optimizer that works in normalized
        space).  Exact because the intercept has factor 1 / shift 0."""
        f = self.factors_or_ones(w_orig.shape[0])
        w = w_orig / f
        if self.shifts is not None:
            if self.intercept_id is None:
                raise ValueError("shift-based normalization requires an intercept")
            # shift[intercept] == 0, so the dot sees only real features.
            w = w.at[self.intercept_id].add(jnp.dot(self.shifts, w_orig))
        return w

    def variances_to_original_space(self, variances: Optional[Array]) -> Optional[Array]:
        """Transform per-coefficient variances alongside
        :meth:`model_to_original_space` under the diagonal-posterior
        approximation: w_orig_j = factor_j * w_j gives
        var_orig_j = factor_j^2 * var_j, and the intercept's
        w_int -= (shift*factor) . w adds sum((shift_j*factor_j)^2 * var_j)
        to its variance (independent coordinates)."""
        if variances is None:
            return None
        f = self.factors_or_ones(variances.shape[0])
        var = variances * f * f
        if self.shifts is not None:
            if self.intercept_id is None:
                raise ValueError("shift-based normalization requires an intercept")
            sf = self.shifts * f  # intercept entry is 0 (shift forced to 0)
            var = var.at[self.intercept_id].add(jnp.dot(sf * sf, variances))
        return var

    @classmethod
    def build(
        cls,
        norm_type: str,
        summary: "BasicStatisticalSummary",
        intercept_id: Optional[int] = None,
    ) -> Optional["NormalizationContext"]:
        """Build from a feature summary, mirroring NormalizationContext.apply
        semantics per NormalizationType."""
        norm_type = norm_type.lower()
        if norm_type not in NORMALIZATION_TYPES:
            raise ValueError(f"unknown normalization type {norm_type!r}")
        if norm_type == "none":
            return None
        if norm_type == "scale_with_standard_deviation":
            factors = _safe_inverse(jnp.sqrt(summary.variance))
            shifts = None
        elif norm_type == "scale_with_max_magnitude":
            mag = jnp.maximum(jnp.abs(summary.max), jnp.abs(summary.min))
            factors = _safe_inverse(mag)
            shifts = None
        else:  # standardization
            if intercept_id is None:
                raise ValueError("standardization requires an intercept feature")
            factors = _safe_inverse(jnp.sqrt(summary.variance))
            shifts = summary.mean
        if intercept_id is not None:
            factors = factors.at[intercept_id].set(1.0)
            if shifts is not None:
                shifts = shifts.at[intercept_id].set(0.0)
        return cls(factors=factors, shifts=shifts, intercept_id=intercept_id)


def _safe_inverse(x: Array) -> Array:
    return jnp.where(x > 0.0, 1.0 / jnp.where(x > 0.0, x, 1.0), 1.0)


# A pytree so objectives carrying a normalization context can be passed as
# jit arguments (core/problem.py cached solvers): the factor/shift arrays are
# dynamic leaves, the intercept position is static structure.
jax.tree_util.register_dataclass(
    NormalizationContext,
    data_fields=("factors", "shifts"),
    meta_fields=("intercept_id",),
)


# Imported late to avoid a cycle; stats only needs jnp.
from photon_tpu.core.stats import BasicStatisticalSummary  # noqa: E402
