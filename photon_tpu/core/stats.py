"""One-pass feature statistics.

Rebuild of the reference's ``BasicStatisticalSummary`` (photon-lib .../stat —
SURVEY.md §2.1): per-feature mean / variance / min / max / nnz over a dataset,
consumed by normalization.  Computed as a single jitted reduction per batch
with an associative merge, so it streams over sharded data the same way the
reference's Spark summarizer folds partitions.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from photon_tpu.data.batch import Batch, DenseBatch

Array = jax.Array


class BasicStatisticalSummary(NamedTuple):
    """Per-feature moments; all arrays are [d]."""

    count: Array  # scalar: total examples
    mean: Array
    variance: Array
    min: Array
    max: Array
    num_nonzeros: Array

    @classmethod
    def from_batch(cls, batch: Batch, dim: int) -> "BasicStatisticalSummary":
        return _summarize(batch, dim)

    def merge(self, other: "BasicStatisticalSummary") -> "BasicStatisticalSummary":
        return _merge(self, other)


@jax.jit
def _merge(a: BasicStatisticalSummary, b: BasicStatisticalSummary) -> BasicStatisticalSummary:
    n = a.count + b.count
    wa = jnp.where(n > 0, a.count / jnp.maximum(n, 1), 0.0)
    wb = jnp.where(n > 0, b.count / jnp.maximum(n, 1), 0.0)
    mean = wa * a.mean + wb * b.mean
    # Chan et al. parallel variance merge.
    delta = b.mean - a.mean
    m2 = (
        a.variance * jnp.maximum(a.count - 1, 0)
        + b.variance * jnp.maximum(b.count - 1, 0)
        + delta * delta * a.count * b.count / jnp.maximum(n, 1)
    )
    var = m2 / jnp.maximum(n - 1, 1)
    return BasicStatisticalSummary(
        count=n,
        mean=mean,
        variance=var,
        min=jnp.minimum(a.min, b.min),
        max=jnp.maximum(a.max, b.max),
        num_nonzeros=a.num_nonzeros + b.num_nonzeros,
    )


def _summarize(batch: Batch, dim: int) -> BasicStatisticalSummary:
    if isinstance(batch, DenseBatch):
        x = batch.x
        n = jnp.asarray(x.shape[0], jnp.float32)
        mean = jnp.mean(x, axis=0)
        var = jnp.var(x, axis=0, ddof=1) if x.shape[0] > 1 else jnp.zeros(dim)
        return BasicStatisticalSummary(
            count=n,
            mean=mean,
            variance=var,
            min=jnp.min(x, axis=0),
            max=jnp.max(x, axis=0),
            num_nonzeros=jnp.sum(x != 0.0, axis=0).astype(jnp.float32),
        )
    # Sparse: scatter-add moments; implicit zeros participate in mean/var/min/max.
    ids, vals = batch.ids, batch.vals
    n = jnp.asarray(ids.shape[0], jnp.float32)
    # Padding entries are (0, 0.0): they add 0 to sums, but would corrupt nnz,
    # so mask them out of counting.
    valid = (vals != 0.0)
    s1 = jnp.zeros(dim).at[ids].add(vals)
    s2 = jnp.zeros(dim).at[ids].add(vals * vals)
    nnz = jnp.zeros(dim).at[ids].add(valid.astype(jnp.float32))
    mean = s1 / n
    var = (s2 - n * mean * mean) / jnp.maximum(n - 1, 1)
    var = jnp.maximum(var, 0.0)
    # min/max over explicit values; features with nnz < n also see implicit 0.
    big = jnp.float32(jnp.inf)
    mn = jnp.full(dim, big).at[ids].min(jnp.where(valid, vals, big))
    mx = jnp.full(dim, -big).at[ids].max(jnp.where(valid, vals, -big))
    has_implicit_zero = nnz < n
    mn = jnp.where(has_implicit_zero, jnp.minimum(mn, 0.0), mn)
    mx = jnp.where(has_implicit_zero, jnp.maximum(mx, 0.0), mx)
    mn = jnp.where(jnp.isinf(mn), 0.0, mn)
    mx = jnp.where(jnp.isinf(mx), 0.0, mx)
    return BasicStatisticalSummary(
        count=n, mean=mean, variance=var, min=mn, max=mx, num_nonzeros=nnz
    )


def summarize(batches, dim: int) -> BasicStatisticalSummary:
    """Summarize an iterable of batches with the associative merge."""
    total = None
    for b in batches:
        s = _summarize(b, dim)
        total = s if total is None else _merge(total, s)
    if total is None:
        raise ValueError("no batches to summarize")
    return total
