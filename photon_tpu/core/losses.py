"""Pointwise GLM loss functions.

Each loss is defined on the *margin* ``z = w . x + offset`` and a label, and
exposes the value plus first/second derivatives with respect to the margin
(``d1`` ≙ the reference's ``DzLoss``, ``d2`` ≙ ``DzzLoss``).  This mirrors the
reference's ``PointwiseLossFunction`` hierarchy
(photon-lib .../function/glm: LogisticLossFunction, SquaredLossFunction,
PoissonLossFunction, SmoothedHingeLossFunction — SURVEY.md §2.1), but as pure
vectorized JAX functions so they fuse into the objective's XLA program.

Label conventions match the reference: binary losses take labels in {0, 1}
(smoothed hinge converts to ±1 internally), Poisson takes non-negative counts,
squared loss takes real labels.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class PointwiseLoss:
    """A pointwise loss l(z, y) with derivatives in the margin z.

    Attributes:
      name: registry key, e.g. ``"logistic"``.
      value: ``(margin, label) -> loss`` per example.
      d1: first derivative of loss w.r.t. margin (the reference's DzLoss).
      d2: second derivative w.r.t. margin (DzzLoss); always >= 0 for the
        convex losses here, which TRON's Gauss-Newton Hessian relies on.
      mean: the inverse link function ``margin -> E[y]`` used for prediction
        (sigmoid for logistic, identity for linear, exp for Poisson).
    """

    name: str
    value: Callable[[Array, Array], Array]
    d1: Callable[[Array, Array], Array]
    d2: Callable[[Array, Array], Array]
    mean: Callable[[Array], Array]

    def value_and_d1(self, margin: Array, label: Array) -> tuple[Array, Array]:
        return self.value(margin, label), self.d1(margin, label)


@jax.custom_jvp
def _logistic_value(z: Array, y: Array) -> Array:
    # log(1 + e^z) - y*z, computed stably as max(z,0) + log1p(e^-|z|) - y*z.
    return jnp.maximum(z, 0.0) + jnp.log1p(jnp.exp(-jnp.abs(z))) - y * z


@_logistic_value.defjvp
def _logistic_value_jvp(primals, tangents):
    # The stable formulation is made of max/abs kinks that all sit at
    # EXACTLY z=0 — the value every margin takes on the first evaluation
    # from w0=0.  Autodiff's subgradient choice there yields d/dz = -y
    # instead of sigmoid(0)-y, which can stall L-BFGS at the start point
    # (wrong first direction -> every Armijo trial rejected -> ftol fires
    # while still at w0).  Pin the exact derivative.
    z, y = primals
    tz, ty = tangents
    return _logistic_value(z, y), (jax.nn.sigmoid(z) - y) * tz + (-z) * ty


def _logistic_d1(z: Array, y: Array) -> Array:
    return jax.nn.sigmoid(z) - y


def _logistic_d2(z: Array, y: Array) -> Array:
    s = jax.nn.sigmoid(z)
    return s * (1.0 - s)


LOGISTIC = PointwiseLoss(
    name="logistic",
    value=_logistic_value,
    d1=_logistic_d1,
    d2=_logistic_d2,
    mean=jax.nn.sigmoid,
)


def _squared_value(z: Array, y: Array) -> Array:
    d = z - y
    return 0.5 * d * d


SQUARED = PointwiseLoss(
    name="squared",
    value=_squared_value,
    d1=lambda z, y: z - y,
    d2=lambda z, y: jnp.ones_like(z),
    mean=lambda z: z,
)


# float32 exp overflows to inf at z ~ 88 (f64 at ~709, so the reference
# tolerates margins ours cannot) — and objective/Hessian terms ACCUMULATE
# e^z across rows, so the cap must leave headroom for row sums too:
# e^30 ~ 1e13 is astronomically above any real Poisson rate yet ~25 orders
# below f32 max.  Beyond the cap the NLL continues LINEARLY at the
# clamped-exp slope, and d1/d2 are the EXACT first/second derivatives of
# that linearized objective (d2 = 0 past the cap): a flat value — or a d2
# claiming e^cap curvature the value no longer has — would make Armijo
# trials or TRON's accept/reject model mispredict and stall in exactly the
# diverging region the optimizer must escape from.  Autodiff matches the
# analytic derivatives everywhere except the measure-zero cap point itself
# (min/max tie gradients average the one-sided slopes there); for any sane
# fit (rate <= e^30) all of this is byte-identical to the plain exp.
_POISSON_MAX_EXPONENT = 30.0


def _poisson_exp(z: Array) -> Array:
    """Clamped rate e^min(z, cap) — slope of the linearized NLL (d1 + y)
    and the prediction mean."""
    return jnp.exp(jnp.minimum(z, _POISSON_MAX_EXPONENT))


def _poisson_exp_linearized(z: Array) -> Array:
    """exp below the cap, linear continuation above (same value and slope
    at the junction), so the objective stays finite AND strictly
    increasing in z at the clamped-exp rate."""
    ez = _poisson_exp(z)
    return ez + ez * jnp.maximum(z - _POISSON_MAX_EXPONENT, 0.0)


def _poisson_value(z: Array, y: Array) -> Array:
    # Negative log-likelihood up to a label-only constant: e^z - y*z.
    return _poisson_exp_linearized(z) - y * z


def _poisson_d2(z: Array, y: Array) -> Array:
    # Exact second derivative of the linearized NLL: 0 past the cap.
    del y
    return jnp.where(z <= _POISSON_MAX_EXPONENT, _poisson_exp(z), 0.0)


POISSON = PointwiseLoss(
    name="poisson",
    value=_poisson_value,
    d1=lambda z, y: _poisson_exp(z) - y,
    d2=_poisson_d2,
    mean=_poisson_exp,
)


def _hinge_parts(z: Array, y01: Array) -> tuple[Array, Array]:
    # Convert {0,1} labels to ±1 and form the classification margin t = y*z.
    y = 2.0 * y01 - 1.0
    return y, y * z


def _smoothed_hinge_value(z: Array, y01: Array) -> Array:
    # Rennie's smoothed hinge: 1/2 - t for t<=0, (1-t)^2/2 for 0<t<1, 0 for t>=1.
    _, t = _hinge_parts(z, y01)
    return jnp.where(t <= 0.0, 0.5 - t, jnp.where(t < 1.0, 0.5 * (1.0 - t) ** 2, 0.0))


def _smoothed_hinge_d1(z: Array, y01: Array) -> Array:
    y, t = _hinge_parts(z, y01)
    dt = jnp.where(t <= 0.0, -1.0, jnp.where(t < 1.0, t - 1.0, 0.0))
    return y * dt


def _smoothed_hinge_d2(z: Array, y01: Array) -> Array:
    _, t = _hinge_parts(z, y01)
    return jnp.where((t > 0.0) & (t < 1.0), 1.0, 0.0)


SMOOTHED_HINGE = PointwiseLoss(
    name="smoothed_hinge",
    value=_smoothed_hinge_value,
    d1=_smoothed_hinge_d1,
    d2=_smoothed_hinge_d2,
    mean=lambda z: (z > 0.0).astype(z.dtype),
)

LOSSES: dict[str, PointwiseLoss] = {
    loss.name: loss for loss in (LOGISTIC, SQUARED, POISSON, SMOOTHED_HINGE)
}

# Task-type aliases matching the reference's TaskType enum
# (LOGISTIC_REGRESSION / LINEAR_REGRESSION / POISSON_REGRESSION / SMOOTHED_HINGE...).
TASK_TO_LOSS: dict[str, PointwiseLoss] = {
    "logistic_regression": LOGISTIC,
    "linear_regression": SQUARED,
    "poisson_regression": POISSON,
    "smoothed_hinge_loss_linear_svm": SMOOTHED_HINGE,
}

# Tasks whose labels live in {0, 1} — drives label validation, LIBSVM label
# normalization, and the task-default (binary) down-sampler.
BINARY_TASKS = ("logistic_regression", "smoothed_hinge_loss_linear_svm")


def get_loss(name: str) -> PointwiseLoss:
    key = name.lower()
    if key in LOSSES:
        return LOSSES[key]
    if key in TASK_TO_LOSS:
        return TASK_TO_LOSS[key]
    raise KeyError(f"unknown loss {name!r}; available: {sorted(LOSSES)}")
