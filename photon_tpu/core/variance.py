"""Matrix-free FULL variance computation: diag(H⁻¹) without materializing H.

The reference's ``VarianceComputationType.FULL`` inverts the full Hessian
(photon-api .../optimization — SURVEY.md §2.2 'L2 + variance'), which is
feasible only for modest dimensions: at the bench dimension d=262144 the
dense ``[d, d]`` Hessian is a 256 GB allocation (VERDICT r2 weak #5).  For
large d this module estimates ``diag(H⁻¹)`` matrix-free:

- conjugate-gradient solves against the Hessian-vector product (exact for
  GLM objectives: ``Hv = Xᵀ diag(weight·d2) X v + l2·v``), and
- a Hutchinson-style probe estimator
  ``diag(H⁻¹) ≈ E_z[z ⊙ H⁻¹ z]`` with Rademacher probes ``z``.

For diagonal Hessians (orthogonal features) the estimator is exact for any
probe; in general its per-coordinate error decays as 1/sqrt(num_probes) —
it is a posterior-width ESTIMATE, which is what GLMix uses the variances
for (documented departure from the reference's exact-but-small-scale
semantics).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

Array = jax.Array

# Above this dimension the dense [d, d] Cholesky path is refused: the
# Hessian materialization grows quadratically (8192² f32 = 256 MB; the
# bench dim 262144² would be 256 GB).
FULL_DENSE_MAX_DIM = 8192


def cg_solve(
    hvp: Callable[[Array], Array],
    b: Array,
    tol: float = 1e-6,
    max_iterations: int = 250,
) -> Array:
    """Conjugate gradient for ``H x = b`` with H SPD, as a lax.while_loop.

    The inner-loop analog of TRON's trust-region CG (LIBLINEAR-style), reused
    for variance probes.  Runs until ``||r|| <= tol * ||b||`` or the
    iteration cap.
    """
    b_norm = jnp.linalg.norm(b)

    def cond(state):
        _, r, _, rs, it = state
        return (jnp.sqrt(rs) > tol * jnp.maximum(b_norm, 1e-30)) & (
            it < max_iterations
        )

    def body(state):
        x, r, p, rs, it = state
        hp = hvp(p)
        alpha = rs / jnp.maximum(jnp.dot(p, hp), 1e-30)
        x = x + alpha * p
        r = r - alpha * hp
        rs_new = jnp.dot(r, r)
        p = r + (rs_new / jnp.maximum(rs, 1e-30)) * p
        return x, r, p, rs_new, it + 1

    x0 = jnp.zeros_like(b)
    state = (x0, b, b, jnp.dot(b, b), jnp.int32(0))
    x, *_ = lax.while_loop(cond, body, state)
    return x


def hutchinson_diag_inverse(
    hvp: Callable[[Array], Array],
    dim: int,
    seed: int = 0,
    num_probes: int = 32,
    cg_tol: float = 1e-5,
    cg_max_iterations: int = 250,
    jitter: float = 1e-9,
) -> Array:
    """Estimate ``diag(H⁻¹)`` via Rademacher probes and CG solves.

    Probes run under ``lax.scan`` (sequential — each probe is itself a fully
    parallel CG over the device mesh when ``hvp`` psums).  Deliberately NOT
    wrapped in an outer ``jax.jit``: callers pass fresh ``hvp`` closures per
    fit, and a jit keyed on closure identity would recompile every call
    while retaining each executable (with the batch baked in as constants)
    in the global cache forever.
    """
    keys = jax.random.split(jax.random.PRNGKey(seed), num_probes)

    # Same flat-direction guard as the dense path's 1e-9*I jitter
    # (problem.py): with no regularization and unreached features H is
    # singular and raw CG would diverge, contaminating every coordinate.
    def hvp_reg(v):
        return hvp(v) + jitter * v

    def one_probe(acc, key):
        z = jax.random.rademacher(key, (dim,), dtype=jnp.float32)
        x = cg_solve(hvp_reg, z, tol=cg_tol, max_iterations=cg_max_iterations)
        return acc + z * x, None

    total, _ = lax.scan(one_probe, jnp.zeros(dim, jnp.float32), keys)
    # H is SPD, so true diag(H⁻¹) > 0; clamp estimator noise.
    return jnp.maximum(total / num_probes, 0.0)
