"""GLM objective functions: weighted loss + regularization, with derivatives.

This is the rebuild of the reference's objective-function stack —
``ObjectiveFunction`` / ``DiffFunction`` / ``TwiceDiffFunction`` traits plus
``DistributedGLMLossFunction`` / ``SingleNodeGLMLossFunction`` and the
per-partition aggregators (``ValueAndGradientAggregator``,
``HessianVectorAggregator``, ``HessianDiagonalAggregator``) — SURVEY.md
§2.1/§2.2/§3.4.  Where the reference folds examples through Breeze/BLAS
``dot``/``axpy`` per partition and tree-aggregates to the driver, here the
whole evaluation is one XLA program: ``jax.value_and_grad`` over a batched
margin computation; Hessian-vector products come from ``jax.jvp`` of the
gradient (exact for GLM objectives).  Under a sharded mesh the same code runs
per shard and `psum`s — see :mod:`photon_tpu.parallel`.

The L2 term is added analytically (as in the reference); L1 is *not* part of
the smooth objective — OWL-QN handles it via its orthant logic, matching the
reference's split (SURVEY.md §2.1 "Regularization").
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import tree_util

from photon_tpu.core.losses import PointwiseLoss, get_loss
from photon_tpu.core.normalization import NormalizationContext
from photon_tpu.data.batch import Batch, DenseBatch, FeatureMajorAux, SparseBatch, margins

Array = jax.Array


def _fm_segment_grad(per_row: Array, fm: FeatureMajorAux, dim: int) -> Array:
    """``g[f] = sum_e per_row[row_e] * val_e`` over a feature-major layout.

    The production sparse-gradient kernel (VERDICT r2 item 1): entries are
    pre-sorted by feature id within each block, so the reduction is a
    ``segment_sum(indices_are_sorted=True)`` — no per-evaluation device sort,
    unlike the unsorted scatter-add XLA would otherwise lower.  ``per_row``
    is any per-row scalar (dz for gradients, d2·(x·v) for Hv products).

    Handles both the block-local view (S == 1: inside shard_map, or a
    single-device batch) and a multi-block batch evaluated on one device
    (S > 1: block-local rows are offset to global rows; per-block sorted
    segment sums are summed).
    """
    s, _ = fm.ids.shape
    ns = per_row.shape[0] // s
    rows = fm.rows + (jnp.arange(s, dtype=fm.rows.dtype) * ns)[:, None]
    contrib = jnp.take(per_row, rows.reshape(-1), axis=0).reshape(s, -1) * fm.vals

    def _block(c, i):
        return jax.ops.segment_sum(
            c, i, num_segments=dim, indices_are_sorted=True
        )

    if s == 1:
        return _block(contrib[0], fm.ids[0])
    return jnp.sum(jax.vmap(_block)(contrib, fm.ids), axis=0)


@dataclasses.dataclass(frozen=True)
class RegularizationContext:
    """L1/L2/elastic-net configuration.

    Mirrors the reference's ``RegularizationContext`` /
    ``RegularizationType`` (NONE/L1/L2/ELASTIC_NET).  ``alpha`` is the
    elastic-net mixing weight: ``l1 = alpha * weight``,
    ``l2 = (1 - alpha) * weight``.
    """

    reg_type: str = "none"  # none | l1 | l2 | elastic_net
    reg_weight: float = 0.0
    alpha: float = 0.5

    def __post_init__(self):
        if self.reg_type not in ("none", "l1", "l2", "elastic_net"):
            raise ValueError(f"unknown regularization type {self.reg_type!r}")

    @property
    def l1_weight(self) -> float:
        if self.reg_type == "l1":
            return self.reg_weight
        if self.reg_type == "elastic_net":
            return self.alpha * self.reg_weight
        return 0.0

    @property
    def l2_weight(self) -> float:
        if self.reg_type == "l2":
            return self.reg_weight
        if self.reg_type == "elastic_net":
            return (1.0 - self.alpha) * self.reg_weight
        return 0.0

    def replace(self, **kw) -> "RegularizationContext":
        return dataclasses.replace(self, **kw)


NO_REG = RegularizationContext()


def _static_zero(x) -> bool:
    """True only for a concrete (Python-scalar) zero weight.

    Objectives are jit pytrees whose reg weights may be tracers (so one
    compiled sweep program serves every lambda); a tracer is never
    "statically zero" and takes the unconditional-arithmetic path."""
    return isinstance(x, (int, float)) and x == 0.0


@dataclasses.dataclass(frozen=True)
class GlmObjective:
    """Smooth part of a GLM objective: sum_i weight_i * loss(margin_i, y_i)
    + (l2/2) ||w||^2, with optional feature normalization applied inside the
    objective (the model itself stays in the original feature space, as in
    the reference's NormalizationContext design).

    All methods are pure functions of ``(w, batch)`` and jit/vmap/shard
    cleanly.  ``l1_weight`` is carried for OWL-QN but never enters the smooth
    value/gradient.
    """

    loss: PointwiseLoss
    l2_weight: float = 0.0
    l1_weight: float = 0.0
    normalization: Optional[NormalizationContext] = None

    @classmethod
    def create(
        cls,
        loss: str | PointwiseLoss,
        reg: RegularizationContext = NO_REG,
        normalization: Optional[NormalizationContext] = None,
    ) -> "GlmObjective":
        if isinstance(loss, str):
            loss = get_loss(loss)
        return cls(
            loss=loss,
            l2_weight=reg.l2_weight,
            l1_weight=reg.l1_weight,
            normalization=normalization,
        )

    # -- margins under normalization ------------------------------------------
    def _margins(self, w: Array, batch: Batch) -> Array:
        if self.normalization is None:
            return margins(w, batch)
        # (x - shift) * factor . w  ==  x . (factor * w) - (shift * factor) . w:
        # keeps sparse batches sparse (SURVEY.md §2.1 Normalization).
        w_eff, correction = self.normalization.effective_coefficients(w)
        return margins(w_eff, batch) - correction

    def _xu_product(self, kernel: str, u: Array, batch: Batch) -> Array:
        """Per-row ``X u`` products (no offset) through the selected
        kernel's forward: the pallas path uses the TRANSPOSED aligned
        layout when the batch carries one (``sum_e u[f_e] v_e`` per row via
        the same position-reduce kernel — KERNEL_NOTES.md option (a)); the
        benes path runs the slab gather + static Clos permutation
        (ops/benes.py — no random E-access); everything else takes the
        row-major XLA gather.  The single dispatch point for margins AND
        Hv's ``X v``."""
        if kernel == "benes":
            from photon_tpu.ops.benes import benes_xu_product

            n, k = batch.ids.shape
            return benes_xu_product(u, batch.al, batch.benes, n, k)
        if kernel in ("pallas", "xchg") and batch.al_t is not None:
            from photon_tpu.ops.pallas_gather import aligned_segment_grad

            return aligned_segment_grad(u, batch.al_t, batch.ids.shape[0])
        return jnp.sum(jnp.take(u, batch.ids, axis=0) * batch.vals, axis=-1)

    def _margins_for_kernel(self, kernel: str, w: Array, batch: Batch) -> Array:
        fwd_kernel = kernel == "benes" or (
            kernel in ("pallas", "xchg") and batch.al_t is not None
        )
        if not fwd_kernel:
            # Single home of the normalization algebra for the XLA forward.
            return self._margins(w, batch)
        if self.normalization is None:
            return self._xu_product(kernel, w, batch) + batch.offset
        w_eff, correction = self.normalization.effective_coefficients(w)
        return self._xu_product(kernel, w_eff, batch) + batch.offset - correction

    # -- value / gradient ------------------------------------------------------
    def data_value(self, w: Array, batch: Batch) -> Array:
        z = self._margins(w, batch)
        return jnp.sum(batch.weight * self.loss.value(z, batch.label))

    def value(self, w: Array, batch: Batch) -> Array:
        v = self.data_value(w, batch)
        if not _static_zero(self.l2_weight):
            v = v + 0.5 * self.l2_weight * jnp.dot(w, w)
        return v

    # -- static-sparsity fast path --------------------------------------------
    def _sparse_kernel(self, batch: Batch, dim: Optional[int] = None) -> Optional[str]:
        """Which static-layout gradient kernel applies to this batch:
        ``"fm"`` (pre-sorted segment sum over FeatureMajorAux), ``"pallas"``
        (slab-aligned Mosaic reduce over AlignedLayoutDev), or ``None``
        (autodiff — the unsorted scatter XLA lowers is faster on some
        platforms).  When the coefficient dim is known, the choice is the
        measured-on-this-backend selection (ops/sparse_grad_select.py)."""
        if not (isinstance(batch, SparseBatch) and batch.ids.ndim == 2):
            return None
        has_fm = batch.fm is not None
        has_al = batch.al is not None
        has_benes = batch.benes is not None and has_al
        # The cumsum-reduce xchg variant (bounds set) never touches the
        # aligned layout at runtime, so a batch can carry the route alone
        # — the streaming layout cache relies on this (no layout bytes
        # cached or shipped per chunk).  The aligned-reduce variant still
        # requires ``al``.
        has_xchg = batch.xchg is not None and (
            has_al or getattr(batch.xchg, "bounds", None) is not None
        )
        if not (has_fm or has_al or has_xchg):
            return None
        if dim is None:
            if has_fm:
                return "fm"
            if has_al:
                return "pallas"
            return "xchg"  # bounds-only route (streamed cumsum chunks)
        from photon_tpu.ops.sparse_grad_select import select_kernel

        n, k = batch.ids.shape
        choice = select_kernel(
            n * k, dim, n,
            has_fm=has_fm, has_aligned=has_al, has_benes=has_benes,
            has_xchg=has_xchg,
            # Whether values were pre-permuted at attach changes the
            # per-step data movement the probe must time (baked: dz
            # expansion only; unbaked — streamed chunks: the full product
            # stream rides the exchange).
            xchg_baked=(
                has_xchg and getattr(batch.xchg, "vals_dest", None) is not None
            ),
        )
        return None if choice == "autodiff" else choice

    def _segment_grad(self, kernel: str, per_row: Array, batch: Batch, dim: int) -> Array:
        """``g[f] = sum_e per_row[row_e] * val_e`` via the selected static
        layout (the reduction both the gradient and Hv share)."""
        if kernel == "xchg":
            from photon_tpu.ops.vperm import xchg_segment_grad

            return xchg_segment_grad(
                per_row, batch.vals, batch.al, batch.xchg, dim
            )
        if kernel == "benes":
            from photon_tpu.ops.benes import benes_segment_grad

            return benes_segment_grad(
                per_row, batch.vals, batch.al, batch.benes, dim
            )
        if kernel == "pallas":
            from photon_tpu.ops.pallas_gather import aligned_segment_grad

            return aligned_segment_grad(per_row, batch.al, dim)
        return _fm_segment_grad(per_row, batch.fm, dim)

    def _fast_data_value_and_grad(
        self, w: Array, batch: Batch, kernel: str = "fm"
    ) -> tuple[Array, Array]:
        """Data term (no regularization) of value+gradient via the selected
        static entry layout; the TPU replacement for the reference's
        ValueAndGradientAggregator fold (SURVEY.md §3.4).

        Under normalization the margin is ``F(x - s) · w`` per example, so
        ``g = F (Xᵀ dz - s Σ dz)`` — one extra scalar sum and two
        elementwise ops over the same sorted segment sum (the sparse batch
        never densifies, mirroring hessian_diagonal's algebra)."""
        z = self._margins_for_kernel(kernel, w, batch)
        v = jnp.sum(batch.weight * self.loss.value(z, batch.label))
        dz = batch.weight * self.loss.d1(z, batch.label)
        g = self._segment_grad(kernel, dz, batch, w.shape[0])
        norm = self.normalization
        if norm is not None:
            if norm.shifts is not None:
                g = g - norm.shifts * jnp.sum(dz)
            g = g * norm.factors_or_ones(w.shape[0])
        return v, g

    def _fast_data_hessian_vector(
        self, w: Array, v: Array, batch: Batch, kernel: str = "fm"
    ) -> Array:
        """Data term of ``H v = Xᵀ diag(weight·d2) X v`` — exact for GLMs
        (margins are linear in w), same layout trick as the gradient.
        Both ``X·u`` products route through the kernel's forward (the
        pallas path reuses the transposed layout for ``X v`` too).
        Unnormalized objectives only — callers gate on it (normalized Hv
        goes through jvp of the normalized gradient instead), and the
        algebra below would be silently half-normalized otherwise."""
        assert self.normalization is None, (
            "fast Hv requires an unnormalized objective"
        )
        z = self._margins_for_kernel(kernel, w, batch)
        d2w = batch.weight * self.loss.d2(z, batch.label)
        xv = self._xu_product(kernel, v, batch)
        return self._segment_grad(kernel, d2w * xv, batch, w.shape[0])

    def value_and_grad(self, w: Array, batch: Batch) -> tuple[Array, Array]:
        kernel = self._sparse_kernel(batch, int(w.shape[0]))
        if kernel is not None:
            val, g = self._fast_data_value_and_grad(w, batch, kernel)
            if not _static_zero(self.l2_weight):
                val = val + 0.5 * self.l2_weight * jnp.dot(w, w)
                g = g + self.l2_weight * w
            return val, g
        if (
            not isinstance(batch, DenseBatch)
            and batch.ids.ndim == 2
            and self.normalization is None
        ):
            from photon_tpu.ops.pallas_sparse import (
                fused_value_and_grad,
                kernel_supported,
                pallas_enabled,
            )

            # Fused Pallas pass: gather + loss + dz + scatter in one kernel
            # (photon_tpu.ops.pallas_sparse); L2 added analytically, as in
            # the XLA path.  kernel_supported() is an EAGER one-time Mosaic
            # capability probe — a try/except here could not catch lowering
            # failures, which surface when the enclosing jit (the
            # optimizer's while_loop) compiles.  On v5e Mosaic lacks vector
            # scatter-add, so this routes back to XLA there.
            if pallas_enabled() and kernel_supported(
                self.loss, int(batch.ids.shape[1]), int(w.shape[0])
            ):
                v, g = fused_value_and_grad(
                    self.loss, w, batch.ids, batch.vals,
                    batch.label, batch.offset, batch.weight,
                )
                if not _static_zero(self.l2_weight):
                    v = v + 0.5 * self.l2_weight * jnp.dot(w, w)
                    g = g + self.l2_weight * w
                return v, g
        return jax.value_and_grad(self.value)(w, batch)

    def grad(self, w: Array, batch: Batch) -> Array:
        if self._sparse_kernel(batch, int(w.shape[0])) is not None:
            return self.value_and_grad(w, batch)[1]
        return jax.grad(self.value)(w, batch)

    def _differentiable_grad(self, w: Array, batch: Batch) -> Array:
        """Gradient via a kernel jax.jvp can differentiate THROUGH: the
        pallas kernel has no JVP rule (``pallas_call`` is not
        differentiable), so callers that re-differentiate the gradient
        (normalized Hv below) route it to the fm layout — always built
        alongside the aligned one — or plain autodiff.  The benes path
        contains the same pallas_call and routes identically."""
        kernel = self._sparse_kernel(batch, int(w.shape[0]))
        if kernel in ("pallas", "benes", "xchg"):
            kernel = "fm" if batch.fm is not None else None
        if kernel is not None:
            _, g = self._fast_data_value_and_grad(w, batch, kernel)
            if not _static_zero(self.l2_weight):
                g = g + self.l2_weight * w
            return g
        return jax.grad(self.value)(w, batch)

    # -- second order ----------------------------------------------------------
    def hessian_vector(self, w: Array, v: Array, batch: Batch) -> Array:
        """Exact Hessian-vector product via jvp of the gradient — the TPU
        equivalent of the reference's HessianVectorAggregator treeAggregate
        (SURVEY.md §3.4, 'TRON's Hv = jax.jvp')."""
        kernel = (
            self._sparse_kernel(batch, int(w.shape[0]))
            if self.normalization is None
            else None
        )
        if kernel is not None:
            # (normalized Hv falls back to jvp-of-grad, which differentiates
            # through the normalized fast gradient and stays exact)
            hv = self._fast_data_hessian_vector(w, v, batch, kernel)
            if not _static_zero(self.l2_weight):
                hv = hv + self.l2_weight * v
            return hv
        return jax.jvp(lambda u: self._differentiable_grad(u, batch), (w,), (v,))[1]

    def hvp_operator(self, w: Array, batch: Batch):
        """Curvature operator at ``w``: precompute the per-row curvature
        ``D(w) = weight·d2(margins)`` ONCE and return ``v -> Xᵀ(D·(X v)) +
        λ₂ v`` — the matrix-free Newton-CG inner-loop workhorse (ISSUE 14:
        two sparse matvecs per CG iteration, never a ``[d, d]`` matrix,
        and no margin recomputation per product).  Exact for GLMs (margins
        are linear in ``w``).  Static-layout batches route both matvecs
        through the selected kernel (the gradient's layout trick);
        normalized objectives and exotic batch shapes fall back to the
        per-call jvp-of-gradient, still matrix-free."""
        if self.normalization is not None:
            return lambda v: self.hessian_vector(w, v, batch)
        dim = int(w.shape[0])
        kernel = self._sparse_kernel(batch, dim)
        if kernel is not None:
            z = self._margins_for_kernel(kernel, w, batch)
            d2w = batch.weight * self.loss.d2(z, batch.label)

            def hv_kernel(v: Array) -> Array:
                xv = self._xu_product(kernel, v, batch)
                out = self._segment_grad(kernel, d2w * xv, batch, dim)
                if not _static_zero(self.l2_weight):
                    out = out + self.l2_weight * v
                return out

            return hv_kernel
        if isinstance(batch, DenseBatch):
            xu = lambda v: batch.x @ v  # noqa: E731
            xtu = lambda u: batch.x.T @ u  # noqa: E731
        elif batch.ids.ndim == 2:
            xu = lambda v: jnp.sum(  # noqa: E731
                jnp.take(v, batch.ids, axis=0) * batch.vals, axis=-1
            )
            xtu = lambda u: jnp.zeros(dim, w.dtype).at[batch.ids].add(  # noqa: E731
                u[:, None] * batch.vals
            )
        else:
            return lambda v: self.hessian_vector(w, v, batch)
        z = self._margins(w, batch)
        d2w = batch.weight * self.loss.d2(z, batch.label)

        def hv(v: Array) -> Array:
            out = xtu(d2w * xu(v))
            if not _static_zero(self.l2_weight):
                out = out + self.l2_weight * v
            return out

        return hv

    def hessian_vector_product(self, w: Array, v: Array, batch: Batch) -> Array:
        """One matrix-free ``H v`` (``Xᵀ(D(w)·(X v)) + λ₂ v``) — the
        canonical single-product entry; loops over many ``v`` at one ``w``
        should hold :meth:`hvp_operator` instead (D(w) computed once)."""
        return self.hvp_operator(w, batch)(v)

    def hessian_diagonal(self, w: Array, batch: Batch) -> Array:
        """diag(H) = sum_i weight_i * d2_i * x_ij^2 + l2 (HessianDiagonalAggregator);
        used for per-coefficient variance (VarianceComputationType.SIMPLE)."""
        z = self._margins(w, batch)
        d2w = batch.weight * self.loss.d2(z, batch.label)
        norm = self.normalization
        factors = None if norm is None else norm.factors_or_ones(w.shape[0])
        shifts = None if norm is None else norm.shifts
        # diag_j = f_j^2 * sum_i d2_i (x_ij - s_j)^2
        #        = f_j^2 * (A_j - 2 s_j B_j + s_j^2 C)   with
        # A_j = sum d2_i x_ij^2,  B_j = sum d2_i x_ij,  C = sum d2_i —
        # all three computable without densifying sparse batches.
        if isinstance(batch, DenseBatch):
            a = (batch.x * batch.x).T @ d2w
            b = batch.x.T @ d2w if shifts is not None else None
        else:
            a = jnp.zeros_like(w).at[batch.ids].add(d2w[:, None] * batch.vals * batch.vals)
            b = (
                jnp.zeros_like(w).at[batch.ids].add(d2w[:, None] * batch.vals)
                if shifts is not None
                else None
            )
        diag = a
        if shifts is not None:
            c = jnp.sum(d2w)
            diag = a - 2.0 * shifts * b + shifts * shifts * c
        if factors is not None:
            diag = diag * factors * factors
        return diag + self.l2_weight

    def hessian_matrix(self, w: Array, batch: Batch) -> Array:
        """Full Hessian ``H = Xᵀ diag(weight·d2) X + l2·I`` (the reference's
        HessianMatrixAggregator; used by VarianceComputationType.FULL).
        Feasible for modest dims — per-entity random effects and small
        fixed effects.  Under normalization the Hessian is taken in the
        normalized feature space (matching hessian_diagonal), expanded as
        ``F (A - B sᵀ - s Bᵀ + C s sᵀ) F`` with ``A = Xᵀ D X``,
        ``B = Xᵀ D 1``, ``C = Σ D`` so sparse batches stay sparse."""
        z = self._margins(w, batch)
        d2w = batch.weight * self.loss.d2(z, batch.label)
        d = w.shape[0]
        if isinstance(batch, DenseBatch):
            a = jnp.einsum("ni,n,nj->ij", batch.x, d2w, batch.x)
            b = batch.x.T @ d2w
        else:
            c_i = d2w[:, None, None] * batch.vals[:, :, None] * batch.vals[:, None, :]
            a = jnp.zeros((d, d), w.dtype).at[
                batch.ids[:, :, None], batch.ids[:, None, :]
            ].add(c_i)
            b = jnp.zeros(d, w.dtype).at[batch.ids].add(d2w[:, None] * batch.vals)
        h = a
        norm = self.normalization
        if norm is not None:
            shifts = norm.shifts
            if shifts is not None:
                c = jnp.sum(d2w)
                h = (
                    h
                    - b[:, None] * shifts[None, :]
                    - shifts[:, None] * b[None, :]
                    + c * shifts[:, None] * shifts[None, :]
                )
            factors = norm.factors_or_ones(d)
            h = h * factors[:, None] * factors[None, :]
        return h + self.l2_weight * jnp.eye(d, dtype=w.dtype)

    # -- prediction ------------------------------------------------------------
    def predict_mean(self, w: Array, batch: Batch) -> Array:
        return self.loss.mean(self._margins(w, batch))


# Objectives are jit/vmap pytrees: reg weights (and normalization arrays) are
# DYNAMIC leaves, so one compiled solver program serves a whole lambda sweep /
# hyperparameter search — only shapes and the loss retrace (see
# core/problem.py's cached solvers).
tree_util.register_dataclass(
    GlmObjective,
    data_fields=("l2_weight", "l1_weight", "normalization"),
    meta_fields=("loss",),
)
