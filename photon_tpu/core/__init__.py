"""Math core: pointwise losses, GLM objectives, optimizers, normalization, stats.

Equivalent of the reference's ``photon-lib`` module
(photon-lib/src/main/scala/com/linkedin/photon/ml/ — see SURVEY.md §2.1).
"""
