"""ctypes binding + schema compiler for the native GAME Avro decoder
(src/avro_game.cpp).

``decode_file`` returns the columnar streams of one container file —
scalar doubles, interned id-column strings, and per-bag CSR entries with
a (name, term) pair vocab in first-seen ENTRY order (the exact id-
assignment order of the pure-Python reader's ``setdefault`` loop) — or
None whenever the file/schema falls outside the native subset, in which
case callers use the Python reader (photon_tpu/data/game_io.py).
"""

from __future__ import annotations

import ctypes
import dataclasses
import struct
from typing import Optional

import numpy as np

from photon_tpu.native.build import get_lib

# Opcodes — must match avro_game.cpp.
_OP_DOUBLE = 1
_OP_OPT_DOUBLE = 2
_OP_STRING = 3
_OP_SKIP_STRING = 4
_OP_SKIP_OPT_STRING = 5
_OP_BAG = 6
_OP_SKIP_BAG = 7
_OP_SKIP_DOUBLE = 8
_OP_SKIP_OPT_DOUBLE = 9

_declared = False


def _declare(lib) -> None:
    global _declared
    if _declared:
        return
    c = ctypes
    lib.gav_open.restype = c.c_void_p
    lib.gav_open.argtypes = [c.c_char_p, c.c_int64, c.c_char_p,
                             c.c_char_p, c.c_int64]
    lib.gav_decode.restype = c.c_int64
    lib.gav_decode.argtypes = [c.c_void_p]
    lib.gav_error.restype = c.c_char_p
    lib.gav_error.argtypes = [c.c_void_p]
    for name, args in (
        ("gav_doubles", [c.c_void_p, c.c_int32, c.POINTER(c.c_double)]),
        ("gav_string_ids", [c.c_void_p, c.c_int32, c.POINTER(c.c_int32)]),
        ("gav_string_vocab", [c.c_void_p, c.c_int32, c.POINTER(c.c_int32),
                              c.c_char_p]),
        ("gav_bag_nnz", [c.c_void_p, c.c_int32, c.POINTER(c.c_int32)]),
        ("gav_bag_pairs", [c.c_void_p, c.c_int32, c.POINTER(c.c_int32)]),
        ("gav_bag_vals", [c.c_void_p, c.c_int32, c.POINTER(c.c_float)]),
        ("gav_pair_vocab", [c.c_void_p, c.c_int32, c.POINTER(c.c_int32),
                            c.c_char_p]),
    ):
        fn = getattr(lib, name)
        fn.restype = None
        fn.argtypes = args
    for name in ("gav_string_vocab_size", "gav_string_vocab_bytes",
                 "gav_bag_entries", "gav_pair_vocab_size",
                 "gav_pair_vocab_bytes"):
        fn = getattr(lib, name)
        fn.restype = c.c_int64
        fn.argtypes = [c.c_void_p, c.c_int32]
    lib.gav_close.restype = None
    lib.gav_close.argtypes = [c.c_void_p]
    _declared = True


@dataclasses.dataclass
class CompiledSchema:
    """Flat opcode program + the slot each consumed field landed in."""

    descriptor: bytes
    dbl_slots: dict  # field name -> double-stream slot
    str_slots: dict  # field name -> string-stream slot
    bag_slots: dict  # field name -> bag slot


def _is_feature_record(items, named: dict) -> bool:
    if isinstance(items, str):
        items = named.get(items)
    if not isinstance(items, dict) or items.get("type") != "record":
        return False
    fields = items.get("fields", [])
    return (
        len(fields) == 3
        and [f["name"] for f in fields] == ["name", "term", "value"]
        and [f["type"] for f in fields] == ["string", "string", "double"]
    )


def compile_schema(
    schema: dict, bag_fields: set, id_fields: set,
    opt_defaults: Optional[dict] = None,
    dbl_fields: Optional[set] = None,
) -> Optional[CompiledSchema]:
    """Record schema -> opcode descriptor; None when any field falls
    outside the native subset (caller then uses the Python reader).

    ``opt_defaults`` maps field name -> value substituted for null in
    ``["null", "double"]`` unions (0.0 when unlisted — matching the Python
    reader's ``rec.get(...) or 0.0`` for offset; weight passes 1.0).
    ``dbl_fields`` limits which PLAIN double fields are decoded (others
    are skipped without storage); None decodes all of them.
    """
    if not isinstance(schema, dict) or schema.get("type") != "record":
        return None
    named: dict = {}

    def register(s):
        if isinstance(s, dict):
            if s.get("type") in ("record", "enum") and "name" in s:
                named[s["name"]] = s
            if s.get("type") == "record":
                for f in s.get("fields", []):
                    register(f["type"])
            elif s.get("type") == "array":
                register(s.get("items"))
        elif isinstance(s, list):
            for b in s:
                register(b)

    register(schema)
    opt_defaults = opt_defaults or {}
    out = bytearray()
    dbl_slots: dict = {}
    str_slots: dict = {}
    bag_slots: dict = {}
    n_dbl = n_str = n_bag = 0
    for field in schema.get("fields", []):
        name, ftype = field["name"], field["type"]
        if isinstance(ftype, dict) and ftype.get("type") == "array":
            if not _is_feature_record(ftype.get("items"), named):
                return None
            if name in bag_fields:
                out.append(_OP_BAG)
                bag_slots[name] = n_bag
                n_bag += 1
            else:
                out.append(_OP_SKIP_BAG)
            continue
        if isinstance(ftype, list):
            if len(ftype) != 2 or "null" not in ftype:
                return None
            null_branch = ftype.index("null")
            other = ftype[1 - null_branch]
            if other == "double":
                if name in id_fields:
                    return None  # id columns must be plain strings
                # Consume fields with a known null-default; skip the rest.
                if name in opt_defaults:
                    out.append(_OP_OPT_DOUBLE)
                    out.append(null_branch)
                    out.extend(struct.pack("<d", float(opt_defaults[name])))
                    dbl_slots[name] = n_dbl
                    n_dbl += 1
                else:
                    out.append(_OP_SKIP_OPT_DOUBLE)
                    out.append(null_branch)
            elif other == "string":
                if name in id_fields:
                    return None
                out.append(_OP_SKIP_OPT_STRING)
                out.append(null_branch)
            else:
                return None
            continue
        if ftype == "double":
            if dbl_fields is None or name in dbl_fields:
                out.append(_OP_DOUBLE)
                dbl_slots[name] = n_dbl
                n_dbl += 1
            else:
                out.append(_OP_SKIP_DOUBLE)
            continue
        if ftype == "string":
            if name in id_fields:
                out.append(_OP_STRING)
                str_slots[name] = n_str
                n_str += 1
            else:
                out.append(_OP_SKIP_STRING)
            continue
        return None  # anything else: Python reader
    if not bag_fields.issubset(bag_slots) or not id_fields.issubset(str_slots):
        return None
    return CompiledSchema(bytes(out), dbl_slots, str_slots, bag_slots)


@dataclasses.dataclass
class DecodedFile:
    n: int
    doubles: dict  # field -> np.float64 [n]
    id_columns: dict  # field -> np object array [n] of str
    bags: dict  # field -> (nnz[n] i32, pair_ids[e] i32, vals[e] f32, pairs)
    # pairs: list[(name, term)] in first-seen entry order


def decode_file(
    path: str, data_offset: int, sync: bytes, compiled: CompiledSchema
) -> Optional[DecodedFile]:
    """Run the native decoder over one container file's data blocks."""
    lib = get_lib()
    if lib is None:
        return None
    try:
        _declare(lib)
    except AttributeError:
        return None  # stale .so without the gav_* entry points
    handle = lib.gav_open(
        path.encode(), data_offset, sync, compiled.descriptor,
        len(compiled.descriptor),
    )
    if not handle:
        return None
    try:
        n = lib.gav_decode(handle)
        if n < 0:
            raise ValueError(
                f"{path}: native Avro decode failed: "
                f"{lib.gav_error(handle).decode()}"
            )

        def _i32ptr(a):
            return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))

        doubles = {}
        for field, slot in compiled.dbl_slots.items():
            a = np.empty(n, np.float64)
            lib.gav_doubles(
                handle, slot, a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
            )
            doubles[field] = a
        id_columns = {}
        for field, slot in compiled.str_slots.items():
            idx = np.empty(n, np.int32)
            lib.gav_string_ids(handle, slot, _i32ptr(idx))
            vs = int(lib.gav_string_vocab_size(handle, slot))
            vb = int(lib.gav_string_vocab_bytes(handle, slot))
            lens = np.empty(max(vs, 1), np.int32)
            raw = ctypes.create_string_buffer(max(vb, 1))
            lib.gav_string_vocab(handle, slot, _i32ptr(lens), raw)
            vocab, off = [], 0
            for ln in lens[:vs]:
                vocab.append(raw.raw[off:off + ln].decode("utf-8"))
                off += int(ln)
            id_columns[field] = np.array(vocab, dtype=object)[idx] \
                if vs else np.empty(n, object)
        bags = {}
        for field, slot in compiled.bag_slots.items():
            nnz = np.empty(n, np.int32)
            lib.gav_bag_nnz(handle, slot, _i32ptr(nnz))
            e = int(lib.gav_bag_entries(handle, slot))
            pair_ids = np.empty(max(e, 1), np.int32)
            vals = np.empty(max(e, 1), np.float32)
            lib.gav_bag_pairs(handle, slot, _i32ptr(pair_ids))
            lib.gav_bag_vals(
                handle, slot, vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
            )
            vs = int(lib.gav_pair_vocab_size(handle, slot))
            vb = int(lib.gav_pair_vocab_bytes(handle, slot))
            lens = np.empty(max(2 * vs, 1), np.int32)
            raw = ctypes.create_string_buffer(max(vb, 1))
            lib.gav_pair_vocab(handle, slot, _i32ptr(lens), raw)
            pairs, off = [], 0
            for i in range(vs):
                nl, tl = int(lens[2 * i]), int(lens[2 * i + 1])
                pairs.append((
                    raw.raw[off:off + nl].decode("utf-8"),
                    raw.raw[off + nl:off + nl + tl].decode("utf-8"),
                ))
                off += nl + tl
            bags[field] = (nnz, pair_ids[:e], vals[:e], pairs)
        return DecodedFile(n=int(n), doubles=doubles, id_columns=id_columns,
                           bags=bags)
    finally:
        lib.gav_close(handle)
