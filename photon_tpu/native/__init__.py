"""Native (C++) runtime components: compiled on demand, always optional.

The reference's runtime leans on native code via the JVM (netlib BLAS JNI,
PalDB off-heap maps — SURVEY.md §2.4); this package is the rebuild's native
layer for the HOST side of the pipeline (device compute is XLA/Pallas):

- ``libsvm_native`` — multi-threaded mmap LIBSVM parser (data loader)
- ``index_store`` — PalDB-equivalent read-only mmap feature-index store

The shared library builds lazily with ``g++ -O3`` on first use and every
entry point degrades to pure Python when the toolchain or build is
unavailable (``PHOTON_TPU_NO_NATIVE=1`` forces the fallback).
"""

from photon_tpu.native.build import get_lib, native_disabled

__all__ = ["get_lib", "native_disabled"]
