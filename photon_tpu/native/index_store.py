"""ctypes binding for the mmap feature-index store (src/index_store.cpp).

The native half of :class:`photon_tpu.data.index_map.OffHeapIndexMap` — the
rebuild of the reference's PalDBIndexMap (SURVEY.md §2.3)."""

from __future__ import annotations

import ctypes
from typing import Iterable, Optional

import numpy as np

from photon_tpu.native.build import get_lib


def build_store(path: str, keys: Iterable[str]) -> bool:
    """Write a store file mapping each key to its position.  False when the
    native library is unavailable (caller falls back to JSON)."""
    lib = get_lib()
    if lib is None:
        return False
    encoded = [k.encode() for k in keys]
    blob = b"".join(encoded)
    lens = np.asarray([len(k) for k in encoded], np.int64)
    offs = np.zeros(len(encoded), np.int64)
    if len(encoded) > 1:
        np.cumsum(lens[:-1], out=offs[1:])
    rc = lib.ixs_build(
        path.encode(),
        blob,
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(encoded),
    )
    return rc == 0


class StoreHandle:
    """Open store with key<->id lookups; close()s on GC."""

    def __init__(self, path: str):
        lib = get_lib()
        if lib is None:
            raise OSError("native library unavailable")
        self._lib = lib
        self._handle = lib.ixs_open(path.encode())
        if not self._handle:
            raise OSError(f"cannot open index store {path!r}")
        self.path = path

    def __len__(self) -> int:
        return int(self._lib.ixs_n_keys(self._handle))

    def get_id(self, key: str, default: int = -1) -> int:
        raw = key.encode()
        out = int(self._lib.ixs_get(self._handle, raw, len(raw)))
        return default if out < 0 else out

    def get_key(self, idx: int) -> str:
        buf = ctypes.create_string_buffer(256)
        n = int(self._lib.ixs_key_at(self._handle, idx, buf, 256))
        if n < 0:
            raise IndexError(f"id {idx} out of range")
        if n > 256:  # rare long key: retry with the exact size
            buf = ctypes.create_string_buffer(n)
            self._lib.ixs_key_at(self._handle, idx, buf, n)
        return buf.raw[: min(n, len(buf.raw))].decode()

    def close(self) -> None:
        if self._handle:
            self._lib.ixs_close(self._handle)
            self._handle = None

    def __del__(self):  # noqa: D105
        try:
            self.close()
        except Exception:
            pass


def open_store(path: str) -> Optional[StoreHandle]:
    try:
        return StoreHandle(path)
    except OSError:
        return None
