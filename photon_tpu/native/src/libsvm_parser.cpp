// Multi-threaded LIBSVM parser.
//
// Native-runtime component of the TPU rebuild (SURVEY.md §2.4): the
// reference's hot IO paths run on the JVM (Spark/Avro readers); here the
// host-side data loader is native C++ so parse throughput keeps up with
// device compute.  The file is mmap'd, line-indexed in one pass, and parsed
// into CSR arrays by a thread pool; Python (ctypes) sees three calls:
// svm_open -> svm_parse -> svm_close.

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <charconv>
#include <clocale>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

namespace {

struct SvmFile {
  char* data = nullptr;
  size_t size = 0;
  int fd = -1;
  bool owned = false;  // heap copy instead of mmap (page-boundary case)
  std::vector<size_t> line_start;  // offsets of non-empty payload lines
  std::vector<size_t> line_end;    // exclusive; comments/whitespace trimmed
  std::vector<int64_t> row_nnz;
  int64_t total_nnz = 0;
};

unsigned nthreads(int64_t rows) {
  unsigned n = std::thread::hardware_concurrency();
  if (n == 0) n = 1;
  n = std::min(n, 16u);
  // Tiny files: thread spawn dominates.
  if (rows < 4096) n = 1;
  return n;
}

// Locale-free float parse via std::from_chars (~3-5x strtof), bounded at
// `end` (a number can never bleed past the trimmed line).  Parity shims:
// an optional leading '+' (strtof/Python accept it; from_chars does not)
// and the out-of-range case, which falls back to strtof so overflowing
// magnitudes become +/-inf and underflows become 0/denormal exactly as
// before (the svm_open terminator guarantee keeps strtof in bounds).
// Returns the end of the parsed token, or `p` itself on no-parse.
//
// libstdc++ shipped floating-point from_chars only from GCC 11
// (__cpp_lib_to_chars); older toolchains take a strtof path for every
// token, shimmed for cross-toolchain parity: strtof ALSO skips leading
// whitespace (refused up front — from_chars and the Python reference both
// reject it), accepts hex floats ("0x2" must parse as the leading zero
// only, like from_chars' general format), and honors LC_NUMERIC (a
// comma-decimal locale set by any host library would reparse "1.5" as "1"),
// so glibc builds parse under a cached "C" locale via strtof_l.
#if defined(__cpp_lib_to_chars)
inline const char* parse_float(const char* p, const char* end, float* out) {
  const char* q = p;
  // Skip one '+' only when a number follows: "+-2.5" must stay a parse
  // error (strtof and the Python fallback both reject double signs).
  if (q + 1 < end && *q == '+' &&
      ((q[1] >= '0' && q[1] <= '9') || q[1] == '.' || q[1] == 'i' ||
       q[1] == 'I' || q[1] == 'n' || q[1] == 'N'))
    q++;
  auto r = std::from_chars(q, end, *out);
  if (r.ec == std::errc()) return r.ptr;
  if (r.ec == std::errc::result_out_of_range) {
    char* ep = nullptr;
    *out = strtof(p, &ep);
    return ep;
  }
  return p;
}
#else
inline const char* parse_float(const char* p, const char* end, float* out) {
  if (p >= end || *p == ' ' || *p == '\t' || *p == '\n' || *p == '\r' ||
      *p == '\f' || *p == '\v')
    return p;
  const char* q = p;
  if (*q == '+' || *q == '-') ++q;
  if (q + 1 < end && q[0] == '0' && (q[1] == 'x' || q[1] == 'X')) {
    // from_chars parity: hex is not in the general format — "0x2" parses
    // as the leading zero and stops at the 'x'.
    *out = (*p == '-') ? -0.0f : 0.0f;
    return q + 1;
  }
  char* ep = nullptr;
#if defined(__GLIBC__)
  static const locale_t c_locale = newlocale(LC_ALL_MASK, "C", (locale_t)0);
  *out = c_locale != (locale_t)0 ? strtof_l(p, &ep, c_locale)
                                 : strtof(p, &ep);
#else
  *out = strtof(p, &ep);
#endif
  if (ep == p || ep > end) return p;
  return ep;
}
#endif

}  // namespace

extern "C" {

// Map the file and index its data lines + per-row nonzero counts.
// Returns an opaque handle, or null on IO failure / empty file.
void* svm_open(const char* path) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size == 0) {
    close(fd);
    return nullptr;
  }
  void* map = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (map == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  auto* f = new SvmFile;
  f->data = static_cast<char*>(map);
  f->size = static_cast<size_t>(st.st_size);
  f->fd = fd;

  // strtof/strtol need a readable terminator after the last byte.  A file
  // whose size is an exact multiple of the page size has NO zero-filled
  // tail, so a final line without '\n' would read one byte past the
  // mapping.  Copy to a null-terminated heap buffer in that (rare) case.
  const size_t page = static_cast<size_t>(sysconf(_SC_PAGESIZE));
  if (f->size % page == 0 && f->data[f->size - 1] != '\n') {
    char* copy = static_cast<char*>(malloc(f->size + 1));
    if (!copy) {
      munmap(map, f->size);
      close(fd);
      delete f;
      return nullptr;
    }
    memcpy(copy, f->data, f->size);
    copy[f->size] = '\0';
    munmap(map, f->size);
    close(fd);
    f->data = copy;
    f->fd = -1;
    f->owned = true;
  }

  size_t pos = 0;
  while (pos < f->size) {
    const char* nl = static_cast<const char*>(
        memchr(f->data + pos, '\n', f->size - pos));
    size_t end = nl ? static_cast<size_t>(nl - f->data) : f->size;
    size_t s = pos, e = end;
    const char* hash =
        static_cast<const char*>(memchr(f->data + s, '#', e - s));
    if (hash) e = static_cast<size_t>(hash - f->data);
    while (s < e &&
           (f->data[s] == ' ' || f->data[s] == '\t' || f->data[s] == '\r'))
      s++;
    while (e > s && (f->data[e - 1] == ' ' || f->data[e - 1] == '\t' ||
                     f->data[e - 1] == '\r'))
      e--;
    if (e > s) {
      f->line_start.push_back(s);
      f->line_end.push_back(e);
    }
    pos = end + 1;
  }

  const int64_t rows = static_cast<int64_t>(f->line_start.size());
  f->row_nnz.assign(rows, 0);
  const unsigned nt = nthreads(rows);
  std::vector<std::thread> ts;
  std::vector<int64_t> partial(nt, 0);
  for (unsigned t = 0; t < nt; ++t) {
    ts.emplace_back([f, t, nt, rows, &partial]() {
      int64_t local = 0;
      for (int64_t i = t; i < rows; i += nt) {
        const char* p = f->data + f->line_start[i];
        const char* e = f->data + f->line_end[i];
        int64_t c = 0;
        while (p < e && (p = static_cast<const char*>(
                             memchr(p, ':', e - p))) != nullptr) {
          c++;
          p++;
        }
        f->row_nnz[i] = c;
        local += c;
      }
      partial[t] = local;
    });
  }
  for (auto& th : ts) th.join();
  for (int64_t v : partial) f->total_nnz += v;
  return f;
}

int64_t svm_rows(void* h) {
  return static_cast<int64_t>(static_cast<SvmFile*>(h)->line_start.size());
}

int64_t svm_total_nnz(void* h) { return static_cast<SvmFile*>(h)->total_nnz; }

void svm_row_nnz(void* h, int64_t* out) {
  auto* f = static_cast<SvmFile*>(h);
  memcpy(out, f->row_nnz.data(), f->row_nnz.size() * sizeof(int64_t));
}

// Parse every row into caller-allocated CSR arrays.  row_ptr is the
// exclusive prefix sum of row_nnz (rows + 1 entries).  Returns the max
// feature id seen after the zero/one-based adjustment, -1 for an all-empty
// file, or -2 on malformed input.
//
// Bounds note: strtof/strtol may scan a few bytes past a row's logical end
// but never past the buffer: either the final page's zero-filled tail
// terminates the scan, or svm_open copied the file into a null-terminated
// heap buffer (exact-page-multiple files with no trailing newline).
int64_t svm_parse(void* h, const int64_t* row_ptr, float* labels,
                  int32_t* ids, float* vals, int zero_based) {
  auto* f = static_cast<SvmFile*>(h);
  const int64_t rows = static_cast<int64_t>(f->line_start.size());
  const unsigned nt = nthreads(rows);
  std::vector<std::thread> ts;
  std::vector<int64_t> maxids(nt, -1);
  std::vector<int> errs(nt, 0);
  const int off = zero_based ? 0 : 1;
  for (unsigned t = 0; t < nt; ++t) {
    ts.emplace_back([=, &maxids, &errs]() {
      int64_t mx = -1;
      for (int64_t i = t; i < rows; i += nt) {
        const char* p = f->data + f->line_start[i];
        const char* e = f->data + f->line_end[i];
        const char* endp = parse_float(p, e, &labels[i]);
        if (endp == p) {
          errs[t] = 1;
          return;
        }
        p = endp;
        int64_t w = row_ptr[i];
        while (p < e) {
          while (p < e && (*p == ' ' || *p == '\t')) p++;
          if (p >= e) break;
          // int64 parse (from_chars: no '+' — skip one for strtoll/Python
          // parity); an out-of-range id errors below exactly as strtoll's
          // LLONG_MAX saturation did.
          const char* idp = (*p == '+' && p + 1 < e) ? p + 1 : p;
          long long id;
          auto idr = std::from_chars(idp, e, id);
          if (idr.ec == std::errc::result_out_of_range) id = INT64_MAX;
          else if (idr.ec != std::errc()) {
            errs[t] = 1;
            return;
          }
          endp = idr.ptr;
          if (endp == idp || endp >= e || *endp != ':') {
            errs[t] = 1;
            return;
          }
          // Feature ids land in int32 storage after the zero/one-based
          // adjustment; out-of-range ids (overflowing files, negative ids)
          // must be a parse error, not a silent int32 wraparound.
          if (id < off || id - off > INT32_MAX) {
            errs[t] = 1;
            return;
          }
          p = endp + 1;
          // The value must start immediately after the colon: a bare "id:"
          // at end of line (p >= e) or "id: val" would otherwise let strtof
          // skip whitespace — including the newline, stealing the NEXT
          // line's label as this feature's value.  The Python parser errors
          // on both, and both paths must accept the same files.
          if (p >= e || *p == ' ' || *p == '\t' || *p == '\r' ||
              *p == '\n' || *p == '\v' || *p == '\f') {
            errs[t] = 1;
            return;
          }
          float v;
          endp = parse_float(p, e, &v);
          if (endp == p) {
            errs[t] = 1;
            return;
          }
          p = endp;
          ids[w] = static_cast<int32_t>(id - off);
          vals[w] = v;
          if (ids[w] > mx) mx = ids[w];
          ++w;
        }
        if (w != row_ptr[i + 1]) {
          errs[t] = 1;
          return;
        }
      }
      maxids[t] = std::max(maxids[t], mx);
    });
  }
  for (auto& th : ts) th.join();
  for (int er : errs)
    if (er) return -2;
  int64_t mx = -1;
  for (int64_t v : maxids) mx = std::max(mx, v);
  return mx;
}

void svm_close(void* h) {
  auto* f = static_cast<SvmFile*>(h);
  if (f->owned) {
    free(f->data);
  } else {
    munmap(f->data, f->size);
    close(f->fd);
  }
  delete f;
}

}  // extern "C"
