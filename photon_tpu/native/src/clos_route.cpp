// Clos routing for static element permutations (the `benes` sparse kernel).
//
// The TPU-side plan (ops/KERNEL_NOTES.md, round-4 second-window verdicts)
// rewrites the random E-element exchange between row-major and
// feature-major entry orders as: per-row local permutations + matrix
// transposes.  Any permutation of an [A x B] grid factors as
//
//     P1 (independent B-perm per row) . T . P2 (A-perm per row of [B,A])
//        . T . P3 (independent B-perm per row)
//
// iff each element is assigned a "middle column" color c in [0,B) such
// that no two elements sharing a source row get the same color and no two
// elements sharing a destination row get the same color.  Model each
// element as an edge (source_row -> dest_row) of a B-regular bipartite
// multigraph on A+A vertices; a proper B-edge-coloring (exists by Konig's
// theorem) IS that assignment.  This file computes the coloring by Euler
// splitting: walk Euler circuits, label edges alternately, recurse on the
// two (B/2)-regular halves until degree 1.  Bipartite circuits have even
// length, so the alternation splits every vertex's degree exactly in half
// at every level; B must be a power of two.
//
// This is host-side, one-time-per-layout routing (the permutation is
// static data layout, not step data); the device step then runs only
// sequential reads, lane-local shuffles, and transposes.
//
// Exposed C API (ctypes):
//   clos_edge_color(E, A, B, l[], r[], color[]) -> 0 ok / <0 error

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

// One Euler-split edge coloring over edges[0..E) of a B-regular bipartite
// multigraph with A vertices per side.  Iterative over an explicit task
// stack; scratch vectors are reused across tasks to bound allocation.
struct Scratch {
  // CSR adjacency over 2A vertices; each edge appears twice (once per
  // endpoint).  slot -> edge id and slot -> other endpoint are derivable,
  // we store edge ids and recompute endpoints from l/r.
  std::vector<int64_t> head;     // per vertex: next unused slot cursor
  std::vector<int64_t> stop;     // per vertex: end of slot range
  std::vector<int64_t> slots;    // 2E slot -> edge id
  std::vector<uint8_t> used;     // per edge: consumed in current walk
  std::vector<int64_t> stack;        // edge frames for Hierholzer
  std::vector<int64_t> slots_vstack; // vertex frames for Hierholzer
  std::vector<int64_t> circuit;      // edge ids in circuit order
};

int color_one(int64_t E, int32_t A, int32_t B, const int32_t* l,
              const int32_t* r, int32_t* color, Scratch& s) {
  if (B <= 0 || (B & (B - 1)) != 0) return -1;  // power of two required
  // Task = (subset of edges, color base, span).  Subsets are stored in a
  // shared arena; tasks reference [begin, end) ranges.
  std::vector<int64_t> arena(E);
  for (int64_t e = 0; e < E; ++e) arena[e] = e;
  struct Task {
    int64_t begin, end;
    int32_t base, span;
  };
  std::vector<Task> tasks;
  tasks.push_back({0, E, 0, B});

  const int64_t V = 2 * static_cast<int64_t>(A);
  s.head.assign(V + 1, 0);
  s.stop.assign(V, 0);

  while (!tasks.empty()) {
    Task t = tasks.back();
    tasks.pop_back();
    const int64_t n = t.end - t.begin;
    if (t.span == 1) {
      for (int64_t i = t.begin; i < t.end; ++i) color[arena[i]] = t.base;
      continue;
    }
    // Build CSR over the subset's touched vertices.  Count, prefix, fill.
    // head/stop are sized for all V vertices; untouched ones get empty
    // ranges, cost O(V) per task — fine at A<=2^13, E>=2^12 per task.
    std::fill(s.head.begin(), s.head.end(), 0);
    for (int64_t i = t.begin; i < t.end; ++i) {
      const int64_t e = arena[i];
      s.head[l[e] + 1]++;
      s.head[A + r[e] + 1]++;
    }
    for (int64_t v = 0; v < V; ++v) s.head[v + 1] += s.head[v];
    s.slots.resize(2 * n);
    // stop = end of each vertex's range; head stays the walking cursor.
    for (int64_t v = 0; v < V; ++v) s.stop[v] = s.head[v + 1];
    {
      std::vector<int64_t> fill(s.head.begin(), s.head.end() - 1);
      for (int64_t i = t.begin; i < t.end; ++i) {
        const int64_t e = arena[i];
        s.slots[fill[l[e]]++] = e;
        s.slots[fill[A + r[e]]++] = e;
      }
    }
    s.used.assign(n, 0);
    // Map edge id -> dense index within subset for `used`.  Avoid a hash:
    // stash dense index in color[] temporarily (it is overwritten later
    // anyway) — color[e] = dense index for subset edges.
    for (int64_t i = t.begin; i < t.end; ++i)
      color[arena[i]] = static_cast<int32_t>(i - t.begin);

    // Hierholzer from every vertex with unused slots; label circuit edges
    // alternately.  Bipartite circuits have even length, so cyclic
    // alternation gives every vertex visit one edge of each label and the
    // vertex's degree splits exactly in half.  The frame stack stores
    // (vertex << 1 packing not needed — two parallel stacks would do, but
    // a single stack of packed pairs keeps cache behavior simple): we
    // push the edge used to REACH a vertex; popping emits that edge, so
    // `circuit` holds the Euler circuit in reverse traversal order —
    // still a circuit, which is all alternation needs.
    const int64_t half = t.begin + n / 2;
    int64_t lo = t.begin, hi = half;  // arena write cursors for halves
    for (int64_t v0 = 0; v0 < V; ++v0) {
      while (s.head[v0] < s.stop[v0]) {
        // Skip already-consumed slots at the start vertex.
        if (s.used[color[s.slots[s.head[v0]]]]) {
          s.head[v0]++;
          continue;
        }
        // Walk one circuit starting at v0.  stack holds packed frames:
        // vertex in the high bits is unnecessary — we keep two arrays.
        s.stack.clear();    // edge taken to reach the frame's vertex
        s.circuit.clear();  // emitted circuit edges (reverse order)
        std::vector<int64_t>& vstack = s.slots_vstack;
        vstack.clear();
        vstack.push_back(v0);
        s.stack.push_back(-1);
        while (!vstack.empty()) {
          const int64_t v = vstack.back();
          // Advance the cursor past used slots.
          while (s.head[v] < s.stop[v] &&
                 s.used[color[s.slots[s.head[v]]]]) {
            s.head[v]++;
          }
          if (s.head[v] < s.stop[v]) {
            const int64_t e = s.slots[s.head[v]];
            s.used[color[e]] = 1;
            const int64_t a = l[e], b = A + r[e];
            vstack.push_back(v == a ? b : a);
            s.stack.push_back(e);
          } else {
            const int64_t e = s.stack.back();
            s.stack.pop_back();
            vstack.pop_back();
            if (e >= 0) s.circuit.push_back(e);
          }
        }
        // Alternate labels along the circuit.
        for (size_t i = 0; i < s.circuit.size(); ++i) {
          const int64_t e = s.circuit[i];
          if (i % 2 == 0) {
            arena[lo++] = e;
          } else {
            arena[hi++] = e;
          }
        }
      }
    }
    if (lo != half || hi != t.end) return -2;  // split imbalance: bug
    tasks.push_back({t.begin, half, t.base, t.span / 2});
    tasks.push_back({half, t.end,
                     static_cast<int32_t>(t.base + t.span / 2), t.span / 2});
  }
  return 0;
}

}  // namespace

extern "C" {

int32_t clos_edge_color(int64_t E, int32_t A, int32_t B, const int32_t* l,
                        const int32_t* r, int32_t* color) {
  // color[] doubles as int32 scratch for dense subset indices (see
  // color_one), so edge counts past INT32_MAX would wrap and corrupt the
  // coloring; refuse explicitly (distinct code: -1 = bad B, -2 =
  // internal split invariant, -3 = size limit).
  if (E < 0 || E > INT32_MAX) return -3;
  Scratch s;
  return color_one(E, A, B, l, r, color, s);
}

}  // extern "C"
