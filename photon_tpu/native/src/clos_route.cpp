// Clos routing for static element permutations (the `benes` sparse kernel).
//
// The TPU-side plan (ops/KERNEL_NOTES.md, round-4 second-window verdicts)
// rewrites the random E-element exchange between row-major and
// feature-major entry orders as: per-row local permutations + matrix
// transposes.  Any permutation of an [A x B] grid factors as
//
//     P1 (independent B-perm per row) . T . P2 (A-perm per row of [B,A])
//        . T . P3 (independent B-perm per row)
//
// iff each element is assigned a "middle column" color c in [0,B) such
// that no two elements sharing a source row get the same color and no two
// elements sharing a destination row get the same color.  Model each
// element as an edge (source_row -> dest_row) of a B-regular bipartite
// multigraph on A+A vertices; a proper B-edge-coloring (exists by Konig's
// theorem) IS that assignment.  This file computes the coloring by Euler
// splitting: walk Euler circuits, label edges alternately, recurse on the
// two (B/2)-regular halves until degree 1.  Bipartite circuits have even
// length, so the alternation splits every vertex's degree exactly in half
// at every level; B must be a power of two.
//
// This is host-side, one-time-per-layout routing (the permutation is
// static data layout, not step data); the device step then runs only
// sequential reads, lane-local shuffles, and transposes.
//
// Exposed C API (ctypes):
//   clos_edge_color(E, A, B, l[], r[], color[]) -> 0 ok / <0 error

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

// One Euler-split edge coloring over edges[0..E) of a B-regular bipartite
// multigraph with A vertices per side.  Iterative over an explicit task
// stack; scratch vectors are reused across tasks to bound allocation.
//
// Performance shape (round-4 rework): every per-task structure is a
// DENSE int32 copy of the subset (endpoints included), so the Euler
// walk's three dependent indirections (cursor -> slot -> used) touch
// arrays of the SUBSET's size — tasks halve per level, so deeper levels
// run cache-resident instead of striding the full-E arrays.  This took
// the walk from ~75 ns/edge-step to ~20 ns at production sizes.
struct Scratch {
  std::vector<int32_t> head;     // per vertex: next unused slot cursor
  std::vector<int32_t> stop;     // per vertex: end of slot range
  std::vector<int32_t> slots;    // 2n slot -> dense edge index
  std::vector<int32_t> ld, rd;   // dense endpoints (rd pre-offset by A)
  std::vector<int32_t> sub;      // dense index -> global edge id
  std::vector<uint8_t> used;     // per dense edge: consumed in walk
  std::vector<int32_t> stack;    // edge frames for Hierholzer
  std::vector<int32_t> vstack;   // vertex frames for Hierholzer
  std::vector<int32_t> circuit;  // dense edge ids in circuit order
};

int color_one(int64_t E, int32_t A, int32_t B, const int32_t* l,
              const int32_t* r, int32_t* color, Scratch& s) {
  if (B <= 0 || (B & (B - 1)) != 0) return -1;  // power of two required
  // Task = (subset of edges, color base, span).  Subsets are stored in a
  // shared arena; tasks reference [begin, end) ranges.
  std::vector<int32_t> arena(E);
  for (int64_t e = 0; e < E; ++e) arena[e] = static_cast<int32_t>(e);
  struct Task {
    int64_t begin, end;
    int32_t base, span;
  };
  std::vector<Task> tasks;
  tasks.push_back({0, E, 0, B});

  const int32_t V = 2 * A;
  s.head.assign(V + 1, 0);
  s.stop.assign(V, 0);

  while (!tasks.empty()) {
    Task t = tasks.back();
    tasks.pop_back();
    const int64_t n = t.end - t.begin;
    if (t.span == 1) {
      for (int64_t i = t.begin; i < t.end; ++i) color[arena[i]] = t.base;
      continue;
    }
    // Dense subset copy: one scattered read of l/r per level, then the
    // whole task works on contiguous int32 arrays.
    s.sub.resize(n);
    s.ld.resize(n);
    s.rd.resize(n);
    std::memcpy(s.sub.data(), arena.data() + t.begin, n * sizeof(int32_t));
    for (int64_t i = 0; i < n; ++i) {
      const int32_t e = s.sub[i];
      s.ld[i] = l[e];
      s.rd[i] = A + r[e];
    }
    // CSR over the subset's vertices: count, prefix, fill.  head/stop
    // cover all V vertices (untouched ones get empty ranges) — O(V) per
    // task, small next to n at every level that matters.
    std::fill(s.head.begin(), s.head.end(), 0);
    for (int64_t i = 0; i < n; ++i) {
      s.head[s.ld[i] + 1]++;
      s.head[s.rd[i] + 1]++;
    }
    for (int32_t v = 0; v < V; ++v) s.head[v + 1] += s.head[v];
    s.slots.resize(2 * n);
    for (int32_t v = 0; v < V; ++v) s.stop[v] = s.head[v + 1];
    {
      std::vector<int32_t> fill(s.head.begin(), s.head.end() - 1);
      for (int64_t i = 0; i < n; ++i) {
        s.slots[fill[s.ld[i]]++] = static_cast<int32_t>(i);
        s.slots[fill[s.rd[i]]++] = static_cast<int32_t>(i);
      }
    }
    s.used.assign(n, 0);

    // Hierholzer from every vertex with unused slots; label circuit edges
    // alternately.  Bipartite circuits have even length, so cyclic
    // alternation gives every vertex visit one edge of each label and the
    // vertex's degree splits exactly in half.  We push the edge used to
    // REACH a vertex; popping emits it, so `circuit` holds the Euler
    // circuit in reverse traversal order — still a circuit, which is all
    // alternation needs.
    const int64_t half = t.begin + n / 2;
    int64_t lo = t.begin, hi = half;  // arena write cursors for halves
    for (int32_t v0 = 0; v0 < V; ++v0) {
      while (s.head[v0] < s.stop[v0]) {
        if (s.used[s.slots[s.head[v0]]]) {
          s.head[v0]++;
          continue;
        }
        s.stack.clear();
        s.circuit.clear();
        s.vstack.clear();
        s.vstack.push_back(v0);
        s.stack.push_back(-1);
        while (!s.vstack.empty()) {
          const int32_t v = s.vstack.back();
          while (s.head[v] < s.stop[v] && s.used[s.slots[s.head[v]]]) {
            s.head[v]++;
          }
          if (s.head[v] < s.stop[v]) {
            const int32_t e = s.slots[s.head[v]];
            s.used[e] = 1;
            const int32_t a = s.ld[e], b = s.rd[e];
            s.vstack.push_back(v == a ? b : a);
            s.stack.push_back(e);
          } else {
            const int32_t e = s.stack.back();
            s.stack.pop_back();
            s.vstack.pop_back();
            if (e >= 0) s.circuit.push_back(e);
          }
        }
        // Alternate labels along the circuit (dense -> global ids).
        for (size_t i = 0; i < s.circuit.size(); ++i) {
          const int32_t g = s.sub[s.circuit[i]];
          if (i % 2 == 0) {
            arena[lo++] = g;
          } else {
            arena[hi++] = g;
          }
        }
      }
    }
    if (lo != half || hi != t.end) return -2;  // split imbalance: bug
    tasks.push_back({t.begin, half, t.base, t.span / 2});
    tasks.push_back({half, t.end,
                     static_cast<int32_t>(t.base + t.span / 2), t.span / 2});
  }
  return 0;
}

}  // namespace

extern "C" {

int32_t clos_edge_color(int64_t E, int32_t A, int32_t B, const int32_t* l,
                        const int32_t* r, int32_t* color) {
  // The arena, dense subset arrays (sub/ld/rd/slots), and the CSR
  // prefix sums in head are int32; head reaches 2*E at the root task,
  // so edge counts must stay under INT32_MAX/2 or the cursors wrap and
  // index out of bounds.  Refuse explicitly (distinct code: -1 = bad B,
  // -2 = internal split invariant, -3 = size limit).
  if (E < 0 || E > INT32_MAX / 2) return -3;
  Scratch s;
  return color_one(E, A, B, l, r, color, s);
}

}  // extern "C"
