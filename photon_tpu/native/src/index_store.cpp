// Read-only mmap'd feature-index store.
//
// Native equivalent of the reference's PalDB index maps (photon-client
// index/PalDBIndexMap — SURVEY.md §2.3/§2.4): feature-key -> id lookups
// against an off-heap, memory-mapped file, so huge feature vocabularies
// never materialize as in-process hash maps.  Open-addressed FNV-1a hash
// table at load factor <= 0.5, plus an id -> key table for reverse lookup.
//
// File layout (little-endian):
//   Header{magic, version, n_keys, n_buckets, blob_bytes}
//   int64 buckets[n_buckets]   — blob offset of the record, or -1
//   int64 by_id[n_keys]        — blob offset per id (reverse lookup)
//   blob: records [int32 key_len][key bytes][int64 id]

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x53584950;  // "PIXS"

struct Header {
  uint32_t magic;
  uint32_t version;
  int64_t n_keys;
  int64_t n_buckets;
  int64_t blob_bytes;
};

struct Store {
  char* data;
  size_t size;
  int fd;
  const Header* hdr;
  const int64_t* buckets;
  const int64_t* by_id;
  const char* blob;
};

uint64_t fnv1a(const char* s, int64_t n) {
  uint64_t h = 1469598103934665603ULL;
  for (int64_t i = 0; i < n; ++i) {
    h ^= static_cast<unsigned char>(s[i]);
    h *= 1099511628211ULL;
  }
  return h;
}

}  // namespace

extern "C" {

// Build the store file from n keys packed into one blob (offs/lens per key).
// Ids are assigned in input order. Returns 0 on success.
int ixs_build(const char* path, const char* keys, const int64_t* offs,
              const int64_t* lens, int64_t n) {
  int64_t n_buckets = 16;
  while (n_buckets < 2 * n) n_buckets <<= 1;

  std::vector<char> blob;
  std::vector<int64_t> recoff(static_cast<size_t>(n));
  for (int64_t i = 0; i < n; ++i) {
    recoff[i] = static_cast<int64_t>(blob.size());
    int32_t len = static_cast<int32_t>(lens[i]);
    const char* lp = reinterpret_cast<const char*>(&len);
    blob.insert(blob.end(), lp, lp + 4);
    blob.insert(blob.end(), keys + offs[i], keys + offs[i] + lens[i]);
    int64_t id = i;
    const char* ip = reinterpret_cast<const char*>(&id);
    blob.insert(blob.end(), ip, ip + 8);
  }

  std::vector<int64_t> buckets(static_cast<size_t>(n_buckets), -1);
  for (int64_t i = 0; i < n; ++i) {
    uint64_t b = fnv1a(keys + offs[i], lens[i]) &
                 static_cast<uint64_t>(n_buckets - 1);
    while (buckets[b] != -1) b = (b + 1) & static_cast<uint64_t>(n_buckets - 1);
    buckets[b] = recoff[i];
  }

  FILE* fp = fopen(path, "wb");
  if (!fp) return -1;
  Header hdr{kMagic, 1, n, n_buckets, static_cast<int64_t>(blob.size())};
  int ok = fwrite(&hdr, sizeof hdr, 1, fp) == 1 &&
           fwrite(buckets.data(), 8, buckets.size(), fp) == buckets.size() &&
           (n == 0 ||
            fwrite(recoff.data(), 8, recoff.size(), fp) == recoff.size()) &&
           (blob.empty() ||
            fwrite(blob.data(), 1, blob.size(), fp) == blob.size());
  if (fclose(fp) != 0) ok = 0;
  return ok ? 0 : -1;
}

void* ixs_open(const char* path) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 ||
      st.st_size < static_cast<off_t>(sizeof(Header))) {
    close(fd);
    return nullptr;
  }
  void* map = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (map == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  auto* s = new Store;
  s->data = static_cast<char*>(map);
  s->size = static_cast<size_t>(st.st_size);
  s->fd = fd;
  s->hdr = reinterpret_cast<const Header*>(s->data);
  // Validate the header AND that every declared section fits inside the
  // file — a truncated store must fail open, not segfault on first lookup.
  bool ok = s->hdr->magic == kMagic && s->hdr->version == 1 &&
            s->hdr->n_keys >= 0 && s->hdr->n_buckets > 0 &&
            s->hdr->blob_bytes >= 0 &&
            ((s->hdr->n_buckets & (s->hdr->n_buckets - 1)) == 0);
  if (ok) {
    // Divide instead of multiply: a corrupt header with n_buckets ~ 2^61
    // would overflow 8 * n_buckets and sneak past a multiplied bound.
    const uint64_t avail = static_cast<uint64_t>(s->size) - sizeof(Header);
    const uint64_t nb = static_cast<uint64_t>(s->hdr->n_buckets);
    const uint64_t nk = static_cast<uint64_t>(s->hdr->n_keys);
    const uint64_t bb = static_cast<uint64_t>(s->hdr->blob_bytes);
    ok = nb <= avail / 8 && nk <= (avail - 8 * nb) / 8 &&
         bb <= avail - 8 * nb - 8 * nk;
  }
  if (!ok) {
    munmap(map, s->size);
    close(fd);
    delete s;
    return nullptr;
  }
  s->buckets = reinterpret_cast<const int64_t*>(s->data + sizeof(Header));
  s->by_id = s->buckets + s->hdr->n_buckets;
  s->blob = reinterpret_cast<const char*>(s->by_id + s->hdr->n_keys);
  return s;
}

int64_t ixs_n_keys(void* h) { return static_cast<Store*>(h)->hdr->n_keys; }

// key -> id, or -1 when absent.
int64_t ixs_get(void* h, const char* key, int64_t len) {
  auto* s = static_cast<Store*>(h);
  const int64_t nb = s->hdr->n_buckets;
  uint64_t b = fnv1a(key, len) & static_cast<uint64_t>(nb - 1);
  const int64_t blob_bytes = s->hdr->blob_bytes;
  for (int64_t probe = 0; probe < nb; ++probe) {
    int64_t off = s->buckets[b];
    if (off < 0) return -1;
    if (off + 12 > blob_bytes) return -1;  // corrupt bucket entry
    const char* rec = s->blob + off;
    int32_t rlen;
    memcpy(&rlen, rec, 4);
    if (rlen < 0 || off + 12 + rlen > blob_bytes) return -1;
    if (rlen == len && memcmp(rec + 4, key, len) == 0) {
      int64_t id;
      memcpy(&id, rec + 4 + rlen, 8);
      return id;
    }
    b = (b + 1) & static_cast<uint64_t>(nb - 1);
  }
  return -1;
}

// id -> key bytes (copied into buf, truncated to cap); returns the key's
// full length, or -1 for an out-of-range id.
int64_t ixs_key_at(void* h, int64_t id, char* buf, int64_t cap) {
  auto* s = static_cast<Store*>(h);
  if (id < 0 || id >= s->hdr->n_keys) return -1;
  const int64_t off = s->by_id[id];
  if (off < 0 || off + 12 > s->hdr->blob_bytes) return -1;
  const char* rec = s->blob + off;
  int32_t rlen;
  memcpy(&rlen, rec, 4);
  if (rlen < 0 || off + 12 + rlen > s->hdr->blob_bytes) return -1;
  int64_t n = rlen < cap ? rlen : cap;
  memcpy(buf, rec + 4, static_cast<size_t>(n));
  return rlen;
}

void ixs_close(void* h) {
  auto* s = static_cast<Store*>(h);
  munmap(s->data, s->size);
  close(s->fd);
  delete s;
}

}  // extern "C"
