// Columnar decoder for GAME training-record Avro container files.
//
// The pure-Python codec (photon_tpu/data/avro_codec.py) decodes each record
// into a dict and the reader walks features in Python — the throughput
// ceiling of the 1B-row GAME ingestion story.  The reference reads the same
// records through the JVM's native Avro decoder; this is the TPU rebuild's
// equivalent (SURVEY.md §2.4 "native where the reference's is").
//
// Scope: the TrainingExampleAvro shape (photon_tpu/data/game_io.py) over
// null-codec container blocks.  Python parses the container HEADER (schema
// JSON, codec, sync marker) and compiles the record schema into a flat
// opcode descriptor; this decoder executes it per record over an mmapped
// file, emitting columnar streams:
//   - one f64 stream per (OPT_)DOUBLE slot (null -> descriptor default),
//   - one i32 stream + interned vocab per STRING slot (entity-id columns),
//   - per BAG slot: per-record nnz, per-entry interned (name, term) pair
//     ids + f32 values, and the pair vocab in first-seen order (which is
//     entry order — exactly the Python reader's first-seen id assignment).
// Schemas outside the compiled subset fall back to the Python reader.
//
// Written from the public Avro 1.x wire spec (zigzag varints, length-
// prefixed strings, block-structured arrays); no Avro implementation code.

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

// Descriptor opcodes (must match photon_tpu/native/avro_native.py).
enum Op : uint8_t {
  OP_DOUBLE = 1,        // scalar double
  OP_OPT_DOUBLE = 2,    // + null_branch(1B) + default(8B LE double)
  OP_STRING = 3,        // interned string -> id stream
  OP_SKIP_STRING = 4,   // decoded, discarded
  OP_SKIP_OPT_STRING = 5,  // + null_branch(1B)
  OP_BAG = 6,           // array<{string,string,double}>
  OP_SKIP_BAG = 7,      // decoded, discarded
  OP_SKIP_DOUBLE = 8,
  OP_SKIP_OPT_DOUBLE = 9,  // + null_branch(1B)
};

struct Vocab {
  // Composite-key interner: key bytes are length-unambiguous
  // (u32 name_len + name + term), values are first-seen ids.
  std::unordered_map<std::string, int32_t> map;
  std::vector<std::string> names;  // per id
  std::vector<std::string> terms;
};

struct BagOut {
  std::vector<int32_t> nnz;     // per record
  std::vector<int32_t> pairs;   // per entry
  std::vector<float> vals;      // per entry
  Vocab vocab;
};

struct StrOut {
  std::vector<int32_t> idx;  // per record
  std::vector<std::string> vocab;
  std::unordered_map<std::string, int32_t> map;
};

struct GavFile {
  const uint8_t* base = nullptr;
  size_t size = 0;
  size_t pos = 0;       // first block offset (from Python header parse)
  uint8_t sync[16];
  std::vector<uint8_t> desc;
  std::vector<std::vector<double>> dbl;  // per (OPT_)DOUBLE slot
  std::vector<StrOut> str;               // per STRING slot
  std::vector<BagOut> bags;              // per BAG slot
  int64_t n_records = 0;
  std::string error;
  int fd = -1;
};

struct Cursor {
  const uint8_t* p;
  const uint8_t* end;
  bool fail = false;
};

inline int64_t read_varlong(Cursor& c) {
  uint64_t acc = 0;
  int shift = 0;
  while (true) {
    if (c.p >= c.end) { c.fail = true; return 0; }
    uint8_t b = *c.p++;
    acc |= static_cast<uint64_t>(b & 0x7F) << shift;
    if (!(b & 0x80)) break;
    shift += 7;
    if (shift > 63) { c.fail = true; return 0; }
  }
  return static_cast<int64_t>((acc >> 1) ^ (~(acc & 1) + 1));
}

inline double read_double(Cursor& c) {
  if (c.p + 8 > c.end) { c.fail = true; return 0.0; }
  double v;
  std::memcpy(&v, c.p, 8);
  c.p += 8;
  return v;
}

// Returns (ptr, len) of a length-prefixed string; nullptr on bounds error.
// (n is compared against the remaining byte count, never added to the
// pointer first — a hostile length must not overflow the arithmetic.)
inline const char* read_str(Cursor& c, int64_t* len) {
  int64_t n = read_varlong(c);
  if (c.fail || n < 0 || n > c.end - c.p) { c.fail = true; return nullptr; }
  const char* s = reinterpret_cast<const char*>(c.p);
  c.p += n;
  *len = n;
  return s;
}

bool decode_record(GavFile* h, Cursor& c) {
  size_t di = 0;
  int dbl_slot = 0, str_slot = 0, bag_slot = 0;
  const std::vector<uint8_t>& d = h->desc;
  while (di < d.size()) {
    switch (d[di++]) {
      case OP_DOUBLE:
        h->dbl[dbl_slot++].push_back(read_double(c));
        break;
      case OP_OPT_DOUBLE: {
        uint8_t null_branch = d[di++];
        double dflt;
        std::memcpy(&dflt, &d[di], 8);
        di += 8;
        int64_t branch = read_varlong(c);
        h->dbl[dbl_slot++].push_back(
            branch == null_branch ? dflt : read_double(c));
        break;
      }
      case OP_SKIP_DOUBLE:
        read_double(c);
        break;
      case OP_SKIP_OPT_DOUBLE: {
        uint8_t null_branch = d[di++];
        if (read_varlong(c) != null_branch) read_double(c);
        break;
      }
      case OP_STRING: {
        int64_t len;
        const char* s = read_str(c, &len);
        if (c.fail) return false;
        StrOut& so = h->str[str_slot++];
        std::string key(s, len);
        auto it = so.map.find(key);
        int32_t id;
        if (it == so.map.end()) {
          id = static_cast<int32_t>(so.vocab.size());
          so.vocab.push_back(key);
          so.map.emplace(std::move(key), id);
        } else {
          id = it->second;
        }
        so.idx.push_back(id);
        break;
      }
      case OP_SKIP_STRING: {
        int64_t len;
        read_str(c, &len);
        break;
      }
      case OP_SKIP_OPT_STRING: {
        uint8_t null_branch = d[di++];
        if (read_varlong(c) != null_branch) {
          int64_t len;
          read_str(c, &len);
        }
        break;
      }
      case OP_BAG:
      case OP_SKIP_BAG: {
        bool keep = d[di - 1] == OP_BAG;
        BagOut* bo = keep ? &h->bags[bag_slot++] : nullptr;
        int32_t count = 0;
        while (true) {
          int64_t n = read_varlong(c);
          if (c.fail) return false;
          if (n == 0) break;
          if (n < 0) {  // block with byte-size prefix
            if (n == INT64_MIN) {  // -n would be signed-overflow UB
              c.fail = true;
              return false;
            }
            read_varlong(c);
            if (c.fail) return false;
            n = -n;
          }
          for (int64_t i = 0; i < n; i++) {
            int64_t nlen, tlen;
            const char* name = read_str(c, &nlen);
            const char* term = read_str(c, &tlen);
            double value = read_double(c);
            if (c.fail) return false;
            if (keep) {
              uint32_t nl = static_cast<uint32_t>(nlen);
              std::string key;
              key.reserve(4 + nlen + tlen);
              key.append(reinterpret_cast<const char*>(&nl), 4);
              key.append(name, nlen);
              key.append(term, tlen);
              auto it = bo->vocab.map.find(key);
              int32_t id;
              if (it == bo->vocab.map.end()) {
                id = static_cast<int32_t>(bo->vocab.names.size());
                bo->vocab.names.emplace_back(name, nlen);
                bo->vocab.terms.emplace_back(term, tlen);
                bo->vocab.map.emplace(std::move(key), id);
              } else {
                id = it->second;
              }
              bo->pairs.push_back(id);
              bo->vals.push_back(static_cast<float>(value));
            }
          }
          count += static_cast<int32_t>(n);
        }
        if (keep) bo->nnz.push_back(count);
        break;
      }
      default:
        h->error = "bad descriptor opcode";
        return false;
    }
    if (c.fail) return false;
  }
  return true;
}

}  // namespace

extern "C" {

void* gav_open(const char* path, int64_t data_offset, const uint8_t* sync,
               const uint8_t* desc, int64_t desc_len) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < data_offset) {
    ::close(fd);
    return nullptr;
  }
  GavFile* h = new GavFile();
  h->fd = fd;
  h->size = static_cast<size_t>(st.st_size);
  if (h->size > 0) {
    void* m = mmap(nullptr, h->size, PROT_READ, MAP_PRIVATE, fd, 0);
    if (m == MAP_FAILED) {
      ::close(fd);
      delete h;
      return nullptr;
    }
    h->base = static_cast<const uint8_t*>(m);
  }
  h->pos = static_cast<size_t>(data_offset);
  std::memcpy(h->sync, sync, 16);
  h->desc.assign(desc, desc + desc_len);
  // Pre-size slot vectors by scanning the descriptor.
  size_t di = 0;
  while (di < h->desc.size()) {
    switch (h->desc[di++]) {
      case OP_DOUBLE: h->dbl.emplace_back(); break;
      case OP_OPT_DOUBLE: h->dbl.emplace_back(); di += 9; break;
      case OP_SKIP_OPT_DOUBLE: di += 1; break;
      case OP_SKIP_OPT_STRING: di += 1; break;
      case OP_STRING: h->str.emplace_back(); break;
      case OP_BAG: h->bags.emplace_back(); break;
      default: break;
    }
  }
  return h;
}

// Decode all blocks; returns record count or -1 (gav_error has detail).
int64_t gav_decode(void* hp) {
  GavFile* h = static_cast<GavFile*>(hp);
  Cursor c{h->base + h->pos, h->base + h->size};
  while (c.p < c.end) {
    int64_t count = read_varlong(c);
    if (c.fail) { h->error = "truncated block header"; return -1; }
    if (count < 0) {  // would desync n_records from the column lengths
      h->error = "negative block record count";
      return -1;
    }
    int64_t bytes = read_varlong(c);
    if (c.fail || bytes < 0 || bytes > c.end - c.p) {
      h->error = "bad block byte size";
      return -1;
    }
    const uint8_t* block_end = c.p + bytes;
    for (int64_t i = 0; i < count; i++) {
      if (!decode_record(h, c)) {
        if (h->error.empty()) h->error = "truncated record";
        return -1;
      }
    }
    if (c.p != block_end) {
      h->error = "block size mismatch (codec not null?)";
      return -1;
    }
    if (c.p + 16 > c.end || std::memcmp(c.p, h->sync, 16) != 0) {
      h->error = "sync marker mismatch";
      return -1;
    }
    c.p += 16;
    h->n_records += count;
  }
  return h->n_records;
}

const char* gav_error(void* hp) {
  return static_cast<GavFile*>(hp)->error.c_str();
}

void gav_doubles(void* hp, int32_t slot, double* out) {
  auto& v = static_cast<GavFile*>(hp)->dbl[slot];
  std::memcpy(out, v.data(), v.size() * sizeof(double));
}

void gav_string_ids(void* hp, int32_t slot, int32_t* out) {
  auto& v = static_cast<GavFile*>(hp)->str[slot].idx;
  std::memcpy(out, v.data(), v.size() * sizeof(int32_t));
}

int64_t gav_string_vocab_size(void* hp, int32_t slot) {
  return static_cast<GavFile*>(hp)->str[slot].vocab.size();
}

int64_t gav_string_vocab_bytes(void* hp, int32_t slot) {
  int64_t total = 0;
  for (auto& s : static_cast<GavFile*>(hp)->str[slot].vocab) total += s.size();
  return total;
}

void gav_string_vocab(void* hp, int32_t slot, int32_t* lens, char* bytes) {
  for (auto& s : static_cast<GavFile*>(hp)->str[slot].vocab) {
    *lens++ = static_cast<int32_t>(s.size());
    std::memcpy(bytes, s.data(), s.size());
    bytes += s.size();
  }
}

int64_t gav_bag_entries(void* hp, int32_t slot) {
  return static_cast<GavFile*>(hp)->bags[slot].pairs.size();
}

void gav_bag_nnz(void* hp, int32_t slot, int32_t* out) {
  auto& v = static_cast<GavFile*>(hp)->bags[slot].nnz;
  std::memcpy(out, v.data(), v.size() * sizeof(int32_t));
}

void gav_bag_pairs(void* hp, int32_t slot, int32_t* out) {
  auto& v = static_cast<GavFile*>(hp)->bags[slot].pairs;
  std::memcpy(out, v.data(), v.size() * sizeof(int32_t));
}

void gav_bag_vals(void* hp, int32_t slot, float* out) {
  auto& v = static_cast<GavFile*>(hp)->bags[slot].vals;
  std::memcpy(out, v.data(), v.size() * sizeof(float));
}

int64_t gav_pair_vocab_size(void* hp, int32_t slot) {
  return static_cast<GavFile*>(hp)->bags[slot].vocab.names.size();
}

int64_t gav_pair_vocab_bytes(void* hp, int32_t slot) {
  auto& v = static_cast<GavFile*>(hp)->bags[slot].vocab;
  int64_t total = 0;
  for (auto& s : v.names) total += s.size();
  for (auto& s : v.terms) total += s.size();
  return total;
}

// lens: name_len, term_len per pair (2 * size); bytes: name then term, pair
// by pair, concatenated.
void gav_pair_vocab(void* hp, int32_t slot, int32_t* lens, char* bytes) {
  auto& v = static_cast<GavFile*>(hp)->bags[slot].vocab;
  for (size_t i = 0; i < v.names.size(); i++) {
    *lens++ = static_cast<int32_t>(v.names[i].size());
    *lens++ = static_cast<int32_t>(v.terms[i].size());
    std::memcpy(bytes, v.names[i].data(), v.names[i].size());
    bytes += v.names[i].size();
    std::memcpy(bytes, v.terms[i].data(), v.terms[i].size());
    bytes += v.terms[i].size();
  }
}

void gav_close(void* hp) {
  GavFile* h = static_cast<GavFile*>(hp);
  if (h->base) munmap(const_cast<uint8_t*>(h->base), h->size);
  if (h->fd >= 0) ::close(h->fd);
  delete h;
}

}  // extern "C"
