"""Lazy on-demand build of the native shared library.

Compiles ``src/*.cpp`` into ``_photon_native.so`` with g++ the first time a
native entry point is used (and whenever a source is newer than the built
library).  Failures are cached for the process so a missing toolchain costs
one attempt, not one per call.
"""

from __future__ import annotations

import ctypes
import glob
import os
import subprocess
import threading
from typing import Optional

_HERE = os.path.dirname(__file__)
_SRC_DIR = os.path.join(_HERE, "src")
_LIB_PATH = os.path.join(_HERE, "_photon_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_failed = False


def native_disabled() -> bool:
    return os.environ.get("PHOTON_TPU_NO_NATIVE", "") not in ("", "0")


def _needs_build(sources: list[str]) -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    return any(os.path.getmtime(s) > lib_mtime for s in sources)


def _compile(sources: list[str]) -> bool:
    # Compile to a process-unique temp path and os.replace() atomically:
    # concurrent first-use builds must never CDLL a half-written .so.
    tmp_path = f"{_LIB_PATH}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        "-o", tmp_path, *sources,
    ]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=300
        )
        if proc.returncode != 0 or not os.path.exists(tmp_path):
            return False
        os.replace(tmp_path, _LIB_PATH)
    except (OSError, subprocess.TimeoutExpired):
        return False
    finally:
        if os.path.exists(tmp_path):
            try:
                os.unlink(tmp_path)
            except OSError:
                pass
    return True


def _declare(lib: ctypes.CDLL) -> None:
    c = ctypes
    lib.svm_open.restype = c.c_void_p
    lib.svm_open.argtypes = [c.c_char_p]
    lib.svm_rows.restype = c.c_int64
    lib.svm_rows.argtypes = [c.c_void_p]
    lib.svm_total_nnz.restype = c.c_int64
    lib.svm_total_nnz.argtypes = [c.c_void_p]
    lib.svm_row_nnz.restype = None
    lib.svm_row_nnz.argtypes = [c.c_void_p, c.POINTER(c.c_int64)]
    lib.svm_parse.restype = c.c_int64
    lib.svm_parse.argtypes = [
        c.c_void_p, c.POINTER(c.c_int64), c.POINTER(c.c_float),
        c.POINTER(c.c_int32), c.POINTER(c.c_float), c.c_int,
    ]
    lib.svm_close.restype = None
    lib.svm_close.argtypes = [c.c_void_p]

    lib.ixs_build.restype = c.c_int
    lib.ixs_build.argtypes = [
        c.c_char_p, c.c_char_p, c.POINTER(c.c_int64),
        c.POINTER(c.c_int64), c.c_int64,
    ]
    lib.ixs_open.restype = c.c_void_p
    lib.ixs_open.argtypes = [c.c_char_p]
    lib.ixs_n_keys.restype = c.c_int64
    lib.ixs_n_keys.argtypes = [c.c_void_p]
    lib.ixs_get.restype = c.c_int64
    lib.ixs_get.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
    lib.ixs_key_at.restype = c.c_int64
    lib.ixs_key_at.argtypes = [c.c_void_p, c.c_int64, c.c_char_p, c.c_int64]
    lib.ixs_close.restype = None
    lib.ixs_close.argtypes = [c.c_void_p]

    lib.clos_edge_color.restype = c.c_int32
    lib.clos_edge_color.argtypes = [
        c.c_int64, c.c_int32, c.c_int32, c.POINTER(c.c_int32),
        c.POINTER(c.c_int32), c.POINTER(c.c_int32),
    ]


def get_lib() -> Optional[ctypes.CDLL]:
    """The native library, building it if needed; None when unavailable."""
    global _lib, _failed
    if native_disabled():
        return None
    if _lib is not None:
        return _lib
    if _failed:
        return None
    with _lock:
        if _lib is not None or _failed:
            return _lib
        sources = sorted(glob.glob(os.path.join(_SRC_DIR, "*.cpp")))
        if not sources:
            _failed = True
            return None
        if _needs_build(sources) and not _compile(sources):
            _failed = True
            return None
        lib = None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
            _declare(lib)
        except (OSError, AttributeError):
            # AttributeError: a stale prebuilt .so predating newly declared
            # symbols (mtime >= sources, so _needs_build skipped the
            # rebuild — e.g. archive extraction resets mtimes).  One forced
            # rebuild from the present sources before giving up; without it
            # a single stale artifact permanently demotes EVERY native
            # entry point (readers included) to the Python fallbacks.
            # dlclose the stale handle first: the loader caches by
            # pathname, so re-dlopening the same path would hand back the
            # old link map even after os.replace swapped the file.
            if lib is not None:
                try:
                    import _ctypes

                    _ctypes.dlclose(lib._handle)
                except Exception:  # noqa: BLE001 - best-effort unload
                    pass
                lib = None
            if not _compile(sources):
                _failed = True
                return None
            try:
                lib = ctypes.CDLL(_LIB_PATH)
                _declare(lib)
            except (OSError, AttributeError):
                _failed = True
                return None
        _lib = lib
    return _lib
