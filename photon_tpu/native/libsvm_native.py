"""ctypes binding for the native LIBSVM parser (src/libsvm_parser.cpp).

``parse_file`` returns the same (rows, labels, dim) triple as the pure
Python parser in :mod:`photon_tpu.data.libsvm` — per-row (ids, vals) arrays
are zero-copy views into one flat CSR allocation.
"""

from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

from photon_tpu.native.build import get_lib


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def parse_file(path: str, zero_based: bool = False) -> Optional[tuple]:
    """(rows, labels, dim) or None when the native path is unavailable.

    Raises ValueError on malformed input (matching the Python parser's
    failure behavior rather than silently falling back to it, which would
    parse the bad file a second time just to fail again).
    """
    lib = get_lib()
    if lib is None:
        return None
    handle = lib.svm_open(path.encode())
    if not handle:
        return None  # IO error/empty: let the Python path report it
    try:
        n = lib.svm_rows(handle)
        if n == 0:
            return [], np.zeros(0, np.float32), 0
        nnz = np.empty(n, np.int64)
        lib.svm_row_nnz(handle, _ptr(nnz, ctypes.c_int64))
        row_ptr = np.zeros(n + 1, np.int64)
        np.cumsum(nnz, out=row_ptr[1:])
        total = int(row_ptr[-1])
        labels = np.empty(n, np.float32)
        ids = np.empty(total, np.int32)
        vals = np.empty(total, np.float32)
        max_id = lib.svm_parse(
            handle,
            _ptr(row_ptr, ctypes.c_int64),
            _ptr(labels, ctypes.c_float),
            _ptr(ids, ctypes.c_int32),
            _ptr(vals, ctypes.c_float),
            1 if zero_based else 0,
        )
        if max_id == -2:
            raise ValueError(f"{path}: malformed LIBSVM input")
        rows = [
            (ids[row_ptr[i]: row_ptr[i + 1]], vals[row_ptr[i]: row_ptr[i + 1]])
            for i in range(n)
        ]
        return rows, labels, int(max_id) + 1
    finally:
        lib.svm_close(handle)
