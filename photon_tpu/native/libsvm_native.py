"""ctypes binding for the native LIBSVM parser (src/libsvm_parser.cpp).

``parse_file`` returns the same (rows, labels, dim) triple as the pure
Python parser in :mod:`photon_tpu.data.libsvm` — per-row (ids, vals) arrays
are zero-copy views into one flat CSR allocation.
"""

from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

from photon_tpu.native.build import get_lib


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def _open_indexed(path: str):
    """Shared prologue: open + line-index a file natively.

    Returns ``(lib, handle, n, nnz)`` — the caller owns ``svm_close`` —
    or None when the native library is unavailable or the open fails
    (IO error / empty file: the Python path reports those).  ``nnz`` is
    None for 0-row files.
    """
    lib = get_lib()
    if lib is None:
        return None
    handle = lib.svm_open(path.encode())
    if not handle:
        return None
    try:
        n = lib.svm_rows(handle)
        if n == 0:
            return lib, handle, 0, None
        nnz = np.empty(n, np.int64)
        lib.svm_row_nnz(handle, _ptr(nnz, ctypes.c_int64))
        return lib, handle, int(n), nnz
    except BaseException:
        # The caller only owns svm_close after a successful return; an
        # allocation failure here must not leak the mmap + fd.
        lib.svm_close(handle)
        raise


def scan_meta(path: str) -> Optional[tuple[int, int]]:
    """(row count, max nnz per row) via the native line indexer only — no
    value parsing or materialization.  The metadata pass of the streaming
    pipeline (data/streaming.LibsvmFileSource with a known feature dim);
    None when the native library is unavailable."""
    opened = _open_indexed(path)
    if opened is None:
        return None
    lib, handle, n, nnz = opened
    try:
        return (n, int(nnz.max())) if n else (0, 0)
    finally:
        lib.svm_close(handle)


def parse_file_csr(path: str, zero_based: bool = False) -> Optional[tuple]:
    """Flat-CSR parse: ``(labels, row_ptr, ids, vals, dim)`` — no per-row
    materialization.  The hot-path variant of :func:`parse_file` for
    consumers that pad/assemble vectorized (building n per-row numpy views
    costs more than the C++ parse itself at streaming scale); None when the
    native library is unavailable.  Raises ValueError on malformed input.
    """
    opened = _open_indexed(path)
    if opened is None:
        return None
    lib, handle, n, nnz = opened
    try:
        if n == 0:
            return (np.zeros(0, np.float32), np.zeros(1, np.int64),
                    np.zeros(0, np.int32), np.zeros(0, np.float32), 0)
        row_ptr = np.zeros(n + 1, np.int64)
        np.cumsum(nnz, out=row_ptr[1:])
        total = int(row_ptr[-1])
        labels = np.empty(n, np.float32)
        ids = np.empty(total, np.int32)
        vals = np.empty(total, np.float32)
        max_id = lib.svm_parse(
            handle,
            _ptr(row_ptr, ctypes.c_int64),
            _ptr(labels, ctypes.c_float),
            _ptr(ids, ctypes.c_int32),
            _ptr(vals, ctypes.c_float),
            1 if zero_based else 0,
        )
        if max_id == -2:
            raise ValueError(f"{path}: malformed LIBSVM input")
        return labels, row_ptr, ids, vals, int(max_id) + 1
    finally:
        lib.svm_close(handle)


def parse_file(path: str, zero_based: bool = False) -> Optional[tuple]:
    """(rows, labels, dim) or None when the native path is unavailable.

    Raises ValueError on malformed input (matching the Python parser's
    failure behavior rather than silently falling back to it, which would
    parse the bad file a second time just to fail again).
    """
    opened = _open_indexed(path)
    if opened is None:
        return None
    lib, handle, n, nnz = opened
    try:
        if n == 0:
            return [], np.zeros(0, np.float32), 0
        row_ptr = np.zeros(n + 1, np.int64)
        np.cumsum(nnz, out=row_ptr[1:])
        total = int(row_ptr[-1])
        labels = np.empty(n, np.float32)
        ids = np.empty(total, np.int32)
        vals = np.empty(total, np.float32)
        max_id = lib.svm_parse(
            handle,
            _ptr(row_ptr, ctypes.c_int64),
            _ptr(labels, ctypes.c_float),
            _ptr(ids, ctypes.c_int32),
            _ptr(vals, ctypes.c_float),
            1 if zero_based else 0,
        )
        if max_id == -2:
            raise ValueError(f"{path}: malformed LIBSVM input")
        rows = [
            (ids[row_ptr[i]: row_ptr[i + 1]], vals[row_ptr[i]: row_ptr[i + 1]])
            for i in range(n)
        ]
        return rows, labels, int(max_id) + 1
    finally:
        lib.svm_close(handle)
