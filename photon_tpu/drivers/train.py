"""Single-model GLM training driver (the reference's legacy ``Driver``).

End-to-end: read data → optional normalization → regularization-weight sweep
→ validate each model → select best → write models + metrics
(SURVEY.md §3.2).  Runs the fixed-effect distributed path when more than one
device is visible (mesh + psum), single-device otherwise — same optimizer
code either way.

Usage:
    python -m photon_tpu.drivers.train \\
        --input a1a.libsvm --task logistic_regression \\
        --optimizer lbfgs --reg-type l2 --reg-weights 0.1,1,10 \\
        --validation-input a1a.t --evaluators AUC,LOGISTIC_LOSS \\
        --output-dir /tmp/model --backend tpu
"""

from __future__ import annotations

import argparse
import json
import os
import time

import jax.numpy as jnp
import numpy as np

from photon_tpu.drivers import common


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "photon_tpu.drivers.train", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    common.add_common_args(p)
    common.add_data_args(p)
    p.add_argument("--task", default="logistic_regression",
                   choices=("logistic_regression", "linear_regression",
                            "poisson_regression", "smoothed_hinge_loss_linear_svm"))
    p.add_argument("--optimizer", default="lbfgs", choices=("lbfgs", "owlqn", "tron"))
    p.add_argument("--reg-type", default="l2",
                   choices=("none", "l1", "l2", "elastic_net"))
    p.add_argument("--reg-weights", default="1.0",
                   help="comma-separated sweep of regularization weights")
    p.add_argument("--elastic-net-alpha", type=float, default=0.5)
    p.add_argument("--max-iterations", type=int, default=100)
    p.add_argument("--tolerance", type=float, default=1e-7)
    p.add_argument("--normalization", default="none",
                   choices=("none", "scale_with_standard_deviation",
                            "scale_with_max_magnitude", "standardization"))
    p.add_argument("--evaluators", default=None,
                   help="comma-separated evaluator names; default per task")
    p.add_argument("--variance-computation", default="none",
                   choices=("none", "simple"))
    p.add_argument("--model-format", default="avro", choices=("avro", "json"))
    p.add_argument("--save-all-models", action="store_true",
                   help="write every sweep model, not just the best")
    return p


def run(args: argparse.Namespace) -> dict:
    common.select_backend(args.backend)
    # Imports after backend pinning (device init happens on first jax use).
    import jax

    from photon_tpu.core.normalization import NormalizationContext
    from photon_tpu.core.objective import GlmObjective, RegularizationContext
    from photon_tpu.core.optimizers import OptimizationStatesTracker, OptimizerConfig
    from photon_tpu.core.problem import GlmOptimizationProblem, ProblemConfig
    from photon_tpu.core.stats import BasicStatisticalSummary
    from photon_tpu.data.model_io import save_glm_model
    from photon_tpu.evaluation.evaluators import (
        MultiEvaluator,
        default_evaluators_for_task,
    )
    from photon_tpu.models.glm import Coefficients, model_for_task
    from photon_tpu.parallel import DistributedGlmObjective, shard_batch
    from photon_tpu.utils import PhotonLogger
    from photon_tpu.utils.logging import maybe_profile

    logger = PhotonLogger("photon_tpu.train", args.log_file)
    os.makedirs(args.output_dir, exist_ok=True)

    with logger.timed("load-data"):
        batch, dim, index_map = common.load_dataset(
            args.input, args.intercept, args.task
        )
        val_batch = common.load_validation(
            args.validation_input, dim, args.intercept, args.task
        )
        logger.info("train: %d examples, %d features", batch.num_examples, dim)

    if args.data_validation != "off":
        from photon_tpu.data.validation import apply_validation, validate_batch

        apply_validation(
            validate_batch(batch, args.task), args.data_validation, logger
        )

    norm = None
    if args.normalization != "none":
        with logger.timed("summarize"):
            summary = BasicStatisticalSummary.from_batch(batch, dim)
            norm = NormalizationContext.build(
                args.normalization, summary, intercept_id=index_map.intercept_id
            )

    mesh = common.maybe_mesh()
    if mesh is not None:
        logger.info("mesh: %d devices on axis 'data'", mesh.devices.size)
        batch = shard_batch(batch, mesh)

    if args.evaluators:
        evaluators = common.build_flat_evaluators(args.evaluators, "training")
    else:
        evaluators = MultiEvaluator(default_evaluators_for_task(args.task))

    lambdas = common.parse_weights_list(args.reg_weights)
    opt_config = OptimizerConfig(
        max_iterations=args.max_iterations, tolerance=args.tolerance
    )
    optimizer = args.optimizer
    if args.reg_type in ("l1", "elastic_net") and optimizer != "owlqn":
        logger.warning("reg-type %s requires owlqn; switching optimizer", args.reg_type)
        optimizer = "owlqn"

    sweep = []
    w_start = jnp.zeros(dim, jnp.float32)
    for lam in lambdas:
        reg = RegularizationContext(args.reg_type, lam, args.elastic_net_alpha)
        obj = GlmObjective.create(args.task, reg, normalization=norm)
        objective = obj if mesh is None else DistributedGlmObjective(obj, mesh)
        problem = GlmOptimizationProblem(
            objective,
            ProblemConfig(
                optimizer=optimizer,
                regularization=reg,
                optimizer_config=opt_config,
                variance_computation=args.variance_computation,
            ),
        )
        with logger.timed(f"train-lambda-{lam}"), maybe_profile(args.profile_dir):
            t0 = time.monotonic()
            coefficients, result = problem.run(batch, w_start)
            jax.block_until_ready(coefficients.means)
            wall = time.monotonic() - t0
        tracker = OptimizationStatesTracker(result, wall)
        logger.info("lambda=%g %s", lam, tracker.summary().splitlines()[0])

        # Store the model in the original feature space (variances too —
        # mixing original-space means with normalized-space variances would
        # mis-scale the GLMix posterior by factor^2 per coordinate).
        means = coefficients.means
        variances = coefficients.variances
        if norm is not None:
            means = norm.model_to_original_space(means)
            variances = norm.variances_to_original_space(variances)
        model = model_for_task(args.task, Coefficients(means, variances))

        metrics = {}
        if val_batch is not None:
            scores = common.scores_on(val_batch, model)
            metrics = evaluators.evaluate(
                scores, np.asarray(val_batch.label), np.asarray(val_batch.weight)
            )
            logger.info("lambda=%g validation %s", lam, metrics)
        sweep.append(
            {
                "lambda": lam,
                "model": model,
                "metrics": metrics,
                "iterations": tracker.iterations,
                "convergence_reason": tracker.convergence_reason,
                "wall_time_s": wall,
                "final_value": float(result.value),
            }
        )

    # Best-model selection by the primary evaluator (falls back to final
    # objective value when there is no validation set).
    primary = evaluators.primary
    if val_batch is not None:
        best = sweep[0]
        for entry in sweep[1:]:
            if primary.better_than(
                entry["metrics"][primary.name], best["metrics"][primary.name]
            ):
                best = entry
    else:
        best = min(sweep, key=lambda e: e["final_value"])

    with logger.timed("save-models"):
        index_map.save(os.path.join(args.output_dir, "feature_index.json"))
        ext = "avro" if args.model_format == "avro" else "json"
        save_glm_model(
            os.path.join(args.output_dir, f"best_model.{ext}"),
            best["model"], index_map, fmt=args.model_format,
        )
        if args.save_all_models:
            for entry in sweep:
                save_glm_model(
                    os.path.join(
                        args.output_dir, f"model_lambda_{entry['lambda']:g}.{ext}"
                    ),
                    entry["model"], index_map, fmt=args.model_format,
                )
        summary_payload = {
            "task": args.task,
            "optimizer": optimizer,
            "best_lambda": best["lambda"],
            "sweep": [
                {k: v for k, v in entry.items() if k != "model"}
                for entry in sweep
            ],
            "phase_times": logger.phase_times,
        }
        with open(os.path.join(args.output_dir, "training_summary.json"), "w") as f:
            json.dump(summary_payload, f, indent=1)
    logger.info("best lambda=%g -> %s/best_model.%s",
                best["lambda"], args.output_dir, ext)
    return summary_payload


def main(argv=None) -> None:
    run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
