"""Single-model GLM training driver (the reference's legacy ``Driver``).

End-to-end: read data → optional normalization → regularization-weight sweep
→ validate each model → select best → write models + metrics
(SURVEY.md §3.2).  Runs the fixed-effect distributed path when more than one
device is visible (mesh + psum), single-device otherwise — same optimizer
code either way.

Usage:
    python -m photon_tpu.drivers.train \\
        --input a1a.libsvm --task logistic_regression \\
        --optimizer lbfgs --reg-type l2 --reg-weights 0.1,1,10 \\
        --validation-input a1a.t --evaluators AUC,LOGISTIC_LOSS \\
        --output-dir /tmp/model --backend tpu
"""

from __future__ import annotations

import argparse
import os
import time

import jax.numpy as jnp
import numpy as np

from photon_tpu.drivers import common


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "photon_tpu.drivers.train", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    common.add_common_args(p)
    common.add_distributed_args(p)
    common.add_data_args(p)
    p.add_argument("--task", default="logistic_regression",
                   choices=("logistic_regression", "linear_regression",
                            "poisson_regression", "smoothed_hinge_loss_linear_svm"))
    p.add_argument("--optimizer", default="lbfgs", choices=("lbfgs", "owlqn", "tron"))
    p.add_argument("--reg-type", default="l2",
                   choices=("none", "l1", "l2", "elastic_net"))
    p.add_argument("--reg-weights", default="1.0",
                   help="comma-separated sweep of regularization weights")
    p.add_argument("--elastic-net-alpha", type=float, default=0.5)
    p.add_argument("--max-iterations", type=int, default=100)
    p.add_argument("--tolerance", type=float, default=1e-7)
    p.add_argument("--dtype", default="float32",
                   choices=("float32", "bfloat16"),
                   help="storage dtype for FEATURE VALUES (labels, weights, "
                   "coefficients, and all arithmetic stay float32); "
                   "bfloat16 halves the value stream the sparse hot loop "
                   "reads from HBM")
    p.add_argument("--normalization", default="none",
                   choices=("none", "scale_with_standard_deviation",
                            "scale_with_max_magnitude", "standardization"))
    p.add_argument("--evaluators", default=None,
                   help="comma-separated evaluator names; default per task")
    p.add_argument("--variance-computation", default="none",
                   choices=("none", "simple", "full"))
    p.add_argument("--model-format", default="avro", choices=("avro", "json"))
    p.add_argument("--sweep-warm-start", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="start each regularization weight's fit from the "
                   "previous weight's solution (the regularization-path "
                   "trick; the reference's warm-start option). "
                   "--no-sweep-warm-start makes every lambda start cold")
    p.add_argument("--save-all-models", action="store_true",
                   help="write every sweep model, not just the best")
    p.add_argument("--stream", action="store_true",
                   help="host-streamed training for data beyond device "
                   "memory: --input is a glob/dir of LIBSVM files, each "
                   "re-streamed per objective evaluation (lbfgs only)")
    p.add_argument("--feature-dim", type=int, default=None,
                   help="with --stream: known feature dimension (e.g. from "
                   "a feature-indexing run) — skips the full metadata "
                   "parse in favor of a cheap row/nnz scan")
    p.add_argument("--checkpoint-dir", default=None,
                   help="preemption-safe sweep checkpoints under this "
                   "directory (one lam-NNN chain per sweep weight; rank 0 "
                   "writes).  With --stream: the full mid-fit L-BFGS loop "
                   "state every --checkpoint-every iterations.  Resident "
                   "path: one completed snapshot per finished lambda, so "
                   "a killed sweep resumes without re-fitting finished "
                   "weights")
    p.add_argument("--checkpoint-every", type=int, default=1,
                   help="with --stream + --checkpoint-dir: snapshot every "
                   "N L-BFGS iterations (each iteration is >= one full "
                   "streamed pass, so the default checkpoints every "
                   "iteration).  The resident path checkpoints per "
                   "completed lambda and ignores this")
    p.add_argument("--checkpoint-async", default=None, choices=("on", "off"),
                   help="publish checkpoints from a background thread "
                   "(default on, or PHOTON_CHECKPOINT_ASYNC); 'off' "
                   "restores inline synchronous writes")
    p.add_argument("--checkpoint-max-staged-mb", type=float, default=None,
                   help="cap the async publisher's staged host copies: a "
                   "snapshot over this many MB publishes blocking instead "
                   "of holding a second snapshot-sized host allocation "
                   "(PHOTON_CHECKPOINT_MAX_STAGED_MB; default unbounded)")
    p.add_argument("--resume", default=None, choices=("auto", "latest"),
                   help="with --checkpoint-dir: restore the sweep from its "
                   "checkpoints — completed weights are rebuilt from their "
                   "final snapshots without re-fitting (streamed: without "
                   "streaming a pass; the interrupted streamed weight "
                   "continues mid-fit); 'latest' requires a published "
                   "checkpoint, 'auto' starts fresh when there is none")
    return p


def _run_streaming(args: argparse.Namespace, logger, session) -> dict:
    """Host-streamed lambda sweep (data beyond device memory; lbfgs)."""
    import glob as globmod

    import jax

    from photon_tpu.core.losses import BINARY_TASKS
    from photon_tpu.core.objective import GlmObjective, RegularizationContext
    from photon_tpu.core.optimizers import OptimizationStatesTracker, OptimizerConfig
    from photon_tpu.data.index_map import IndexMap, feature_key
    from photon_tpu.data.streaming import (
        LibsvmFileSource,
        StreamingObjective,
        shard_files_for_process,
        streaming_lbfgs,
    )
    from photon_tpu.evaluation.evaluators import (
        MultiEvaluator,
        default_evaluators_for_task,
    )
    from photon_tpu.models.glm import Coefficients, model_for_task

    os.makedirs(args.output_dir, exist_ok=True)
    if args.normalization != "none":
        raise ValueError("--stream does not support --normalization")
    if getattr(args, "dtype", "float32") != "float32":
        raise ValueError("--stream does not support --dtype yet")
    if args.optimizer != "lbfgs" or args.reg_type in ("l1", "elastic_net"):
        raise ValueError("--stream supports the lbfgs optimizer with l2/none "
                         "regularization")
    from photon_tpu.fault.checkpoint import StreamCheckpointer

    if os.path.isdir(args.input):
        files = sorted(
            os.path.join(args.input, f) for f in os.listdir(args.input)
            if not f.startswith((".", "_"))
        )
    else:
        files = sorted(globmod.glob(args.input)) or [args.input]
    with logger.timed("scan-metadata"):
        # Metadata over the GLOBAL list (all hosts must agree on dim);
        # each process then streams only its file shard.
        source = LibsvmFileSource(
            files, intercept=args.intercept,
            binary_labels=args.task in BINARY_TASKS,
            feature_dim=args.feature_dim,
            telemetry=session,  # io.retries from retried part reads
        ).with_files(shard_files_for_process(files))
    logger.info(
        "streaming %d of %d files, %d rows total, dim %d, nnz capacity %d",
        len(source.files), len(files), source.num_examples, source.dim,
        source.capacity,
    )
    # Multi-process: all ranks record metrics, only rank 0 writes artifacts.
    session.write = jax.process_index() == 0
    session.gauge("train.num_examples").set(source.num_examples)
    session.gauge("train.num_features").set(source.dim)
    session.gauge("train.stream_files").set(len(source.files))
    if args.data_validation != "off":
        # Streamed data must get the same validation as resident data
        # (ADVICE r1: the streaming path skipped it entirely): one extra
        # host pass over this process's chunks before training starts.
        from photon_tpu.data.libsvm import normalize_binary_labels, parse_libsvm
        from photon_tpu.data.validation import (
            DataValidationError,
            _feature_issues,
            apply_validation,
            validate_columns,
        )

        with logger.timed("validate-data"):
            # Host-side pass over the raw parses: no device round-trip for
            # data that is streamed precisely because it is large.  Files
            # validate on the host-IO pool; issues keep file order.  Each
            # in-progress file holds a full parse transiently, so cap the
            # concurrency below the general IO width.
            from photon_tpu.utils.io_pool import io_threads, map_ordered

            def _file_issues(fpath):
                from photon_tpu.data.libsvm import parse_csr_or_none

                csr = parse_csr_or_none(fpath)
                if csr is not None:  # flat values, no per-row views
                    labels, _, _, allv, _ = csr
                else:
                    data = parse_libsvm(fpath)
                    labels = data.labels
                    allv = (
                        np.concatenate([v for _, v in data.rows])
                        if data.rows else np.zeros(0, np.float32)
                    )
                if args.task in BINARY_TASKS:
                    labels = normalize_binary_labels(labels)
                out = list(validate_columns(labels, None, None, args.task))
                if allv.size:
                    out.extend(
                        _feature_issues(
                            allv.reshape(-1, 1), os.path.basename(fpath)
                        )
                    )
                return out

            issues = []
            for file_issues in map_ordered(
                _file_issues, source.files, workers=min(io_threads(), 4)
            ):
                issues.extend(file_issues)
            if jax.process_count() > 1:
                # Agreement step: every process must reach the same
                # pass/fail decision, else a bad shard on one host would
                # leave the clean hosts hanging in the first collective.
                from jax.experimental import multihost_utils

                import numpy as _np

                totals = multihost_utils.process_allgather(
                    _np.asarray([len(issues)], _np.int32)
                )
                remote = int(_np.sum(totals)) - len(issues)
                if remote > 0 and args.data_validation == "error":
                    raise DataValidationError(
                        f"data validation failed on another process "
                        f"({remote} issues elsewhere; local: {len(issues)})"
                    )
            apply_validation(issues, args.data_validation, logger)

    val_batch = common.load_validation(
        args.validation_input, source.dim, args.intercept, args.task
    )
    if args.evaluators:
        evaluators = common.build_flat_evaluators(args.evaluators, "training")
    else:
        evaluators = MultiEvaluator(default_evaluators_for_task(args.task))

    opt_config = OptimizerConfig(
        max_iterations=args.max_iterations, tolerance=args.tolerance
    )
    # Multi-process runs: each host streams its file shard; gradients sum
    # across hosts so every process optimizes the GLOBAL objective.
    all_reduce = None
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        def all_reduce(x):
            return multihost_utils.process_allgather(x).sum(axis=0)

    sweep = []
    w_start = jnp.zeros(source.dim, jnp.float32)
    for i, lam in enumerate(common.parse_weights_list(args.reg_weights)):
        reg = RegularizationContext(args.reg_type, lam, args.elastic_net_alpha)
        objective = StreamingObjective(
            GlmObjective.create(args.task, reg), source.chunk_iter_factory,
            all_reduce=all_reduce,
        )
        # Mid-fit checkpointing: one chain per sweep weight, published
        # through the shared (async-capable) checkpoint publisher.  The
        # fingerprint pins what makes a snapshot THIS fit's state — the
        # iteration budget is deliberately excluded (resuming with more
        # iterations continues the fit, same rule as descent checkpoints).
        checkpointer = resume_state = None
        fingerprint = {
            "kind": StreamCheckpointer.KIND,
            "task": args.task,
            "reg_type": args.reg_type,
            "lambda": lam,
            "alpha": args.elastic_net_alpha,
            "dim": int(source.dim),
            "num_examples": int(source.num_examples),
            "intercept": bool(args.intercept),
            "warm_start": bool(args.sweep_warm_start),
            # Optimizer state-shape/semantics: the snapshot's S/Y/rho ring
            # buffers are sized by history_length, and tolerance changes
            # what "converged" means — a resume across either must refuse
            # loudly, not continue with mismatched curvature state.
            "history_length": int(opt_config.history_length),
            "tolerance": float(opt_config.tolerance),
        }
        if args.checkpoint_dir:
            checkpointer = StreamCheckpointer(
                os.path.join(args.checkpoint_dir, f"lam-{i:03d}"),
                telemetry=session, logger=logger,
                async_publish=args.checkpoint_async,
                max_staged_mb=args.checkpoint_max_staged_mb,
            )
            if args.resume:
                # Per-weight resume is auto-style: weights the interrupted
                # run never reached have no chain and start fresh (the
                # 'latest' strictness was enforced up front).
                from photon_tpu.fault.checkpoint import require_fingerprint

                resume_state = require_fingerprint(
                    checkpointer.load("auto"), fingerprint,
                    f"lambda={lam:g}",
                )
        with logger.timed(f"train-lambda-{lam}"):
            t0 = time.monotonic()
            result = streaming_lbfgs(
                objective, w_start, opt_config,
                checkpointer=checkpointer,
                checkpoint_every=max(1, args.checkpoint_every),
                resume_state=resume_state,
                fingerprint=fingerprint,
            )
            jax.block_until_ready(result.w)
            wall = time.monotonic() - t0
        if args.sweep_warm_start:
            w_start = result.w
        tracker = OptimizationStatesTracker(result, wall)
        tracker.record_to(session.registry, optimizer="lbfgs", lam=f"{lam:g}")
        logger.info("lambda=%g %s", lam, tracker.summary().splitlines()[0])
        model = model_for_task(args.task, Coefficients(result.w))
        metrics = {}
        if val_batch is not None:
            scores = common.scores_on(val_batch, model)
            metrics = evaluators.evaluate(
                scores, np.asarray(val_batch.label), np.asarray(val_batch.weight)
            )
            logger.info("lambda=%g validation %s", lam, metrics)
        sweep.append({
            "lambda": lam, "model": model, "metrics": metrics,
            "iterations": tracker.iterations,
            "convergence_reason": tracker.convergence_reason,
            "wall_time_s": wall, "final_value": float(result.value),
            "states": tracker.states(),
        })

    index_map = IndexMap.build(
        [feature_key(f"f{i}") for i in range(source.feature_dim)],
        intercept=args.intercept,
    )
    if jax.process_index() != 0:
        # Every host trained the same global model; only rank 0 writes.
        return {"streaming": True, "rank": jax.process_index()}
    return common.select_and_save_sweep(
        sweep, evaluators, val_batch is not None, index_map, args, logger,
        extra_summary={"optimizer": "lbfgs", "streaming": True},
        telemetry=session,
    )


def run(args: argparse.Namespace) -> dict:
    distributed = common.maybe_init_distributed(args)
    if not distributed:
        common.select_backend(args.backend)
    from photon_tpu.utils import PhotonLogger

    logger = PhotonLogger("photon_tpu.train", args.log_file)
    with common.telemetry_run(
        args, "train", logger, preemptible=True
    ) as session:
        # Shared --resume strictness of BOTH data paths ('latest' means a
        # PUBLISHED checkpoint, not .tmp debris) — validated before any
        # data work.
        if args.resume and not args.checkpoint_dir:
            raise ValueError("--resume needs --checkpoint-dir")
        if args.resume == "latest":
            from photon_tpu.fault.checkpoint import has_published_checkpoint

            if not has_published_checkpoint(args.checkpoint_dir):
                raise ValueError(
                    f"--resume latest: no published checkpoint under "
                    f"{args.checkpoint_dir!r}"
                )
        if getattr(args, "stream", False):
            return _run_streaming(args, logger, session)
        if distributed:
            # The resident-data path has no work to split across processes —
            # every rank would redundantly load the full dataset and race on
            # the output files.  Multi-process GLM training is the streaming
            # path's job (per-process file shards + cross-process gradient
            # sum).
            raise ValueError(
                "--coordinator requires --stream for this driver (the "
                "resident-data path is single-process; use --stream for "
                "multi-process)"
            )
        return _run_resident(args, logger, session)


def _run_resident(args: argparse.Namespace, logger, session) -> dict:
    """Device-resident lambda sweep (the default path)."""
    # Imports after backend pinning (device init happens on first jax use).
    import jax

    from photon_tpu.core.normalization import NormalizationContext
    from photon_tpu.core.objective import GlmObjective, RegularizationContext
    from photon_tpu.core.optimizers import OptimizationStatesTracker, OptimizerConfig
    from photon_tpu.core.problem import GlmOptimizationProblem, ProblemConfig
    from photon_tpu.core.stats import BasicStatisticalSummary
    from photon_tpu.evaluation.evaluators import (
        MultiEvaluator,
        default_evaluators_for_task,
    )
    from photon_tpu.models.glm import Coefficients, model_for_task
    from photon_tpu.parallel import DistributedGlmObjective, shard_batch
    from photon_tpu.utils.logging import maybe_profile

    os.makedirs(args.output_dir, exist_ok=True)

    with logger.timed("load-data"):
        batch, dim, index_map = common.load_dataset(
            args.input, args.intercept, args.task,
            avro_field=args.avro_feature_field,
        )
        val_batch = common.load_validation(
            args.validation_input, dim, args.intercept, args.task,
            avro_field=args.avro_feature_field, index_map=index_map,
        )
        logger.info("train: %d examples, %d features", batch.num_examples, dim)
        # Logical row count, captured BEFORE any mesh padding below: the
        # resident checkpoint fingerprint must be mesh-shape independent.
        n_examples = batch.num_examples
        session.gauge("train.num_examples").set(batch.num_examples)
        session.gauge("train.num_features").set(dim)

    if args.data_validation != "off":
        from photon_tpu.data.validation import apply_validation, validate_batch

        apply_validation(
            validate_batch(batch, args.task), args.data_validation, logger
        )

    norm = None
    if args.normalization != "none":
        with logger.timed("summarize"):
            summary = BasicStatisticalSummary.from_batch(batch, dim)
            norm = NormalizationContext.build(
                args.normalization, summary, intercept_id=index_map.intercept_id
            )

    mesh = common.maybe_mesh()
    if mesh is not None:
        logger.info("mesh: %d devices on axis 'data'", mesh.devices.size)
        # Attaches the per-shard feature-major layout — and the per-shard
        # aligned/xchg layouts when the kernel selector could route to
        # them (gated inside shard_batch), so the fast kernels run under
        # the sharded objective too.
        batch = shard_batch(batch, mesh, aligned_dim=dim)
    else:
        from photon_tpu.data.batch import SparseBatch, attach_feature_major
        from photon_tpu.ops.sparse_grad_select import aligned_layout_wanted

        if isinstance(batch, SparseBatch) and batch.ids.ndim == 2:
            # Single-device: attach the pre-sorted layout so objectives take
            # the segment-sum gradient path (exact under normalization too).
            # The slab-aligned layout (Pallas kernel eligibility) is built
            # only when the selector could actually route to it.
            batch = attach_feature_major(
                batch,
                aligned_dim=dim
                if aligned_layout_wanted(int(batch.ids.size)) else None,
            )

    if args.dtype != "float32":
        from photon_tpu.data.batch import batch_astype

        # After normalization stats (summaries use full-precision values)
        # and after the feature-major attach (astype converts its vals too).
        batch = batch_astype(batch, args.dtype)
        logger.info("feature values stored as %s (f32 arithmetic)", args.dtype)

    if args.evaluators:
        evaluators = common.build_flat_evaluators(args.evaluators, "training")
    else:
        evaluators = MultiEvaluator(default_evaluators_for_task(args.task))

    lambdas = common.parse_weights_list(args.reg_weights)
    opt_config = OptimizerConfig(
        max_iterations=args.max_iterations, tolerance=args.tolerance
    )
    optimizer = args.optimizer
    if args.reg_type in ("l1", "elastic_net") and optimizer != "owlqn":
        logger.warning("reg-type %s requires owlqn; switching optimizer", args.reg_type)
        optimizer = "owlqn"

    # Minimal resident checkpoint/resume (ROADMAP known edge): one
    # COMPLETED snapshot per finished lambda, in the StreamCheckpointer's
    # state shape — a killed sweep resumes by rebuilding finished weights
    # from their snapshots instead of re-fitting them.  (Mid-fit
    # granularity stays a --stream feature: a resident fit is one jitted
    # optimizer run with no interior host loop to snapshot.)
    from photon_tpu.core.optimizers.base import OptimizerResult
    from photon_tpu.fault.checkpoint import (
        StreamCheckpointer,
        StreamState,
        require_fingerprint,
    )
    from photon_tpu.fault.preemption import (
        PreemptedError,
        preemption_requested,
        preemption_reason,
    )

    sweep = []
    w_start = jnp.zeros(dim, jnp.float32)
    for i, lam in enumerate(lambdas):
        # The resident path's preemption boundary: between lambdas (each
        # lambda is one jitted solve with no interior host loop).  Every
        # finished lambda is already checkpointed, so stopping here loses
        # nothing resumable.
        if preemption_requested():
            hint = (
                "resume with --resume auto" if args.checkpoint_dir
                else "no --checkpoint-dir — a restart begins from scratch"
            )
            raise PreemptedError(
                f"preempted ({preemption_reason()}) before lambda={lam:g}; "
                f"{hint}"
            )
        reg = RegularizationContext(args.reg_type, lam, args.elastic_net_alpha)
        # What makes a snapshot THIS lambda's completed fit.  Unlike the
        # streamed fingerprint, max_iterations IS pinned: only the final
        # state is snapshotted, so a raised budget cannot continue a
        # completed resident fit — it must refuse and re-fit.
        fingerprint = {
            "kind": StreamCheckpointer.KIND,
            "path": "resident",
            "task": args.task,
            "optimizer": optimizer,
            "reg_type": args.reg_type,
            "lambda": lam,
            "alpha": args.elastic_net_alpha,
            "dim": int(dim),
            "num_examples": int(n_examples),
            "intercept": bool(args.intercept),
            "normalization": args.normalization,
            "dtype": args.dtype,
            "variance": args.variance_computation,
            "warm_start": bool(args.sweep_warm_start),
            "max_iterations": int(opt_config.max_iterations),
            "tolerance": float(opt_config.tolerance),
        }
        checkpointer = resume_state = None
        if args.checkpoint_dir:
            checkpointer = StreamCheckpointer(
                os.path.join(args.checkpoint_dir, f"lam-{i:03d}"),
                telemetry=session, logger=logger,
                async_publish=args.checkpoint_async,
                max_staged_mb=args.checkpoint_max_staged_mb,
            )
            if args.resume:
                resume_state = require_fingerprint(
                    checkpointer.load("auto"), fingerprint,
                    f"lambda={lam:g}",
                )
        if resume_state is not None and resume_state.completed:
            # Finished weight: rebuild model + convergence record from the
            # snapshot, zero solves.  The solver-space iterate (w_opt)
            # restores the warm-start chain exactly, so later un-resumed
            # lambdas fit from the same start the uninterrupted sweep used.
            arrays_ = resume_state.arrays
            result = OptimizerResult(
                w=jnp.asarray(arrays_["w_opt"]),
                value=jnp.asarray(float(resume_state.scalars["f"])),
                grad_norm=jnp.asarray(float(resume_state.scalars["gnorm"])),
                iterations=jnp.asarray(resume_state.iteration, jnp.int32),
                converged=jnp.asarray(
                    bool(resume_state.scalars.get("converged", False))
                ),
                reason=jnp.asarray(int(resume_state.reason), jnp.int32),
                history_value=jnp.asarray(arrays_["hv"]),
                history_grad_norm=jnp.asarray(arrays_["hg"]),
                history_valid=jnp.asarray(arrays_["hvalid"]),
            )
            wall = 0.0
            means = jnp.asarray(arrays_["means"])
            variances = (
                jnp.asarray(arrays_["variances"])
                if "variances" in arrays_ else None
            )
            if args.sweep_warm_start:
                w_start = result.w
            session.counter("train.lambdas_resumed").inc()
            logger.info(
                "lambda=%g restored from completed checkpoint (no refit)",
                lam,
            )
        else:
            obj = GlmObjective.create(args.task, reg, normalization=norm)
            objective = (
                obj if mesh is None else DistributedGlmObjective(obj, mesh)
            )
            problem = GlmOptimizationProblem(
                objective,
                ProblemConfig(
                    optimizer=optimizer,
                    regularization=reg,
                    optimizer_config=opt_config,
                    variance_computation=args.variance_computation,
                ),
            )
            with logger.timed(f"train-lambda-{lam}"), \
                    maybe_profile(args.profile_dir):
                t0 = time.monotonic()
                coefficients, result = problem.run(batch, w_start)
                jax.block_until_ready(coefficients.means)
                wall = time.monotonic() - t0
            if args.sweep_warm_start:
                # Next lambda starts from this optimum (normalized space —
                # the original-space conversion below works on copies).
                w_start = coefficients.means
            # Store the model in the original feature space (variances too
            # — mixing original-space means with normalized-space variances
            # would mis-scale the GLMix posterior by factor^2/coordinate).
            means = coefficients.means
            variances = coefficients.variances
            if norm is not None:
                means = norm.model_to_original_space(means)
                variances = norm.variances_to_original_space(variances)
            if checkpointer is not None:
                arrays_ = {
                    # Solver-space iterate (the warm-start chain) AND the
                    # original-space model are both snapshotted; history
                    # buffers make the convergence trace restorable.
                    "w_opt": coefficients.means,
                    "means": means,
                    "hv": result.history_value,
                    "hg": result.history_grad_norm,
                    "hvalid": result.history_valid,
                }
                if variances is not None:
                    arrays_["variances"] = variances
                checkpointer.save(StreamState(
                    iteration=int(result.iterations),
                    arrays=arrays_,
                    scalars={
                        "f": float(result.value),
                        "gnorm": float(result.grad_norm),
                        "converged": bool(result.converged),
                    },
                    completed=True,
                    reason=int(result.reason),
                    fingerprint=fingerprint,
                ))
                checkpointer.drain()
        tracker = OptimizationStatesTracker(result, wall)
        tracker.record_to(session.registry, optimizer=optimizer, lam=f"{lam:g}")
        logger.info("lambda=%g %s", lam, tracker.summary().splitlines()[0])
        model = model_for_task(args.task, Coefficients(means, variances))

        metrics = {}
        if val_batch is not None:
            scores = common.scores_on(val_batch, model)
            metrics = evaluators.evaluate(
                scores, np.asarray(val_batch.label), np.asarray(val_batch.weight)
            )
            logger.info("lambda=%g validation %s", lam, metrics)
        sweep.append(
            {
                "lambda": lam,
                "model": model,
                "metrics": metrics,
                "iterations": tracker.iterations,
                "convergence_reason": tracker.convergence_reason,
                "wall_time_s": wall,
                "final_value": float(result.value),
                "states": tracker.states(),
            }
        )

    return common.select_and_save_sweep(
        sweep, evaluators, val_batch is not None, index_map, args, logger,
        extra_summary={"optimizer": optimizer}, telemetry=session,
    )


def main(argv=None) -> None:
    # PreemptedError -> exit 75 (EX_TEMPFAIL): a preempted run is a clean,
    # resumable stop, not a crash.
    common.run_cli(run, build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
