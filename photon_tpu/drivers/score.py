"""GLM scoring driver: load a saved model, score a dataset, write scores.

Scoring half of the reference's legacy driver / ``GameScoringDriver``'s GLM
path (SURVEY.md §3.3): read model (name/term-keyed) → join onto the current
index map → score → optional metrics → write scores + metrics JSON.
"""

from __future__ import annotations

import argparse
import json
import os

import numpy as np

from photon_tpu.drivers import common


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("photon_tpu.drivers.score", description=__doc__)
    common.add_common_args(p)
    common.add_data_args(p)
    p.add_argument("--model", required=True, help="saved model file (avro/json)")
    p.add_argument("--index-map", default=None,
                   help="feature index map JSON written at training time; "
                   "defaults to feature_index.json next to the model")
    p.add_argument("--evaluators", default=None)
    p.add_argument("--predict-mean", action="store_true",
                   help="write mean predictions (sigmoid/exp link) instead of "
                   "raw scores")
    p.add_argument("--stream", action="store_true",
                   help="score part files (LIBSVM or Avro) one at a time, "
                   "dropping each chunk's features after scoring — for "
                   "scoring sets beyond host memory")
    return p


def run(args: argparse.Namespace) -> dict:
    common.select_backend(args.backend)
    from photon_tpu.utils import PhotonLogger

    logger = PhotonLogger("photon_tpu.score", args.log_file)
    with common.telemetry_run(args, "score", logger) as session:
        return _run(args, logger, session)


def _run(args: argparse.Namespace, logger, session) -> dict:
    from photon_tpu.data.index_map import IndexMap
    from photon_tpu.data.model_io import load_glm_model

    os.makedirs(args.output_dir, exist_ok=True)

    imap_path = args.index_map or os.path.join(
        os.path.dirname(args.model), "feature_index.json"
    )
    index_map = IndexMap.load(imap_path)
    model = load_glm_model(args.model, index_map)
    logger.info("model: %s dim=%d", model.task_type, model.coefficients.dim)

    # Whether the model has an intercept is recorded in the index map, not
    # the CLI flag — trusting the flag would shift every feature id when the
    # model was trained with --no-intercept.
    intercept = index_map.intercept_id is not None
    if intercept != args.intercept:
        logger.warning(
            "index map says intercept=%s; overriding --intercept flag", intercept
        )

    evaluators = (
        common.build_flat_evaluators(args.evaluators, "scoring")
        if args.evaluators else None
    )

    def load_chunk(spec: str):
        # Pad to the model's dimension: scoring files whose max feature id is
        # below the training dim are valid (load_validation handles this).
        return common.load_validation(
            spec, model.coefficients.dim, intercept,
            task=model.task_type,
            avro_field=getattr(args, "avro_feature_field", "features"),
            index_map=index_map,
        )

    def score_chunk(batch):
        raw = np.asarray(model.compute_score(batch))
        if args.predict_mean and model.task_type == "poisson_regression":
            # f32 predicted rates saturate at e^30 (the f64 reference computes
            # exp to ~e^709); flag affected rows so parity comparisons against
            # reference scores are explainable (ADVICE r3).
            from photon_tpu.core.losses import _POISSON_MAX_EXPONENT

            n_capped = int((raw > _POISSON_MAX_EXPONENT).sum())
            if n_capped:
                logger.info(
                    "%d scoring margins exceed the Poisson exp cap (%g); "
                    "their predicted means are clamped to e^cap",
                    n_capped, _POISSON_MAX_EXPONENT,
                )
        out = np.asarray(model.loss.mean(raw)) if args.predict_mean else raw
        return raw, out

    scores_path = os.path.join(args.output_dir, "scores.txt")
    if args.stream:
        # File-at-a-time: features dropped per chunk; only (score, label,
        # weight) survive when evaluators need a final pass (the scoring
        # analog of train --stream; SURVEY.md §7 '1B-row ingestion').
        raw_chunks, label_chunks, weight_chunks = [], [], []

        def on_chunk(batch, raw):
            if evaluators is not None:
                raw_chunks.append(raw)
                label_chunks.append(np.asarray(batch.label))
                weight_chunks.append(np.asarray(batch.weight))

        n = common.stream_score_parts(
            args.input, load_chunk,
            lambda b: (*score_chunk(b), b.num_examples),
            scores_path, logger, on_chunk, telemetry=session,
        )
        raw_scores = labels = weights = None
        if evaluators is not None:
            raw_scores = np.concatenate(raw_chunks)
            labels = np.concatenate(label_chunks)
            weights = np.concatenate(weight_chunks)
    else:
        with logger.timed("load-data"):
            batch = load_chunk(args.input)
        with logger.timed("score"):
            raw_scores, scores = score_chunk(batch)
        np.savetxt(scores_path, scores, fmt="%.8g")
        n = int(scores.shape[0])
        labels = np.asarray(batch.label)
        weights = np.asarray(batch.weight)

    metrics = {}
    if evaluators is not None:
        with logger.timed("evaluate"):
            metrics = evaluators.evaluate(raw_scores, labels, weights)
        logger.info("metrics %s", metrics)
        with open(os.path.join(args.output_dir, "metrics.json"), "w") as f:
            json.dump(metrics, f, indent=1)
    session.gauge("score.num_scored").set(n)
    for name, value in metrics.items():
        session.gauge("score.metric", metric=name).set(value)
    return {"num_scored": n, "metrics": metrics, "streamed": bool(args.stream)}


def main(argv=None) -> None:
    run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
