"""Online GAME scoring service driver (fleet serving loop).

Loads a saved GAME model ONCE (shared model artifact), builds ``--replicas``
scorer replicas — each owning device-resident serving tables — behind the
deadline-aware fleet router, pre-compiles every replica's bucket ladder,
then drives a seeded traffic stream through the service with closed-loop
clients.  ``--traffic powerlaw`` (default) generates requests through the
fleet traffic generator — power-law entity popularity, optional cold-start
storm segment — while ``--traffic geometric`` keeps the PR 9 seeded
geometric row-window stream for bench continuity.  ``--transport tcp``
serves over the real socket ingest (loopback; clients are
``ScoringClient`` connections) instead of in-process submission, and
``--deadline-ms`` arms admission control (requests whose queue-wait
projection blows the budget are shed and counted, never queued).

Scores land in ``<output-dir>/scores.txt`` in request order (admitted
requests only); the telemetry run report carries the full ``serving.*``
block including the "Serving fleet" section (per-replica QPS/depth, shed
breakdown, deadline hit rate).

    python -m photon_tpu.drivers.serve_game \\
        --model out/best_model --input test.avro \\
        --feature-bags global=features,per_user=userFeatures \\
        --id-columns userId \\
        --requests 500 --clients 8 --replicas 2 --transport tcp \\
        --deadline-ms 25 --max-batch 128 --max-delay-ms 2 \\
        --output-dir served
"""

from __future__ import annotations

import argparse
import json
import os

import numpy as np

from photon_tpu.drivers import common
from photon_tpu.drivers.train_game import _load_game_data


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "photon_tpu.drivers.serve_game", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    common.add_common_args(p)
    p.add_argument("--model", required=True, help="GAME model directory")
    p.add_argument("--input", required=True,
                   help="request feature source: Avro file/dir/glob or "
                   "synthetic-game spec (see train_game); requests are row "
                   "sets cut from it")
    p.add_argument("--feature-bags", default=None)
    p.add_argument("--id-columns", default=None)
    p.add_argument("--requests", type=int, default=256,
                   help="number of requests to serve")
    p.add_argument("--request-rows-mean", type=float, default=8.0,
                   help="mean rows per request (geometric long-tail, "
                   "clipped to [1, --max-batch])")
    p.add_argument("--clients", type=int, default=4,
                   help="closed-loop client threads (tcp: one connection "
                   "each)")
    p.add_argument("--replicas", type=int, default=1,
                   help="scorer replicas behind the fleet router (each "
                   "owns its device-resident tables)")
    p.add_argument("--traffic", choices=("powerlaw", "geometric"),
                   default="powerlaw",
                   help="request stream: power-law entity popularity via "
                   "the fleet traffic generator (default), or the PR 9 "
                   "seeded geometric row windows (bench continuity)")
    p.add_argument("--popularity-alpha", type=float, default=1.1,
                   help="power-law popularity exponent (powerlaw traffic)")
    p.add_argument("--storm-frac", type=float, default=0.0,
                   help="fraction of requests in a cold-start storm "
                   "segment (unknown entities; powerlaw traffic)")
    p.add_argument("--transport", choices=("inproc", "tcp"),
                   default="inproc",
                   help="inproc: submit straight to the router; tcp: "
                   "serve over the loopback socket ingest")
    p.add_argument("--replica-backend", choices=("thread", "subprocess"),
                   default="thread",
                   help="replica runtime: threads in this process, or one "
                   "child process per replica (own Python/jax runtime, "
                   "frame protocol over loopback, devices dealt per child)")
    p.add_argument("--supervise", action="store_true",
                   help="attach the self-healing supervisor: health probes "
                   "(ping + known-answer score vs the host oracle), "
                   "crash/hang detection, backed-off resurrection with "
                   "canary-gated rejoin, flap quarantine")
    p.add_argument("--probe-interval-ms", type=float, default=500.0,
                   help="supervisor health-probe interval")
    p.add_argument("--deadline-ms", type=float, default=0.0,
                   help="per-request deadline budget; 0 disables "
                   "admission shedding")
    p.add_argument("--max-batch", type=int, default=128,
                   help="bucket-ladder cap / batcher coalescing cap (rows)")
    p.add_argument("--max-delay-ms", type=float, default=2.0,
                   help="batcher window: max time the first queued request "
                   "waits for coalescing partners")
    p.add_argument("--seed", type=int, default=0,
                   help="traffic stream seed")
    p.add_argument("--table-dtype", choices=("f32", "bf16", "int8"),
                   default="f32",
                   help="storage dtype for the device-resident serving "
                   "tables (ISSUE 17): bf16 halves table bytes, int8 "
                   "quarters them (per-row absmax scale row); gathers "
                   "decode on device and ALL accumulation stays f32")
    p.add_argument("--models", type=int, default=1,
                   help="tenant models hosted per replica (ISSUE 18 "
                   "multi-model arena): N tenants m0..m{N-1} of the saved "
                   "model share ONE gather-table allocation and ONE "
                   "compiled bucket ladder; traffic is split across them "
                   "by seeded hash-of-user arms unless --splits overrides")
    p.add_argument("--splits", default=None,
                   help="traffic split spec 'm0=0.7,m1=0.3' (weights "
                   "normalize): each request's user hashes to an arm, the "
                   "arm is the tenant model id it scores against")
    p.add_argument("--tenant-queue-rows", type=int, default=0,
                   help="per-tenant admission budget (queued rows cap per "
                   "model id); 0 disables tenant isolation shedding")
    return p


def request_sizes(n_requests: int, mean: float, cap: int,
                  seed: int) -> np.ndarray:
    """Seeded long-tailed request-size stream (geometric, clipped to
    [1, cap]) — shared by ``--traffic geometric``, the traffic generator,
    and ``bench.py --mode serving`` so the measured arrival pattern is the
    served one."""
    from photon_tpu.serving.traffic import geometric_sizes

    return geometric_sizes(n_requests, mean, cap, np.random.default_rng(seed))


def _publish_text(output_dir: str, name: str, write_fn, session,
                  logger) -> None:
    """Atomic, retried artifact publish (the score_game convention, PR 7):
    each attempt writes a fresh temp file and renames it into place, so a
    crash or a stall-escalated abandoned writer can never leave a torn
    artifact — readers see the previous complete file or the new one."""
    import tempfile

    from photon_tpu.fault.injection import fault_point
    from photon_tpu.fault.retry import retry_call

    def attempt():
        fault_point("io:write", path=name)
        fd, tmp = tempfile.mkstemp(
            prefix=f".{name}-", suffix=".tmp", dir=output_dir
        )
        try:
            with os.fdopen(fd, "w") as f:
                write_fn(f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(output_dir, name))
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    retry_call(attempt, site="serve:write", telemetry=session, logger=logger)


def run(args: argparse.Namespace) -> dict:
    common.select_backend(args.backend)
    from photon_tpu.utils import PhotonLogger

    logger = PhotonLogger("photon_tpu.serve_game", args.log_file)
    with common.telemetry_run(args, "serve_game", logger) as session:
        return _run(args, logger, session)


def _run(args: argparse.Namespace, logger, session) -> dict:
    from photon_tpu.fault.retry import retry_call
    from photon_tpu.game.model_io import load_game_model
    from photon_tpu.serving import (
        AdmissionPolicy,
        ScoringClient,
        ServingFleet,
        TrafficSpec,
        generate_traffic,
        request_spec_for_dataset,
        run_closed_loop_outcomes,
    )

    os.makedirs(args.output_dir, exist_ok=True)

    with logger.timed("load-model"):
        model, index_maps = retry_call(
            lambda: load_game_model(args.model),
            site="model:load", telemetry=session, logger=logger,
        )
        logger.info("model: %s, coordinates %s", model.task_type,
                    list(model.coordinates))

    with logger.timed("load-data"):
        data, _ = _load_game_data(
            args.input, args, index_maps=index_maps, telemetry=session
        )
        logger.info("request source: %d rows", data.num_examples)

    deadline_s = (
        args.deadline_ms / 1000.0 if args.deadline_ms > 0 else None
    )
    # Multi-model arena (ISSUE 18): N tenants of the saved model share one
    # arena allocation + one compiled ladder per replica; traffic routes by
    # seeded split arms (arm id == tenant model id).
    models = (
        {f"m{i}": model for i in range(args.models)}
        if args.models > 1 else None
    )
    splits = None
    if args.splits:
        splits = {}
        for part in args.splits.split(","):
            arm, _, weight = part.partition("=")
            splits[arm.strip()] = float(weight or 1.0)
    elif models:
        splits = {mid: 1.0 / len(models) for mid in models}
    with logger.timed("build-fleet"):
        fleet = ServingFleet(
            model,
            replicas=args.replicas,
            backend=args.replica_backend,
            request_spec=request_spec_for_dataset(model, data),
            max_batch=args.max_batch,
            max_delay_s=args.max_delay_ms / 1000.0,
            telemetry=session,
            admission=AdmissionPolicy(
                default_deadline_s=deadline_s,
                tenant_queue_rows=args.tenant_queue_rows or None,
            ),
            table_dtype=args.table_dtype,
            models=models,
        ).warmup()
        if args.supervise:
            from photon_tpu.serving import SupervisorPolicy

            fleet.supervise(
                SupervisorPolicy(
                    probe_interval_s=args.probe_interval_ms / 1000.0
                ),
                logger=logger,
            )
        logger.info("fleet warm: %d %s replicas, %d programs compiled%s",
                    args.replicas, args.replica_backend, fleet.compilations,
                    ", supervised" if args.supervise else "")

    spec = TrafficSpec(
        requests=args.requests,
        mean_rows=args.request_rows_mean,
        max_rows=args.max_batch,
        popularity=args.traffic,
        alpha=args.popularity_alpha,
        storm_frac=args.storm_frac if args.traffic == "powerlaw" else 0.0,
        deadline_ms=args.deadline_ms if args.deadline_ms > 0 else None,
        seed=args.seed,
        splits=splits,
    )
    traffic = generate_traffic(data, model, spec)

    server = fleet.serve() if args.transport == "tcp" else None
    clients: list = []

    def factory(tid: int):
        if server is None:
            return lambda item: fleet.score(
                item.request, deadline_s=item.deadline_s
            )
        client = ScoringClient(server.address, telemetry=session)
        clients.append(client)
        return lambda item: client.score(
            item.request, deadline_s=item.deadline_s
        )

    try:
        with logger.timed("serve"):
            outcomes, wall = run_closed_loop_outcomes(
                factory, traffic.items, clients=args.clients
            )
    finally:
        for client in clients:
            client.close()
        fleet.close()

    ok = [o for o in outcomes if o.status == "ok"]
    shed = [o for o in outcomes if o.status == "shed"]
    errors = [o for o in outcomes if o.status == "error"]
    if errors:
        raise RuntimeError(
            f"{len(errors)} request(s) failed; first: {errors[0].reason}"
        )

    rows = int(sum(o.item.request.num_rows for o in ok))
    qps = len(ok) / wall if wall > 0 else 0.0
    lat_ms = np.sort(np.asarray(
        [o.latency_s for o in ok], np.float64
    )) * 1e3 if ok else np.zeros(1)
    p50 = float(np.percentile(lat_ms, 50))
    p99 = float(np.percentile(lat_ms, 99))
    session.gauge("serving.qps").set(qps)
    session.gauge("serving.rows_per_second").set(rows / wall if wall else 0.0)

    _publish_text(
        args.output_dir, "scores.txt",
        lambda f: np.savetxt(
            f,
            np.concatenate([o.scores for o in ok])
            if ok else np.zeros(0, np.float32),
            fmt="%.8g",
        ),
        session, logger,
    )

    def _counter(name):
        return sum(
            m["value"]
            for m in session.registry.snapshot().get("counters", [])
            if m["name"] == name
        ) if session.enabled else 0

    cold = _counter("serving.cold_entities")
    summary = {
        "requests": len(outcomes),
        "served": len(ok),
        "shed": len(shed),
        "shed_fraction": round(len(shed) / len(outcomes), 4)
        if outcomes else 0.0,
        "rows": rows,
        "wall_s": round(wall, 4),
        "qps": round(qps, 2),
        "rows_per_sec": round(rows / wall, 1) if wall else 0.0,
        "latency_p50_ms": round(p50, 3),
        "latency_p99_ms": round(p99, 3),
        "cold_entities": int(cold),
        "compiled_programs": fleet.compilations,
        "replicas": args.replicas,
        "replica_backend": args.replica_backend,
        "supervised": bool(args.supervise),
        "replica_deaths": int(_counter("serving.replica_deaths")),
        "resurrections": int(_counter("serving.replica_resurrections")),
        "quarantined": int(_counter("serving.replica_quarantined")),
        "transport": args.transport,
        "traffic": args.traffic,
        "deadline_ms": args.deadline_ms,
        "table_dtype": args.table_dtype,
        "models": args.models,
        "splits": splits,
        "tenant_shed": sum(
            1 for o in shed if "tenant_budget" in str(o.reason or "")
        ),
    }
    _publish_text(
        args.output_dir, "serving_summary.json",
        lambda f: json.dump(summary, f, indent=1),
        session, logger,
    )
    logger.info(
        "served %d/%d requests (%d rows, %d shed) at %.1f req/s; latency "
        "p50 %.2f ms p99 %.2f ms; %d cold entities",
        summary["served"], summary["requests"], rows, summary["shed"],
        qps, p50, p99, summary["cold_entities"],
    )
    return summary


def main(argv=None) -> None:
    run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
