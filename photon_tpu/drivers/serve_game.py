"""Online GAME scoring service driver (in-process request loop).

Loads a saved GAME model ONCE into device-resident serving tables
(photon_tpu.serving.GameScorer), pre-compiles the bucket ladder, then
drives a closed-loop request stream through the async batcher — the
serving-shape workload (``--clients`` concurrent users, request sizes drawn
from a seeded long-tailed distribution) run in-process so the service layer
is exercised and measured without a network stack.  Scores land in
``<output-dir>/scores.txt`` in request order; the telemetry run report
carries the full ``serving.*`` block (request/batch counters, bucket
occupancy, padded fraction, latency distributions, cold entities,
host-syncs-per-batch).

    python -m photon_tpu.drivers.serve_game \\
        --model out/best_model --input test.avro \\
        --feature-bags global=features,per_user=userFeatures \\
        --id-columns userId \\
        --requests 500 --clients 8 --max-batch 128 --max-delay-ms 2 \\
        --output-dir served
"""

from __future__ import annotations

import argparse
import json
import os

import numpy as np

from photon_tpu.drivers import common
from photon_tpu.drivers.train_game import _load_game_data


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "photon_tpu.drivers.serve_game", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    common.add_common_args(p)
    p.add_argument("--model", required=True, help="GAME model directory")
    p.add_argument("--input", required=True,
                   help="request feature source: Avro file/dir/glob or "
                   "synthetic-game spec (see train_game); requests are row "
                   "windows cut from it")
    p.add_argument("--feature-bags", default=None)
    p.add_argument("--id-columns", default=None)
    p.add_argument("--requests", type=int, default=256,
                   help="number of requests to serve")
    p.add_argument("--request-rows-mean", type=float, default=8.0,
                   help="mean rows per request (geometric long-tail, "
                   "clipped to [1, --max-batch])")
    p.add_argument("--clients", type=int, default=4,
                   help="closed-loop client threads")
    p.add_argument("--max-batch", type=int, default=128,
                   help="bucket-ladder cap / batcher coalescing cap (rows)")
    p.add_argument("--max-delay-ms", type=float, default=2.0,
                   help="batcher window: max time the first queued request "
                   "waits for coalescing partners")
    p.add_argument("--seed", type=int, default=0,
                   help="request-size stream seed")
    return p


def request_sizes(n_requests: int, mean: float, cap: int,
                  seed: int) -> np.ndarray:
    """Seeded long-tailed request-size stream (geometric, clipped to
    [1, cap]) — shared by this driver and ``bench.py --mode serving`` so
    the measured arrival pattern is the served one."""
    rng = np.random.default_rng(seed)
    p = min(1.0, max(1.0 / max(mean, 1.0), 1e-6))
    return np.clip(rng.geometric(p, size=n_requests), 1, max(1, cap))


def _publish_text(output_dir: str, name: str, write_fn, session,
                  logger) -> None:
    """Atomic, retried artifact publish (the score_game convention, PR 7):
    each attempt writes a fresh temp file and renames it into place, so a
    crash or a stall-escalated abandoned writer can never leave a torn
    artifact — readers see the previous complete file or the new one."""
    import tempfile

    from photon_tpu.fault.injection import fault_point
    from photon_tpu.fault.retry import retry_call

    def attempt():
        fault_point("io:write", path=name)
        fd, tmp = tempfile.mkstemp(
            prefix=f".{name}-", suffix=".tmp", dir=output_dir
        )
        try:
            with os.fdopen(fd, "w") as f:
                write_fn(f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(output_dir, name))
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    retry_call(attempt, site="serve:write", telemetry=session, logger=logger)


def run(args: argparse.Namespace) -> dict:
    common.select_backend(args.backend)
    from photon_tpu.utils import PhotonLogger

    logger = PhotonLogger("photon_tpu.serve_game", args.log_file)
    with common.telemetry_run(args, "serve_game", logger) as session:
        return _run(args, logger, session)


def _run(args: argparse.Namespace, logger, session) -> dict:
    from photon_tpu.fault.retry import retry_call
    from photon_tpu.game.model_io import load_game_model
    from photon_tpu.serving import (
        GameScorer,
        RequestBatcher,
        build_requests,
        request_spec_for_dataset,
        run_closed_loop,
    )

    os.makedirs(args.output_dir, exist_ok=True)

    with logger.timed("load-model"):
        model, index_maps = retry_call(
            lambda: load_game_model(args.model),
            site="model:load", telemetry=session, logger=logger,
        )
        logger.info("model: %s, coordinates %s", model.task_type,
                    list(model.coordinates))

    with logger.timed("load-data"):
        data, _ = _load_game_data(
            args.input, args, index_maps=index_maps, telemetry=session
        )
        logger.info("request source: %d rows", data.num_examples)

    with logger.timed("build-scorer"):
        scorer = GameScorer(
            model,
            mesh=common.maybe_mesh(),
            request_spec=request_spec_for_dataset(model, data),
            max_batch=args.max_batch,
            telemetry=session,
        ).warmup()
        logger.info("scorer warm: buckets %s, %d programs compiled",
                    scorer.buckets, scorer.compilations)

    sizes = request_sizes(
        args.requests, args.request_rows_mean, args.max_batch, args.seed
    )
    requests = build_requests(data, model, sizes)

    with logger.timed("serve"):
        with RequestBatcher(
            scorer, max_batch=args.max_batch,
            max_delay_s=args.max_delay_ms / 1000.0, telemetry=session,
        ) as batcher:
            scores, latencies, wall = run_closed_loop(
                batcher, requests, clients=args.clients
            )

    rows = int(sum(sizes))
    qps = len(requests) / wall if wall > 0 else 0.0
    lat_ms = np.sort(np.asarray(latencies, np.float64)) * 1e3
    p50 = float(np.percentile(lat_ms, 50))
    p99 = float(np.percentile(lat_ms, 99))
    session.gauge("serving.qps").set(qps)
    session.gauge("serving.rows_per_second").set(rows / wall if wall else 0.0)

    _publish_text(
        args.output_dir, "scores.txt",
        lambda f: np.savetxt(f, np.concatenate(scores), fmt="%.8g"),
        session, logger,
    )

    cold = sum(
        m["value"]
        for m in session.registry.snapshot().get("counters", [])
        if m["name"] == "serving.cold_entities"
    ) if session.enabled else 0
    summary = {
        "requests": len(requests),
        "rows": rows,
        "wall_s": round(wall, 4),
        "qps": round(qps, 2),
        "rows_per_sec": round(rows / wall, 1) if wall else 0.0,
        "latency_p50_ms": round(p50, 3),
        "latency_p99_ms": round(p99, 3),
        "cold_entities": int(cold),
        "compiled_programs": scorer.compilations,
    }
    _publish_text(
        args.output_dir, "serving_summary.json",
        lambda f: json.dump(summary, f, indent=1),
        session, logger,
    )
    logger.info(
        "served %d requests (%d rows) at %.1f req/s; latency p50 %.2f ms "
        "p99 %.2f ms; %d cold entities",
        summary["requests"], rows, qps, p50, p99, summary["cold_entities"],
    )
    return summary


def main(argv=None) -> None:
    run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
