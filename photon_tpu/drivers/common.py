"""Shared driver plumbing: backend selection, data loading, param parsing.

The reference's drivers are Spark applications configured through Spark-ML
``Param``s (SURVEY.md §5 'Config / flag system'); these drivers are plain
argparse CLIs with the same vocabulary (task type, optimizer, tolerance,
max-iter, regularization type + weight list, normalization, evaluators,
IO paths) plus ``--backend=tpu|cpu``.
"""

from __future__ import annotations

import argparse
import contextlib
import os
import time
from typing import Optional

import numpy as np

from photon_tpu.telemetry import NULL_SESSION, TelemetrySession, telemetry_enabled


def select_backend(backend: str) -> None:
    """Pin the JAX platform before any device use.

    ``cpu`` forces the host platform (needed in sandboxes where the TPU
    plugin's device init requires real hardware); ``tpu`` (default) lets the
    environment's TPU platform load.
    """
    import jax

    if backend == "cpu":
        jax.config.update("jax_platforms", "cpu")
    # "tpu": leave the environment's platform selection alone.
    _enable_compilation_cache()


def _enable_compilation_cache() -> None:
    """Persistent XLA compilation cache for every driver run
    (``PHOTON_COMPILATION_CACHE`` overrides the location, ``off`` disables;
    an already-configured cache dir — tests, bench, the operator — wins)."""
    from photon_tpu.utils.compilation_cache import enable

    enable(
        "PHOTON_COMPILATION_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "photon_tpu_xla"),
    )


def add_telemetry_arg(parser: argparse.ArgumentParser) -> None:
    """The one definition of ``--telemetry`` (drivers that skip
    add_common_args — index_features — reuse it, so flag/default/gate
    text cannot diverge)."""
    parser.add_argument("--telemetry", action=argparse.BooleanOptionalAction,
                        default=True,
                        help="write structured telemetry (metrics registry "
                        "snapshot, tracing spans, run report) under "
                        "<output-dir>/telemetry/; PHOTON_TELEMETRY=off "
                        "disables process-wide")


def add_fault_args(parser: argparse.ArgumentParser) -> None:
    """Fault-tolerance flags shared by every driver: fault injection (the
    CLI face of :mod:`photon_tpu.fault.injection`; overrides
    ``PHOTON_FAULTS``), preemption handling, and the run watchdog."""
    parser.add_argument("--faults", default=None, metavar="SPEC",
                        help="inject deterministic faults for recovery "
                        "testing, e.g. 'io:read:p=0.3,descent:kill:iter=2,"
                        "preempt:iter=1,solve:nan:coord=per_item' "
                        "(overrides PHOTON_FAULTS)")
    parser.add_argument("--faults-seed", type=int, default=0,
                        help="seed of the fault plan's RNG streams")
    parser.add_argument("--on-preempt", default="checkpoint",
                        choices=("checkpoint", "ignore"),
                        help="SIGTERM/SIGINT handling: 'checkpoint' "
                        "(default) finishes the current iteration, "
                        "publishes its checkpoint, and exits with code 75 "
                        "(EX_TEMPFAIL) so wrappers can resubmit; 'ignore' "
                        "leaves the default signal behavior (the atomic "
                        "checkpoint protocol still preserves the previous "
                        "published checkpoint)")
    parser.add_argument("--stall-timeout", type=float, default=None,
                        metavar="SECONDS",
                        help="run watchdog: emit watchdog.stalled telemetry "
                        "when iteration/IO progress heartbeats go silent "
                        "for this long, and escalate a guarded-IO call "
                        "hung past it to a retriable timeout (retried with "
                        "backoff like any transient fault).  Default: "
                        "PHOTON_STALL_TIMEOUT_S, else off")


def add_common_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--backend", choices=("tpu", "cpu"), default="tpu",
                        help="compute platform (tpu uses the environment's "
                        "TPU runtime; cpu forces host execution)")
    parser.add_argument("--output-dir", required=True)
    parser.add_argument("--log-file", default=None)
    parser.add_argument("--profile-dir", default=None,
                        help="write a jax.profiler trace of the train phase")
    add_telemetry_arg(parser)
    add_fault_args(parser)


def add_distributed_args(parser: argparse.ArgumentParser) -> None:
    """Multi-process (multi-host) runtime flags (SURVEY.md §2.6, §7 step 7).

    The reference scales out through Spark's cluster manager; the TPU
    rebuild uses JAX's distributed runtime: every process calls
    ``jax.distributed.initialize`` against process 0's coordinator, after
    which one global mesh spans all processes' devices and `pjit`/shard_map
    emit ICI/DCN collectives across them.
    """
    parser.add_argument("--coordinator", default=None,
                        help="host:port of process 0; presence of this flag "
                        "enables the multi-process runtime "
                        "(jax.distributed.initialize)")
    parser.add_argument("--process-id", type=int, default=None,
                        help="this process's rank in [0, --num-processes)")
    parser.add_argument("--num-processes", type=int, default=None,
                        help="total number of processes in the job")


def maybe_init_distributed(args: argparse.Namespace) -> bool:
    """Initialize the JAX distributed runtime when --coordinator is given.

    Must run before any backend/device use (the runtime wires the
    coordination service into backend creation).  Returns True when the
    process joined a multi-process job.
    """
    coordinator = getattr(args, "coordinator", None)
    if coordinator is None:
        return False
    if getattr(args, "process_id", None) is None or getattr(
        args, "num_processes", None
    ) is None:
        raise ValueError(
            "--coordinator requires --process-id and --num-processes"
        )
    import jax

    # Backend choice must be pinned before initialize() touches devices.
    select_backend(getattr(args, "backend", "tpu"))
    # Pin the sparse-gradient kernel across processes: auto-selection is a
    # per-process wall-clock measurement, so near the kernel crossover two
    # processes could pick different kernels — different per-shard reduction
    # orders — giving non-identical float results across ranks (VERDICT r3
    # weak 2).  An explicit PHOTON_SPARSE_GRAD (any value but "auto") is the
    # operator's pin and is respected; otherwise every rank defaults to
    # autodiff — the measured-fastest kernel on real TPU hardware at the
    # headline shape (1.881 vs fm's 1.124 steps/s; ops/KERNEL_NOTES.md
    # round-4 hardware table).
    if os.environ.get("PHOTON_SPARSE_GRAD", "auto") == "auto":
        os.environ["PHOTON_SPARSE_GRAD"] = "autodiff"
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
    )
    return True


def init_telemetry(args: argparse.Namespace, driver: str, logger) -> TelemetrySession:
    """One telemetry session per driver run, attached to the logger so
    every ``timed()`` phase becomes a span."""
    session = TelemetrySession(
        driver, enabled=telemetry_enabled(getattr(args, "telemetry", None))
    )
    session.attach(logger)
    return session


@contextlib.contextmanager
def telemetry_run(args: argparse.Namespace, driver: str, logger,
                  preemptible: bool = False):
    """Run-report bracket around a driver body: yields the session, then
    finalizes it into ``<output-dir>/telemetry/`` — with status "error" and
    the exception recorded when the body raises (failed runs leave a report
    saying where they died, the observability the reference gets from
    trawling driver logs), or status "preempted" when the body stopped at
    an iteration boundary on a preemption request.  Bodies of multi-process
    drivers set ``session.write = (process_index == 0)`` once they know
    their rank; until then the operator-declared ``--process-id`` gates
    writing, so a failure before that point (bad input path on every rank)
    cannot have N processes concurrently writing the same run_report.json.

    Also the one installation point of the run-scoped resilience machinery
    every driver shares: the ``--on-preempt`` SIGTERM/SIGINT handler
    (restored on exit), the ``--stall-timeout`` watchdog thread, and the
    stall-timeout override the guarded-IO retry layer reads.

    ``preemptible``: only the TRAINING drivers pass True — their loops
    poll the preemption flag at iteration boundaries.  Everything else
    keeps stock signal behavior: installing a flag-setting handler in a
    driver nothing polls would swallow Ctrl-C outright."""
    from photon_tpu.fault.injection import install_from_args, set_plan
    from photon_tpu.fault.preemption import PreemptedError, PreemptionHandler
    from photon_tpu.fault.watchdog import (
        Watchdog,
        clear_heartbeats,
        set_stall_timeout,
        stall_timeout,
    )

    install_from_args(args)  # --faults SPEC (no-op without the flag)
    session = init_telemetry(args, driver, logger)
    if getattr(args, "coordinator", None) is not None:
        session.write = (getattr(args, "process_id", None) or 0) == 0
    flag_timeout = getattr(args, "stall_timeout", None)
    if flag_timeout is not None:
        set_stall_timeout(flag_timeout)
    watchdog = None
    if stall_timeout() > 0:
        watchdog = Watchdog(
            stall_timeout(), telemetry=session, logger=logger
        ).start()
    handler = PreemptionHandler(
        (getattr(args, "on_preempt", None) or "checkpoint")
        if preemptible else "ignore",
        logger=logger,
    )
    try:
        with handler:
            yield session
    except PreemptedError as e:
        # A preemption is a CLEAN exit (checkpoint published, distinct
        # exit code) — the report says so instead of reading like a crash.
        session.finalize(
            getattr(args, "output_dir", None), status="preempted",
            error=str(e),
        )
        raise
    except BaseException as e:
        session.finalize(
            getattr(args, "output_dir", None), status="error",
            error=f"{type(e).__name__}: {e}",
        )
        raise
    else:
        session.finalize(getattr(args, "output_dir", None))
    finally:
        if watchdog is not None:
            watchdog.stop()
        # Run-scoped: the stall timeout, progress heartbeats, and any
        # --faults plan must not leak into a later in-process run.
        set_stall_timeout(None)
        clear_heartbeats()
        if getattr(args, "faults", None):
            # A --faults plan is scoped to THIS run: clear it so a later
            # in-process driver run without the flag is not injected.
            set_plan(None)


def run_cli(run_fn, args: argparse.Namespace) -> None:
    """Driver ``main()`` tail: run the driver and map a preemption stop to
    the distinct :data:`~photon_tpu.fault.preemption.PREEMPTED_EXIT_CODE`
    (75, EX_TEMPFAIL) — schedulers and run wrappers can then resubmit a
    preempted run instead of treating it as a crash.  Everything else
    propagates unchanged."""
    from photon_tpu.fault.preemption import (
        PREEMPTED_EXIT_CODE,
        PreemptedError,
    )

    try:
        run_fn(args)
    except PreemptedError as e:
        import sys

        print(f"preempted: {e}", file=sys.stderr)
        raise SystemExit(PREEMPTED_EXIT_CODE)


def add_data_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--input", required=True,
                        help="training data: a LIBSVM file path, or "
                        "synthetic:<task>:<n>:<dim>[:seed[:weight_seed]] for "
                        "generated data (weight_seed pins the true model so "
                        "train/validation can share it across seeds)")
    parser.add_argument("--validation-input", default=None,
                        help="validation data (same formats)")
    parser.add_argument("--intercept", action=argparse.BooleanOptionalAction,
                        default=True)
    parser.add_argument("--data-validation", default="error",
                        choices=("error", "warn", "off"),
                        help="row sanity checks before training (the "
                        "reference's DataValidators strictness)")
    parser.add_argument("--avro-feature-field", default="features",
                        help="record field holding the feature array when "
                        "--input is Avro (the reference's featureBagsPath "
                        "default bag)")


from photon_tpu.core.losses import BINARY_TASKS  # noqa: E402  (single source)


def stream_score_parts(input_spec, load_chunk, score_chunk, scores_path,
                       logger, on_chunk=None, telemetry=None) -> int:
    """Shared file-at-a-time scoring skeleton for the ``--stream`` modes of
    both scoring drivers (legacy ``score`` and ``score_game``): list the
    part files FIRST (no spurious empty scores.txt on a bad glob), skip
    empty parts via the typed :class:`~photon_tpu.data.game_io.
    NoRecordsError`, write scores incrementally, drop each chunk's features
    before the next file loads.  ``score_chunk(chunk) -> (raw, out, n)``;
    ``on_chunk(chunk, raw)`` accumulates whatever the caller's evaluator
    pass needs.  Returns the total row count (> 0, else NoRecordsError).
    """
    from photon_tpu.data.game_io import (
        NoRecordsError,
        _input_files,
        narrow_avro_dir,
    )

    t = telemetry or NULL_SESSION
    files = _input_files(narrow_avro_dir(input_spec))
    n = 0
    t0 = time.monotonic()
    with open(scores_path, "w") as out_f, \
            t.span("stream-score", files=len(files)):
        for path in files:
            # span=False: one retained Span per part file would grow the
            # run report unboundedly on exactly the beyond-host-memory
            # datasets --stream exists for; the stream.* histograms carry
            # the per-chunk timing distribution instead, and the single
            # stream-score span above carries the loop's wall-clock.
            with logger.timed(f"score-{os.path.basename(path)}", span=False):
                chunk_t0 = time.monotonic()
                try:
                    chunk = load_chunk(path)
                except NoRecordsError:
                    # Part layouts routinely contain empty parts; only a
                    # zero-row TOTAL is an error (below).
                    logger.info("skipping empty part %s", path)
                    t.counter("stream.chunks_skipped_empty").inc()
                    continue
                if getattr(chunk, "num_examples", None) == 0:
                    # Loaders that return a 0-row batch instead of raising
                    # (the LIBSVM path) get the same skip-empty contract as
                    # Avro's NoRecordsError (ADVICE r3).
                    logger.info("skipping empty part %s", path)
                    t.counter("stream.chunks_skipped_empty").inc()
                    continue
                raw, out, real_n = score_chunk(chunk)
                np.savetxt(out_f, out, fmt="%.8g")
                if on_chunk is not None:
                    on_chunk(chunk, raw)
                n += real_n
                t.counter("stream.chunks_scored").inc()
                t.counter("stream.rows_scored").inc(real_n)
                t.histogram("stream.chunk_rows").observe(real_n)
                t.histogram("stream.chunk_seconds").observe(
                    time.monotonic() - chunk_t0
                )
                del chunk, raw, out
    if n == 0:
        raise NoRecordsError(f"no rows in {input_spec!r}")
    wall = time.monotonic() - t0
    if wall > 0:
        t.gauge("stream.rows_per_second").set(n / wall)
    return n


def _is_avro_input(spec: str) -> bool:
    if spec.endswith(".avro"):
        return True
    from photon_tpu.data.game_io import is_avro_dir

    return is_avro_dir(spec)


def load_dataset(
    spec: str,
    intercept: bool,
    task: str = "logistic_regression",
    avro_field: str = "features",
    index_map=None,
):
    """Load (batch, dim, index_map) from an --input spec.

    LIBSVM {-1,+1} labels are normalized to {0,1} only for binary tasks;
    regression labels pass through untouched.  Avro input (file/dir of
    TrainingExampleAvro records, the reference's AvroDataReader feeding the
    legacy driver — SURVEY.md §2.3) reads name/term features from
    ``avro_field``; pass ``index_map`` to reproduce a training run's feature
    indexing (features absent from the map are dropped).
    """
    from photon_tpu.data.index_map import IndexMap, feature_key

    binary = task in BINARY_TASKS
    if _is_avro_input(spec):
        from photon_tpu.data.game_io import read_game_avro
        from photon_tpu.game.model import shard_to_batch

        maps = None if index_map is None else {"global": index_map}
        # Directory narrowing to *.avro happens inside read_game_avro
        # (game_io.narrow_avro_dir — the one copy of the rule).
        data, out_maps = read_game_avro(
            spec, {"global": avro_field}, [], index_maps=maps,
            intercept=intercept,
        )
        shard = data.shards["global"]
        batch = shard_to_batch(shard, data.label, data.offset, data.weight)
        return batch, shard.dim, out_maps["global"]
    if spec.startswith("synthetic:"):
        from photon_tpu.data.synthetic import make_glm_data

        parts = spec.split(":")
        task, n, dim = parts[1], int(parts[2]), int(parts[3])
        seed = int(parts[4]) if len(parts) > 4 else 0
        weight_seed = int(parts[5]) if len(parts) > 5 else None
        batch, _ = make_glm_data(
            n, dim, task=task, seed=seed, intercept=intercept,
            weight_seed=weight_seed,
        )
        keys = [feature_key(f"f{i}") for i in range(dim - (1 if intercept else 0))]
        return batch, dim, IndexMap.build(keys, intercept=intercept)

    if not os.path.exists(spec):
        raise FileNotFoundError(f"--input {spec} does not exist")
    from photon_tpu.data.libsvm import load_sparse_batch

    batch, dim, raw_dim = load_sparse_batch(
        spec, intercept=intercept, binary_labels=binary
    )
    keys = [feature_key(f"f{i}") for i in range(raw_dim)]
    return batch, dim, IndexMap.build(keys, intercept=intercept)


def load_validation(
    spec: Optional[str], train_dim: int, intercept: bool,
    task: str = "logistic_regression",
    avro_field: str = "features",
    index_map=None,
):
    """Load validation/scoring data padded to the training dimension
    (files whose max feature id is below the training dim are valid)."""
    if spec is None:
        return None
    if _is_avro_input(spec):
        if index_map is None:
            raise ValueError(
                "Avro validation input needs the training index map "
                "(features must share the training run's indexing)"
            )
        batch, dim, _ = load_dataset(
            spec, intercept, task, avro_field=avro_field, index_map=index_map
        )
        if dim != train_dim:
            raise ValueError(f"validation dim {dim} != train dim {train_dim}")
        return batch
    if spec.startswith("synthetic:"):
        batch, dim, _ = load_dataset(spec, intercept, task)
        if dim != train_dim:
            raise ValueError(f"validation dim {dim} != train dim {train_dim}")
        return batch
    from photon_tpu.data.libsvm import load_sparse_batch

    feature_dim = train_dim - (1 if intercept else 0)
    batch, _, _ = load_sparse_batch(
        spec, dim=feature_dim, intercept=intercept,
        binary_labels=task in BINARY_TASKS,
        max_feature_dim=feature_dim,  # early-reject before pad + transfer
    )
    return batch


def maybe_mesh(min_devices: int = 2):
    """A 1-D data mesh over all devices when more than one is present."""
    import jax

    if len(jax.devices()) >= min_devices:
        from photon_tpu.parallel import create_mesh

        return create_mesh()
    return None


def parse_weights_list(s: str) -> list[float]:
    return [float(tok) for tok in s.split(",") if tok.strip()]


def scores_on(batch, model) -> np.ndarray:
    return np.asarray(model.compute_score(batch))


def select_and_save_sweep(
    sweep: list, evaluators, has_validation: bool, index_map, args, logger,
    extra_summary: Optional[dict] = None, telemetry=None,
) -> dict:
    """Shared tail of the GLM training drivers: pick the best lambda (by
    primary evaluator, falling back to final objective value), save model
    file(s) + feature index, and write training_summary.json."""
    import json

    from photon_tpu.data.model_io import save_glm_model

    t = telemetry or NULL_SESSION
    primary = evaluators.primary
    if has_validation:
        best = sweep[0]
        for entry in sweep[1:]:
            if primary.better_than(
                entry["metrics"][primary.name], best["metrics"][primary.name]
            ):
                best = entry
    else:
        best = min(sweep, key=lambda e: e["final_value"])

    with logger.timed("save-models"):
        index_map.save(os.path.join(args.output_dir, "feature_index.json"))
        ext = "avro" if args.model_format == "avro" else "json"
        save_glm_model(
            os.path.join(args.output_dir, f"best_model.{ext}"),
            best["model"], index_map, fmt=args.model_format,
        )
        if args.save_all_models:
            for entry in sweep:
                save_glm_model(
                    os.path.join(
                        args.output_dir, f"model_lambda_{entry['lambda']:g}.{ext}"
                    ),
                    entry["model"], index_map, fmt=args.model_format,
                )
        summary_payload = {
            "task": args.task,
            "best_lambda": best["lambda"],
            "sweep": [
                {k: v for k, v in entry.items() if k != "model"}
                for entry in sweep
            ],
            "phase_times": logger.phase_times,
            **(extra_summary or {}),
        }
        with open(os.path.join(args.output_dir, "training_summary.json"), "w") as f:
            json.dump(summary_payload, f, indent=1)
        write_diagnostic_reports(sweep, best, args.output_dir)
    t.counter("train.sweep_entries").inc(len(sweep))
    t.gauge("train.best_lambda").set(best["lambda"])
    for name, value in (best.get("metrics") or {}).items():
        t.gauge("train.best_metric", metric=name).set(value)
    logger.info("best lambda=%g -> %s/best_model.%s",
                best["lambda"], args.output_dir, ext)
    return summary_payload


def _coefficient_summary(model) -> dict:
    """Summary statistics of a fitted GLM model's coefficients — the
    content of the reference's per-model diagnostic (means distribution,
    sparsity, norms; variance distribution when computed)."""
    means = np.asarray(model.coefficients.means, np.float64)
    out = {
        "dim": int(means.size),
        "nonzero": int(np.count_nonzero(means)),
        "mean": float(means.mean()) if means.size else 0.0,
        "std": float(means.std()) if means.size else 0.0,
        "min": float(means.min()) if means.size else 0.0,
        "max": float(means.max()) if means.size else 0.0,
        "l1_norm": float(np.abs(means).sum()),
        "l2_norm": float(np.sqrt((means * means).sum())),
    }
    variances = model.coefficients.variances
    if variances is not None:
        v = np.asarray(variances, np.float64)
        out["variance"] = {
            "mean": float(v.mean()), "min": float(v.min()), "max": float(v.max()),
        }
    return out


def write_diagnostic_reports(sweep: list, best: dict, output_dir: str) -> None:
    """Per-lambda diagnostic report artifacts (the rebuild of the legacy
    driver's deprecated diagnostic reports — SURVEY.md §3.2): for every
    sweep entry a JSON report (convergence trace, coefficient summary
    stats, evaluator table) under ``diagnostics/``, plus one human-readable
    ``diagnostics/report.md`` table over the whole sweep."""
    import json

    diag_dir = os.path.join(output_dir, "diagnostics")
    os.makedirs(diag_dir, exist_ok=True)
    lines = [
        "# Training diagnostic report", "",
        "| lambda | best | iterations | converged | final value | "
        "wall (s) | nnz | l2 norm | metrics |",
        "|---|---|---|---|---|---|---|---|---|",
    ]
    for entry in sweep:
        coef = _coefficient_summary(entry["model"])
        report = {
            "lambda": entry["lambda"],
            "selected_best": entry is best,
            "iterations": entry["iterations"],
            "convergence_reason": entry["convergence_reason"],
            "final_value": entry["final_value"],
            "wall_time_s": entry["wall_time_s"],
            "coefficients": coef,
            "metrics": entry.get("metrics") or {},
            "convergence_trace": entry.get("states") or [],
        }
        with open(
            os.path.join(diag_dir, f"report_lambda_{entry['lambda']:g}.json"), "w"
        ) as f:
            json.dump(report, f, indent=1)
        metric_cell = ", ".join(
            f"{k}={v:.6g}" for k, v in (entry.get("metrics") or {}).items()
        ) or "—"
        lines.append(
            f"| {entry['lambda']:g} | {'*' if entry is best else ''} "
            f"| {entry['iterations']} | {entry['convergence_reason']} "
            f"| {entry['final_value']:.6g} | {entry['wall_time_s']:.2f} "
            f"| {coef['nonzero']}/{coef['dim']} | {coef['l2_norm']:.4g} "
            f"| {metric_cell} |"
        )
    with open(os.path.join(diag_dir, "report.md"), "w") as f:
        f.write("\n".join(lines) + "\n")


def build_flat_evaluators(spec: str, driver_kind: str):
    """Build a MultiEvaluator from a comma-separated ``--evaluators`` spec,
    rejecting sharded (per-entity) evaluators up front — LIBSVM/synthetic
    input carries no entity ids, and failing after an expensive train/score
    pass would waste the run (GAME drivers plumb entity ids instead)."""
    from photon_tpu.evaluation.evaluators import MultiEvaluator, get_evaluator

    evaluators = MultiEvaluator([get_evaluator(n) for n in spec.split(",")])
    for ev in evaluators.evaluators:
        if ev.entity_column is not None:
            raise ValueError(
                f"evaluator {ev.name} needs per-entity ids, which "
                f"LIBSVM/synthetic input does not carry; use the GAME "
                f"{driver_kind} driver for sharded evaluators"
            )
    return evaluators
