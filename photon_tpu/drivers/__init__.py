"""CLI drivers: train/score entry points.

Equivalent of the reference's ``photon-client`` drivers (legacy ``Driver``,
``GameTrainingDriver``, ``GameScoringDriver`` — SURVEY.md §2.3), with
``--backend=tpu|cpu`` replacing spark-submit.
"""
