"""GAME training driver (the reference's ``GameTrainingDriver``).

End-to-end (SURVEY.md §3.1): read Avro training data (feature bags +
entity-id columns) → build per-coordinate GAME datasets → GameEstimator.fit
over the per-coordinate regularization sweep → evaluate → save the best GAME
model directory (per-coordinate name/term-keyed Avro coefficients).

Coordinate configs are ``name:key=value,...`` specs (or ``@file.json``):

    python -m photon_tpu.drivers.train_game \\
        --input train.avro --task logistic_regression \\
        --feature-bags global=features,per_user=userFeatures \\
        --id-columns userId \\
        --coordinate global:type=fixed,shard=global,optimizer=lbfgs,reg_weights=0.1+1 \\
        --coordinate per_user:type=random,shard=per_user,entity=userId,reg_weights=1 \\
        --descent-iterations 2 --validation-split 0.2 --output-dir out

Spec keys: ``type`` (fixed|random|factored_random), ``shard``, ``entity``
(random variants only), ``latent_dim``/``latent_iterations`` (factored),
``optimizer`` (lbfgs|owlqn|tron), ``reg_type``, ``reg_weights`` (``+``-joined
sweep list), ``alpha`` (elastic net), ``max_iters``, ``tolerance``,
``variance`` (none|simple), ``active_row_cap`` (random), ``downsample``
(fixed), ``seed``.  The sweep is the cross product of every coordinate's
``reg_weights`` list (the reference's GameOptimizationConfiguration grid).
"""

from __future__ import annotations

import argparse
import itertools
import json
import os

from photon_tpu.drivers import common


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "photon_tpu.drivers.train_game", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    common.add_common_args(p)
    common.add_distributed_args(p)
    p.add_argument("--input", required=True,
                   help="training data: Avro file/dir/glob, or "
                   "synthetic-game:<entities>:<rows_mean>:<fixed_dim>:"
                   "<random_dim>[:n_random[:seed]]")
    p.add_argument("--validation-input", default=None,
                   help="validation data (same format as --input)")
    p.add_argument("--validation-split", type=float, default=None,
                   help="fraction of --input rows held out for validation "
                   "(alternative to --validation-input)")
    p.add_argument("--feature-bags", default=None,
                   help="shard=recordField pairs, comma separated "
                   "(Avro input only)")
    p.add_argument("--id-columns", default=None,
                   help="entity id columns to read, comma separated "
                   "(Avro input only)")
    p.add_argument("--index-maps", default=None,
                   help="directory of feature_index_<shard>.json maps from "
                   "the index_features driver; features absent from a map "
                   "are dropped (fixed-index training)")
    p.add_argument("--data-validation", default="error",
                   choices=("error", "warn", "off"),
                   help="row sanity checks before training (the reference's "
                   "DataValidators strictness)")
    p.add_argument("--task", default="logistic_regression",
                   choices=("logistic_regression", "linear_regression",
                            "poisson_regression", "smoothed_hinge_loss_linear_svm"))
    p.add_argument("--coordinate", action="append", required=True,
                   dest="coordinates", metavar="NAME:K=V,...",
                   help="one per coordinate, in update order; or a single "
                   "@configs.json")
    p.add_argument("--descent-iterations", type=int, default=1)
    p.add_argument("--residuals", default=None,
                   choices=("auto", "device", "host"),
                   help="residual passing between coordinates: 'device' "
                   "keeps per-coordinate score vectors in a device-resident "
                   "sharded table (default via auto; SPMD-safe, runs under "
                   "multi-process meshes), 'host' restores the float64 "
                   "numpy accumulate (escape hatch).  Overrides "
                   "PHOTON_RESIDUALS")
    p.add_argument("--validation-pipeline", default=None,
                   choices=("auto", "device", "host"),
                   help="validation scoring/evaluation: 'device' keeps a "
                   "per-coordinate validation score table on device, "
                   "re-scores only retrained coordinates, and runs the "
                   "jitted metrics (one scalar sync per metric); 'host' "
                   "restores the full per-iteration GameModel.score fetch "
                   "+ numpy evaluators.  'auto' (default) follows "
                   "--residuals.  Overrides PHOTON_VALIDATION")
    p.add_argument("--stream-chunks", type=int, default=None,
                   metavar="ROWS",
                   help="out-of-core GAME: train with the streamed descent "
                   "— rows partitioned into ROWS-sized chunks, score "
                   "tables tiled at the host tier, chunks double-buffered "
                   "h2d on the io pool (device residency bounded by the "
                   "chunk window, not the dataset).  Single-controller; "
                   "replaces --residuals/--validation-pipeline.  Also "
                   "auto-enabled by --max-resident-mb")
    p.add_argument("--max-resident-mb", type=float, default=None,
                   help="device-residency budget in MB: when the dataset's "
                   "resident-fit estimate exceeds it, streaming "
                   "auto-enables with a chunk size whose in-flight window "
                   "fits the budget (explicit --stream-chunks wins)")
    p.add_argument("--max-host-mb", type=float, default=None,
                   help="host-RAM budget in MB for the streamed tier "
                   "(mirrors --max-resident-mb one tier up): when the "
                   "streamed fit's host working set — feature chunks + "
                   "score tiles — exceeds it, the disk-backed tile store "
                   "auto-enables (spilling to --spill-dir) with an LRU "
                   "host cache bounded by this budget, and streaming "
                   "itself auto-enables if no device budget already did. "
                   "NOTE: the ingestion path still materializes the "
                   "dataset once to build the store (ROADMAP tiering "
                   "edge (a)); the budget bounds the fit's STEADY-STATE "
                   "working set, not the initial load")
    p.add_argument("--spill-dir", default=None,
                   help="directory for the disk-backed tile store "
                   "(per-chunk feature blocks + score tiles).  Setting it "
                   "forces spilling; otherwise --max-host-mb derives "
                   "<output-dir>/tile_store when the host budget is "
                   "exceeded.  Requires streamed mode")
    p.add_argument("--tile-dtype", choices=("f32", "bf16", "int8"),
                   default="f32",
                   help="storage codec for the DISK tier's tile store "
                   "(ISSUE 17): bf16 halves and int8 (per-row absmax "
                   "scale row) quarters spilled feature blocks and score "
                   "tiles; host-resident tiles and all accumulation stay "
                   "f32.  Requires --spill-dir (or a --max-host-mb that "
                   "derives one)")
    p.add_argument("--dtype", default="float32",
                   choices=("float32", "bfloat16"),
                   help="storage dtype for FEATURE VALUES in every shard "
                   "(labels, weights, coefficients, and all arithmetic stay "
                   "float32); bfloat16 halves the value stream each "
                   "coordinate's gathers read from HBM")
    p.add_argument("--evaluators", default=None,
                   help="comma-separated; sharded variants take the id "
                   "column, e.g. SHARDED_AUC:userId")
    p.add_argument("--initial-model", default=None,
                   help="GAME model directory for warm start")
    p.add_argument("--locked-coordinates", default=None,
                   help="comma-separated coordinates to freeze at the "
                   "initial model (partial retraining)")
    p.add_argument("--tuning", default="none",
                   choices=("none", "random", "bayesian"),
                   help="tune per-coordinate regularization weights on the "
                   "validation metric (reference: hyperParameterTuning "
                   "RANDOM|BAYESIAN) instead of the reg_weights grid")
    p.add_argument("--tuning-iterations", type=int, default=10)
    p.add_argument("--tuning-range", default="1e-4:1e4",
                   help="lo:hi log-scale range for tuned reg weights")
    p.add_argument("--model-format", default="avro", choices=("avro", "json"))
    p.add_argument("--save-all-models", action="store_true")
    p.add_argument("--checkpoint", action=argparse.BooleanOptionalAction,
                   default=False,
                   help="write each sweep entry's model as it finishes "
                   "(resume via --initial-model)")
    p.add_argument("--checkpoint-dir", default=None,
                   help="preemption-safe descent checkpointing: after every "
                   "outer iteration the full restart state (models, "
                   "residual score rows, best-model tracking, history) is "
                   "published atomically under this directory (one "
                   "subdirectory per sweep entry; rank 0 writes under "
                   "multi-controller)")
    p.add_argument("--checkpoint-async", default=None, choices=("on", "off"),
                   help="publish descent checkpoints from a background "
                   "thread (default on, or PHOTON_CHECKPOINT_ASYNC): the "
                   "loop stages the d2h copies (copy_to_host_async) and "
                   "the serialize+fsync+rename runs behind the next "
                   "iteration's compute; LATEST may lag the loop by one "
                   "iteration.  'off' restores inline synchronous writes")
    p.add_argument("--checkpoint-max-staged-mb", type=float, default=None,
                   help="cap the async publisher's staged host copies "
                   "(checkpoint.staged_bytes): a snapshot over this many "
                   "MB publishes blocking on the loop thread instead of "
                   "holding a second snapshot-sized host allocation while "
                   "training runs ahead.  Default: "
                   "PHOTON_CHECKPOINT_MAX_STAGED_MB, else unbounded")
    p.add_argument("--resume", default=None, metavar="auto|latest|PATH",
                   help="restore a descent mid-sweep from --checkpoint-dir: "
                   "'auto' resumes whatever is checkpointed (fresh start "
                   "otherwise), 'latest' requires a checkpoint, a path "
                   "names one checkpoint version directory.  Completed "
                   "sweep entries are rebuilt from their snapshots without "
                   "re-running; a resumed fit matches an uninterrupted one "
                   "— including on a DIFFERENT device/process count "
                   "(checkpoints are mesh-shape portable)")
    p.add_argument("--max-quarantined", type=int, default=8,
                   help="how many non-finite solves/score rows may be "
                   "quarantined (previous iterate kept, descent.quarantined "
                   "telemetry) before the run fails; -1 = unlimited")
    return p


_KNOWN_COORDINATE_KEYS = {
    "type", "shard", "entity", "optimizer", "reg_type", "reg_weights",
    "alpha", "max_iters", "tolerance", "variance", "active_row_cap",
    "downsample", "downsampler", "projection", "projected_dim", "seed",
    "row_split",
    "latent_dim", "latent_iterations",
}


def _validate_coordinate(name: str, kv: dict, origin: str) -> tuple[str, dict]:
    unknown = set(kv) - _KNOWN_COORDINATE_KEYS
    if unknown:
        raise ValueError(f"unknown coordinate key(s) {sorted(unknown)} in {origin}")
    if kv.get("type", "fixed") not in ("fixed", "random", "factored_random"):
        raise ValueError(
            f"coordinate type must be fixed|random|factored_random in {origin}"
        )
    if "shard" not in kv:
        raise ValueError(f"coordinate {name!r} needs shard=<feature shard>")
    if kv.get("type") in ("random", "factored_random") and "entity" not in kv:
        raise ValueError(f"random coordinate {name!r} needs entity=<id column>")
    return name, kv


def parse_coordinate_spec(spec: str):
    """``name:key=value,...`` -> (name, dict).  Raises on unknown keys."""
    name, _, body = spec.partition(":")
    if not name or not body:
        raise ValueError(f"bad coordinate spec {spec!r} (want name:key=value,...)")
    kv = {}
    for tok in body.split(","):
        k, _, v = tok.partition("=")
        kv[k.strip()] = v.strip()
    return _validate_coordinate(name, kv, repr(spec))


def _coordinate_specs(args) -> list[tuple[str, dict]]:
    if len(args.coordinates) == 1 and args.coordinates[0].startswith("@"):
        path = args.coordinates[0][1:]
        with open(path) as f:
            payload = json.load(f)
        return [
            _validate_coordinate(c.pop("name"), c, f"{path} entry {i}")
            for i, c in enumerate(payload)
        ]
    return [parse_coordinate_spec(s) for s in args.coordinates]


def _coord_bool(value) -> bool:
    """Coordinate-spec boolean: accepts JSON true/false (the @file path
    passes Python bools through) and the CLI strings true/1/yes /
    false/0/no.  Anything else raises — a typo like ``row_split=ture``
    silently disabling a feature is exactly the spec-validation failure
    mode the other keys reject (ADVICE r3)."""
    if isinstance(value, bool):
        return value
    s = str(value).strip().lower()
    if s in ("true", "1", "yes"):
        return True
    if s in ("false", "0", "no"):
        return False
    raise ValueError(
        f"coordinate-spec boolean must be true/false/1/0/yes/no, got {value!r}"
    )


def _coord_config(kv: dict, lam: float, task: str = "logistic_regression"):
    """Build one coordinate's config with regularization weight ``lam``.

    ``downsampler`` defaults to the task-appropriate sampler (binary for
    logistic/hinge, uniform otherwise — the reference's rule).
    """
    from photon_tpu.core.objective import RegularizationContext
    from photon_tpu.core.optimizers import OptimizerConfig
    from photon_tpu.core.problem import ProblemConfig
    from photon_tpu.game.coordinate import (
        FactoredRandomEffectCoordinateConfig,
        FixedEffectCoordinateConfig,
        RandomEffectCoordinateConfig,
    )

    reg_type = kv.get("reg_type", "l2")
    optimizer = kv.get("optimizer", "lbfgs")
    if reg_type in ("l1", "elastic_net"):
        optimizer = "owlqn"
    problem = ProblemConfig(
        optimizer=optimizer,
        regularization=RegularizationContext(
            reg_type, lam, float(kv.get("alpha", 0.5))
        ),
        optimizer_config=OptimizerConfig(
            max_iterations=int(kv.get("max_iters", 50)),
            tolerance=float(kv.get("tolerance", 1e-7)),
        ),
        variance_computation=kv.get("variance", "none"),
    )
    if kv.get("type", "fixed") == "fixed":
        if _coord_bool(kv.get("row_split", False)):
            raise ValueError(
                "row_split applies to random coordinates only (the fixed "
                "effect is already data-sharded with psum)"
            )
        downsampler = kv.get("downsampler") or "auto"
        if downsampler == "auto":
            from photon_tpu.core.losses import BINARY_TASKS

            downsampler = "binary" if task.lower() in BINARY_TASKS else "default"
        return FixedEffectCoordinateConfig(
            shard_name=kv["shard"],
            problem=problem,
            downsampling_rate=float(kv.get("downsample", 1.0)),
            downsampler=downsampler,
            seed=int(kv.get("seed", 0)),
        )
    cap = kv.get("active_row_cap")
    if kv.get("type") == "factored_random":
        if _coord_bool(kv.get("row_split", False)):
            raise ValueError(
                "row_split is not supported for factored_random coordinates "
                "(the pooled latent solve already spans the mesh)"
            )
        if kv.get("projection") or kv.get("projected_dim") or kv.get("variance"):
            raise ValueError(
                "projection/projected_dim/variance are not supported for "
                "factored_random coordinates (the latent projection IS the "
                "dimensionality reduction; z-space variances do not "
                "transport to w = L z)"
            )
        return FactoredRandomEffectCoordinateConfig(
            shard_name=kv["shard"],
            entity_column=kv["entity"],
            latent_dim=int(kv.get("latent_dim", 4)),
            latent_iterations=int(kv.get("latent_iterations", 2)),
            problem=problem,
            active_row_cap=None if cap in (None, "") else int(cap),
            seed=int(kv.get("seed", 0)),
        )
    pdim = kv.get("projected_dim")
    return RandomEffectCoordinateConfig(
        shard_name=kv["shard"],
        entity_column=kv["entity"],
        problem=problem,
        active_row_cap=None if cap in (None, "") else int(cap),
        projection=kv.get("projection", "none"),
        projected_dim=None if pdim in (None, "") else int(pdim),
        seed=int(kv.get("seed", 0)),
        row_split=_coord_bool(kv.get("row_split", False)),
    )


def _combo_label(specs, combo) -> str:
    return ",".join(f"{name}={lam:g}" for (name, _), lam in zip(specs, combo))


def _build_sweep(specs, task: str):
    """Cross product of per-coordinate reg weights -> configuration list."""
    weight_lists = []
    for _, kv in specs:
        weights = [float(w) for w in str(kv.get("reg_weights", "1.0")).split("+")]
        weight_lists.append(weights)

    configurations = []
    for combo in itertools.product(*weight_lists):
        coords = {
            name: _coord_config(kv, lam, task)
            for (name, kv), lam in zip(specs, combo)
        }
        configurations.append((_combo_label(specs, combo), coords, combo))
    return configurations


def _load_game_data(spec: str, args, index_maps=None, telemetry=None):
    """(dataset, index_maps) from an input spec (Avro or synthetic-game)."""
    if spec.startswith("synthetic-game:"):
        from photon_tpu.data.synthetic import make_game_dataset

        parts = spec.split(":")
        n_e, rows, fdim, rdim = (int(x) for x in parts[1:5])
        n_random = int(parts[5]) if len(parts) > 5 else 1
        seed = int(parts[6]) if len(parts) > 6 else 0
        data, maps = make_game_dataset(
            n_e, rows, fdim, rdim, seed=seed, n_random_coords=n_random
        )
        if index_maps is not None:
            # Synthetic features are positional; a model trained on other
            # data can only be applied if its maps agree key-for-key —
            # otherwise coefficients would land on the wrong columns.
            for name, imap in maps.items():
                other = index_maps.get(name)
                if other is not None and list(other.keys()) != list(imap.keys()):
                    raise ValueError(
                        f"model's index map for shard {name!r} does not match "
                        "the synthetic-game feature layout; score the data "
                        "the model was trained for"
                    )
        return data, (index_maps or maps)
    from photon_tpu.data.game_io import read_game_avro

    bags, id_cols = parse_bags_and_id_columns(args)
    return read_game_avro(
        spec, bags, id_cols, index_maps=index_maps, telemetry=telemetry
    )


def parse_feature_bags(feature_bags: str) -> dict:
    """--feature-bags 'shard=field,...' -> dict; the ONE parse of this flag
    (training, index-map loading, and streamed scoring all share it)."""
    return dict(tok.split("=", 1) for tok in feature_bags.split(","))


def parse_bags_and_id_columns(args) -> tuple[dict, list]:
    """--feature-bags + --id-columns -> (dict, list); shared by the training
    and (streamed) scoring drivers so parsing can never diverge."""
    if not args.feature_bags or not args.id_columns:
        raise ValueError(
            "Avro input needs --feature-bags and --id-columns "
            "(shard=field pairs and entity id fields)"
        )
    bags = parse_feature_bags(args.feature_bags)
    id_cols = [c.strip() for c in args.id_columns.split(",") if c.strip()]
    return bags, id_cols


def _has_published_checkpoint(checkpoint_dir) -> bool:
    """True when any descent checkpoint chain under ``checkpoint_dir`` has
    a published version (shared strictness rule — fault.checkpoint)."""
    from photon_tpu.fault.checkpoint import has_published_checkpoint

    return has_published_checkpoint(checkpoint_dir)


def run(args: argparse.Namespace) -> dict:
    common.maybe_init_distributed(args) or common.select_backend(args.backend)
    from photon_tpu.utils import PhotonLogger

    logger = PhotonLogger("photon_tpu.train_game", args.log_file)
    with common.telemetry_run(
        args, "train_game", logger, preemptible=True
    ) as session:
        return _run(args, logger, session)


def _run(args: argparse.Namespace, logger, session) -> dict:
    from photon_tpu.evaluation.evaluators import (
        MultiEvaluator,
        default_evaluators_for_task,
        get_evaluator,
    )
    from photon_tpu.game.data import split_game_dataset
    from photon_tpu.game.estimator import GameEstimator, GameOptimizationConfiguration
    from photon_tpu.game.model_io import load_game_model, save_game_model
    from photon_tpu.utils.logging import maybe_profile

    os.makedirs(args.output_dir, exist_ok=True)
    specs = _coordinate_specs(args)
    if args.resume and not args.checkpoint_dir:
        raise ValueError("--resume needs --checkpoint-dir")
    if args.resume == "latest" and not _has_published_checkpoint(
        args.checkpoint_dir
    ):
        # Strictness means a PUBLISHED checkpoint (a LATEST pointer), not
        # just directory debris from a run killed before its first publish.
        raise ValueError(
            f"--resume latest: no published checkpoint under "
            f"{args.checkpoint_dir!r}"
        )
    if args.resume and args.resume not in ("auto", "latest"):
        # An explicit checkpoint path names one descent run, so a
        # multi-entry sweep (or tuning, whose configurations are sampled)
        # is rejected up front — before the data load, not after entry 0
        # has already burned its fit.
        if args.tuning != "none" or len(_build_sweep(specs, args.task)) > 1:
            raise ValueError(
                "an explicit --resume path applies to a single sweep "
                "entry; use --resume auto for sweeps/tuning"
            )

    prebuilt_maps = None
    if args.index_maps:
        if not args.feature_bags:
            raise ValueError("--index-maps needs --feature-bags")
        from photon_tpu.data.index_map import IndexMap

        bags = parse_feature_bags(args.feature_bags)
        prebuilt_maps = {
            shard: IndexMap.load(
                os.path.join(args.index_maps, f"feature_index_{shard}.json")
            )
            for shard in bags
        }

    with logger.timed("load-data"):
        data, index_maps = _load_game_data(
            args.input, args, index_maps=prebuilt_maps, telemetry=session
        )
        val_data = None
        if args.validation_input:
            val_data, _ = _load_game_data(
                args.validation_input, args, index_maps=index_maps,
                telemetry=session,
            )
        elif args.validation_split:
            data, val_data = split_game_dataset(data, args.validation_split)
        if args.dtype != "float32":
            from photon_tpu.game.data import dataset_astype

            # Training data only: validation stays f32 (scoring promotes
            # anyway; metrics must not depend on the storage option).
            data = dataset_astype(data, args.dtype)
            logger.info("feature values stored as %s (f32 arithmetic)",
                        args.dtype)
        logger.info(
            "train: %d examples, shards %s", data.num_examples,
            {n: s.dim for n, s in data.shards.items()},
        )
        session.gauge("train.num_examples").set(data.num_examples)
        for shard_name, shard in data.shards.items():
            session.gauge("train.shard_dim", shard=shard_name).set(shard.dim)

    if args.data_validation != "off":
        from photon_tpu.data.validation import (
            apply_validation,
            validate_game_dataset,
        )

        apply_validation(
            validate_game_dataset(data, args.task), args.data_validation, logger
        )

    if args.evaluators:
        evaluators = MultiEvaluator(
            [get_evaluator(n) for n in args.evaluators.split(",")]
        )
    else:
        evaluators = MultiEvaluator(default_evaluators_for_task(args.task))

    initial_model = None
    if args.initial_model:
        initial_model, _ = load_game_model(args.initial_model)
    locked = (
        [c.strip() for c in args.locked_coordinates.split(",") if c.strip()]
        if args.locked_coordinates else []
    )

    mesh = common.maybe_mesh()
    stream_rows = None
    if args.stream_chunks is not None:
        if args.stream_chunks < 1:
            raise ValueError(
                f"--stream-chunks must be >= 1, got {args.stream_chunks}"
            )
        stream_rows = args.stream_chunks
    elif args.max_resident_mb is not None:
        from photon_tpu.game.tiles import (
            chunk_rows_for_budget,
            resident_bytes_estimate,
        )

        estimate = resident_bytes_estimate(data, n_coordinates=len(specs))
        budget = int(args.max_resident_mb * (1 << 20))
        session.gauge("stream.resident_estimate_bytes").set(estimate)
        if estimate > budget:
            stream_rows = chunk_rows_for_budget(data, args.max_resident_mb)
            logger.info(
                "resident estimate %.1f MB exceeds --max-resident-mb %.1f: "
                "streaming enabled with %d-row chunks",
                estimate / (1 << 20), args.max_resident_mb, stream_rows,
            )
    if args.max_host_mb is not None and args.max_host_mb <= 0:
        raise ValueError(
            f"--max-host-mb must be > 0, got {args.max_host_mb}"
        )
    spill_dir = args.spill_dir
    if args.max_host_mb is not None:
        # ISSUE 11 satellite: the auto-enable gate used to size against
        # device memory only — fold the HOST estimate in, so a dataset
        # past host RAM auto-enables streaming AND spilling instead of
        # OOM-ing the host tier.
        from photon_tpu.game.tiles import (
            chunk_rows_for_budget,
            stream_host_bytes_estimate,
        )

        host_estimate = stream_host_bytes_estimate(
            data, n_coordinates=len(specs)
        )
        host_budget = int(args.max_host_mb * (1 << 20))
        session.gauge("stream.host_estimate_bytes").set(host_estimate)
        if host_estimate > host_budget:
            if stream_rows is None:
                # Past host RAM with no device pressure configured:
                # stream anyway (the resident path would pin even more),
                # chunked so the in-flight window fits the host budget.
                stream_rows = chunk_rows_for_budget(data, args.max_host_mb)
                logger.info(
                    "host estimate %.1f MB exceeds --max-host-mb %.1f: "
                    "streaming enabled with %d-row chunks",
                    host_estimate / (1 << 20), args.max_host_mb,
                    stream_rows,
                )
            if spill_dir is None:
                spill_dir = os.path.join(args.output_dir, "tile_store")
            logger.info(
                "host estimate %.1f MB exceeds --max-host-mb %.1f: "
                "disk-backed tile store enabled at %s",
                host_estimate / (1 << 20), args.max_host_mb, spill_dir,
            )
    if spill_dir is not None and not stream_rows:
        raise ValueError(
            "--spill-dir requires streamed mode (--stream-chunks or a "
            "--max-resident-mb/--max-host-mb budget the dataset exceeds)"
        )
    if spill_dir is not None:
        session.gauge("stream.spilled").set(1)
    if stream_rows:
        import jax as _jax_stream

        if _jax_stream.process_count() > 1:
            raise ValueError(
                "--stream-chunks/--max-resident-mb streaming runs "
                "single-controller; drop the multi-process flags"
            )
        if mesh is not None:
            # A single-host multi-device mesh is an execution choice the
            # streamed loop does not use: fall back to one device rather
            # than refuse the run.
            logger.info(
                "streamed descent is single-controller: ignoring the "
                "%d-device mesh", len(_jax_stream.devices()),
            )
            mesh = None
        if args.residuals not in (None, "auto") or (
            args.validation_pipeline not in (None, "auto")
        ):
            logger.info(
                "streamed descent replaces --residuals/"
                "--validation-pipeline; ignoring the explicit flags"
            )
        session.gauge("stream.chunk_rows").set(stream_rows)
    estimator = GameEstimator(
        args.task,
        data,
        validation_data=val_data,
        evaluators=evaluators if val_data is not None else None,
        mesh=mesh,
        logger=logger,
        telemetry=session,
        # The streamed estimator refuses explicit engine modes; the driver
        # already warned above, so strip them here.
        residual_mode=None if stream_rows else args.residuals,
        validation_mode=None if stream_rows else args.validation_pipeline,
        stream_chunks=stream_rows,
        spill_dir=spill_dir,
        max_host_mb=args.max_host_mb if spill_dir is not None else None,
        tile_dtype=args.tile_dtype,
    )

    import jax as _jax

    # Multi-process runs: only process 0 writes checkpoints, models, and
    # summaries (the reference's driver-writes semantics; every rank still
    # participates in the collectives inside fit).
    is_primary = _jax.process_index() == 0
    session.write = is_primary

    results = []
    checkpoint_fn = None
    if args.checkpoint and is_primary:
        # Per-descent-iteration intermediate model (SURVEY.md §5): each
        # completed coordinate pass overwrites checkpoint/latest, so a
        # killed run resumes via --initial-model <out>/checkpoint/latest.
        ckpt_base = os.path.join(args.output_dir, "checkpoint")
        ckpt_dir = os.path.join(ckpt_base, "latest")

        def checkpoint_fn(iteration, model):
            # Atomic publish: write each checkpoint into an alternating slot
            # dir, then atomically repoint the `latest` symlink (os.replace
            # on a symlink is atomic; directories cannot be swapped
            # atomically on POSIX) — a crash at ANY instant leaves `latest`
            # resolving to a complete checkpoint (ADVICE r1).
            import shutil

            # Write into whichever slot `latest` does NOT currently resolve
            # to, so the live checkpoint is never touched mid-write.
            live = (
                os.path.basename(os.path.realpath(ckpt_dir))
                if os.path.islink(ckpt_dir) else None
            )
            slot = os.path.join(
                ckpt_base, "slot-1" if live == "slot-0" else "slot-0"
            )
            shutil.rmtree(slot, ignore_errors=True)
            save_game_model(slot, model, index_maps, fmt=args.model_format,
                            telemetry=session)
            tmp_link = os.path.join(ckpt_base, ".latest.tmp")
            if os.path.lexists(tmp_link):
                os.remove(tmp_link)
            if os.path.isdir(ckpt_dir) and not os.path.islink(ckpt_dir):
                # Migrate a pre-symlink layout: park the old dir aside first
                # (never deleted until the new link is live).  A dir cannot
                # be atomically replaced by a symlink on POSIX, so migration
                # has a one-time window where `latest` is missing — both
                # `latest.pre-symlink` and the new slot hold complete
                # checkpoints throughout it.
                aside = ckpt_dir + ".pre-symlink"
                shutil.rmtree(aside, ignore_errors=True)
                os.rename(ckpt_dir, aside)
            else:
                aside = None
            os.symlink(os.path.basename(slot), tmp_link)
            os.replace(tmp_link, ckpt_dir)
            if aside is not None:
                shutil.rmtree(aside, ignore_errors=True)
            logger.info("checkpoint: iteration %d -> %s", iteration, ckpt_dir)

    max_quarantined = (
        None if args.max_quarantined < 0 else args.max_quarantined
    )
    fit_seq = itertools.count()

    def _slug(label: str) -> str:
        return "".join(c if c.isalnum() else "-" for c in label)[:80]

    def fit_config(config) -> "object":
        # One stable checkpoint subdirectory per sweep entry (sequence
        # number + sanitized label), so every descent run owns its own
        # versioned checkpoint chain and mid-sweep resume can tell finished
        # entries from the interrupted one.
        ckpt_dir = resume = None
        if args.checkpoint_dir:
            seq = next(fit_seq)
            ckpt_dir = os.path.join(
                args.checkpoint_dir,
                f"{seq:03d}-{_slug(config.name or 'config')}",
            )
            # Per-entry resume is auto-style: entries the interrupted run
            # never reached have no checkpoint and start fresh ('latest'
            # strictness — at least one checkpoint exists — was enforced
            # above; explicit paths were validated single-entry up front).
            resume = args.resume if args.resume != "latest" else "auto"
        result = estimator.fit(
            [config], initial_model=initial_model, locked_coordinates=locked,
            checkpoint_fn=checkpoint_fn,
            checkpoint_dir=ckpt_dir, resume=resume,
            max_quarantined=max_quarantined,
            checkpoint_async=args.checkpoint_async,
            checkpoint_max_staged_mb=args.checkpoint_max_staged_mb,
        )[0]
        results.append(result)
        if (args.checkpoint or args.save_all_models) and is_primary:
            save_game_model(
                os.path.join(args.output_dir, f"model_{config.name}"),
                result.model, index_maps, fmt=args.model_format,
                telemetry=session,
            )
        return result

    with maybe_profile(args.profile_dir):
        if args.tuning != "none":
            # Tune per-coordinate reg weights on the validation metric
            # (reference: hyperParameterTuning RANDOM|BAYESIAN, §3.5).
            if val_data is None:
                raise ValueError("--tuning needs validation data")
            from photon_tpu.hyperparameter import (
                GaussianProcessSearch,
                RandomSearch,
                SearchDimension,
                SearchSpace,
            )

            lo, hi = (float(x) for x in args.tuning_range.split(":"))
            # Locked coordinates keep their configured weight: their model is
            # frozen, so searching their dimension would be dead weight.
            space = SearchSpace([
                SearchDimension(name, lo, hi, log_scale=True)
                for name, _ in specs
                if name not in locked
            ])
            if not space.dimensions:
                raise ValueError(
                    "--tuning needs at least one unlocked coordinate"
                )
            primary = evaluators.primary

            def weight_for(name: str, kv: dict, params) -> float:
                if name in locked:
                    return float(str(kv.get("reg_weights", "1.0")).split("+")[0])
                return params[name]

            def evaluate(params):
                combo = [weight_for(name, kv, params) for name, kv in specs]
                config = GameOptimizationConfiguration(
                    coordinates={
                        name: _coord_config(kv, weight_for(name, kv, params), args.task)
                        for name, kv in specs
                    },
                    descent_iterations=args.descent_iterations,
                    name=_combo_label(specs, combo),
                )
                result = fit_config(config)
                return result.metrics[primary.name]

            search_cls = (
                GaussianProcessSearch if args.tuning == "bayesian" else RandomSearch
            )
            search_cls(
                space, evaluate, maximize=primary.maximize
            ).find(args.tuning_iterations)
        else:
            for label, coords, _ in _build_sweep(specs, args.task):
                fit_config(GameOptimizationConfiguration(
                    coordinates=coords,
                    descent_iterations=args.descent_iterations,
                    name=label,
                ))
    best = estimator.select_best(results)
    for name, value in best.metrics.items():
        session.gauge("train.best_metric", metric=name).set(value)
    if not is_primary:
        return {"rank": _jax.process_index(), "best": best.configuration.name}

    with logger.timed("save-model"):
        save_game_model(
            os.path.join(args.output_dir, "best_model"),
            best.model, index_maps, fmt=args.model_format, telemetry=session,
        )
    summary = {
        "task": args.task,
        "best_configuration": best.configuration.name,
        "best_metrics": best.metrics,
        "sweep": [
            {
                "configuration": r.configuration.name,
                "metrics": r.metrics,
                "history": [
                    {"iteration": h["iteration"], "metrics": h["metrics"]}
                    for h in r.descent.history
                ],
            }
            for r in results
        ],
        "phase_times": logger.phase_times,
    }
    with open(os.path.join(args.output_dir, "training_summary.json"), "w") as f:
        json.dump(summary, f, indent=1)
    logger.info(
        "best configuration %s -> %s/best_model",
        best.configuration.name, args.output_dir,
    )
    return summary


def main(argv=None) -> None:
    # PreemptedError -> exit 75 (EX_TEMPFAIL): a preempted run is a clean,
    # resumable stop, not a crash.
    common.run_cli(run, build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
