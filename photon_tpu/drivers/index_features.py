"""Feature indexing driver (the reference's ``FeatureIndexingDriver``).

A standalone job (SURVEY.md §2.3 'Feature indexing job') that scans Avro
training data once, builds the (name, term) -> id map per feature bag, and
writes them for later training/scoring runs — the reference materializes
PalDB stores consumed executor-side; here the output is the JSON index
format plus, optionally, the native mmap store
(photon_tpu.data.index_map.OffHeapIndexMap) for vocabularies that should
not live in process memory.

    python -m photon_tpu.drivers.index_features \\
        --input 'train/*.avro' \\
        --feature-bags global=features,per_user=userFeatures \\
        --output-dir maps [--store mmap]
"""

from __future__ import annotations

import argparse
import json
import os

from photon_tpu.drivers import common


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "photon_tpu.drivers.index_features", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--input", required=True,
                   help="Avro training data: file, directory, or glob")
    p.add_argument("--feature-bags", required=True,
                   help="shard=recordField pairs, comma separated")
    p.add_argument("--output-dir", required=True)
    p.add_argument("--intercept", action=argparse.BooleanOptionalAction,
                   default=True)
    p.add_argument("--store", default="json", choices=("json", "mmap"),
                   help="mmap additionally writes the native off-heap store "
                   "(PalDB equivalent)")
    p.add_argument("--log-file", default=None)
    common.add_telemetry_arg(p)
    return p


def run(args: argparse.Namespace) -> dict:
    from photon_tpu.utils import PhotonLogger

    logger = PhotonLogger("photon_tpu.index_features", args.log_file)
    with common.telemetry_run(args, "index_features", logger) as session:
        return _run(args, logger, session)


def _run(args: argparse.Namespace, logger, session) -> dict:
    from photon_tpu.data import avro_codec
    from photon_tpu.data.game_io import _input_files
    from photon_tpu.data.index_map import INTERCEPT_KEY, IndexMap, feature_key

    os.makedirs(args.output_dir, exist_ok=True)
    bags = dict(tok.split("=", 1) for tok in args.feature_bags.split(","))

    key_order: dict[str, dict] = {shard: {} for shard in bags}
    n_records = 0
    with logger.timed("scan"):
        for path in _input_files(args.input):
            # Lazy record iteration: the indexing job scans arbitrarily large
            # part-file inputs holding only the vocabularies in memory.
            for rec in avro_codec.iter_container(path):
                n_records += 1
                for shard, field in bags.items():
                    seen = key_order[shard]
                    for ntv in rec.get(field, ()):
                        key = feature_key(ntv["name"], ntv["term"])
                        if key != INTERCEPT_KEY:  # implicit on read
                            seen.setdefault(key, None)

    session.counter("index.records_scanned").inc(n_records)
    summary = {"num_records": n_records, "shards": {}}
    with logger.timed("write"):
        for shard, seen in key_order.items():
            imap = IndexMap.build(list(seen), intercept=args.intercept)
            json_path = os.path.join(
                args.output_dir, f"feature_index_{shard}.json"
            )
            imap.save(json_path)
            entry = {"num_features": len(imap), "json": json_path}
            if args.store == "mmap":
                from photon_tpu.data.index_map import OffHeapIndexMap

                store_path = os.path.join(
                    args.output_dir, f"feature_index_{shard}.pixs"
                )
                OffHeapIndexMap.build_file(
                    store_path, seen, intercept=args.intercept
                ).close()
                entry["mmap"] = store_path
            summary["shards"][shard] = entry
            session.gauge("index.num_features", shard=shard).set(len(imap))
            logger.info("shard %s: %d features", shard, len(imap))
    with open(os.path.join(args.output_dir, "indexing_summary.json"), "w") as f:
        json.dump(summary, f, indent=1)
    return summary


def main(argv=None) -> None:
    run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
