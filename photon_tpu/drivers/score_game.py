"""GAME scoring driver (the reference's ``GameScoringDriver``).

SURVEY.md §3.3: load a saved GAME model directory → read + index scoring
data with the model's per-shard feature maps → per-coordinate score
accumulation (fixed: broadcast coefficients; random: gather by entity index,
the TPU shape of the reference's shuffle-join) → write scores (+ optional
metrics).

    python -m photon_tpu.drivers.score_game \\
        --input test.avro --model out/best_model \\
        --feature-bags global=features,per_user=userFeatures \\
        --id-columns userId \\
        --evaluators AUC,SHARDED_AUC:userId --output-dir scored
"""

from __future__ import annotations

import argparse
import json
import os

import numpy as np

from photon_tpu.drivers import common
from photon_tpu.drivers.train_game import _load_game_data


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "photon_tpu.drivers.score_game", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    common.add_common_args(p)
    p.add_argument("--input", required=True,
                   help="scoring data: Avro file/dir/glob or synthetic-game "
                   "spec (see train_game)")
    p.add_argument("--model", required=True, help="GAME model directory")
    p.add_argument("--feature-bags", default=None)
    p.add_argument("--id-columns", default=None)
    p.add_argument("--evaluators", default=None)
    p.add_argument("--predict-mean", action="store_true",
                   help="write mean predictions (inverse link) instead of "
                   "raw scores")
    return p


def run(args: argparse.Namespace) -> dict:
    common.select_backend(args.backend)
    from photon_tpu.evaluation.evaluators import MultiEvaluator, get_evaluator
    from photon_tpu.game.model_io import load_game_model
    from photon_tpu.utils import PhotonLogger

    logger = PhotonLogger("photon_tpu.score_game", args.log_file)
    os.makedirs(args.output_dir, exist_ok=True)

    with logger.timed("load-model"):
        model, index_maps = load_game_model(args.model)
        logger.info(
            "model: %s, coordinates %s", model.task_type,
            list(model.coordinates),
        )

    with logger.timed("load-data"):
        # Index scoring features through the model's training-time maps —
        # unseen features drop, matching the reference's fixed-index scoring.
        data, _ = _load_game_data(args.input, args, index_maps=index_maps)
        logger.info("scoring %d examples", data.num_examples)

    with logger.timed("score"):
        raw_scores = model.score(data)
        if args.predict_mean:
            import jax.numpy as jnp

            from photon_tpu.core.losses import get_loss

            out_scores = np.asarray(
                get_loss(model.task_type).mean(jnp.asarray(raw_scores))
            )
        else:
            out_scores = raw_scores
    np.savetxt(os.path.join(args.output_dir, "scores.txt"), out_scores, fmt="%.8g")

    metrics = {}
    if args.evaluators:
        evaluators = MultiEvaluator(
            [get_evaluator(n) for n in args.evaluators.split(",")]
        )
        metrics = evaluators.evaluate(
            raw_scores, data.label, data.weight, dict(data.id_columns)
        )
        logger.info("metrics %s", metrics)
        with open(os.path.join(args.output_dir, "metrics.json"), "w") as f:
            json.dump(metrics, f, indent=1)
    return {"num_scored": int(data.num_examples), "metrics": metrics}


def main(argv=None) -> None:
    run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
