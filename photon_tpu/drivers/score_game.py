"""GAME scoring driver (the reference's ``GameScoringDriver``).

SURVEY.md §3.3: load a saved GAME model directory → read + index scoring
data with the model's per-shard feature maps → per-coordinate score
accumulation (fixed: broadcast coefficients; random: gather by entity index,
the TPU shape of the reference's shuffle-join) → write scores (+ optional
metrics).

    python -m photon_tpu.drivers.score_game \\
        --input test.avro --model out/best_model \\
        --feature-bags global=features,per_user=userFeatures \\
        --id-columns userId \\
        --evaluators AUC,SHARDED_AUC:userId --output-dir scored
"""

from __future__ import annotations

import argparse
import json
import os

import numpy as np

from photon_tpu.drivers import common
from photon_tpu.drivers.train_game import _load_game_data


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "photon_tpu.drivers.score_game", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    common.add_common_args(p)
    p.add_argument("--input", required=True,
                   help="scoring data: Avro file/dir/glob or synthetic-game "
                   "spec (see train_game)")
    p.add_argument("--model", required=True, help="GAME model directory")
    p.add_argument("--feature-bags", default=None)
    p.add_argument("--id-columns", default=None)
    p.add_argument("--evaluators", default=None)
    p.add_argument("--predict-mean", action="store_true",
                   help="write mean predictions (inverse link) instead of "
                   "raw scores")
    p.add_argument("--stream", action="store_true",
                   help="score Avro part files one at a time: features for "
                   "each chunk are dropped after scoring, so host memory is "
                   "bounded by the scores/labels, not the feature arrays "
                   "(for scoring sets far beyond host memory)")
    return p


def _evaluate_and_dump(args, logger, scores, label, weight, id_columns,
                       session=None) -> dict:
    """Shared evaluator + metrics.json tail of both scoring paths."""
    from photon_tpu.evaluation.evaluators import MultiEvaluator, get_evaluator

    evaluators = MultiEvaluator(
        [get_evaluator(s) for s in args.evaluators.split(",")]
    )
    with logger.timed("evaluate"):
        metrics = evaluators.evaluate(scores, label, weight, id_columns)
    if session is not None:
        for name, value in metrics.items():
            session.gauge("score.metric", metric=name).set(value)
    logger.info("metrics %s", metrics)
    with open(os.path.join(args.output_dir, "metrics.json"), "w") as f:
        json.dump(metrics, f, indent=1)
    return metrics


def _pad_pow2_rows(chunk):
    """Pad a chunk dataset to the next power-of-two row count with
    zero-weight rows, so part files of varying sizes bucket into O(log n)
    distinct shapes — the jitted scoring kernels compile once per bucket
    instead of once per file.  Padded rows reuse the chunk's first entity
    key (always valid for the vocabulary dtype); their scores are sliced
    off before anything is written.  Returns (padded, real_n)."""
    import dataclasses

    from photon_tpu.game.data import DenseShard, SparseShard

    from photon_tpu.utils import pow2_at_least

    n = chunk.num_examples
    target = pow2_at_least(n)
    if target == n:
        return chunk, n
    pad = target - n

    def pad_rows(a: np.ndarray) -> np.ndarray:
        widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
        return np.pad(a, widths)

    shards = {}
    for name, shard in chunk.shards.items():
        if isinstance(shard, SparseShard):
            shards[name] = SparseShard(
                pad_rows(shard.ids), pad_rows(shard.vals), shard.dim
            )
        else:
            shards[name] = DenseShard(pad_rows(shard.x))
    return dataclasses.replace(
        chunk,
        label=pad_rows(chunk.label),
        offset=pad_rows(chunk.offset),
        weight=pad_rows(chunk.weight),
        shards=shards,
        id_columns={
            c: np.concatenate([v, np.full(pad, v[0], v.dtype)])
            for c, v in chunk.id_columns.items()
        },
    ), n


def _run_streaming(args, model, index_maps, logger, session) -> dict:
    """File-at-a-time scoring: each part file becomes a chunk dataset indexed
    through the model's maps, is scored, and its features are dropped before
    the next file loads — the scoring analog of the legacy GLM driver's
    ``--stream`` (drivers/train.py; SURVEY.md §7 '1B-row ingestion').
    GAME *training* streams at the ingestion layer instead
    (game_io.read_game_avro's lazy CSR build).  Without --evaluators
    nothing but the incrementally-written scores.txt is retained; with them,
    the per-row (score, label, weight, entity ids) survive for the final
    metrics pass."""
    import jax.numpy as jnp

    from photon_tpu.core.losses import get_loss
    from photon_tpu.data.game_io import read_game_avro
    from photon_tpu.drivers.train_game import parse_bags_and_id_columns

    if args.input.startswith("synthetic-game:"):
        raise ValueError("--stream needs Avro part-file input")
    bags, id_cols = parse_bags_and_id_columns(args)

    scores_chunks, label_chunks, weight_chunks = [], [], []
    ids_chunks = {c: [] for c in id_cols}

    def load_chunk(path):
        chunk, _ = read_game_avro(
            path, bags, id_cols, index_maps=index_maps, telemetry=session
        )
        return chunk

    def score_chunk(chunk):
        padded, real_n = _pad_pow2_rows(chunk)
        raw = model.score(padded)[:real_n]
        out = raw
        if args.predict_mean:
            out = np.asarray(get_loss(model.task_type).mean(jnp.asarray(raw)))
        return raw, out, real_n

    def on_chunk(chunk, raw):
        if args.evaluators:
            scores_chunks.append(np.asarray(raw))
            label_chunks.append(chunk.label)
            weight_chunks.append(chunk.weight)
            for c in id_cols:
                ids_chunks[c].append(chunk.id_columns[c])

    n = common.stream_score_parts(
        args.input, load_chunk, score_chunk,
        os.path.join(args.output_dir, "scores.txt"), logger, on_chunk,
        telemetry=session,
    )
    session.gauge("score.num_scored").set(n)

    metrics = {}
    if args.evaluators:
        metrics = _evaluate_and_dump(
            args, logger,
            np.concatenate(scores_chunks),
            np.concatenate(label_chunks),
            np.concatenate(weight_chunks),
            {c: np.concatenate(v) for c, v in ids_chunks.items()},
            session=session,
        )
    return {"num_scored": n, "metrics": metrics, "streamed": True}


def _score_batch_dataset(model, data, logger, session) -> np.ndarray:
    """Non-streamed scoring through the serving gather-table build: the
    same :class:`~photon_tpu.serving.GameScorer` (device-resident fixed
    weights + per-entity gather tables, one compiled program for the
    dataset's padded shape) that the online service runs, so the batch and
    serving scoring paths cannot drift.  ``PHOTON_BATCH_SCORER=host``
    falls back to the host ``GameModel.score`` accumulation (float64 on
    host — the parity oracle the serving tests pin against)."""
    if os.environ.get("PHOTON_BATCH_SCORER", "device") == "host":
        logger.info("PHOTON_BATCH_SCORER=host: host scoring path")
        return model.score(data)
    from photon_tpu.serving import GameScorer, request_spec_for_dataset

    scorer = GameScorer(
        model,
        mesh=common.maybe_mesh(),
        request_spec=request_spec_for_dataset(model, data),
        telemetry=session,
    )
    return scorer.score_dataset(data)


def run(args: argparse.Namespace) -> dict:
    common.select_backend(args.backend)
    from photon_tpu.utils import PhotonLogger

    logger = PhotonLogger("photon_tpu.score_game", args.log_file)
    with common.telemetry_run(args, "score_game", logger) as session:
        return _run(args, logger, session)


def _run(args: argparse.Namespace, logger, session) -> dict:
    from photon_tpu.fault.injection import fault_point
    from photon_tpu.fault.retry import retry_call
    from photon_tpu.game.model_io import load_game_model

    os.makedirs(args.output_dir, exist_ok=True)

    with logger.timed("load-model"):
        # The model directory read spans many small files; a transient
        # storage error retries instead of killing the scoring run.
        model, index_maps = retry_call(
            lambda: load_game_model(args.model),
            site="model:load", telemetry=session, logger=logger,
        )
        logger.info(
            "model: %s, coordinates %s", model.task_type,
            list(model.coordinates),
        )

    if args.stream:
        return _run_streaming(args, model, index_maps, logger, session)

    with logger.timed("load-data"):
        # Index scoring features through the model's training-time maps —
        # unseen features drop, matching the reference's fixed-index
        # scoring.  The session rides along so the guarded Avro reads'
        # io.retries land in THIS run's report — the same fault/retry
        # visibility the train drivers have (the streamed path below
        # already plumbed it).
        data, _ = _load_game_data(
            args.input, args, index_maps=index_maps, telemetry=session
        )
        logger.info("scoring %d examples", data.num_examples)
        session.gauge("score.num_scored").set(data.num_examples)

    with logger.timed("score"):
        raw_scores = _score_batch_dataset(model, data, logger, session)
        if args.predict_mean:
            import jax.numpy as jnp

            from photon_tpu.core.losses import get_loss

            out_scores = np.asarray(
                get_loss(model.task_type).mean(jnp.asarray(raw_scores))
            )
        else:
            out_scores = raw_scores

    def _write_scores():
        # io:write fault window + retry, published ATOMICALLY: each attempt
        # writes a fresh temp file and renames it into place.  Plain
        # in-place rewrites would be retry-safe only for sequential
        # attempts — under a stall-timeout escalation the abandoned hung
        # attempt can unwedge later and keep writing, and two writers
        # interleaving into one truncated file is silent corruption.  With
        # per-attempt temps the late writer at worst re-publishes identical
        # complete content.
        import tempfile

        fault_point("io:write", path="scores.txt")
        fd, tmp = tempfile.mkstemp(
            prefix=".scores-", suffix=".tmp", dir=args.output_dir
        )
        try:
            with os.fdopen(fd, "w") as f:
                np.savetxt(f, out_scores, fmt="%.8g")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(args.output_dir, "scores.txt"))
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    retry_call(
        _write_scores, site="scores:write", telemetry=session, logger=logger
    )

    metrics = {}
    if args.evaluators:
        metrics = _evaluate_and_dump(
            args, logger, raw_scores, data.label, data.weight,
            dict(data.id_columns), session=session,
        )
    return {"num_scored": int(data.num_examples), "metrics": metrics}


def main(argv=None) -> None:
    run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
