"""Online GAME learning driver: continual training + zero-downtime refresh.

The data-in → model-out loop as a CLI (ISSUE 15): fit an initial GAME
model on ``--input``, stand up a serving fleet on it, then watch
``--append-dir`` for appended part files — each poll drains the backlog
through the online-learning service (in-place device-data growth,
warm-started partial refresh with untouched coordinates locked, canary
``rollout`` publish) and records the append→serving refresh latency.

    python -m photon_tpu.drivers.online_game \\
        --input train.avro --append-dir appends/ \\
        --feature-bags global=features,per_user=userFeatures \\
        --id-columns userId \\
        --coordinate fixed:type=fixed,shard=global \\
        --coordinate per_user:type=random,shard=per_user,entity=userId \\
        --task logistic_regression --replicas 2 \\
        --checkpoint-dir ckpt --output-dir out

The refresh loop is preemption-safe end to end with ``--checkpoint-dir``:
a killed refresh resumes exactly (``descent:kill`` → restart → the same
pending parts re-ingest, the round's descent checkpoint restores), and a
kill between train and publish (``online:refresh:kill``) republishes the
completed fit without retraining.  The final model and an
``online_summary.json`` (rounds, rows, latency distribution) land in
``--output-dir``; the telemetry run report carries the full
``## Online learning`` section.
"""

from __future__ import annotations

import argparse
import json
import os

import numpy as np

from photon_tpu.drivers import common
from photon_tpu.drivers.train_game import (
    _build_sweep,
    _coordinate_specs,
    _load_game_data,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "photon_tpu.drivers.online_game", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    common.add_common_args(p)
    p.add_argument("--input", required=True,
                   help="initial training data: Avro file/dir/glob or "
                   "synthetic-game spec (see train_game)")
    p.add_argument("--append-dir", required=True,
                   help="directory of appended part files (Avro), watched "
                   "by the online feed; the consumed cursor lives here")
    p.add_argument("--feature-bags", default=None)
    p.add_argument("--id-columns", default=None)
    p.add_argument("--task", default="logistic_regression")
    p.add_argument("--coordinate", action="append", required=True,
                   dest="coordinates", metavar="NAME:key=value,...",
                   help="coordinate spec (train_game grammar); exactly one "
                   "configuration — online refresh is not a sweep")
    p.add_argument("--initial-iterations", type=int, default=2,
                   help="outer descent iterations of the initial fit")
    p.add_argument("--refresh-iterations", type=int, default=2,
                   help="outer descent iterations per online refresh "
                   "(warm-started)")
    p.add_argument("--max-rounds", type=int, default=0,
                   help="stop after this many refresh rounds (0 = drain "
                   "the append directory once)")
    p.add_argument("--replicas", type=int, default=1,
                   help="serving replicas behind the fleet router")
    p.add_argument("--table-capacity-factor", type=int, default=2,
                   help="pre-provisioned serving-table headroom factor: "
                   "vocabulary growth hot-swaps in place until it outgrows "
                   "factor x the initial entity count (then pow2)")
    p.add_argument("--no-lock-untouched", action="store_true",
                   help="retrain every coordinate each refresh instead of "
                   "locking the ones the appended rows do not touch")
    p.add_argument("--rollout-parity-tol", type=float, default=1e-3,
                   help="canary parity gate of each publish")
    p.add_argument("--checkpoint-dir", default=None,
                   help="per-round descent checkpoints + the durable round "
                   "counter (preemption-safe refresh)")
    p.add_argument("--max-quarantined", type=int, default=8)
    p.add_argument("--seed", type=int, default=0)
    return p


def run(args: argparse.Namespace) -> dict:
    common.select_backend(args.backend)
    from photon_tpu.utils import PhotonLogger

    logger = PhotonLogger("photon_tpu.online_game", args.log_file)
    with common.telemetry_run(args, "online_game", logger) as session:
        return _run(args, logger, session)


def _run(args: argparse.Namespace, logger, session) -> dict:
    from photon_tpu.game.estimator import GameEstimator
    from photon_tpu.game.model_io import save_game_model
    from photon_tpu.online import (
        DirectoryFeed,
        OnlineLearningService,
        RefreshPolicy,
    )
    from photon_tpu.serving.fleet import ServingFleet
    from photon_tpu.serving.scorer import request_spec_for_dataset

    os.makedirs(args.output_dir, exist_ok=True)

    specs = _coordinate_specs(args)
    configurations = _build_sweep(specs, args.task)
    if len(configurations) != 1:
        raise ValueError(
            "online refresh takes exactly ONE configuration (no "
            "reg-weight sweeps); got "
            f"{len(configurations)} combinations"
        )
    _label, coords, _combo = configurations[0]
    from photon_tpu.game.estimator import GameOptimizationConfiguration

    config = GameOptimizationConfiguration(
        coordinates=coords,
        descent_iterations=args.initial_iterations,
        name="online",
    )

    with logger.timed("load-data"):
        data, index_maps = _load_game_data(
            args.input, args, telemetry=session
        )
        logger.info("initial training data: %d rows", data.num_examples)

    def load_part(path):
        return _load_game_data(
            path, args, index_maps=index_maps, telemetry=session
        )[0]

    feed = DirectoryFeed(
        args.append_dir, loader=load_part,
        telemetry=session, logger=logger,
    )
    # RESTART: parts already published by a previous run are skipped by
    # the feed's consumed cursor, but the merged training data itself is
    # not durable — re-merge them (sorted order, the original ingest
    # order) so the reconstructed dataset equals the killed run's.
    consumed = feed.consumed_sources()
    if consumed:
        from photon_tpu.online import merge_append

        n_before = data.num_examples
        column_filled = False
        with logger.timed("replay-consumed-parts"):
            for name in consumed:
                part = load_part(os.path.join(args.append_dir, name))
                data, absent = merge_append(data, part)
                column_filled = column_filled or any(
                    mask.any() for mask in absent.values()
                )
        logger.info(
            "restart: re-merged %d published part(s) (%d rows) into the "
            "training data", len(consumed), data.num_examples - n_before,
        )
        if column_filled:
            logger.warning(
                "restart: a published part omitted an id column; its "
                "missing-marker rows will form a marker entity in the "
                "rebuilt layouts (cold rebuilds have no absent-row mask)"
            )

    estimator = GameEstimator(
        args.task, data, telemetry=session, logger=logger
    )
    with logger.timed("initial-fit"):
        model = estimator.fit(
            [config], max_quarantined=args.max_quarantined
        )[0].model

    with logger.timed("build-fleet"):
        fleet = ServingFleet(
            model,
            replicas=args.replicas,
            request_spec=request_spec_for_dataset(model, data),
            telemetry=session,
            table_capacity_factor=args.table_capacity_factor,
        ).warmup()
        logger.info("fleet warm: %d replicas, %d programs",
                    args.replicas, fleet.compilations)

    service = OnlineLearningService(
        estimator, config, feed, model=model, fleet=fleet,
        checkpoint_dir=args.checkpoint_dir,
        policy=RefreshPolicy(
            refresh_iterations=args.refresh_iterations,
            lock_untouched=not args.no_lock_untouched,
            max_quarantined=args.max_quarantined,
            rollout_parity_tol=args.rollout_parity_tol,
        ),
        telemetry=session,
        logger=logger,
    )

    rounds = []
    try:
        with logger.timed("online-refresh"):
            while True:
                result = service.refresh_once()
                if result is None:
                    break
                rounds.append(result)
                if args.max_rounds and len(rounds) >= args.max_rounds:
                    break
    finally:
        fleet.close()

    model_dir = os.path.join(args.output_dir, "model")
    with logger.timed("save-model"):
        save_game_model(
            model_dir, service.model, index_maps or {}, telemetry=session
        )

    latencies = [r.latency_s for r in rounds]
    summary = {
        "rounds": len(rounds),
        "rows_ingested": int(sum(r.rows for r in rounds)),
        "coordinates": list(config.coordinates),
        "locked_per_round": [r.locked for r in rounds],
        "published": sum(1 for r in rounds if r.published),
        "replicas": args.replicas,
        "refresh_latency_s": {
            "mean": round(float(np.mean(latencies)), 4) if latencies else 0.0,
            "max": round(float(np.max(latencies)), 4) if latencies else 0.0,
        },
        "compiled_programs": fleet.compilations,
        "model_dir": model_dir,
    }
    with open(os.path.join(args.output_dir, "online_summary.json"), "w") as f:
        json.dump(summary, f, indent=1)
    logger.info(
        "online loop done: %d round(s), %d rows, mean refresh %.3fs",
        summary["rounds"], summary["rows_ingested"],
        summary["refresh_latency_s"]["mean"],
    )
    return summary


def main(argv=None) -> None:
    common.run_cli(run, build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
