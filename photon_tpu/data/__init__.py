"""Data layer: batches, readers (LIBSVM/Avro), index maps, GAME data pipeline.

Equivalent of the reference's data handling spread across
photon-lib .../data (LabeledPoint), photon-api .../data (GameDatum,
FixedEffectDataset, RandomEffectDataset), and photon-client .../data/avro
(AvroDataReader) — SURVEY.md §2.1–2.3 — redesigned for XLA: static-shape
padded batches instead of RDDs of sparse Breeze vectors.
"""

from photon_tpu.data.batch import DenseBatch, SparseBatch, margins  # noqa: F401
