"""LIBSVM text format reader.

Bench config (1) — "fixed-effect logistic GLM on a1a LIBSVM" — requires a
LIBSVM reader (SURVEY.md §6).  The reference reads Avro; LIBSVM support is a
rebuild addition driven by the benchmark configs.

Format per line: ``<label> <id>:<val> <id>:<val> ...`` with 1-based feature
ids (a1a convention).  Lines may carry a trailing ``# comment``.  Output is a
:class:`SparseBatch` with 0-based ids and optionally an appended intercept
feature at index ``dim`` (the reference adds the intercept as a feature via
its index map, so models stay a single coefficient vector).

A native C++ fast-path parser lives in :mod:`photon_tpu.native`; this module
falls back to pure Python when the shared library isn't built.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from photon_tpu.data.batch import SparseBatch, sparse_batch_from_rows


@dataclasses.dataclass
class LibsvmData:
    """Parsed LIBSVM file: ragged rows + labels, before padding/batching."""

    rows: list  # list[(np.ndarray ids, np.ndarray vals)]
    labels: np.ndarray
    dim: int  # number of features (0-based ids < dim), excluding intercept

    @property
    def num_examples(self) -> int:
        return len(self.rows)


def parse_libsvm(path: str, zero_based: bool = False) -> LibsvmData:
    """Parse a LIBSVM file (uses the native parser when available)."""
    try:
        from photon_tpu.native import libsvm_native

        parsed = libsvm_native.parse_file(path, zero_based)
        if parsed is not None:
            return LibsvmData(*parsed)
    except ImportError:
        pass
    return _parse_libsvm_py(path, zero_based)


def _parse_libsvm_py(path: str, zero_based: bool) -> LibsvmData:
    rows = []
    labels = []
    max_id = -1
    off = 0 if zero_based else 1
    with open(path, "rb") as f:
        for raw in f:
            line = raw.split(b"#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            labels.append(float(parts[0]))
            ids = np.empty(len(parts) - 1, np.int32)
            vals = np.empty(len(parts) - 1, np.float32)
            for j, tok in enumerate(parts[1:]):
                k, v = tok.split(b":")
                fid = int(k) - off
                if fid < 0 or fid > np.iinfo(np.int32).max:
                    # Same contract as the native parser: out-of-range ids
                    # are a parse error, never a silent int32 wraparound.
                    raise ValueError(
                        f"{path}: feature id {int(k)} out of int32 range "
                        f"(or below the {'0' if zero_based else '1'}-based minimum)"
                    )
                ids[j] = fid
                vals[j] = float(v)
            if len(ids):
                max_id = max(max_id, int(ids.max()))
            rows.append((ids, vals))
    return LibsvmData(rows=rows, labels=np.asarray(labels, np.float32), dim=max_id + 1)


def normalize_binary_labels(labels: np.ndarray) -> np.ndarray:
    """Map {-1,+1} (LIBSVM convention) or {0,1} labels to {0,1}."""
    out = labels.copy()
    out[out < 0] = 0.0
    return out


def parse_csr_or_none(path: str):
    """Native flat-CSR parse, or None when the native library is absent or
    cannot handle the file — malformed input still raises (ValueError), so
    bad files fail loudly instead of being re-parsed by the fallback just
    to fail again.  The ONE home of the fallback policy for CSR consumers
    (streaming chunk loads, metadata scans)."""
    try:
        from photon_tpu.native import libsvm_native

        return libsvm_native.parse_file_csr(path)
    except ValueError:
        raise
    except Exception:  # noqa: BLE001 — native unavailable: caller falls back
        return None


def load_sparse_batch(
    path: str,
    dim: int | None = None,
    intercept: bool = True,
    capacity: int | None = None,
    binary_labels: bool = True,
    max_feature_dim: int | None = None,
) -> tuple["SparseBatch", int, int]:
    """Parse + pad one LIBSVM file: ``(batch, total_dim, raw_dim)``.

    THE one home of the flat-CSR-or-rows branch: tries the native CSR fast
    path (no per-row materialization) and falls back to the rows-based
    builder when the native library is absent; both produce byte-identical
    batches.  ``raw_dim`` is the file's feature dimension before the
    intercept column (callers build index maps from it).

    ``max_feature_dim`` raises ValueError BEFORE padding when the file's
    raw dimension exceeds it — validation loads reject oversized files
    without paying the pad + device transfer for a batch they discard."""

    def _check(raw_dim: int) -> None:
        if max_feature_dim is not None and raw_dim > max_feature_dim:
            raise ValueError(
                f"{path}: feature id {raw_dim - 1} >= dim {max_feature_dim}"
            )

    csr = parse_csr_or_none(path)
    if csr is not None:
        labels, row_ptr, flat_ids, flat_vals, raw_dim = csr
        _check(raw_dim)
        batch, total_dim = csr_to_sparse_batch(
            labels, row_ptr, flat_ids, flat_vals,
            dim=raw_dim if dim is None else dim,
            intercept=intercept, capacity=capacity,
            binary_labels=binary_labels,
        )
        return batch, total_dim, raw_dim
    data = parse_libsvm(path)
    _check(data.dim)
    batch, total_dim = to_sparse_batch(
        data, dim=dim, intercept=intercept, capacity=capacity,
        binary_labels=binary_labels,
    )
    return batch, total_dim, data.dim


def csr_to_sparse_batch(
    labels: np.ndarray,
    row_ptr: np.ndarray,
    flat_ids: np.ndarray,
    flat_vals: np.ndarray,
    dim: int | None = None,
    intercept: bool = True,
    capacity: int | None = None,
    binary_labels: bool = True,
) -> tuple["SparseBatch", int]:
    """Vectorized flat-CSR -> padded SparseBatch (the hot streaming path;
    byte-identical output to :func:`to_sparse_batch` over the same rows,
    without materializing n per-row arrays).

    ``dim`` is the feature dimension BEFORE the intercept column; defaults
    to ``flat_ids.max() + 1``.  ``capacity`` counts the intercept slot when
    ``intercept=True``, exactly like the rows-based builder.
    """
    import jax.numpy as jnp

    from photon_tpu.data.batch import pad_row_capacity

    n = int(row_ptr.shape[0]) - 1
    d = int(dim) if dim is not None else (
        int(flat_ids.max()) + 1 if flat_ids.size else 0
    )
    nnz = np.diff(row_ptr)
    k_row = nnz + (1 if intercept else 0)
    k = capacity if capacity is not None else pad_row_capacity(k_row)
    if n and int(k_row.max()) > k:
        raise ValueError(
            f"row with {int(k_row.max())} nonzeros exceeds capacity {k}; "
            f"raise `capacity` instead of truncating features"
        )
    ids = np.zeros((n, k), dtype=np.int32)
    vals = np.zeros((n, k), dtype=np.float32)
    if flat_ids.size:
        row_of = np.repeat(np.arange(n, dtype=np.int64), nnz)
        within = np.arange(flat_ids.size, dtype=np.int64) - np.repeat(
            row_ptr[:-1], nnz
        )
        ids[row_of, within] = flat_ids
        vals[row_of, within] = flat_vals
    if intercept and n:
        rows_idx = np.arange(n, dtype=np.int64)
        ids[rows_idx, nnz] = d
        vals[rows_idx, nnz] = 1.0
    out_labels = normalize_binary_labels(labels) if binary_labels else labels
    batch = SparseBatch(
        ids=jnp.asarray(ids),
        vals=jnp.asarray(vals),
        label=jnp.asarray(np.asarray(out_labels, np.float32)),
        offset=jnp.zeros(n, jnp.float32),
        weight=jnp.ones(n, jnp.float32),
    )
    return batch, d + (1 if intercept else 0)


def to_sparse_batch(
    data: LibsvmData,
    dim: int | None = None,
    intercept: bool = True,
    capacity: int | None = None,
    binary_labels: bool = True,
) -> tuple[SparseBatch, int]:
    """Pad rows into a SparseBatch; returns (batch, total_dim).

    With ``intercept=True`` a constant-1 feature is appended at index
    ``dim`` (so ``total_dim = dim + 1``), matching the reference's
    intercept-as-feature design.
    """
    d = dim if dim is not None else data.dim
    rows = data.rows
    if intercept:
        rows = [
            (np.append(ids, np.int32(d)), np.append(vals, np.float32(1.0)))
            for ids, vals in rows
        ]
    labels = normalize_binary_labels(data.labels) if binary_labels else data.labels
    batch = sparse_batch_from_rows(rows, labels, capacity=capacity)
    return batch, d + (1 if intercept else 0)
