"""Static-shape training batches.

The reference represents a training point as ``data.LabeledPoint(label,
features: Breeze vector, offset, weight)`` (photon-lib .../data/LabeledPoint —
SURVEY.md §2.1) and streams RDD partitions of them through per-partition
aggregators.  XLA wants static shapes and batched math instead, so the rebuild
uses two batch layouts:

- :class:`DenseBatch` — ``x: [n, d]`` feature matrix.  Right layout for
  low/moderate-dimensional problems; margins are a single MXU matmul.
- :class:`SparseBatch` — padded COO-per-row layout ``ids/vals: [n, k]`` with a
  fixed per-row capacity ``k`` (pad with ``id=0, val=0``).  Margins are a
  gather + row-sum; gradients come out of ``jax.grad`` as scatter-adds.  This
  replaces Breeze ``SparseVector`` + BLAS ``dot``/``axpy`` with one fused XLA
  program, and keeps shapes static for the compiler (SURVEY.md §7 "sparse
  features on TPU").

Both carry ``label``, ``offset`` (GAME residual-passing depends on it), and
``weight`` exactly like ``LabeledPoint``.

The padding convention ``id=0, val=0.0`` makes padded entries contribute
``w[0] * 0.0 = 0`` to margins and zero to scatter-add gradients, so no masks
are needed in the hot loop.
"""

from __future__ import annotations

import os
from typing import NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


class FeatureMajorAux(NamedTuple):
    """Static feature-major (sorted-by-feature-id) view of a batch's entries.

    The production gradient of a sparse GLM is a scatter-add of per-entry
    contributions into the coefficient vector; XLA lowers an unsorted
    scatter-add on TPU as sort + segmented reduce, paying an O(E log E)
    device sort on EVERY objective evaluation.  The sparsity pattern is
    static across a whole optimizer run (the reference exploits the same
    invariant by pre-building per-partition aggregator layouts — SURVEY.md
    §3.4), so the sort is done ONCE host-side at batch build and the runtime
    reduction becomes ``segment_sum(..., indices_are_sorted=True)``.

    All arrays are ``[S, E_s]`` where ``S`` is the number of contiguous
    row blocks (1 for single-device batches; the mesh axis size for sharded
    batches, so that sharding on the leading axis gives every device its own
    block-local sorted view) and ``E_s = rows_per_block * k``:

    - ``ids``: int32 feature ids, non-decreasing within each block.
    - ``rows``: int32 BLOCK-LOCAL source row of each entry.
    - ``vals``: float entry values — float32, or the storage dtype set by
      :func:`batch_astype` (0.0 for the row-padding entries, which therefore
      contribute nothing, same convention as SparseBatch).
    """

    ids: Array
    rows: Array
    vals: Array


class DenseBatch(NamedTuple):
    """A batch of examples with dense features."""

    x: Array  # [n, d] float
    label: Array  # [n] float
    offset: Array  # [n] float
    weight: Array  # [n] float

    @property
    def num_examples(self) -> int:
        return self.x.shape[0]

    @property
    def dim(self) -> int:
        return self.x.shape[1]


class SparseBatch(NamedTuple):
    """A batch of examples with padded sparse features.

    ``ids[i, j]`` / ``vals[i, j]`` give the j-th nonzero of example i; rows
    with fewer than ``k`` nonzeros are padded with ``(0, 0.0)``.

    ``fm`` optionally carries the static feature-major entry layout
    (:class:`FeatureMajorAux`, built by :func:`attach_feature_major`); when
    present, objectives compute gradients via a pre-sorted segment sum
    instead of an unsorted scatter — see
    :meth:`photon_tpu.core.objective.GlmObjective.value_and_grad`.
    """

    ids: Array  # [n, k] int32
    vals: Array  # [n, k] float
    label: Array  # [n] float
    offset: Array  # [n] float
    weight: Array  # [n] float
    fm: Optional[FeatureMajorAux] = None
    # Optional slab-aligned layout (ops/pallas_gather.AlignedLayoutDev) for
    # the Pallas gradient kernel; attach with
    # ``attach_feature_major(..., aligned_dim=d)``.  Single-block batches
    # only (each shard of a distributed batch builds its own).
    al: Optional["object"] = None
    # Optional TRANSPOSED aligned layout (rows as the slab dictionary) for
    # the Pallas FORWARD (margins) direction; attach with
    # ``attach_feature_major(..., aligned_dim=d, aligned_forward=True)``.
    al_t: Optional["object"] = None
    # Optional static Clos routing (ops/benes.BenesAux) for the `benes`
    # kernel — value/grad/Hv with no random E-element access; built by
    # ``attach_feature_major(..., aligned_dim=d)`` when
    # ``PHOTON_SPARSE_GRAD=benes``.  Requires ``al``.
    benes: Optional["object"] = None
    # Optional vperm routing (ops/vperm.VpermRoute) for the `xchg` kernel:
    # row-major products ride a 3-pass static permutation into aligned
    # slot order instead of the per-step E-element XLA gather.  Built by
    # ``attach_feature_major(..., aligned_dim=d)`` when
    # ``PHOTON_SPARSE_GRAD`` is ``xchg`` or ``auto``.  Requires ``al``
    # (and uses ``al_t`` for margins when present).
    xchg: Optional["object"] = None

    @property
    def num_examples(self) -> int:
        return self.ids.shape[0]


Batch = Union[DenseBatch, SparseBatch]


def margins(w: Array, batch: Batch) -> Array:
    """Per-example margins ``w . x_i + offset_i``.

    The rebuild's equivalent of the reference aggregators' per-example
    ``margin = dot(coefficients, features) + offset`` inner loop
    (ValueAndGradientAggregator — SURVEY.md §3.4), batched.
    Supports a leading batch dimension on ``w`` being absent only; use vmap
    for batched models.
    """
    if isinstance(batch, DenseBatch):
        return batch.x @ w + batch.offset
    # Gather-based sparse dot: padded entries hit w[0] with val 0.
    return jnp.sum(jnp.take(w, batch.ids, axis=0) * batch.vals, axis=-1) + batch.offset


def dense_batch(
    x: np.ndarray,
    label: np.ndarray,
    offset: np.ndarray | None = None,
    weight: np.ndarray | None = None,
    dtype=jnp.float32,
) -> DenseBatch:
    n = x.shape[0]
    return DenseBatch(
        x=jnp.asarray(x, dtype),
        label=jnp.asarray(label, dtype),
        offset=jnp.zeros(n, dtype) if offset is None else jnp.asarray(offset, dtype),
        weight=jnp.ones(n, dtype) if weight is None else jnp.asarray(weight, dtype),
    )


def pad_row_capacity(nnz_per_row: np.ndarray, bucket_sizes: tuple[int, ...] | None = None) -> int:
    """Pick the padded per-row capacity k: smallest power-of-two-ish bucket
    >= max nnz, so recompiles are bounded across batches."""
    max_nnz = int(nnz_per_row.max()) if len(nnz_per_row) else 1
    if bucket_sizes is None:
        k = 1
        while k < max_nnz:
            k *= 2
        return k
    for b in bucket_sizes:
        if b >= max_nnz:
            return b
    raise ValueError(
        f"max nnz per row ({max_nnz}) exceeds the largest capacity bucket "
        f"({bucket_sizes[-1]}); truncating would silently drop features"
    )


def sparse_batch_from_rows(
    rows: list[tuple[np.ndarray, np.ndarray]],
    label: np.ndarray,
    offset: np.ndarray | None = None,
    weight: np.ndarray | None = None,
    capacity: int | None = None,
    dtype=jnp.float32,
) -> SparseBatch:
    """Build a SparseBatch from per-row (ids, vals) arrays, padding to a fixed
    capacity (power-of-two bucket by default).

    Raises if any row has more nonzeros than the capacity — silently dropping
    features would corrupt margins/gradients with no diagnostic.
    """
    n = len(rows)
    nnz = np.array([len(ids) for ids, _ in rows], dtype=np.int64)
    k = capacity if capacity is not None else pad_row_capacity(nnz)
    if len(nnz) and int(nnz.max()) > k:
        raise ValueError(
            f"row with {int(nnz.max())} nonzeros exceeds capacity {k}; "
            f"raise `capacity` instead of truncating features"
        )
    ids = np.zeros((n, k), dtype=np.int32)
    vals = np.zeros((n, k), dtype=np.float32)
    for i, (r_ids, r_vals) in enumerate(rows):
        m = len(r_ids)
        ids[i, :m] = r_ids
        vals[i, :m] = r_vals
    return SparseBatch(
        ids=jnp.asarray(ids),
        vals=jnp.asarray(vals, dtype),
        label=jnp.asarray(label, dtype),
        offset=jnp.zeros(n, dtype) if offset is None else jnp.asarray(offset, dtype),
        weight=jnp.ones(n, dtype) if weight is None else jnp.asarray(weight, dtype),
    )


def with_offset(batch: Batch, offset: Array) -> Batch:
    """Return the batch with its offset column replaced (GAME residual passing)."""
    return batch._replace(offset=offset)


def attach_feature_major(
    batch: SparseBatch,
    shards: int = 1,
    aligned_dim: int | None = None,
    aligned_forward: bool | None = None,
    geometry_gather=None,
    global_entries: int | None = None,
) -> SparseBatch:
    """Attach the static feature-major layout (:class:`FeatureMajorAux`).

    Host-side: one stable argsort of the flat entries per row block — run
    once per dataset, amortized over every optimizer iteration (the runtime
    win is deleting the per-evaluation device sort inside XLA's scatter
    lowering; see FeatureMajorAux).  ``shards`` must match the mesh data-axis
    size the batch will be sharded over (1 for single-device use); rows are
    split into ``shards`` contiguous blocks, mirroring
    :func:`photon_tpu.parallel.mesh.shard_batch` placement.

    With ``aligned_dim`` (the coefficient dimension) the slab-aligned layout
    for the Pallas gradient kernel is ALSO built and attached (``batch.al``),
    making the batch eligible for the third kernel of
    ops/sparse_grad_select.  With ``shards > 1`` every row block gets its
    OWN layout (block-local rows) and the per-block layouts are padded to
    a common geometry and stacked on a leading shard axis, so sharding
    the batch on that axis hands each device exactly its block's layout
    (VERDICT r5 item 2 — the fast kernels must run under the sharded
    objective; squeeze + dispatch happen in parallel/distributed.py).
    The same applies to the xchg exchange routes: every shard's route is
    built with the SHARED balanced-block geometry (max census across
    shards) or all shards fall back to the colored route together, so
    the stacked route pytree has one uniform treedef.

    ``aligned_forward`` additionally builds the transposed (row-dictionary)
    layout so the Pallas path computes MARGINS through the same kernel
    (``batch.al_t``) — costs a second layout's host build and device
    memory, so it defaults to the ``PHOTON_SPARSE_MARGIN=pallas`` env
    opt-in.
    """
    if not isinstance(batch, SparseBatch) or batch.ids.ndim != 2:
        raise ValueError("feature-major layout requires a 2-D SparseBatch")
    n, k = batch.ids.shape
    if n % shards:
        raise ValueError(f"rows ({n}) not divisible by shards ({shards}); pad first")
    ns = n // shards
    ids = np.asarray(batch.ids).reshape(shards, ns * k)
    vals = np.asarray(batch.vals).reshape(shards, ns * k)
    rows = np.broadcast_to(
        np.repeat(np.arange(ns, dtype=np.int32), k), (shards, ns * k)
    )
    order = np.argsort(ids, axis=1, kind="stable")
    take = np.take_along_axis
    batch = batch._replace(fm=FeatureMajorAux(
        ids=jnp.asarray(take(ids, order, axis=1)),
        rows=jnp.asarray(take(rows, order, axis=1)),
        vals=jnp.asarray(take(vals, order, axis=1)),
    ))
    if aligned_forward and aligned_dim is None:
        raise ValueError(
            "aligned_forward requires aligned_dim (the transposed layout "
            "only serves the pallas kernel, which needs the aligned "
            "gradient layout too)"
        )
    if aligned_dim is not None:
        from photon_tpu.ops.pallas_gather import (
            device_layout,
            load_or_build_aligned_layout,
        )

        from photon_tpu.ops.sparse_grad_select import xchg_route_wanted

        ids_np = np.asarray(batch.ids)
        vals_np = np.asarray(batch.vals, np.float32)
        # Size floors judge the GLOBAL problem (the kernels run at global
        # scale): a multi-process assembly passes the allgathered total
        # so four processes sharing a big batch don't each fall below a
        # local floor and silently lose the route everywhere.
        want_xchg = xchg_route_wanted(global_entries or (n * k))
        if aligned_forward is None:
            # xchg implies the pallas forward: its whole point is deleting
            # the E-element gathers, and XLA margins would reintroduce one.
            aligned_forward = want_xchg or (
                os.environ.get("PHOTON_SPARSE_MARGIN", "xla") == "pallas"
            )
        if shards != 1 or geometry_gather is not None:
            # A geometry gather forces the STACKED form even for one
            # local shard: a multi-process assembly needs every process's
            # aux to carry the leading shard axis (and to agree on the
            # globally-gathered geometry) so the per-process arrays
            # concatenate into one global sharded pytree.
            if os.environ.get("PHOTON_SPARSE_GRAD", "auto") == "benes":
                # Before the expensive per-shard build: rejecting after it
                # would waste the costliest host work in the package.
                raise ValueError(
                    "the benes research kernel is single-shard only"
                )
            return _attach_aligned_sharded(
                batch, ids_np, vals_np, aligned_dim, shards,
                aligned_forward=bool(aligned_forward),
                want_xchg=want_xchg, order=order,
                geometry_gather=geometry_gather,
            )
        from photon_tpu.ops.pallas_gather import layout_content_hash

        base_hash = layout_content_hash(ids_np, vals_np)
        layout = load_or_build_aligned_layout(
            ids_np, vals_np, aligned_dim, base_hash=base_hash
        )
        batch = batch._replace(al=device_layout(layout))
        if aligned_forward:
            batch = batch._replace(al_t=device_layout(
                load_or_build_aligned_layout(
                    ids_np, vals_np, aligned_dim, transposed=True,
                    base_hash=base_hash,
                )
            ))
        if want_xchg:
            from photon_tpu.ops.vperm import build_xchg_aux

            # shards == 1 here, so order[0] is the flat-stream stable
            # argsort the fm aux already paid for.
            batch = batch._replace(
                xchg=build_xchg_aux(
                    layout, ids_np, aligned_dim, order=order[0],
                    vals=vals_np,
                )
            )
        if os.environ.get("PHOTON_SPARSE_GRAD", "auto") == "benes":
            # Explicit opt-in only: the routing (host edge-coloring) is the
            # most expensive layout build in the package; auto mode never
            # pays it speculatively.
            from photon_tpu.ops.benes import build_benes_aux

            batch = batch._replace(
                benes=build_benes_aux(layout, n, k)
            )
    return batch


def _attach_aligned_sharded(
    batch: SparseBatch,
    ids_np: np.ndarray,
    vals_np: np.ndarray,
    aligned_dim: int,
    shards: int,
    aligned_forward: bool,
    want_xchg: bool,
    order: np.ndarray,
    geometry_gather=None,
) -> SparseBatch:
    """Per-shard aligned layouts (+ optional transposed layouts and xchg
    routes), padded to common geometry and stacked on a leading shard
    axis (VERDICT r5 item 2).

    Every shard's arrays must stack into ONE pytree with ONE treedef, so:

    - aligned layouts pad to the max (slabs, tiles) across shards
      (ops/pallas_gather.stack_device_layouts);
    - xchg balanced routes are built with the SHARED max block census
      (``blk_override``), or — when any shard's data defeats the
      balanced form — every shard takes the colored route together
      (``force_colored``); route meta is asserted uniform before
      stacking, and on any mismatch the xchg aux is dropped (the batch
      still carries fm + aligned, so training routes to the next-best
      kernel instead of failing).

    ``geometry_gather(local [S, 4] int64) -> global [S_total, 4]``
    widens the geometry agreement beyond this call's shards — the
    multi-process assembly (data/streaming.make_global_batch) passes a
    process-allgather so every process pads to ONE global geometry and
    the per-process stacked leaves concatenate into one sharded global
    array.  Columns: (n_slabs, n_tiles, al_t n_slabs, al_t n_tiles) for
    the layout phase; (census|-1, 0, 0, 0) for the route phase.
    Default: identity (single-process attach).
    """
    import logging

    from photon_tpu.ops.pallas_gather import (
        load_or_build_aligned_layout,
        pad_aligned_layout,
        stack_device_layouts,
    )

    if geometry_gather is None:
        geometry_gather = lambda arr: arr  # noqa: E731 — identity
    n, k = ids_np.shape
    ns = n // shards
    ids_blocks = ids_np.reshape(shards, ns, k)
    vals_blocks = vals_np.reshape(shards, ns, k)
    from photon_tpu.ops.pallas_gather import layout_content_hash

    base_hashes = [
        layout_content_hash(ids_blocks[s], vals_blocks[s])
        for s in range(shards)
    ]
    layouts = [
        load_or_build_aligned_layout(
            ids_blocks[s], vals_blocks[s], aligned_dim,
            base_hash=base_hashes[s],
        )
        for s in range(shards)
    ]
    layouts_t = (
        [
            load_or_build_aligned_layout(
                ids_blocks[s], vals_blocks[s], aligned_dim,
                transposed=True, base_hash=base_hashes[s],
            )
            for s in range(shards)
        ]
        if aligned_forward else None
    )
    geo_local = np.asarray([
        [
            layouts[s].n_slabs, layouts[s].n_tiles,
            layouts_t[s].n_slabs if layouts_t else 0,
            layouts_t[s].n_tiles if layouts_t else 0,
        ]
        for s in range(shards)
    ], np.int64)
    from photon_tpu.ops.pallas_gather import common_layout_geometry_arr

    geo = np.asarray(geometry_gather(geo_local), np.int64)
    s_tgt, t_tgt = common_layout_geometry_arr(geo[:, :2])
    # Pad FIRST, then build routes against the padded layouts: the
    # aligned-mode exchange's destination is the slot stream, whose
    # length must be uniform across shards for the routes to stack.
    layouts = [pad_aligned_layout(l, s_tgt, t_tgt) for l in layouts]
    batch = batch._replace(al=stack_device_layouts(layouts))
    if aligned_forward:
        st, tt = common_layout_geometry_arr(geo[:, 2:])
        batch = batch._replace(al_t=stack_device_layouts(
            [pad_aligned_layout(l, st, tt) for l in layouts_t]
        ))
    if not want_xchg:
        return batch
    import jax
    import os

    from photon_tpu.ops.vperm import balanced_blk_census, build_xchg_aux

    mode = os.environ.get("PHOTON_XCHG_REDUCE", "aligned")
    e_s = ns * k
    censuses = []
    for s in range(shards):
        if mode == "cumsum":
            dest_src = order[s]
        else:
            dest_src = layouts[s].src.reshape(-1)
        censuses.append(balanced_blk_census(dest_src, e_s, k))
    census_local = np.asarray([
        [-1 if c is None else c, 0, 0, 0] for c in censuses
    ], np.int64)
    census_all = np.asarray(geometry_gather(census_local), np.int64)[:, 0]
    force_colored = bool((census_all < 0).any())
    blk_override = None if force_colored else int(census_all.max())
    auxes = [
        build_xchg_aux(
            layouts[s], ids_blocks[s], aligned_dim, order=order[s],
            vals=vals_blocks[s], blk_override=blk_override,
            force_colored=force_colored,
        )
        for s in range(shards)
    ]
    defs = {jax.tree.structure(a) for a in auxes}
    # Route KIND (2=balanced, 1=colored — _aux_to_npz codes) must match
    # across ALL shards globally, and the drop decision must be agreed
    # globally too: one process keeping the aux while another drops it
    # would give the hosts different program pytrees (hang, not
    # fallback).  Same gather as the geometry negotiation.
    from photon_tpu.ops.vperm import BalancedRoute

    kind = 2 if isinstance(auxes[0].route, BalancedRoute) else 1
    verdict_local = np.asarray(
        [[1 if len(defs) != 1 else 0, kind, 0, 0]], np.int64
    )
    verdict = np.asarray(geometry_gather(verdict_local), np.int64)
    drop = bool(verdict[:, 0].any()) or len(set(
        verdict[:, 1].tolist()
    )) != 1
    if drop:
        logging.getLogger("photon_tpu.batch").warning(
            "per-shard xchg routes came out with mismatched geometry "
            "(locally %d distinct treedefs; global kinds %s); dropping "
            "the xchg aux everywhere — training will route to the "
            "pallas/fm kernels instead",
            len(defs), sorted(set(verdict[:, 1].tolist())),
        )
        return batch
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *auxes)
    return batch._replace(xchg=stacked)


def batch_astype(batch: Batch, dtype) -> Batch:
    """Re-store the batch's FEATURE VALUES in ``dtype`` (e.g. bfloat16).

    TPU-first storage option: feature values are the second-largest stream
    the sparse hot loop reads (after int32 ids), and GLM margins/gradients
    are insensitive to feature-value precision at bf16 scale — all
    arithmetic still happens in float32 via JAX type promotion (coefficients,
    labels, offsets, weights, and every reduction stay f32; only the stored
    values shrink).  The reference has no analog: Breeze vectors are f64.
    """
    import dataclasses

    dtype = jnp.dtype(dtype)
    if isinstance(batch, DenseBatch):
        return batch._replace(x=batch.x.astype(dtype))
    out = batch._replace(vals=batch.vals.astype(dtype))
    if out.fm is not None:
        out = out._replace(fm=out.fm._replace(vals=out.fm.vals.astype(dtype)))
    for aux in ("al", "al_t"):
        lay = getattr(out, aux)
        if lay is not None:
            out = out._replace(**{
                aux: dataclasses.replace(lay, vals=lay.vals.astype(dtype))
            })
    if out.xchg is not None and getattr(out.xchg, "vals_dest", None) is not None:
        # The baked destination stream was permuted from the
        # PRE-conversion values; left untouched, gradients would read
        # different values than the margins (the objective and its
        # gradient must see ONE value stream).  Elementwise casts
        # commute with the static permutation (pads stay zero), so
        # converting the baked stream in place keeps it exactly equal
        # to permute(converted vals) — preserving the fused dz-expansion
        # fast path, working directly on stacked sharded arrays, and
        # keeping vals_fp valid (its guard's loose rtol exists for this
        # conversion).
        out = out._replace(xchg=dataclasses.replace(
            out.xchg, vals_dest=out.xchg.vals_dest.astype(dtype)
        ))
    return out


def pad_batch(batch: Batch, target_n: int) -> Batch:
    """Pad a batch to ``target_n`` examples with zero-weight rows (so padded
    rows contribute nothing to any weighted objective or evaluator)."""
    n = batch.num_examples
    if n == target_n:
        return batch
    if n > target_n:
        raise ValueError(f"batch has {n} rows > target {target_n}")
    pad = target_n - n

    def _pad(a: Array) -> Array:
        widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
        if isinstance(a, jax.Array):
            return jnp.pad(a, widths)
        # Host leaves pad on host: a row-capacity rebuild at a new true
        # row count then uploads at the (unchanged) padded shape and
        # compiles nothing — the point of the capacity headroom.
        return np.pad(np.asarray(a), widths)

    # The feature-major / aligned / routing auxes are row-count- and
    # block-structure-dependent; padding per-leaf would corrupt them (the
    # vperm index planes most destructively).  Strip them (padded rows
    # carry only zero-value entries, so an aux rebuilt after padding is
    # equivalent) and let the caller re-attach at the final row count.
    for aux in ("fm", "al", "al_t", "benes", "xchg"):
        if getattr(batch, aux, None) is not None:
            batch = batch._replace(**{aux: None})
    return jax.tree.map(_pad, batch)
