"""Deterministic synthetic data generators for tests and benchmarks.

Rebuild of the reference's test-data generators (photon-test-utils
CommonTestUtils/GameTestUtils — SURVEY.md §4): seeded generators for GLM
training sets and GAME (fixed + per-entity random effect) datasets, so tests
and benchmarks are reproducible without fixture files.
"""

from __future__ import annotations

import numpy as np

from photon_tpu.data.batch import DenseBatch, dense_batch


def make_glm_data(
    n: int,
    dim: int,
    task: str = "logistic_regression",
    seed: int = 0,
    noise: float = 0.1,
    intercept: bool = True,
    density: float = 1.0,
    weight_seed: int | None = None,
) -> tuple[DenseBatch, np.ndarray]:
    """Synthetic GLM data with known true weights; returns (batch, w_true).

    With ``intercept=True`` the final feature column is constant 1.
    ``weight_seed`` fixes the true weights independently of ``seed`` so
    train/validation splits can share a model while drawing different rows.
    """
    rng = np.random.default_rng(seed)
    d_raw = dim - 1 if intercept else dim
    x = rng.normal(size=(n, d_raw)).astype(np.float32)
    if density < 1.0:
        x *= rng.random((n, d_raw)) < density
    if intercept:
        x = np.concatenate([x, np.ones((n, 1), np.float32)], axis=1)
    w_rng = rng if weight_seed is None else np.random.default_rng(weight_seed)
    w_true = (w_rng.normal(size=dim) * 0.5).astype(np.float32)
    z = x @ w_true
    if task == "logistic_regression":
        p = 1.0 / (1.0 + np.exp(-z))
        y = (rng.random(n) < p).astype(np.float32)
    elif task == "linear_regression":
        y = (z + noise * rng.normal(size=n)).astype(np.float32)
    elif task == "poisson_regression":
        y = rng.poisson(np.exp(np.clip(z, -8, 8))).astype(np.float32)
    elif task == "smoothed_hinge_loss_linear_svm":
        y = (z + noise * rng.normal(size=n) > 0).astype(np.float32)
    else:
        raise KeyError(f"unknown task {task!r}")
    return dense_batch(x, y), w_true


def make_game_data(
    n_entities: int,
    rows_per_entity_mean: int,
    fixed_dim: int,
    random_dim: int,
    seed: int = 0,
    n_random_coords: int = 1,
):
    """Synthetic GAME data: global fixed effect + per-entity random effects.

    Returns a dict with dense feature blocks, labels, and per-coordinate
    entity ids — the host-side precursor the GAME data pipeline buckets.
    Row counts per entity are skewed (geometric-ish) to exercise the
    ragged-bucketing path (SURVEY.md §7 'hard parts').
    """
    rng = np.random.default_rng(seed)
    counts = np.maximum(1, rng.geometric(1.0 / rows_per_entity_mean, n_entities))
    n = int(counts.sum())
    x_fixed = rng.normal(size=(n, fixed_dim)).astype(np.float32)
    x_fixed[:, -1] = 1.0  # intercept
    w_fixed = (rng.normal(size=fixed_dim) * 0.5).astype(np.float32)
    z = x_fixed @ w_fixed

    entity_ids = {}
    x_random = {}
    for c in range(n_random_coords):
        ids = np.repeat(np.arange(n_entities), counts)
        perm = rng.permutation(n) if c > 0 else np.arange(n)
        ids = ids[perm]
        entity_ids[f"re{c}"] = ids.astype(np.int64)
        xr = rng.normal(size=(n, random_dim)).astype(np.float32)
        xr[:, -1] = 1.0
        x_random[f"re{c}"] = xr
        w_re = (rng.normal(size=(n_entities, random_dim)) * 0.5).astype(np.float32)
        z = z + np.sum(xr * w_re[ids], axis=1)

    p = 1.0 / (1.0 + np.exp(-z))
    y = (rng.random(n) < p).astype(np.float32)
    return {
        "x_fixed": x_fixed,
        "x_random": x_random,
        "entity_ids": entity_ids,
        "label": y,
        "weight": np.ones(n, np.float32),
        "n_entities": n_entities,
    }


def make_game_dataset(
    n_entities: int,
    rows_per_entity_mean: int,
    fixed_dim: int,
    random_dim: int,
    seed: int = 0,
    n_random_coords: int = 1,
):
    """GAME data as a ready-to-train ``GameDataset`` + per-shard index maps.

    Shards: ``"global"`` (fixed effect) and ``"re0"``/``"re1"``… (one per
    random coordinate, with a same-named entity-id column).  The last column
    of every shard is the intercept, matching each shard's index map.
    """
    from photon_tpu.data.index_map import IndexMap, feature_key
    from photon_tpu.game.data import DenseShard, GameDataset

    raw = make_game_data(
        n_entities, rows_per_entity_mean, fixed_dim, random_dim,
        seed=seed, n_random_coords=n_random_coords,
    )

    def imap_for(dim: int) -> IndexMap:
        return IndexMap.build(
            [feature_key(f"f{i}") for i in range(dim - 1)], intercept=True
        )

    shards = {"global": DenseShard(raw["x_fixed"])}
    index_maps = {"global": imap_for(fixed_dim)}
    id_columns = {}
    for name, ids in raw["entity_ids"].items():
        shards[name] = DenseShard(raw["x_random"][name])
        index_maps[name] = imap_for(random_dim)
        id_columns[name] = ids
    data = GameDataset.create(raw["label"], shards, id_columns=id_columns)
    return data, index_maps


def write_libsvm(path: str, batch_x: np.ndarray, labels: np.ndarray) -> None:
    """Write a dense matrix as LIBSVM text (1-based ids, skipping zeros)."""
    with open(path, "w") as f:
        for i in range(batch_x.shape[0]):
            row = batch_x[i]
            toks = [f"{int(labels[i]) if labels[i] in (0, 1, -1) else labels[i]}"]
            for j in np.nonzero(row)[0]:
                toks.append(f"{j + 1}:{row[j]:.6g}")
            f.write(" ".join(toks) + "\n")
