"""Feature index maps: (name, term) <-> integer id.

Rebuild of the reference's index-map stack (photon-client .../index:
``IndexMap``, ``DefaultIndexMap``, ``PalDBIndexMap`` + the
``FeatureIndexingJob`` that builds them — SURVEY.md §2.3).  The reference
needs an off-heap PalDB store because JVM driver memory is the constraint;
here a plain dict + numpy arrays with an mmap-able on-disk layout covers the
same sizes on a host with normal memory, and ids only ever reach the device
as integer arrays.

Feature keys follow the reference's Avro convention: a feature is a
``name`` + ``term`` pair rendered as ``"name\x01term"`` (the reference uses a
similar delimiter-joined key), with the intercept under a reserved key.
"""

from __future__ import annotations

import json
import os
from typing import Iterable, Iterator, Optional

import numpy as np

DELIMITER = "\x01"
INTERCEPT_KEY = "(INTERCEPT)"


def feature_key(name: str, term: str = "") -> str:
    return f"{name}{DELIMITER}{term}" if term else name


class IndexMap:
    """Bidirectional feature-key <-> id map with O(1) lookups.

    ``intercept_id`` is set when the map was built with an intercept feature
    (always the last id, matching ``to_sparse_batch``'s convention).
    """

    def __init__(self, keys: list[str], intercept: bool = False):
        if intercept and INTERCEPT_KEY not in keys:
            keys = list(keys) + [INTERCEPT_KEY]
        self._keys = list(keys)
        self._index = {k: i for i, k in enumerate(self._keys)}
        if len(self._index) != len(self._keys):
            raise ValueError("duplicate feature keys in index map")
        self.intercept_id: Optional[int] = self._index.get(INTERCEPT_KEY)

    # -- lookups --------------------------------------------------------------
    def __len__(self) -> int:
        return len(self._keys)

    def __contains__(self, key: str) -> bool:
        return key in self._index

    def get_id(self, key: str, default: int = -1) -> int:
        return self._index.get(key, default)

    def get_key(self, idx: int) -> str:
        return self._keys[idx]

    def keys(self) -> Iterator[str]:
        return iter(self._keys)

    def ids_for(self, keys: Iterable[str]) -> np.ndarray:
        return np.asarray([self.get_id(k) for k in keys], np.int32)

    # -- construction ---------------------------------------------------------
    @classmethod
    def build(cls, keys: Iterable[str], intercept: bool = True) -> "IndexMap":
        """Build from an iterable of (possibly repeated) feature keys,
        assigning ids in first-seen order (deterministic, like the
        reference's indexing job output for a fixed input order)."""
        seen: dict[str, None] = {}
        for k in keys:
            if k not in seen:
                seen[k] = None
        return cls(list(seen), intercept=intercept)

    # -- persistence ----------------------------------------------------------
    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump({"version": 1, "keys": self._keys}, f)

    @classmethod
    def load(cls, path: str) -> "IndexMap":
        with open(path) as f:
            payload = json.load(f)
        return cls(payload["keys"])


class OffHeapIndexMap:
    """Memory-mapped feature index map (the reference's PalDBIndexMap).

    Same lookup interface as :class:`IndexMap`, but keys live in an mmap'd
    native store (photon_tpu.native.index_store) instead of a Python dict —
    the off-heap design the reference uses when feature vocabularies exceed
    driver memory.  ``build_file``/``open`` raise when the native library is
    unavailable; callers that can fall back should catch OSError and use
    :class:`IndexMap`.
    """

    def __init__(self, handle, path: str):
        self._handle = handle
        self.path = path
        self.intercept_id: Optional[int] = None
        iid = handle.get_id(INTERCEPT_KEY)
        if iid >= 0:
            self.intercept_id = iid

    # -- construction ---------------------------------------------------------
    @classmethod
    def build_file(
        cls, path: str, keys: Iterable[str], intercept: bool = True
    ) -> "OffHeapIndexMap":
        from photon_tpu.native import index_store

        seen = dict.fromkeys(keys)  # first-seen order, like IndexMap.build
        if intercept and INTERCEPT_KEY not in seen:
            seen[INTERCEPT_KEY] = None
        if not index_store.build_store(path, list(seen)):
            raise OSError("native index store unavailable (toolchain missing?)")
        return cls.open(path)

    @classmethod
    def open(cls, path: str) -> "OffHeapIndexMap":
        from photon_tpu.native import index_store

        handle = index_store.open_store(path)
        if handle is None:
            raise OSError(f"cannot open index store {path!r}")
        return cls(handle, path)

    # -- lookups (IndexMap interface) ----------------------------------------
    def __len__(self) -> int:
        return len(self._handle)

    def __contains__(self, key: str) -> bool:
        return self._handle.get_id(key) >= 0

    def get_id(self, key: str, default: int = -1) -> int:
        return self._handle.get_id(key, default)

    def get_key(self, idx: int) -> str:
        return self._handle.get_key(idx)

    def keys(self) -> Iterator[str]:
        for i in range(len(self)):
            yield self.get_key(i)

    def ids_for(self, keys: Iterable[str]) -> np.ndarray:
        return np.asarray([self.get_id(k) for k in keys], np.int32)

    def save(self, path: str) -> None:
        """Export as the JSON format for interop with :class:`IndexMap`."""
        IndexMap(list(self.keys())).save(path)

    # -- lifecycle ------------------------------------------------------------
    def close(self) -> None:
        self._handle.close()

    def __enter__(self) -> "OffHeapIndexMap":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
