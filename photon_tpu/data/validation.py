"""Training-data validation (reference: photon-client ``DataValidators`` —
SURVEY.md §2.3): row sanity checks with configurable strictness, run before
training so bad inputs fail loudly instead of corrupting a long fit.

Checks per task type:
- labels finite; binary tasks need labels in {0, 1}; Poisson needs >= 0
- weights finite and > 0 (zero weights are reserved for padding rows)
- offsets finite
- feature values finite
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np

from photon_tpu.core.losses import BINARY_TASKS


class DataValidationError(ValueError):
    pass


@dataclasses.dataclass(frozen=True)
class ValidationIssue:
    check: str
    count: int
    detail: str

    def __str__(self) -> str:
        return f"{self.check}: {self.count} rows ({self.detail})"


def _count(mask: np.ndarray) -> int:
    return int(np.count_nonzero(mask))


def validate_columns(
    label: np.ndarray,
    weight: Optional[np.ndarray],
    offset: Optional[np.ndarray],
    task_type: str,
) -> List[ValidationIssue]:
    issues: List[ValidationIssue] = []
    label = np.asarray(label)
    bad = _count(~np.isfinite(label))
    if bad:
        issues.append(ValidationIssue("non_finite_label", bad, "NaN/Inf labels"))
    task = task_type.lower()
    if task in BINARY_TASKS:
        finite = label[np.isfinite(label)]
        bad = _count(~np.isin(finite, (0.0, 1.0)))
        if bad:
            issues.append(
                ValidationIssue(
                    "non_binary_label", bad,
                    "binary task labels must be 0 or 1 "
                    "(normalize -1/+1 on read)",
                )
            )
    elif task == "poisson_regression":
        finite = label[np.isfinite(label)]
        bad = _count(finite < 0)
        if bad:
            issues.append(
                ValidationIssue("negative_label", bad, "Poisson labels must be >= 0")
            )
    if weight is not None:
        weight = np.asarray(weight)
        bad = _count(~np.isfinite(weight) | (weight <= 0))
        if bad:
            issues.append(
                ValidationIssue(
                    "invalid_weight", bad, "weights must be finite and > 0"
                )
            )
    if offset is not None:
        bad = _count(~np.isfinite(np.asarray(offset)))
        if bad:
            issues.append(ValidationIssue("non_finite_offset", bad, "NaN/Inf offsets"))
    return issues


def _feature_issues(values: np.ndarray, where: str) -> List[ValidationIssue]:
    bad_rows = _count(~np.isfinite(values).all(axis=tuple(range(1, values.ndim))))
    if bad_rows:
        return [
            ValidationIssue(
                f"non_finite_features[{where}]", bad_rows, "NaN/Inf feature values"
            )
        ]
    return []


def validate_batch(batch, task_type: str) -> List[ValidationIssue]:
    """Validate a DenseBatch/SparseBatch (photon_tpu.data.batch)."""
    issues = validate_columns(
        np.asarray(batch.label), np.asarray(batch.weight),
        np.asarray(batch.offset), task_type,
    )
    values = getattr(batch, "x", None)
    if values is None:
        values = batch.vals
    issues += _feature_issues(np.asarray(values), "batch")
    return issues


def validate_game_dataset(data, task_type: str) -> List[ValidationIssue]:
    """Validate a GameDataset (photon_tpu.game.data)."""
    issues = validate_columns(data.label, data.weight, data.offset, task_type)
    for name, shard in data.shards.items():
        values = shard.x if hasattr(shard, "x") else shard.vals
        issues += _feature_issues(np.asarray(values), name)
    return issues


def apply_validation(issues: List[ValidationIssue], mode: str, logger=None) -> None:
    """``error`` raises on any issue; ``warn`` logs them; ``off`` skips.

    (The reference's configurable validation strictness.)
    """
    if mode == "off" or not issues:
        return
    message = "; ".join(str(i) for i in issues)
    if mode == "error":
        raise DataValidationError(f"data validation failed: {message}")
    if mode == "warn":
        if logger is not None:
            logger.warning("data validation: %s", message)
        return
    raise ValueError(f"unknown validation mode {mode!r} (want error|warn|off)")
