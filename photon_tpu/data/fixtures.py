"""Benchmark fixtures with real-dataset statistics (SURVEY.md §6).

The sandbox has no network egress, so the reference bench datasets
(a1a LIBSVM, MovieLens-1M) cannot be downloaded.  These generators
reproduce their published shape and summary statistics deterministically,
so the bench configs exercise realistic sparsity/skew and produce stable
validation metrics across rounds:

- **a1a** (UCI Adult, LIBSVM binary encoding): 1,605 train / 30,956 test
  rows, 123 binary indicator features, 13.87 nnz/row average, ~24.6%
  positive labels, power-law feature frequencies (each row sets one
  indicator per original categorical column).
- **MovieLens-1M shape**: users rating items, zipf-skewed item popularity,
  per-user activity skew, rating>=4 binarization (~57.5% positive) — the
  GAME per-entity regime (user random effect over a global fixed effect).

These are stand-ins, not the real datasets: absolute AUCs differ from
literature numbers, but they are deterministic anchors — a regression in
loss/optimizer/data plumbing moves them.
"""

from __future__ import annotations

import os

import numpy as np

A1A_TRAIN_ROWS = 1605
A1A_TEST_ROWS = 3000  # slice of a1a.t's 30,956 (keeps the fixture ~100 KB)
A1A_DIM = 123
# Original categorical columns of Adult, LIBSVM-encoded as one indicator
# per (column, category): sizes sum to 123.
_A1A_GROUPS = (8, 8, 16, 7, 14, 6, 5, 2, 39, 2, 2, 2, 2, 10)


def _a1a_rows(n_rows: int, rng: np.random.Generator, w_true: np.ndarray):
    """Sample rows the way the LIBSVM Adult encoding produces them: one
    active indicator per categorical group (some groups optional), zipf-ish
    within-group category popularity.  ``w_true`` is the shared sparse
    ground-truth model — train and test MUST draw from the same one or the
    validation AUC is chance."""
    starts = np.concatenate(([0], np.cumsum(_A1A_GROUPS)))[:-1]
    group_probs = []
    for size in _A1A_GROUPS:
        p = 1.0 / (np.arange(size) + 1.3)
        group_probs.append(p / p.sum())
    bias = -0.82  # calibrated to ~24.6% positives
    rows = []
    labels = np.empty(n_rows, np.int8)
    for i in range(n_rows):
        ids = []
        for g, (start, size) in enumerate(zip(starts, _A1A_GROUPS)):
            if rng.random() < 0.01:
                continue  # occasional missing column (a1a avg 13.87 nnz/row)
            cat = rng.choice(size, p=group_probs[g])
            ids.append(start + cat)
        ids = np.sort(np.asarray(ids, np.int64))
        margin = w_true[ids].sum() + bias
        labels[i] = 1 if rng.random() < 1.0 / (1.0 + np.exp(-margin)) else -1
        rows.append(ids)
    return rows, labels


def write_a1a_like(train_path: str, test_path: str | None = None, seed: int = 11):
    """Write the a1a-statistics LIBSVM fixture (1-based ids, binary vals)."""
    rng = np.random.default_rng(seed)
    w_true = rng.standard_normal(A1A_DIM) * 0.8
    w_true[rng.random(A1A_DIM) < 0.5] = 0.0
    for path, n_rows in (
        (train_path, A1A_TRAIN_ROWS),
        (test_path, A1A_TEST_ROWS),
    ):
        if path is None:
            continue
        rows, labels = _a1a_rows(n_rows, rng, w_true)
        with open(path, "w") as f:
            for ids, y in zip(rows, labels):
                f.write(
                    f"{'+1' if y > 0 else '-1'} "
                    + " ".join(f"{j + 1}:1" for j in ids)
                    + "\n"
                )


def make_movielens_like(
    n_users: int = 600,
    n_items: int = 400,
    mean_ratings: int = 18,
    seed: int = 13,
):
    """MovieLens-shaped GAME dataset + index maps (users x zipf items).

    Global fixed-effect features: item-genre indicators (18 genres, as in
    MovieLens-1M) + user demographic buckets; per-user random effect over
    the genre features — the canonical GLMix personalization setup.
    Returns ``(GameDataset, index_maps)`` ready for the GAME pipeline or
    :func:`photon_tpu.data.game_io.write_game_avro`.
    """
    from photon_tpu.data.index_map import IndexMap, feature_key
    from photon_tpu.game.data import DenseShard, GameDataset

    rng = np.random.default_rng(seed)
    n_genres = 18
    item_genres = np.zeros((n_items, n_genres), np.float32)
    for i in range(n_items):
        k = 1 + rng.geometric(0.55)
        item_genres[i, rng.choice(n_genres, size=min(k, 4), replace=False)] = 1.0
    item_pop = 1.0 / (np.arange(n_items) + 2.0) ** 1.1
    item_pop /= item_pop.sum()

    user_taste = rng.standard_normal((n_users, n_genres)).astype(np.float32) * 0.9
    genre_quality = rng.standard_normal(n_genres).astype(np.float32) * 0.5
    item_bias = rng.standard_normal(n_items).astype(np.float32) * 0.6

    users, items, labels = [], [], []
    for u in range(n_users):
        n_r = max(3, int(rng.geometric(1.0 / mean_ratings)))
        seen = rng.choice(n_items, size=min(n_r, n_items), replace=False, p=item_pop)
        for it in seen:
            margin = (
                float(item_genres[it] @ (genre_quality + user_taste[u]))
                + float(item_bias[it])
                + 0.65  # ~57.5% of MovieLens-1M ratings are >= 4
            )
            y = 1.0 if rng.random() < 1.0 / (1.0 + np.exp(-margin)) else 0.0
            users.append(u)
            items.append(int(it))
            labels.append(y)
    users = np.asarray(users, np.int64)
    items = np.asarray(items, np.int64)
    labels = np.asarray(labels, np.float32)
    n = len(labels)

    # Global shard: genre indicators of the rated item + intercept.
    x_global = np.concatenate(
        [item_genres[items], np.ones((n, 1), np.float32)], axis=1
    )
    # Per-user shard: same genre indicators (the user's personal genre
    # model) + per-user intercept.
    x_user = x_global.copy()

    shards = {
        "global": DenseShard(x_global),
        "per_user": DenseShard(x_user),
    }
    index_maps = {}
    for name in shards:
        keys = [feature_key(f"genre{g}") for g in range(n_genres)]
        index_maps[name] = IndexMap.build(keys, intercept=True)
    data = GameDataset(
        shards=shards,
        label=labels,
        offset=np.zeros(n, np.float32),
        weight=np.ones(n, np.float32),
        id_columns={"userId": users, "itemId": items},
    )
    return data, index_maps


# MovieLens-1M genre vocabulary (README order; 18 genres).
_ML_GENRES = (
    "Action", "Adventure", "Animation", "Children's", "Comedy", "Crime",
    "Documentary", "Drama", "Fantasy", "Film-Noir", "Horror", "Musical",
    "Mystery", "Romance", "Sci-Fi", "Thriller", "War", "Western",
)


def movielens_dataset(**fixture_kw):
    """GAME MovieLens dataset: the REAL MovieLens-1M when operators provide
    it (``PHOTON_REAL_DATA_DIR/ml-1m/{ratings,movies}.dat`` — no network
    egress here; VERDICT r3 item 9), else the statistics-matched generator
    :func:`make_movielens_like` with ``fixture_kw``.  Both return
    ``(GameDataset, index_maps)`` with identical shard structure, so bench
    config 4 and drivers are agnostic to which backs them."""
    real_dir = os.environ.get("PHOTON_REAL_DATA_DIR")
    if real_dir:
        mdir = os.path.join(real_dir, "ml-1m")
        if os.path.exists(os.path.join(mdir, "ratings.dat")) and os.path.exists(
            os.path.join(mdir, "movies.dat")
        ):
            return _movielens_real(mdir)
    return make_movielens_like(**fixture_kw)


def _movielens_real(mdir: str):
    """Parse the verbatim MovieLens-1M distribution into the GAME layout
    used by the fixture: label = rating >= 4, global + per-user shards of
    the rated item's genre indicators + intercept."""
    from photon_tpu.data.index_map import IndexMap, feature_key
    from photon_tpu.game.data import DenseShard, GameDataset

    n_genres = len(_ML_GENRES)
    gidx = {g: i for i, g in enumerate(_ML_GENRES)}
    genres_by_movie: dict = {}
    with open(os.path.join(mdir, "movies.dat"), encoding="latin-1") as f:
        for line in f:
            parts = line.rstrip("\n").split("::")
            if len(parts) != 3:
                continue
            vec = np.zeros(n_genres, np.float32)
            for g in parts[2].split("|"):
                gi = gidx.get(g.strip())
                if gi is not None:
                    vec[gi] = 1.0
            genres_by_movie[int(parts[0])] = vec
    users, items, labels = [], [], []
    with open(os.path.join(mdir, "ratings.dat"), encoding="latin-1") as f:
        for line in f:
            parts = line.split("::")
            if len(parts) < 3:
                continue
            movie = int(parts[1])
            if movie not in genres_by_movie:
                continue
            users.append(int(parts[0]))
            items.append(movie)
            labels.append(1.0 if float(parts[2]) >= 4.0 else 0.0)
    if not users:
        raise ValueError(
            f"no joinable ratings found in {mdir!r}: ratings.dat rows must "
            "reference movie ids present in movies.dat (truncated or "
            "mismatched MovieLens drop-in?)"
        )
    users = np.asarray(users, np.int64)
    items = np.asarray(items, np.int64)
    labels = np.asarray(labels, np.float32)
    n = len(labels)
    item_genres = np.stack([genres_by_movie[m] for m in items])
    x_global = np.concatenate([item_genres, np.ones((n, 1), np.float32)], axis=1)
    shards = {
        "global": DenseShard(x_global),
        "per_user": DenseShard(x_global.copy()),
    }
    index_maps = {
        name: IndexMap.build(
            [feature_key(f"genre{g}") for g in range(n_genres)], intercept=True
        )
        for name in shards
    }
    data = GameDataset(
        shards=shards,
        label=labels,
        offset=np.zeros(n, np.float32),
        weight=np.ones(n, np.float32),
        id_columns={"userId": users, "itemId": items},
    )
    return data, index_maps


def a1a_fixture_paths() -> tuple[str, str]:
    """a1a train/test file locations.

    If operators provide the REAL datasets (no network egress here, so
    they must be dropped in by hand — VERDICT r3 item 9), point
    ``PHOTON_REAL_DATA_DIR`` at a directory containing ``a1a`` and
    ``a1a.t`` (the verbatim LIBSVM files); benches and anchor tests then
    run on the real data and report true literature-comparable AUCs.
    Otherwise the repo-committed statistics-matched fixtures are used.
    """
    real_dir = os.environ.get("PHOTON_REAL_DATA_DIR")
    if real_dir:
        train, test = os.path.join(real_dir, "a1a"), os.path.join(real_dir, "a1a.t")
        if os.path.exists(train) and os.path.exists(test):
            return train, test
    base = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        "tests", "fixtures",
    )
    return os.path.join(base, "a1a.libsvm"), os.path.join(base, "a1a.t.libsvm")
