"""Minimal pure-Python Avro binary codec (subset).

The reference persists models and reads training data as Avro
(photon-client .../data/avro, photon-avro-schemas — SURVEY.md §2.3).  This
sandbox has no JVM Avro and may lack fastavro, so this module implements the
small subset of the Avro 1.x spec the framework needs, both directions:

- primitives: null, boolean, int/long (zigzag varint), float, double,
  string, bytes
- complex: record, array, map, union, enum
- Object Container Files (magic ``Obj\\x01``, metadata map with schema JSON,
  null codec, sync-marker-delimited blocks)

Files written here are readable by standard Avro tooling and vice versa
(for the schema subset used).  No code is shared with or derived from any
Avro implementation; this is written from the public format spec.
"""

from __future__ import annotations

import io
import json
import os
import struct
from typing import Any, BinaryIO

MAGIC = b"Obj\x01"


# --------------------------------------------------------------------------
# primitive encoders
# --------------------------------------------------------------------------
def _zigzag_encode(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _zigzag_decode(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def write_long(buf: BinaryIO, n: int) -> None:
    z = _zigzag_encode(n) & 0xFFFFFFFFFFFFFFFF
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            buf.write(bytes([b | 0x80]))
        else:
            buf.write(bytes([b]))
            return


def read_long(buf: BinaryIO) -> int:
    shift = 0
    acc = 0
    while True:
        byte = buf.read(1)
        if not byte:
            raise EOFError("truncated varint")
        b = byte[0]
        acc |= (b & 0x7F) << shift
        if not b & 0x80:
            return _zigzag_decode(acc)
        shift += 7


def write_string(buf: BinaryIO, s: str) -> None:
    raw = s.encode("utf-8")
    write_long(buf, len(raw))
    buf.write(raw)


def read_string(buf: BinaryIO) -> str:
    n = read_long(buf)
    return buf.read(n).decode("utf-8")


def write_bytes(buf: BinaryIO, b: bytes) -> None:
    write_long(buf, len(b))
    buf.write(b)


def read_bytes(buf: BinaryIO) -> bytes:
    return buf.read(read_long(buf))


# --------------------------------------------------------------------------
# schema-driven datum encoder/decoder
# --------------------------------------------------------------------------
class _Named:
    """Registry of named types within one schema (records/enums by name)."""

    def __init__(self):
        self.types: dict[str, Any] = {}


def _resolve(schema: Any, named: _Named) -> Any:
    if isinstance(schema, str) and schema in named.types:
        return named.types[schema]
    return schema


def _register_named(schema: Any, named: _Named) -> None:
    """Walk a schema and register every named type up front, so by-name
    references resolve even when the defining occurrence writes/reads no
    data first (e.g. an empty array of records followed by a by-name
    reference in a later field)."""
    if isinstance(schema, list):
        for branch in schema:
            _register_named(branch, named)
        return
    if not isinstance(schema, dict):
        return
    t = schema.get("type")
    if t in ("record", "enum") and "name" in schema:
        if schema["name"] in named.types:
            return  # already walked (guards recursive schemas)
        named.types[schema["name"]] = schema
    if t == "record":
        for field in schema["fields"]:
            _register_named(field["type"], named)
    elif t == "array":
        _register_named(schema["items"], named)
    elif t == "map":
        _register_named(schema["values"], named)


def write_datum(buf: BinaryIO, datum: Any, schema: Any, named: _Named | None = None) -> None:
    if named is None:
        named = _Named()
        _register_named(schema, named)
    schema = _resolve(schema, named)
    if isinstance(schema, str):
        t = schema
        if t == "null":
            return
        if t == "boolean":
            buf.write(b"\x01" if datum else b"\x00")
        elif t in ("int", "long"):
            write_long(buf, int(datum))
        elif t == "float":
            buf.write(struct.pack("<f", float(datum)))
        elif t == "double":
            buf.write(struct.pack("<d", float(datum)))
        elif t == "string":
            write_string(buf, datum)
        elif t == "bytes":
            write_bytes(buf, datum)
        else:
            raise ValueError(f"unsupported primitive {t!r}")
        return
    if isinstance(schema, list):  # union: pick first matching branch
        for i, branch in enumerate(schema):
            if _matches(datum, branch, named):
                write_long(buf, i)
                write_datum(buf, datum, branch, named)
                return
        raise ValueError(f"datum {datum!r} matches no union branch {schema}")
    t = schema["type"]
    if t == "record":
        for field in schema["fields"]:
            write_datum(buf, datum[field["name"]], field["type"], named)
    elif t == "array":
        items = datum
        if len(items):
            write_long(buf, len(items))
            for item in items:
                write_datum(buf, item, schema["items"], named)
        write_long(buf, 0)
    elif t == "map":
        entries = list(datum.items())
        if entries:
            write_long(buf, len(entries))
            for k, v in entries:
                write_string(buf, k)
                write_datum(buf, v, schema["values"], named)
        write_long(buf, 0)
    elif t == "enum":
        write_long(buf, schema["symbols"].index(datum))
    else:
        # {"type": "string"}-style wrapping of primitives
        write_datum(buf, datum, t, named)


def _matches(datum: Any, branch: Any, named: _Named) -> bool:
    branch = _resolve(branch, named)
    if branch == "null":
        return datum is None
    if datum is None:
        return False
    if isinstance(branch, dict) and branch.get("type") == "array":
        return isinstance(datum, (list, tuple))
    if isinstance(branch, dict) and branch.get("type") in ("record", "map"):
        return isinstance(datum, dict)
    if branch == "string":
        return isinstance(datum, str)
    if branch in ("int", "long"):
        return isinstance(datum, int)
    if branch in ("float", "double"):
        return isinstance(datum, (int, float))
    if branch == "boolean":
        return isinstance(datum, bool)
    return True


def read_datum(buf: BinaryIO, schema: Any, named: _Named | None = None) -> Any:
    if named is None:
        named = _Named()
        _register_named(schema, named)
    schema = _resolve(schema, named)
    if isinstance(schema, str):
        t = schema
        if t == "null":
            return None
        if t == "boolean":
            return buf.read(1) != b"\x00"
        if t in ("int", "long"):
            return read_long(buf)
        if t == "float":
            return struct.unpack("<f", buf.read(4))[0]
        if t == "double":
            return struct.unpack("<d", buf.read(8))[0]
        if t == "string":
            return read_string(buf)
        if t == "bytes":
            return read_bytes(buf)
        raise ValueError(f"unsupported primitive {t!r}")
    if isinstance(schema, list):
        idx = read_long(buf)
        return read_datum(buf, schema[idx], named)
    t = schema["type"]
    if t == "record":
        return {
            f["name"]: read_datum(buf, f["type"], named) for f in schema["fields"]
        }
    if t == "array":
        out = []
        while True:
            n = read_long(buf)
            if n == 0:
                return out
            if n < 0:  # block with byte size prefix
                read_long(buf)
                n = -n
            for _ in range(n):
                out.append(read_datum(buf, schema["items"], named))
    if t == "map":
        out = {}
        while True:
            n = read_long(buf)
            if n == 0:
                return out
            if n < 0:
                read_long(buf)
                n = -n
            for _ in range(n):
                k = read_string(buf)
                out[k] = read_datum(buf, schema["values"], named)
    if t == "enum":
        return schema["symbols"][read_long(buf)]
    return read_datum(buf, t, named)


# --------------------------------------------------------------------------
# Object Container Files
# --------------------------------------------------------------------------
def write_container(path: str, schema: dict, records: list, sync: bytes | None = None) -> None:
    sync = sync or os.urandom(16)
    with open(path, "wb") as f:
        f.write(MAGIC)
        meta_buf = io.BytesIO()
        meta = {
            "avro.schema": json.dumps(schema).encode(),
            "avro.codec": b"null",
        }
        write_long(meta_buf, len(meta))
        for k, v in meta.items():
            write_string(meta_buf, k)
            write_bytes(meta_buf, v)
        write_long(meta_buf, 0)
        f.write(meta_buf.getvalue())
        f.write(sync)
        if records:
            named = _Named()
            _register_named(schema, named)
            block = io.BytesIO()
            for rec in records:
                write_datum(block, rec, schema, named)
            payload = block.getvalue()
            hdr = io.BytesIO()
            write_long(hdr, len(records))
            write_long(hdr, len(payload))
            f.write(hdr.getvalue())
            f.write(payload)
            f.write(sync)


def read_header_meta(f, path: str) -> tuple[dict, dict, bytes]:
    """Parse the container header; returns (schema, metadata map, sync).
    Leaves ``f`` positioned at the first data block (the offset native
    decoders start from)."""
    if f.read(4) != MAGIC:
        raise ValueError(f"{path}: not an Avro container file")
    meta = {}
    while True:
        n = read_long(f)
        if n == 0:
            break
        if n < 0:
            read_long(f)
            n = -n
        for _ in range(n):
            k = read_string(f)
            meta[k] = read_bytes(f)
    schema = json.loads(meta["avro.schema"].decode())
    sync = f.read(16)
    if len(sync) != 16:
        raise ValueError(f"{path}: truncated container header (sync marker)")
    return schema, meta, sync


def _read_header(f, path: str) -> tuple[dict, "_Named", bytes]:
    """Parse the container header; returns (schema, named registry, sync)."""
    schema, _, sync = read_header_meta(f, path)
    named = _Named()
    _register_named(schema, named)
    return schema, named, sync


def _read_blocks(f, schema: dict, named: "_Named", sync: bytes, path: str):
    """Yield records block-at-a-time from an open container positioned just
    past the header."""
    while True:
        try:
            count = read_long(f)
        except EOFError:
            break
        read_long(f)  # byte size (unused, codec is null)
        for _ in range(count):
            yield read_datum(f, schema, named)
        if f.read(16) != sync:
            raise ValueError(f"{path}: sync marker mismatch")


def open_container(path: str):
    """Open a container and parse its header EAGERLY; returns
    ``(open file, schema, named registry, sync)``.

    The retriable prefix of a container read: callers that wrap the open +
    header parse in a retry loop (``photon_tpu.fault.retry``) pair this
    with :func:`iter_records` instead of :func:`iter_container`, whose lazy
    generator would defer the failure past the retry scope.  The caller
    owns closing the returned file.
    """
    f = open(path, "rb")
    try:
        schema, named, sync = _read_header(f, path)
    except BaseException:
        f.close()
        raise
    return f, schema, named, sync


def iter_records(f, schema, named, sync, path: str):
    """Yield records block-at-a-time from an :func:`open_container` result."""
    return _read_blocks(f, schema, named, sync, path)


def iter_container(path: str):
    """Yield records from an Avro container file LAZILY (one at a time).

    The streaming complement of :func:`read_container`: block-at-a-time
    decode, nothing retained — callers consuming billions of rows keep host
    memory bounded by their own accumulators, not the record dicts
    (SURVEY.md §7 '1B-row ingestion without Spark').
    """
    f, schema, named, sync = open_container(path)
    with f:
        yield from iter_records(f, schema, named, sync, path)


def read_container(path: str) -> tuple[dict, list]:
    with open(path, "rb") as f:
        schema, named, sync = _read_header(f, path)
        return schema, list(_read_blocks(f, schema, named, sync, path))
