"""Fast-kernel layouts for STREAMED chunks (VERDICT r5 item 3).

The streaming tier (data/streaming.py tier 3) re-parses the same part
files on every objective evaluation, so until now it could only run the
row-major autodiff kernel: the aligned/xchg layouts cost orders of
magnitude more host time than a chunk parse, and rebuilding them per
pass is economically impossible.  But a chunk's layout and exchange
route are pure functions of its FILE — identical on every pass — so
they can be built once, persisted beside the route cache, and
re-attached to each freshly parsed chunk at stat+load cost:

- **Cache key = file identity (abspath, size, mtime) + parse params**,
  not content: the hit path per pass is one ``stat`` and one ``npz``
  load — no per-pass hashing of multi-MB id streams.
- **Pow2-bucketed geometry**: per-file natural geometry (aligned
  slabs/tiles, balanced block census) is padded UP to powers of two, so
  equal-shaped chunks (every full part file of a dataset) share one
  stacked treedef and therefore ONE jitted per-chunk program — without
  any global pre-pass over all files.
- **No value baking**: a streamed chunk is evaluated once per pass, so
  pre-permuting the value stream (``vals_dest``) would cost one extra
  exchange per evaluation instead of amortizing; the route moves the
  materialized product stream instead.

Amortization math (KERNEL_NOTES.md round-5 streaming section): the
route build is tens of host-seconds per production-size file, paid ONCE
per dataset; an L-BFGS fit re-streams every file ~50-150 times (one
pass per value+gradient evaluation), so the build amortizes to well
under a second per pass while deleting the per-pass E-element gather
the xchg kernel exists to delete.

Select with ``PHOTON_STREAM_KERNEL=autodiff|fm|pallas|xchg`` (default
``autodiff`` — the measured-best round-4 TPU kernel, and the right
default while streamed passes are host-parse-bound).  ``xchg`` honors
``PHOTON_XCHG_REDUCE`` like the resident path.
"""

from __future__ import annotations

import hashlib
import logging
import os
from typing import Optional

import numpy as np

import jax.numpy as jnp

from photon_tpu.data.batch import SparseBatch

_VERSION = 1
_LOG = logging.getLogger("photon_tpu.stream_layouts")

_KERNELS = ("autodiff", "fm", "pallas", "xchg")


def stream_kernel() -> str:
    """The kernel streamed chunks should carry layouts for.

    Defaults to following a FORCED ``PHOTON_SPARSE_GRAD`` (so pinning
    the production kernel pins the streamed path too, with no second
    knob to forget), else ``autodiff``.  Note the layouts only make the
    chunk ELIGIBLE — in ``PHOTON_SPARSE_GRAD=auto`` mode the measured
    selection still arbitrates per shape bucket, exactly as for
    resident batches."""
    k = os.environ.get("PHOTON_STREAM_KERNEL")
    if k is None:
        forced = os.environ.get("PHOTON_SPARSE_GRAD", "auto")
        k = forced if forced in ("fm", "pallas", "xchg") else "autodiff"
    if k not in _KERNELS:
        raise ValueError(
            f"PHOTON_STREAM_KERNEL={k!r}; valid: {'|'.join(_KERNELS)}"
        )
    return k


def stream_kernel_why(kernel: str) -> str:
    """One-line provenance for bench/driver reporting."""
    if kernel == "autodiff":
        return (
            "default: streamed passes are host-parse-bound and autodiff "
            "is the measured-best TPU kernel (KERNEL_NOTES r4 table); "
            "set PHOTON_STREAM_KERNEL to attach cached fast-kernel "
            "layouts per chunk"
        )
    return (
        f"PHOTON_STREAM_KERNEL={kernel}: per-file layouts/routes built "
        "once and cached (pow2-bucketed geometry), re-attached per pass "
        "at stat+load cost (KERNEL_NOTES round-5 streaming section)"
    )


def _pow2(x: int) -> int:
    from photon_tpu.utils import pow2_at_least

    return pow2_at_least(int(x))


def _cache_root() -> Optional[str]:
    from photon_tpu.utils.caches import resolve_cache_dir

    return resolve_cache_dir("PHOTON_STREAM_LAYOUT_CACHE", "stream")


def _aux_cache_path(file_path: str, dim: int, kernel: str,
                    mode: str, capacity: int) -> Optional[str]:
    root = _cache_root()
    if root is None:
        return None
    try:
        st = os.stat(file_path)
        ident = (os.path.abspath(file_path), st.st_size,
                 int(st.st_mtime_ns))
    except OSError:
        return None
    h = hashlib.sha256()
    h.update(repr(ident).encode())
    h.update(f"|{dim}|{capacity}|{kernel}|{mode}|v{_VERSION}".encode())
    return os.path.join(root, "aux_" + h.hexdigest()[:32] + ".npz")


def _needs_layout(kernel: str, mode: str) -> bool:
    return kernel == "pallas" or (kernel == "xchg" and mode == "aligned")


def _build_padded_layout(ids_np: np.ndarray, vals_np: np.ndarray,
                         dim: int):
    """Aligned layout padded to pow2-bucketed (slabs, tiles) so chunks
    of equal shape share one compiled program."""
    from photon_tpu.ops.pallas_gather import (
        build_aligned_layout,
        pad_aligned_layout,
    )

    lay = build_aligned_layout(ids_np, vals_np, dim)
    s2 = _pow2(lay.n_slabs)
    t2 = _pow2(lay.n_tiles + (s2 - lay.n_slabs))
    return pad_aligned_layout(lay, s2, t2)


def _build_aux(ids_np: np.ndarray, vals_np: np.ndarray, dim: int,
               kernel: str, mode: str):
    """(layout | None, XchgAux | None) freshly built with pow2-bucketed
    geometry.  Calls the underlying route builders directly (NOT
    build_xchg_aux) so routes are not double-cached in the route cache —
    the stream cache file is the single store — and so no env mutation
    is needed on the (multi-threaded) chunk-load path."""
    from photon_tpu.ops.vperm import (
        XchgAux,
        balanced_blk_census,
        build_balanced_aligned_route,
        build_balanced_sorted_route,
        build_xchg_route,
        build_xchg_sorted_route,
    )

    layout = (
        _build_padded_layout(ids_np, vals_np, dim)
        if _needs_layout(kernel, mode) else None
    )
    if kernel == "pallas":
        return layout, None
    n, k = ids_np.shape
    e = ids_np.size
    flat = ids_np.reshape(-1).astype(np.int64)
    order = np.argsort(flat, kind="stable")
    if mode == "cumsum":
        census = balanced_blk_census(order, e, k)
        built = (
            build_balanced_sorted_route(
                ids_np, dim, order, blk_override=_pow2(census)
            ) if census is not None else None
        )
        if built is not None:
            aux = XchgAux(route=built[0], bounds=built[1])
        else:
            aux = build_xchg_sorted_route(ids_np, dim, order=order)
    else:
        census = balanced_blk_census(
            layout.src.reshape(-1), e, k
        )
        built = (
            build_balanced_aligned_route(
                layout, ids_np, blk_override=_pow2(census)
            ) if census is not None else None
        )
        aux = XchgAux(route=built) if built is not None else XchgAux(
            route=build_xchg_route(layout, n, k)
        )
    return layout, aux


def _save_aux(path: str, layout, aux) -> None:
    from photon_tpu.ops.vperm import _aux_to_npz

    out = {}
    if layout is not None:
        for name in ("lo", "vals", "rows", "slab_of_tile", "dup_map"):
            out["lay_" + name] = np.asarray(getattr(layout, name))
        out["lay_n_entries"] = np.int64(layout.n_entries)
    if aux is not None:
        for key, val in _aux_to_npz(aux).items():
            out["aux_" + key] = val
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + f".tmp{os.getpid()}.{id(layout) & 0xffff:x}"
        with open(tmp, "wb") as f:
            np.savez(f, **out)
        os.replace(tmp, path)
    except Exception as exc:  # noqa: BLE001 — best-effort cache
        _LOG.warning("stream layout cache write failed (%s)", exc)


def _load_aux(path: str):
    """(layout | None, XchgAux | None) from a cache file, or None on any
    read failure (caller rebuilds)."""
    from photon_tpu.ops.pallas_gather import AlignedLayout
    from photon_tpu.ops.vperm import _aux_from_npz

    try:
        with np.load(path) as z:
            layout = None
            if "lay_lo" in z:
                lo = z["lay_lo"]
                layout = AlignedLayout(
                    lo=lo,
                    vals=z["lay_vals"],
                    rows=z["lay_rows"],
                    slab_of_tile=z["lay_slab_of_tile"],
                    dup_map=z["lay_dup_map"],
                    # Host-only routing field; never needed again once
                    # the route is built (and not cached for size).
                    src=np.full(lo.shape, -1, np.int64),
                    n_entries=int(z["lay_n_entries"]),
                )
            aux = None
            if "aux_kind" in z:
                trimmed = {
                    key[4:]: z[key] for key in z.files
                    if key.startswith("aux_")
                }
                aux = _aux_from_npz(trimmed)
            return layout, aux
    except Exception as exc:  # noqa: BLE001 — corrupt cache = rebuild
        _LOG.warning("stream layout cache read failed (%s); rebuilding",
                     exc)
        return None


def attach_stream_aux(batch: SparseBatch, dim: int,
                      file_path: str) -> SparseBatch:
    """Attach the PHOTON_STREAM_KERNEL layouts to a freshly parsed
    chunk, building them on first touch and loading from the stream
    cache afterwards.  The returned batch routes to the fast kernels
    through the ordinary selection machinery (core/objective)."""
    kernel = stream_kernel()
    if kernel == "autodiff" or not (
        isinstance(batch, SparseBatch) and batch.ids.ndim == 2
    ):
        return batch
    from photon_tpu.data.batch import attach_feature_major

    if kernel == "fm":
        # Cheap (one argsort) relative to the parse; rebuilt per pass.
        return attach_feature_major(batch)
    mode = os.environ.get("PHOTON_XCHG_REDUCE", "aligned")
    path = _aux_cache_path(
        file_path, dim, kernel, mode, int(batch.ids.shape[1])
    )
    layout = aux = None
    if path is not None and os.path.exists(path):
        loaded = _load_aux(path)
        if loaded is not None:
            layout, aux = loaded
    if layout is None and aux is None:
        # Host copies of the chunk arrays happen ONLY on this build
        # branch — the per-pass hit path stays stat + npz load.
        ids_np = np.asarray(batch.ids)
        vals_np = np.asarray(batch.vals, np.float32)
        _LOG.warning(
            "building the %s stream layout for %s (%d entries, "
            "mode=%s) — one-time host work, cached for every later "
            "pass%s",
            kernel, os.path.basename(file_path), ids_np.size, mode,
            "" if path is not None else
            " (caching DISABLED via PHOTON_STREAM_LAYOUT_CACHE=0)",
        )
        layout, aux = _build_aux(ids_np, vals_np, dim, kernel, mode)
        if path is not None:
            _save_aux(path, layout, aux)
    if layout is not None:
        from photon_tpu.ops.pallas_gather import device_layout

        batch = batch._replace(al=device_layout(layout))
    if aux is not None:
        batch = batch._replace(xchg=aux)
    return batch
