"""GAME training-data IO: Avro records -> GameDataset (+ index maps).

Rebuild of the reference's ``AvroDataReader`` / ``GameConverters`` path
(photon-client .../data/avro, SURVEY.md §2.3 'Avro IO' and §3.1): training
records carry a ``response``, optional ``offset``/``weight``/``uid``, one or
more **feature bags** (arrays of name/term/value records), and entity-id
columns (e.g. ``userId``) for random effects.  Reading indexes each bag's
(name, term) keys through a per-shard :class:`IndexMap` and packs rows into
the framework's padded-COO feature shards.

TPU-native shape: the reference materializes an
``RDD[(UniqueSampleId, GameDatum)]``; here the row order of the file(s) IS
the unique-sample-id, and the output is one columnar :class:`GameDataset`
ready for host-side entity bucketing (photon_tpu.game.data).
"""

from __future__ import annotations

import glob as _glob
import os
from array import array

# The streaming CSR builders reinterpret array('i')/array('f') buffers as
# np.int32/np.float32 via np.frombuffer — valid only while C int/float are
# 4 bytes.  True on every supported platform; checked once so a layout
# mismatch fails loudly instead of corrupting ids/values (ADVICE r3).
# A real raise, not an assert: `python -O` must not strip the guard.
if array("i").itemsize != 4 or array("f").itemsize != 4:
    raise ImportError(
        "C int/float are not 32-bit on this platform; the game_io streaming "
        "readers' frombuffer reinterpretation would corrupt data"
    )
from typing import Dict, Optional, Sequence

import numpy as np

from photon_tpu.data import avro_codec
from photon_tpu.data.batch import pad_row_capacity
from photon_tpu.data.index_map import INTERCEPT_KEY, IndexMap, feature_key
from photon_tpu.game.data import GameDataset, SparseShard

FEATURE_SCHEMA = {
    "type": "record",
    "name": "FeatureAvro",
    "namespace": "photon_tpu.generated",
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string"},
        {"name": "value", "type": "double"},
    ],
}


class NoRecordsError(ValueError):
    """Raised when an input yields zero records — a typed contract so
    streaming callers can skip routinely-empty part files without matching
    on error text."""


def _id_field(col: str, bag_fields: Sequence[str]) -> str:
    """Record field holding entity-id column ``col``; suffixed when the name
    collides with a feature-bag field (synthetic data uses one name for
    both the shard and its entity column)."""
    return f"{col}__id" if col in bag_fields else col


def training_example_schema(
    feature_bags: Sequence[str], id_columns: Sequence[str]
) -> dict:
    """Schema for one training record; mirrors the reference's
    TrainingExampleAvro shape (response/offset/weight/uid + feature bags),
    with one array-of-FeatureAvro field per bag and one string field per
    entity-id column."""
    fields = [
        {"name": "response", "type": "double"},
        {"name": "offset", "type": ["null", "double"], "default": None},
        {"name": "weight", "type": ["null", "double"], "default": None},
        {"name": "uid", "type": ["null", "string"], "default": None},
    ]
    for i, bag in enumerate(feature_bags):
        items = FEATURE_SCHEMA if i == 0 else "FeatureAvro"
        fields.append({"name": bag, "type": {"type": "array", "items": items}})
    for col in id_columns:
        fields.append({"name": _id_field(col, feature_bags), "type": "string"})
    return {
        "type": "record",
        "name": "TrainingExampleAvro",
        "namespace": "photon_tpu.generated",
        "fields": fields,
    }


def _input_files(path: str) -> list[str]:
    """A file, a directory of part files, or a glob -> sorted file list.

    Both the directory and glob branches exclude non-files and dot-/
    underscore-prefixed names (in-progress part files and committer markers
    like ``_SUCCESS`` / ``_tmp-0.avro`` must never reach a decoder).
    """
    if os.path.isdir(path):
        pattern = os.path.join(path, "*")
    elif os.path.isfile(path):
        return [path]
    else:
        pattern = path
    files = sorted(
        p
        for p in _glob.glob(pattern)
        if os.path.isfile(p) and not os.path.basename(p).startswith((".", "_"))
    )
    if not files:
        raise FileNotFoundError(f"no input files match {path!r}")
    return files


def is_avro_dir(spec: str) -> bool:
    """True when ``spec`` is a directory holding ``.avro`` part files."""
    return os.path.isdir(spec) and any(
        f.endswith(".avro") for f in os.listdir(spec)
    )


def narrow_avro_dir(spec: str) -> str:
    """A directory qualifying as Avro input -> its ``*.avro`` glob, so stray
    plain-named files (README, schema.json) never reach the decoder; any
    other spec passes through.  The ONE copy of this rule (read_game_avro,
    stream_score_parts, and load_dataset all route through it; the
    qualification predicate :func:`is_avro_dir` is shared too)."""
    if is_avro_dir(spec):
        return os.path.join(spec, "*.avro")
    return spec


def write_game_avro(
    path: str,
    dataset: GameDataset,
    index_maps: Dict[str, IndexMap],
    feature_bags: Optional[Dict[str, str]] = None,
) -> None:
    """Write a GameDataset as TrainingExampleAvro records (test fixtures and
    interop round-trips; the reference ships such files under
    photon-client/src/integTest/resources — SURVEY.md §4).

    ``feature_bags`` maps shard name -> record field name (default: the
    shard name itself).
    """
    feature_bags = feature_bags or {name: name for name in dataset.shards}
    id_cols = sorted(dataset.id_columns)
    bag_fields = [feature_bags[s] for s in sorted(feature_bags)]
    schema = training_example_schema(bag_fields, id_cols)

    def row_nonzeros(shard, i: int):
        """Per-row (feature id, value) pairs, zeros skipped."""
        if isinstance(shard, SparseShard):
            pairs = zip(shard.ids[i], shard.vals[i])
        else:
            row = shard.x[i]
            pairs = zip(np.nonzero(row)[0], row[np.nonzero(row)[0]])
        return [(int(f), float(v)) for f, v in pairs if float(v) != 0.0]

    shard_rows = {
        field: (dataset.shard(shard_name), index_maps[shard_name])
        for shard_name, field in feature_bags.items()
    }

    records = []
    for i in range(dataset.num_examples):
        rec = {
            "response": float(dataset.label[i]),
            "offset": float(dataset.offset[i]),
            "weight": float(dataset.weight[i]),
            "uid": str(i),
        }
        for field, (shard, imap) in shard_rows.items():
            bag = []
            for fid, val in row_nonzeros(shard, i):
                key = imap.get_key(fid)
                if key == INTERCEPT_KEY:
                    continue  # readers re-add the intercept per row
                name, _, term = key.partition("\x01")
                bag.append({"name": name, "term": term, "value": val})
            rec[field] = bag
        for col in id_cols:
            rec[_id_field(col, bag_fields)] = str(dataset.id_columns[col][i])
        records.append(rec)
    avro_codec.write_container(path, schema, records)


def _open_container_records(path: str):
    """:func:`avro_codec.open_container` behind the fault-injection
    ``io:read`` site — the retriable prefix of a file read (injected and
    real transient open/header failures both exercise the retry path)."""
    from photon_tpu.fault.injection import fault_point

    fault_point("io:read", path=path)
    return avro_codec.open_container(path)


def read_game_avro(
    path: str,
    feature_bags: Dict[str, str],
    id_columns: Sequence[str],
    index_maps: Optional[Dict[str, IndexMap]] = None,
    intercept: bool = True,
    telemetry=None,
) -> tuple[GameDataset, Dict[str, IndexMap]]:
    """Read TrainingExampleAvro file(s) into a GameDataset.

    ``feature_bags`` maps shard name -> record field holding that shard's
    feature array.  When ``index_maps`` is None, maps are built from the data
    in first-seen order (the FeatureIndexingJob path collapsed into the read,
    valid single-host); passing training-time maps reproduces the reference's
    fixed-index scoring path — features absent from a map are DROPPED, and
    when an intercept is present every example keeps it.

    Transient IO failures retry with backoff (``photon_tpu.fault.retry``;
    per-file on the native path, open/header on the streaming Python path
    — mid-stream decode errors are not retried because the CSR accumulators
    mutate incrementally), counted as ``io.retries`` on ``telemetry``.
    """
    from photon_tpu.fault.retry import retry_call

    files = _input_files(narrow_avro_dir(path))
    build_maps = index_maps is None

    native = _read_native(
        files, feature_bags, id_columns, index_maps, intercept, telemetry
    )
    if native is not None:
        label, offset, weight, ids_cols, flat_ids, flat_vals, nnz, vocab, n = native
        return _assemble_game_read(
            path, n, label, offset, weight, ids_cols, flat_ids, flat_vals,
            nnz, vocab if build_maps else None, feature_bags, id_columns,
            index_maps, intercept,
        )

    # ONE streaming pass: records are decoded lazily (avro_codec.
    # iter_container) and never retained — host memory is bounded by the
    # flat CSR accumulators below (~entry-sized, i.e. the size of the final
    # arrays), not by per-record dicts.  This is the single-host leg of the
    # reference's RDD ingestion (SURVEY.md §7 '1B-row ingestion').
    #
    # Feature ids are assigned on the fly in first-seen order, which is
    # exactly IndexMap.build's layout; the intercept lands at the END of the
    # vocabulary, so intercept entries carry a -1 sentinel during the scan
    # and are patched once the final vocabulary size is known.
    label = array("f")
    offset = array("f")
    weight = array("f")
    ids_cols: Dict[str, list] = {c: [] for c in id_columns}
    if build_maps:
        vocab: Dict[str, Dict[str, int]] = {s: {} for s in feature_bags}
    flat_ids: Dict[str, array] = {s: array("i") for s in feature_bags}
    flat_vals: Dict[str, array] = {s: array("f") for s in feature_bags}
    nnz: Dict[str, array] = {s: array("i") for s in feature_bags}

    i = 0
    for fpath in files:
        fh, schema, named, sync = retry_call(
            lambda p=fpath: _open_container_records(p),
            site="avro:read", telemetry=telemetry,
        )
        with fh:
            record_iter = avro_codec.iter_records(fh, schema, named, sync, fpath)
            for rec in record_iter:
                label.append(rec["response"])
                offset.append(rec.get("offset") or 0.0)
                weight.append(1.0 if rec.get("weight") is None else rec["weight"])
                for col in id_columns:
                    field = f"{col}__id" if f"{col}__id" in rec else col
                    if field not in rec:
                        raise KeyError(f"record {i} missing id column {col!r}")
                    ids_cols[col].append(rec[field])
                for shard_name, field in feature_bags.items():
                    f_ids, f_vals = flat_ids[shard_name], flat_vals[shard_name]
                    m = 0
                    if build_maps:
                        seen = vocab[shard_name]
                        for ntv in rec.get(field, ()):
                            key = feature_key(ntv["name"], ntv["term"])
                            if key == INTERCEPT_KEY:
                                continue  # implicit: appended once below
                            fid = seen.setdefault(key, len(seen))
                            f_ids.append(fid)
                            f_vals.append(ntv["value"])
                            m += 1
                    else:
                        imap = index_maps[shard_name]
                        for ntv in rec.get(field, ()):
                            key = feature_key(ntv["name"], ntv["term"])
                            if key == INTERCEPT_KEY:
                                continue
                            fid = imap.get_id(key)
                            if fid >= 0:  # absent from a fixed map -> dropped
                                f_ids.append(fid)
                                f_vals.append(ntv["value"])
                                m += 1
                    if build_maps:
                        if intercept:
                            f_ids.append(-1)  # final id patched after the scan
                            f_vals.append(1.0)
                            m += 1
                    elif index_maps[shard_name].intercept_id is not None:
                        f_ids.append(index_maps[shard_name].intercept_id)
                        f_vals.append(1.0)
                        m += 1
                    nnz[shard_name].append(m)
                i += 1
    return _assemble_game_read(
        path, i, label, offset, weight, ids_cols, flat_ids, flat_vals, nnz,
        vocab if build_maps else None, feature_bags, id_columns, index_maps,
        intercept,
    )


def _read_native(files, feature_bags, id_columns, index_maps, intercept,
                 telemetry=None):
    """Columnar native decode of all files (src/avro_game.cpp); returns the
    same accumulator tuple the Python loop produces, or None whenever any
    file falls outside the native subset (non-null codec, unexpected field
    types, missing id columns, stale .so) — the Python reader then runs.

    Per-record Python work is eliminated: the C++ decoder emits flat
    streams with (name, term) pairs interned in first-seen ENTRY order, so
    feature-id assignment (a Python dict walk in the record loop) becomes a
    vocab-sized loop plus numpy remaps — identical ids, values, and
    ordering (intercept appended last within each record) to the Python
    path, pinned by tests.
    """
    if os.environ.get("PHOTON_TPU_NO_NATIVE_AVRO", "") not in ("", "0"):
        return None
    try:
        from photon_tpu.native import avro_native
        from photon_tpu.native.build import get_lib
    except Exception:  # noqa: BLE001 — native is always optional
        return None
    if get_lib() is None:
        return None
    from photon_tpu.data.index_map import INTERCEPT_KEY, feature_key

    build_maps = index_maps is None
    bag_fields = set(feature_bags.values())

    # Header-only pre-flight over ALL files: the fallback decision must be
    # O(files), never O(dataset) — decoding 63 parts natively and then
    # discovering part 64 is outside the subset would throw that work away
    # and re-read everything in Python.
    plans = []
    try:
        for fp in files:
            with open(fp, "rb") as f:
                schema, meta, sync = avro_codec.read_header_meta(f, fp)
                data_offset = f.tell()
            if meta.get("avro.codec", b"null") not in (b"null", b""):
                return None
            if not isinstance(schema, dict):
                return None
            fields = {fld["name"] for fld in schema.get("fields", [])}
            id_field_of = {}
            for col in id_columns:
                field = f"{col}__id" if f"{col}__id" in fields else col
                if field not in fields:
                    return None  # Python path raises the canonical KeyError
                id_field_of[col] = field
            compiled = avro_native.compile_schema(
                schema, bag_fields, set(id_field_of.values()),
                opt_defaults={"offset": 0.0, "weight": 1.0},
                dbl_fields={"response", "offset", "weight"},
            )
            if compiled is None or "response" not in compiled.dbl_slots:
                return None
            plans.append((fp, data_offset, sync, compiled, id_field_of))
    except ValueError:
        # Malformed header: the Python reader produces the canonical error.
        return None

    labels, offsets, weights = [], [], []
    idcols_out: Dict[str, list] = {c: [] for c in id_columns}
    flat_parts: Dict[str, tuple] = {s: ([], [], []) for s in feature_bags}
    gvocab = {s: {} for s in feature_bags} if build_maps else None
    SKIP = -2  # removed entry: intercept-in-data or dropped-by-fixed-map
    n_total = 0

    # Decode files on the host-IO pool (the native call releases the GIL);
    # results are consumed strictly in file order, so first-seen vocab
    # interning stays byte-identical to a sequential read.  Each in-flight
    # decode holds a full file's columns, so cap the concurrency and keep
    # the result window tight (workers + 1 resident files, not 2*workers).
    from photon_tpu.fault.injection import fault_point
    from photon_tpu.utils.io_pool import io_threads, map_ordered

    def _decode(plan):
        # Whole-file decode is atomic (nothing mutated on failure), so the
        # pool retries it wholesale on transient IO errors.
        fault_point("io:read", path=plan[0])
        return avro_native.decode_file(plan[0], plan[1], plan[2], plan[3])

    decode_workers = min(io_threads(), 4)
    decoded_iter = map_ordered(
        _decode, plans, workers=decode_workers, window=decode_workers + 1,
        retry_site="avro:read", telemetry=telemetry,
    )
    for (fp, data_offset, sync, compiled, id_field_of), decoded in zip(
        plans, decoded_iter
    ):
        if decoded is None:
            return None
        n = decoded.n
        n_total += n
        labels.append(decoded.doubles["response"].astype(np.float32))
        off = decoded.doubles.get("offset")
        offsets.append(
            np.zeros(n, np.float32) if off is None else off.astype(np.float32)
        )
        wgt = decoded.doubles.get("weight")
        weights.append(
            np.ones(n, np.float32) if wgt is None else wgt.astype(np.float32)
        )
        for col in id_columns:
            idcols_out[col].extend(decoded.id_columns[id_field_of[col]].tolist())

        for shard_name, field in feature_bags.items():
            nnz_f, pair_ids, vals, pairs = decoded.bags[field]
            nnz_f = nnz_f.astype(np.int64)
            # Vocab-sized feature-id lookup table (pairs are in first-seen
            # entry order, so setdefault here reproduces the Python loop's
            # per-entry first-seen assignment exactly).
            lut = np.empty(max(len(pairs), 1), np.int64)
            if build_maps:
                seen = gvocab[shard_name]
                for pi, (nm, tm) in enumerate(pairs):
                    key = feature_key(nm, tm)
                    lut[pi] = SKIP if key == INTERCEPT_KEY else seen.setdefault(
                        key, len(seen)
                    )
            else:
                imap = index_maps[shard_name]
                for pi, (nm, tm) in enumerate(pairs):
                    key = feature_key(nm, tm)
                    if key == INTERCEPT_KEY:
                        lut[pi] = SKIP
                    else:
                        fid = imap.get_id(key)
                        lut[pi] = fid if fid >= 0 else SKIP
            entry_fids = (
                lut[pair_ids] if len(pair_ids) else np.empty(0, np.int64)
            )
            keep = entry_fids != SKIP
            nnz_kept = nnz_f
            if not keep.all():
                row_idx = np.repeat(np.arange(n, dtype=np.int64), nnz_f)
                nnz_kept = nnz_f - np.bincount(row_idx[~keep], minlength=n)
            kept_fids = entry_fids[keep]
            kept_vals = vals[keep]
            add_intercept = (build_maps and intercept) or (
                not build_maps
                and index_maps[shard_name].intercept_id is not None
            )
            if add_intercept:
                # Intercept entry appended LAST within each record, exactly
                # like the Python loop: scatter kept entries to their final
                # per-row positions, then fill the per-row tail slot.
                final_nnz = nnz_kept + 1
                total = int(final_nnz.sum())
                out_ids = np.empty(total, np.int64)
                out_vals = np.empty(total, np.float32)
                starts = np.concatenate(([0], np.cumsum(final_nnz)))[:-1]
                kept_rows = np.repeat(np.arange(n, dtype=np.int64), nnz_kept)
                kept_starts = np.concatenate(([0], np.cumsum(nnz_kept)))[:-1]
                idx_in_row = np.arange(
                    int(nnz_kept.sum()), dtype=np.int64
                ) - np.repeat(kept_starts, nnz_kept)
                pos = starts[kept_rows] + idx_in_row
                out_ids[pos] = kept_fids
                out_vals[pos] = kept_vals
                tail = starts + nnz_kept
                out_ids[tail] = (
                    -1 if build_maps else index_maps[shard_name].intercept_id
                )
                out_vals[tail] = 1.0
            else:
                final_nnz, out_ids, out_vals = nnz_kept, kept_fids, kept_vals
            fids_l, vals_l, nnz_l = flat_parts[shard_name]
            fids_l.append(out_ids.astype(np.int32))
            vals_l.append(out_vals.astype(np.float32))
            nnz_l.append(final_nnz.astype(np.int32))

    def _cat(parts, dtype):
        return (
            np.concatenate(parts) if parts else np.empty(0, dtype)
        ).astype(dtype, copy=False)

    flat_ids = {s: _cat(flat_parts[s][0], np.int32) for s in feature_bags}
    flat_vals = {s: _cat(flat_parts[s][1], np.float32) for s in feature_bags}
    nnz = {s: _cat(flat_parts[s][2], np.int32) for s in feature_bags}
    return (
        _cat(labels, np.float32), _cat(offsets, np.float32),
        _cat(weights, np.float32), idcols_out, flat_ids, flat_vals, nnz,
        gvocab, n_total,
    )


def _assemble_game_read(
    path, n, label, offset, weight, ids_cols, flat_ids, flat_vals, nnz,
    vocab, feature_bags, id_columns, index_maps, intercept,
):
    """Shared tail of the Python and native read paths: vocab -> index
    maps, vectorized flat CSR -> padded-COO shards, dataset assembly.
    Accumulators may be stdlib ``array`` (Python loop) or numpy arrays
    (native decoder); ``vocab`` is non-None exactly in build-maps mode."""
    build_maps = vocab is not None
    if n == 0:
        raise NoRecordsError(f"no records in {path!r}")

    if build_maps:
        index_maps = {
            s: IndexMap.build(list(vocab[s]), intercept=intercept)
            for s in feature_bags
        }

    # Vectorized CSR -> padded-COO per shard.
    shards: Dict[str, SparseShard] = {}
    for shard_name in feature_bags:
        imap = index_maps[shard_name]
        counts = np.asarray(nnz[shard_name], dtype=np.int32).astype(np.int64)
        ids_f = np.array(flat_ids[shard_name], dtype=np.int32)
        vals_f = np.asarray(flat_vals[shard_name], dtype=np.float32)
        if build_maps and imap.intercept_id is not None:
            ids_f[ids_f < 0] = imap.intercept_id
        k = pad_row_capacity(counts)
        ids = np.zeros((n, k), np.int32)
        vals = np.zeros((n, k), np.float32)
        row_idx = np.repeat(np.arange(n, dtype=np.int64), counts)
        starts = np.concatenate(([0], np.cumsum(counts)))[:-1]
        col_idx = np.arange(int(counts.sum()), dtype=np.int64) - np.repeat(
            starts, counts
        )
        ids[row_idx, col_idx] = ids_f
        vals[row_idx, col_idx] = vals_f
        shards[shard_name] = SparseShard(ids, vals, len(imap))

    dataset = GameDataset(
        label=np.asarray(label, dtype=np.float32),
        offset=np.asarray(offset, dtype=np.float32),
        weight=np.asarray(weight, dtype=np.float32),
        shards=shards,
        id_columns={c: np.asarray(v) for c, v in ids_cols.items()},
    )
    return dataset, index_maps
