"""GAME training-data IO: Avro records -> GameDataset (+ index maps).

Rebuild of the reference's ``AvroDataReader`` / ``GameConverters`` path
(photon-client .../data/avro, SURVEY.md §2.3 'Avro IO' and §3.1): training
records carry a ``response``, optional ``offset``/``weight``/``uid``, one or
more **feature bags** (arrays of name/term/value records), and entity-id
columns (e.g. ``userId``) for random effects.  Reading indexes each bag's
(name, term) keys through a per-shard :class:`IndexMap` and packs rows into
the framework's padded-COO feature shards.

TPU-native shape: the reference materializes an
``RDD[(UniqueSampleId, GameDatum)]``; here the row order of the file(s) IS
the unique-sample-id, and the output is one columnar :class:`GameDataset`
ready for host-side entity bucketing (photon_tpu.game.data).
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Dict, Optional, Sequence

import numpy as np

from photon_tpu.data import avro_codec
from photon_tpu.data.batch import pad_row_capacity
from photon_tpu.data.index_map import INTERCEPT_KEY, IndexMap, feature_key
from photon_tpu.game.data import GameDataset, SparseShard

FEATURE_SCHEMA = {
    "type": "record",
    "name": "FeatureAvro",
    "namespace": "photon_tpu.generated",
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string"},
        {"name": "value", "type": "double"},
    ],
}


def _id_field(col: str, bag_fields: Sequence[str]) -> str:
    """Record field holding entity-id column ``col``; suffixed when the name
    collides with a feature-bag field (synthetic data uses one name for
    both the shard and its entity column)."""
    return f"{col}__id" if col in bag_fields else col


def training_example_schema(
    feature_bags: Sequence[str], id_columns: Sequence[str]
) -> dict:
    """Schema for one training record; mirrors the reference's
    TrainingExampleAvro shape (response/offset/weight/uid + feature bags),
    with one array-of-FeatureAvro field per bag and one string field per
    entity-id column."""
    fields = [
        {"name": "response", "type": "double"},
        {"name": "offset", "type": ["null", "double"], "default": None},
        {"name": "weight", "type": ["null", "double"], "default": None},
        {"name": "uid", "type": ["null", "string"], "default": None},
    ]
    for i, bag in enumerate(feature_bags):
        items = FEATURE_SCHEMA if i == 0 else "FeatureAvro"
        fields.append({"name": bag, "type": {"type": "array", "items": items}})
    for col in id_columns:
        fields.append({"name": _id_field(col, feature_bags), "type": "string"})
    return {
        "type": "record",
        "name": "TrainingExampleAvro",
        "namespace": "photon_tpu.generated",
        "fields": fields,
    }


def _input_files(path: str) -> list[str]:
    """A file, a directory of part files, or a glob -> sorted file list."""
    if os.path.isdir(path):
        files = sorted(
            p
            for p in _glob.glob(os.path.join(path, "*"))
            if os.path.isfile(p) and not os.path.basename(p).startswith((".", "_"))
        )
    elif os.path.isfile(path):
        files = [path]
    else:
        files = sorted(_glob.glob(path))
    if not files:
        raise FileNotFoundError(f"no input files match {path!r}")
    return files


def write_game_avro(
    path: str,
    dataset: GameDataset,
    index_maps: Dict[str, IndexMap],
    feature_bags: Optional[Dict[str, str]] = None,
) -> None:
    """Write a GameDataset as TrainingExampleAvro records (test fixtures and
    interop round-trips; the reference ships such files under
    photon-client/src/integTest/resources — SURVEY.md §4).

    ``feature_bags`` maps shard name -> record field name (default: the
    shard name itself).
    """
    feature_bags = feature_bags or {name: name for name in dataset.shards}
    id_cols = sorted(dataset.id_columns)
    bag_fields = [feature_bags[s] for s in sorted(feature_bags)]
    schema = training_example_schema(bag_fields, id_cols)

    def row_nonzeros(shard, i: int):
        """Per-row (feature id, value) pairs, zeros skipped."""
        if isinstance(shard, SparseShard):
            pairs = zip(shard.ids[i], shard.vals[i])
        else:
            row = shard.x[i]
            pairs = zip(np.nonzero(row)[0], row[np.nonzero(row)[0]])
        return [(int(f), float(v)) for f, v in pairs if float(v) != 0.0]

    shard_rows = {
        field: (dataset.shard(shard_name), index_maps[shard_name])
        for shard_name, field in feature_bags.items()
    }

    records = []
    for i in range(dataset.num_examples):
        rec = {
            "response": float(dataset.label[i]),
            "offset": float(dataset.offset[i]),
            "weight": float(dataset.weight[i]),
            "uid": str(i),
        }
        for field, (shard, imap) in shard_rows.items():
            bag = []
            for fid, val in row_nonzeros(shard, i):
                key = imap.get_key(fid)
                if key == INTERCEPT_KEY:
                    continue  # readers re-add the intercept per row
                name, _, term = key.partition("\x01")
                bag.append({"name": name, "term": term, "value": val})
            rec[field] = bag
        for col in id_cols:
            rec[_id_field(col, bag_fields)] = str(dataset.id_columns[col][i])
        records.append(rec)
    avro_codec.write_container(path, schema, records)


def read_game_avro(
    path: str,
    feature_bags: Dict[str, str],
    id_columns: Sequence[str],
    index_maps: Optional[Dict[str, IndexMap]] = None,
    intercept: bool = True,
) -> tuple[GameDataset, Dict[str, IndexMap]]:
    """Read TrainingExampleAvro file(s) into a GameDataset.

    ``feature_bags`` maps shard name -> record field holding that shard's
    feature array.  When ``index_maps`` is None, maps are built from the data
    in first-seen order (the FeatureIndexingJob path collapsed into the read,
    valid single-host); passing training-time maps reproduces the reference's
    fixed-index scoring path — features absent from a map are DROPPED, and
    when an intercept is present every example keeps it.
    """
    files = _input_files(path)
    records: list[dict] = []
    for f in files:
        _, recs = avro_codec.read_container(f)
        records.extend(recs)
    if not records:
        raise ValueError(f"no records in {path!r}")

    n = len(records)
    label = np.empty(n, np.float32)
    offset = np.zeros(n, np.float32)
    weight = np.ones(n, np.float32)
    ids_cols: Dict[str, list] = {c: [] for c in id_columns}
    build_maps = index_maps is None
    if build_maps:
        index_maps = {}
        key_order: Dict[str, dict] = {s: {} for s in feature_bags}

    # Pass 1: labels/ids + (optionally) discover feature vocabularies.
    for i, rec in enumerate(records):
        label[i] = rec["response"]
        if rec.get("offset") is not None:
            offset[i] = rec["offset"]
        if rec.get("weight") is not None:
            weight[i] = rec["weight"]
        for col in id_columns:
            field = f"{col}__id" if f"{col}__id" in rec else col
            if field not in rec:
                raise KeyError(f"record {i} missing id column {col!r}")
            ids_cols[col].append(rec[field])
        if build_maps:
            for shard_name, field in feature_bags.items():
                seen = key_order[shard_name]
                for ntv in rec.get(field, ()):
                    key = feature_key(ntv["name"], ntv["term"])
                    if key != INTERCEPT_KEY:  # the intercept is implicit
                        seen.setdefault(key, None)
    if build_maps:
        for shard_name in feature_bags:
            index_maps[shard_name] = IndexMap.build(
                list(key_order[shard_name]), intercept=intercept
            )

    # Pass 2: index features into padded-COO shards.
    shards: Dict[str, SparseShard] = {}
    for shard_name, field in feature_bags.items():
        imap = index_maps[shard_name]
        rows_ids, rows_vals, nnz = [], [], np.zeros(n, np.int64)
        for i, rec in enumerate(records):
            r_ids, r_vals = [], []
            for ntv in rec.get(field, ()):
                key = feature_key(ntv["name"], ntv["term"])
                if key == INTERCEPT_KEY:
                    continue  # implicit: appended once below
                fid = imap.get_id(key)
                if fid >= 0:
                    r_ids.append(fid)
                    r_vals.append(ntv["value"])
            if imap.intercept_id is not None:
                r_ids.append(imap.intercept_id)
                r_vals.append(1.0)
            rows_ids.append(r_ids)
            rows_vals.append(r_vals)
            nnz[i] = len(r_ids)
        k = pad_row_capacity(nnz)
        ids = np.zeros((n, k), np.int32)
        vals = np.zeros((n, k), np.float32)
        for i in range(n):
            m = int(nnz[i])
            ids[i, :m] = rows_ids[i]
            vals[i, :m] = rows_vals[i]
        shards[shard_name] = SparseShard(ids, vals, len(imap))

    dataset = GameDataset(
        label=label,
        offset=offset,
        weight=weight,
        shards=shards,
        id_columns={c: np.asarray(v) for c, v in ids_cols.items()},
    )
    return dataset, index_maps
