"""GAME training-data IO: Avro records -> GameDataset (+ index maps).

Rebuild of the reference's ``AvroDataReader`` / ``GameConverters`` path
(photon-client .../data/avro, SURVEY.md §2.3 'Avro IO' and §3.1): training
records carry a ``response``, optional ``offset``/``weight``/``uid``, one or
more **feature bags** (arrays of name/term/value records), and entity-id
columns (e.g. ``userId``) for random effects.  Reading indexes each bag's
(name, term) keys through a per-shard :class:`IndexMap` and packs rows into
the framework's padded-COO feature shards.

TPU-native shape: the reference materializes an
``RDD[(UniqueSampleId, GameDatum)]``; here the row order of the file(s) IS
the unique-sample-id, and the output is one columnar :class:`GameDataset`
ready for host-side entity bucketing (photon_tpu.game.data).
"""

from __future__ import annotations

import glob as _glob
import os
from array import array

# The streaming CSR builders reinterpret array('i')/array('f') buffers as
# np.int32/np.float32 via np.frombuffer — valid only while C int/float are
# 4 bytes.  True on every supported platform; checked once so a layout
# mismatch fails loudly instead of corrupting ids/values (ADVICE r3).
# A real raise, not an assert: `python -O` must not strip the guard.
if array("i").itemsize != 4 or array("f").itemsize != 4:
    raise ImportError(
        "C int/float are not 32-bit on this platform; the game_io streaming "
        "readers' frombuffer reinterpretation would corrupt data"
    )
from typing import Dict, Optional, Sequence

import numpy as np

from photon_tpu.data import avro_codec
from photon_tpu.data.batch import pad_row_capacity
from photon_tpu.data.index_map import INTERCEPT_KEY, IndexMap, feature_key
from photon_tpu.game.data import GameDataset, SparseShard

FEATURE_SCHEMA = {
    "type": "record",
    "name": "FeatureAvro",
    "namespace": "photon_tpu.generated",
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string"},
        {"name": "value", "type": "double"},
    ],
}


class NoRecordsError(ValueError):
    """Raised when an input yields zero records — a typed contract so
    streaming callers can skip routinely-empty part files without matching
    on error text."""


def _id_field(col: str, bag_fields: Sequence[str]) -> str:
    """Record field holding entity-id column ``col``; suffixed when the name
    collides with a feature-bag field (synthetic data uses one name for
    both the shard and its entity column)."""
    return f"{col}__id" if col in bag_fields else col


def training_example_schema(
    feature_bags: Sequence[str], id_columns: Sequence[str]
) -> dict:
    """Schema for one training record; mirrors the reference's
    TrainingExampleAvro shape (response/offset/weight/uid + feature bags),
    with one array-of-FeatureAvro field per bag and one string field per
    entity-id column."""
    fields = [
        {"name": "response", "type": "double"},
        {"name": "offset", "type": ["null", "double"], "default": None},
        {"name": "weight", "type": ["null", "double"], "default": None},
        {"name": "uid", "type": ["null", "string"], "default": None},
    ]
    for i, bag in enumerate(feature_bags):
        items = FEATURE_SCHEMA if i == 0 else "FeatureAvro"
        fields.append({"name": bag, "type": {"type": "array", "items": items}})
    for col in id_columns:
        fields.append({"name": _id_field(col, feature_bags), "type": "string"})
    return {
        "type": "record",
        "name": "TrainingExampleAvro",
        "namespace": "photon_tpu.generated",
        "fields": fields,
    }


def _input_files(path: str) -> list[str]:
    """A file, a directory of part files, or a glob -> sorted file list.

    Both the directory and glob branches exclude non-files and dot-/
    underscore-prefixed names (in-progress part files and committer markers
    like ``_SUCCESS`` / ``_tmp-0.avro`` must never reach a decoder).
    """
    if os.path.isdir(path):
        pattern = os.path.join(path, "*")
    elif os.path.isfile(path):
        return [path]
    else:
        pattern = path
    files = sorted(
        p
        for p in _glob.glob(pattern)
        if os.path.isfile(p) and not os.path.basename(p).startswith((".", "_"))
    )
    if not files:
        raise FileNotFoundError(f"no input files match {path!r}")
    return files


def is_avro_dir(spec: str) -> bool:
    """True when ``spec`` is a directory holding ``.avro`` part files."""
    return os.path.isdir(spec) and any(
        f.endswith(".avro") for f in os.listdir(spec)
    )


def narrow_avro_dir(spec: str) -> str:
    """A directory qualifying as Avro input -> its ``*.avro`` glob, so stray
    plain-named files (README, schema.json) never reach the decoder; any
    other spec passes through.  The ONE copy of this rule (read_game_avro,
    stream_score_parts, and load_dataset all route through it; the
    qualification predicate :func:`is_avro_dir` is shared too)."""
    if is_avro_dir(spec):
        return os.path.join(spec, "*.avro")
    return spec


def write_game_avro(
    path: str,
    dataset: GameDataset,
    index_maps: Dict[str, IndexMap],
    feature_bags: Optional[Dict[str, str]] = None,
) -> None:
    """Write a GameDataset as TrainingExampleAvro records (test fixtures and
    interop round-trips; the reference ships such files under
    photon-client/src/integTest/resources — SURVEY.md §4).

    ``feature_bags`` maps shard name -> record field name (default: the
    shard name itself).
    """
    feature_bags = feature_bags or {name: name for name in dataset.shards}
    id_cols = sorted(dataset.id_columns)
    bag_fields = [feature_bags[s] for s in sorted(feature_bags)]
    schema = training_example_schema(bag_fields, id_cols)

    def row_nonzeros(shard, i: int):
        """Per-row (feature id, value) pairs, zeros skipped."""
        if isinstance(shard, SparseShard):
            pairs = zip(shard.ids[i], shard.vals[i])
        else:
            row = shard.x[i]
            pairs = zip(np.nonzero(row)[0], row[np.nonzero(row)[0]])
        return [(int(f), float(v)) for f, v in pairs if float(v) != 0.0]

    shard_rows = {
        field: (dataset.shard(shard_name), index_maps[shard_name])
        for shard_name, field in feature_bags.items()
    }

    records = []
    for i in range(dataset.num_examples):
        rec = {
            "response": float(dataset.label[i]),
            "offset": float(dataset.offset[i]),
            "weight": float(dataset.weight[i]),
            "uid": str(i),
        }
        for field, (shard, imap) in shard_rows.items():
            bag = []
            for fid, val in row_nonzeros(shard, i):
                key = imap.get_key(fid)
                if key == INTERCEPT_KEY:
                    continue  # readers re-add the intercept per row
                name, _, term = key.partition("\x01")
                bag.append({"name": name, "term": term, "value": val})
            rec[field] = bag
        for col in id_cols:
            rec[_id_field(col, bag_fields)] = str(dataset.id_columns[col][i])
        records.append(rec)
    avro_codec.write_container(path, schema, records)


def read_game_avro(
    path: str,
    feature_bags: Dict[str, str],
    id_columns: Sequence[str],
    index_maps: Optional[Dict[str, IndexMap]] = None,
    intercept: bool = True,
) -> tuple[GameDataset, Dict[str, IndexMap]]:
    """Read TrainingExampleAvro file(s) into a GameDataset.

    ``feature_bags`` maps shard name -> record field holding that shard's
    feature array.  When ``index_maps`` is None, maps are built from the data
    in first-seen order (the FeatureIndexingJob path collapsed into the read,
    valid single-host); passing training-time maps reproduces the reference's
    fixed-index scoring path — features absent from a map are DROPPED, and
    when an intercept is present every example keeps it.
    """
    files = _input_files(narrow_avro_dir(path))
    build_maps = index_maps is None

    # ONE streaming pass: records are decoded lazily (avro_codec.
    # iter_container) and never retained — host memory is bounded by the
    # flat CSR accumulators below (~entry-sized, i.e. the size of the final
    # arrays), not by per-record dicts.  This is the single-host leg of the
    # reference's RDD ingestion (SURVEY.md §7 '1B-row ingestion').
    #
    # Feature ids are assigned on the fly in first-seen order, which is
    # exactly IndexMap.build's layout; the intercept lands at the END of the
    # vocabulary, so intercept entries carry a -1 sentinel during the scan
    # and are patched once the final vocabulary size is known.
    label = array("f")
    offset = array("f")
    weight = array("f")
    ids_cols: Dict[str, list] = {c: [] for c in id_columns}
    if build_maps:
        vocab: Dict[str, Dict[str, int]] = {s: {} for s in feature_bags}
    flat_ids: Dict[str, array] = {s: array("i") for s in feature_bags}
    flat_vals: Dict[str, array] = {s: array("f") for s in feature_bags}
    nnz: Dict[str, array] = {s: array("i") for s in feature_bags}

    i = 0
    for f in files:
        for rec in avro_codec.iter_container(f):
            label.append(rec["response"])
            offset.append(rec.get("offset") or 0.0)
            weight.append(1.0 if rec.get("weight") is None else rec["weight"])
            for col in id_columns:
                field = f"{col}__id" if f"{col}__id" in rec else col
                if field not in rec:
                    raise KeyError(f"record {i} missing id column {col!r}")
                ids_cols[col].append(rec[field])
            for shard_name, field in feature_bags.items():
                f_ids, f_vals = flat_ids[shard_name], flat_vals[shard_name]
                m = 0
                if build_maps:
                    seen = vocab[shard_name]
                    for ntv in rec.get(field, ()):
                        key = feature_key(ntv["name"], ntv["term"])
                        if key == INTERCEPT_KEY:
                            continue  # implicit: appended once below
                        fid = seen.setdefault(key, len(seen))
                        f_ids.append(fid)
                        f_vals.append(ntv["value"])
                        m += 1
                else:
                    imap = index_maps[shard_name]
                    for ntv in rec.get(field, ()):
                        key = feature_key(ntv["name"], ntv["term"])
                        if key == INTERCEPT_KEY:
                            continue
                        fid = imap.get_id(key)
                        if fid >= 0:  # absent from a fixed map -> dropped
                            f_ids.append(fid)
                            f_vals.append(ntv["value"])
                            m += 1
                if build_maps:
                    if intercept:
                        f_ids.append(-1)  # final id patched after the scan
                        f_vals.append(1.0)
                        m += 1
                elif index_maps[shard_name].intercept_id is not None:
                    f_ids.append(index_maps[shard_name].intercept_id)
                    f_vals.append(1.0)
                    m += 1
                nnz[shard_name].append(m)
            i += 1
    n = i
    if n == 0:
        raise NoRecordsError(f"no records in {path!r}")

    if build_maps:
        index_maps = {
            s: IndexMap.build(list(vocab[s]), intercept=intercept)
            for s in feature_bags
        }

    # Vectorized CSR -> padded-COO per shard.
    shards: Dict[str, SparseShard] = {}
    for shard_name in feature_bags:
        imap = index_maps[shard_name]
        counts = np.frombuffer(nnz[shard_name], dtype=np.int32).astype(np.int64)
        ids_f = np.frombuffer(flat_ids[shard_name], dtype=np.int32).copy()
        vals_f = np.frombuffer(flat_vals[shard_name], dtype=np.float32)
        if build_maps and imap.intercept_id is not None:
            ids_f[ids_f < 0] = imap.intercept_id
        k = pad_row_capacity(counts)
        ids = np.zeros((n, k), np.int32)
        vals = np.zeros((n, k), np.float32)
        row_idx = np.repeat(np.arange(n, dtype=np.int64), counts)
        starts = np.concatenate(([0], np.cumsum(counts)))[:-1]
        col_idx = np.arange(int(counts.sum()), dtype=np.int64) - np.repeat(
            starts, counts
        )
        ids[row_idx, col_idx] = ids_f
        vals[row_idx, col_idx] = vals_f
        shards[shard_name] = SparseShard(ids, vals, len(imap))

    dataset = GameDataset(
        label=np.frombuffer(label, dtype=np.float32).copy(),
        offset=np.frombuffer(offset, dtype=np.float32).copy(),
        weight=np.frombuffer(weight, dtype=np.float32).copy(),
        shards=shards,
        id_columns={c: np.asarray(v) for c, v in ids_cols.items()},
    )
    return dataset, index_maps
