"""Down-samplers: uniform and negative-class, with weight correction.

Rebuild of the reference's sampling package (photon-lib ``sampling/``:
``DownSampler``, ``DefaultDownSampler``, ``BinaryClassificationDownSampler``
— SURVEY.md §2.1): down-sampling bounds the fixed-effect training cost on
huge datasets, and re-weights kept rows so the objective stays an unbiased
estimate of the full-data objective.

Host-side row selection (the device never sees dropped rows): samplers
return (row indices, corrected weights) computed from label/weight columns.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class DownSampler:
    """Base: keep every row (rate 1)."""

    rate: float = 1.0

    def __post_init__(self):
        if not 0.0 < self.rate <= 1.0:
            raise ValueError(f"downsampling rate must be in (0, 1], got {self.rate}")

    def down_sample(
        self, label: np.ndarray, weight: np.ndarray, seed: int = 0
    ) -> tuple[np.ndarray, np.ndarray]:
        """(kept row indices, corrected weights for those rows)."""
        rows = np.arange(len(label))
        return rows, np.asarray(weight, np.float32)


@dataclasses.dataclass(frozen=True)
class DefaultDownSampler(DownSampler):
    """Uniform Bernoulli(rate) keep; kept weights scaled by 1/rate."""

    def down_sample(self, label, weight, seed: int = 0):
        if self.rate >= 1.0:
            return super().down_sample(label, weight, seed)
        rng = np.random.default_rng(seed)
        rows = np.nonzero(rng.random(len(label)) < self.rate)[0]
        return rows, (np.asarray(weight, np.float32)[rows] / self.rate)


@dataclasses.dataclass(frozen=True)
class BinaryClassificationDownSampler(DownSampler):
    """Keep every positive; keep negatives at ``rate`` with 1/rate weight
    correction (the reference's imbalanced-binary-data sampler)."""

    def down_sample(self, label, weight, seed: int = 0):
        if self.rate >= 1.0:
            return super().down_sample(label, weight, seed)
        label = np.asarray(label)
        weight = np.asarray(weight, np.float32)
        rng = np.random.default_rng(seed)
        positive = label > 0.5
        keep = positive | (rng.random(len(label)) < self.rate)
        rows = np.nonzero(keep)[0]
        corrected = weight[rows].copy()
        negatives = ~positive[rows]
        corrected[negatives] /= self.rate
        return rows, corrected


def get_down_sampler(kind: str, rate: float) -> DownSampler:
    """``default`` (uniform) or ``binary`` (negative-class only).  The
    reference picks binary for logistic/hinge tasks, default otherwise."""
    key = kind.strip().lower()
    if key == "default":
        return DefaultDownSampler(rate)
    if key == "binary":
        return BinaryClassificationDownSampler(rate)
    raise KeyError(f"unknown down-sampler {kind!r} (want default|binary)")


def down_sampler_for_task(task_type: str, rate: float) -> DownSampler:
    from photon_tpu.core.losses import BINARY_TASKS

    binary = task_type.lower() in BINARY_TASKS
    return get_down_sampler("binary" if binary else "default", rate)
