"""Large-scale input pipeline: sharded files, chunked batches, streaming.

Rebuild of the reference's billion-row story (SURVEY.md §7 step 7).  The
reference leans on Spark: executors each own partitions, ``treeAggregate``
folds them, and the "pipeline" is the cluster.  The TPU equivalents, by
dataset size:

1. **Fits in HBM** — one :class:`photon_tpu.data.batch.SparseBatch` (the
   default path everywhere else in the framework).
2. **Fits in HBM, but intermediates don't** — :class:`ChunkedBatch`: the
   batch stacked as ``[num_chunks, rows_per_chunk, ...]``; the objective
   folds chunks with ``lax.scan``, bounding peak activation memory while
   remaining ONE jittable function — it slots into the existing jitted
   optimizers unchanged (chunk loop ≙ the reference's per-partition fold).
3. **Host RAM only** — :func:`stream_chunks` + :func:`streaming_lbfgs`:
   per-file host parsing sharded across processes, double-buffered
   host→device transfer, and a host-loop L-BFGS whose every objective
   evaluation is one streamed pass (what a Spark scan of a disk-persisted
   RDD does, minus the JVM).

Multi-host: :func:`shard_files_for_process` gives each host its file slice
and :func:`make_global_batch` assembles per-process arrays into one global
sharded array (``jax.make_array_from_process_local_data``) over the mesh.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import queue
import threading
from typing import Callable, Iterable, Iterator, List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from photon_tpu.core.optimizers.base import (
    ConvergenceReason,
    OptimizerConfig,
    OptimizerResult,
    init_history,
)
from photon_tpu.core.optimizers.lbfgs import _two_loop_direction
from photon_tpu.data.batch import SparseBatch
from photon_tpu.fault.injection import fault_point

# Module-level jit: a per-call `jax.jit(...)` wrapper would carry a fresh
# trace cache, re-tracing the two-loop recursion for every lambda in a
# streamed sweep (same discipline as core/problem.cached_solver).
_jitted_direction = jax.jit(_two_loop_direction, static_argnames=("m",))

Array = jax.Array


# ---------------------------------------------------------------------------
# Tier 2: device-resident chunked batch (lax.scan fold inside jit)
# ---------------------------------------------------------------------------


class ChunkedBatch(NamedTuple):
    """A sparse batch stacked into fixed-size chunks.

    Shapes: ids/vals ``[C, R, k]``; label/offset/weight ``[C, R]``.  Padding
    rows carry zero weight.  The per-chunk fold bounds peak memory for the
    gather intermediates at one chunk's worth (the reference's
    per-partition aggregator fold — SURVEY.md §3.4).
    """

    ids: Array
    vals: Array
    label: Array
    offset: Array
    weight: Array

    @property
    def num_chunks(self) -> int:
        return self.ids.shape[0]

    @property
    def num_examples(self) -> int:
        # Physical rows incl. padding; objectives ignore zero-weight rows.
        return self.ids.shape[0] * self.ids.shape[1]

    def chunk(self, c: int) -> SparseBatch:
        return SparseBatch(
            self.ids[c], self.vals[c], self.label[c],
            self.offset[c], self.weight[c],
        )


def chunk_batch(batch: SparseBatch, rows_per_chunk: int) -> ChunkedBatch:
    """Stack a flat SparseBatch into ``[C, rows_per_chunk, ...]`` chunks."""
    n, k = batch.ids.shape
    c = max(1, -(-n // rows_per_chunk))
    pad = c * rows_per_chunk - n

    def pad_rows(a):
        widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
        return jnp.pad(a, widths)

    return ChunkedBatch(
        ids=pad_rows(batch.ids).reshape(c, rows_per_chunk, k),
        vals=pad_rows(batch.vals).reshape(c, rows_per_chunk, k),
        label=pad_rows(batch.label).reshape(c, rows_per_chunk),
        offset=pad_rows(batch.offset).reshape(c, rows_per_chunk),
        weight=pad_rows(batch.weight).reshape(c, rows_per_chunk),
    )


@dataclasses.dataclass(frozen=True)
class ChunkedGlmObjective:
    """GlmObjective adapter folding a ChunkedBatch with ``lax.scan``.

    Exposes the same (value / value_and_grad / hessian_vector) surface the
    optimization problems use, so the existing jitted optimizers run
    unchanged on chunked data.
    """

    objective: object  # GlmObjective

    @property
    def l1_weight(self) -> float:
        return self.objective.l1_weight

    @property
    def l2_weight(self) -> float:
        return self.objective.l2_weight

    def _fold(self, fn, w: Array, chunks: ChunkedBatch, init):
        def step(acc, chunk_leaves):
            chunk = SparseBatch(*chunk_leaves)
            out = fn(w, chunk)
            return jax.tree.map(jnp.add, acc, out), None

        acc, _ = lax.scan(step, init, tuple(chunks))
        return acc

    def value(self, w: Array, chunks: ChunkedBatch) -> Array:
        data = self._fold(self.objective.data_value, w, chunks, jnp.zeros(()))
        if self.objective.l2_weight:
            data = data + 0.5 * self.objective.l2_weight * jnp.dot(w, w)
        return data

    def value_and_grad(self, w: Array, chunks: ChunkedBatch) -> tuple[Array, Array]:
        value, grad = self._fold(
            lambda w_, c: jax.value_and_grad(self.objective.data_value)(w_, c),
            w, chunks, (jnp.zeros(()), jnp.zeros_like(w)),
        )
        l2 = self.objective.l2_weight
        if l2:
            value = value + 0.5 * l2 * jnp.dot(w, w)
            grad = grad + l2 * w
        return value, grad

    def grad(self, w: Array, chunks: ChunkedBatch) -> Array:
        return self.value_and_grad(w, chunks)[1]

    def hessian_vector(self, w: Array, v: Array, chunks: ChunkedBatch) -> Array:
        hv = self._fold(
            lambda w_, c: jax.jvp(
                lambda u: jax.grad(self.objective.data_value)(u, c), (w,), (v,)
            )[1],
            w, chunks, jnp.zeros_like(w),
        )
        return hv + self.objective.l2_weight * v

    def hessian_diagonal(self, w: Array, chunks: ChunkedBatch) -> Array:
        diag = self._fold(
            # data-only diagonal: subtract the per-chunk l2 the underlying
            # objective adds, then add it back once.
            lambda w_, c: self.objective.hessian_diagonal(w_, c)
            - self.objective.l2_weight,
            w, chunks, jnp.zeros_like(w),
        )
        return diag + self.objective.l2_weight

    def hessian_matrix(self, w: Array, chunks: ChunkedBatch) -> Array:
        d = w.shape[0]
        eye = jnp.eye(d, dtype=w.dtype)
        h = self._fold(
            lambda w_, c: self.objective.hessian_matrix(w_, c)
            - self.objective.l2_weight * eye,
            w, chunks, jnp.zeros((d, d), w.dtype),
        )
        return h + self.objective.l2_weight * eye


# ---------------------------------------------------------------------------
# Tier 3: host streaming
# ---------------------------------------------------------------------------


def shard_files_for_process(
    files: Sequence[str],
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
) -> List[str]:
    """This host's slice of the input file list (round-robin by index) —
    the multi-host replacement for Spark's partition assignment."""
    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    return [f for i, f in enumerate(sorted(files)) if i % pc == pi]


def stream_chunks(
    load_chunk: Callable[[int], Optional[SparseBatch]],
    num_chunks: int,
    prefetch: int = 2,
) -> Iterator[SparseBatch]:
    """Iterate device-ready chunks with background prefetch.

    ``load_chunk(i)`` runs on a worker thread (parse + device_put); the
    consumer overlaps device compute with the next chunk's host work —
    the double-buffering SURVEY.md §7 calls for.  Abandoning the generator
    mid-pass (e.g. an exception in the consumer) stops the worker and
    releases its prefetched device batches instead of pinning them.

    With ``PHOTON_IO_THREADS > 1`` (multi-core hosts) chunks load
    CONCURRENTLY on the host-IO pool — the measured 10M-row streaming pass
    is parse-dominated on one core (BASELINE.md row 5s).  Delivery stays
    strictly ordered, and the in-flight window keeps the SAME device-memory
    bound as the single-worker queue (``prefetch`` chunks plus the one
    being consumed) — concurrency beyond that requires the operator to
    raise ``prefetch``, because each in-flight chunk is device-resident.
    """
    from photon_tpu.utils.io_pool import io_threads, map_ordered

    workers = io_threads()
    # The pooled path needs prefetch >= 2 to beat the single-worker queue
    # (with a window of 1 it would serialize load and compute, losing even
    # the overlap the queue below provides).
    if workers > 1 and num_chunks > 1 and prefetch >= 2:
        yield from (
            c for c in map_ordered(
                load_chunk, range(num_chunks),
                workers=min(workers, prefetch), window=prefetch,
            ) if c is not None
        )
        return
    q: "queue.Queue" = queue.Queue(maxsize=max(1, prefetch))
    sentinel = object()
    stop = threading.Event()

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for i in range(num_chunks):
                if stop.is_set() or not put(load_chunk(i)):
                    return
        except BaseException as e:  # surface worker errors to the consumer
            put(e)
        finally:
            put(sentinel)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is sentinel:
                return
            if isinstance(item, BaseException):
                raise item
            if item is not None:
                yield item
    finally:
        stop.set()
        # Drain so a blocked worker can observe the stop event and exit.
        while True:
            try:
                q.get_nowait()
            except queue.Empty:
                break


@functools.partial(jax.jit, static_argnames=("objective", "kernel"))
def _chunk_value_and_grad(objective, kernel, w: Array, chunk: SparseBatch):
    """Shared jitted per-chunk kernel: module-level with the (hashable)
    objective AND the resolved kernel static, so a lambda sweep reuses
    one compilation per chunk shape — and a mid-process kernel flip
    (env change, kernel-comparison sweep) gets a NEW program instead of
    silently reusing the old kernel's under an identical treedef.

    ``kernel`` is resolved EAGERLY by the caller (the caller strips the
    reg weights, so this is the data term): chunks whose carried aux
    wins the measured selection run that fast kernel; everything else —
    bare chunks, and aux-carrying chunks whose selection says autodiff —
    takes the literal pre-round-5 autodiff path.  Deliberately NOT the
    objective's generic value_and_grad: its pallas_sparse fused branch
    would silently change streamed numerics for PHOTON_TPU_PALLAS=1
    users and contradict the bench's kernel attribution."""
    if kernel is None:
        return jax.value_and_grad(objective.data_value)(w, chunk)
    return objective._fast_data_value_and_grad(w, chunk, kernel)


@dataclasses.dataclass
class StreamingObjective:
    """Objective whose every evaluation is one streamed pass over chunks.

    ``chunk_iter_factory`` yields device SparseBatches (typically via
    :func:`stream_chunks`); evaluation accumulates a jitted per-chunk
    value+grad.  In multi-process runs each process streams its own file
    shard and ``all_reduce`` sums across hosts (psum over DCN).
    """

    objective: object  # GlmObjective
    chunk_iter_factory: Callable[[], Iterable[SparseBatch]]
    all_reduce: Optional[Callable[[Array], Array]] = None
    # The kernel the LAST streamed pass actually ran (first chunk's
    # measured selection; "autodiff" when no fast layout won) — bench
    # attribution must report what ran, not the attach-time intent.
    last_kernel: Optional[str] = None

    def value_and_grad(self, w: Array) -> tuple[Array, Array]:
        # Strip the reg weights from the static jit key: data_value ignores
        # them, so every lambda in a sweep shares one compilation.
        data_obj = dataclasses.replace(
            self.objective, l2_weight=0.0, l1_weight=0.0
        )
        total_v = jnp.zeros(())
        total_g = jnp.zeros_like(w)
        first = True
        for chunk in self.chunk_iter_factory():
            # Resolve the kernel eagerly per chunk (host-side; the
            # selection probe caches per shape bucket) and pass it as a
            # STATIC jit argument — see _chunk_value_and_grad.
            kernel = data_obj._sparse_kernel(chunk, int(w.shape[0]))
            if first:
                first = False
                self.last_kernel = kernel or "autodiff"
            v, g = _chunk_value_and_grad(data_obj, kernel, w, chunk)
            total_v = total_v + v
            total_g = total_g + g
        if self.all_reduce is not None:
            total_v = self.all_reduce(total_v)
            total_g = self.all_reduce(total_g)
        l2 = self.objective.l2_weight
        if l2:
            total_v = total_v + 0.5 * l2 * jnp.dot(w, w)
            total_g = total_g + l2 * w
        return total_v, total_g


def streaming_lbfgs(
    objective: StreamingObjective,
    w0: Array,
    config: OptimizerConfig = OptimizerConfig(),
    checkpointer=None,
    checkpoint_every: int = 1,
    resume_state=None,
    fingerprint: Optional[dict] = None,
) -> OptimizerResult:
    """Host-loop L-BFGS for datasets that only fit on the host.

    Same math as :func:`photon_tpu.core.optimizers.lbfgs` (shared two-loop
    recursion, Armijo backtracking, cautious pair updates) but each function
    evaluation is a streamed pass, so the outer loop lives in Python — the
    shape of the reference's driver loop, where every evaluation is a
    cluster scan (SURVEY.md §3.4).

    ``checkpointer`` (a :class:`photon_tpu.fault.checkpoint.
    StreamCheckpointer`) snapshots the COMPLETE loop state — iterate,
    gradient, curvature-pair ring buffer, convergence history, and the
    host scalars — every ``checkpoint_every`` iterations plus a final
    ``completed`` snapshot, published through the same atomic protocol and
    async publisher as the GAME descent checkpoints.  ``resume_state``
    restores a snapshot: a resumed fit continues EXACTLY where the
    interrupted one stopped (every streamed pass already run is skipped,
    including the initial evaluation), and a completed snapshot rebuilds
    the result without streaming a single pass.  ``fingerprint`` is
    stamped into each snapshot; compatibility checks are the caller's.
    """
    m = config.history_length
    d = w0.shape[0]
    dtype = w0.dtype
    direction = _jitted_direction

    if resume_state is not None and resume_state.completed:
        if (_stream_converged(resume_state.reason)
                or resume_state.reason == ConvergenceReason.OBJECTIVE_NOT_IMPROVING
                or resume_state.iteration >= config.max_iterations):
            # The fit genuinely finished (converged, line search dead, or
            # this run's budget already spent): rebuild the result from the
            # final snapshot — zero streamed passes.  A fit that stopped on
            # MAX_ITERATIONS resumed with a LARGER budget falls through and
            # continues — same rule as descent checkpoints (the iteration
            # budget is deliberately outside the fingerprint).
            return _result_from_stream_state(resume_state)

    if resume_state is not None:
        arrays, scalars = resume_state.arrays, resume_state.scalars
        w = jnp.asarray(arrays["w"], dtype)
        g = jnp.asarray(arrays["g"], dtype)
        S = jnp.asarray(arrays["S"], dtype)
        Y = jnp.asarray(arrays["Y"], dtype)
        rho = jnp.asarray(arrays["rho"], dtype)
        hv, hg, hvalid = (
            np.array(arrays["hv"]), np.array(arrays["hg"]),
            np.array(arrays["hvalid"]),
        )
        f, gnorm0 = float(scalars["f"]), float(scalars["gnorm0"])
        num_pairs = int(scalars["num_pairs"])
        insert_pos = int(scalars["insert_pos"])
        gamma = float(scalars["gamma"])
        it = resume_state.iteration
        reason = ConvergenceReason.NOT_CONVERGED
    else:
        w = w0
        f, g = objective.value_and_grad(w)
        f, gnorm0 = float(f), float(jnp.linalg.norm(g))
        hv, hg, hvalid = init_history(
            config.max_iterations, jnp.asarray(f), jnp.asarray(gnorm0)
        )
        # np.array (copy): asarray of a jax array is a read-only view.
        hv, hg, hvalid = np.array(hv), np.array(hg), np.array(hvalid)

        S = jnp.zeros((m, d), dtype)
        Y = jnp.zeros((m, d), dtype)
        rho = jnp.zeros(m, dtype)
        num_pairs, insert_pos, gamma = 0, 0, 1.0
        reason = ConvergenceReason.NOT_CONVERGED
        it = 0

        if gnorm0 == 0.0:
            reason = ConvergenceReason.GRADIENT_TOLERANCE

    def snapshot(completed: bool):
        from photon_tpu.fault.checkpoint import StreamState

        return StreamState(
            iteration=it,
            # The history buffers are the loop's MUTABLE scratch — copy at
            # snapshot time so the async publisher serializes a frozen
            # view, not whatever the next iteration wrote into them.
            arrays={
                "w": w, "g": g, "S": S, "Y": Y, "rho": rho,
                "hv": hv.copy(), "hg": hg.copy(), "hvalid": hvalid.copy(),
            },
            scalars={
                "f": f, "gnorm0": gnorm0, "num_pairs": num_pairs,
                "insert_pos": insert_pos, "gamma": gamma,
            },
            completed=completed,
            reason=int(reason),
            fingerprint=fingerprint or {},
        )

    from photon_tpu.fault.preemption import (
        PreemptedError,
        consume_preempt_injection,
        preemption_requested,
        preemption_reason,
    )
    from photon_tpu.fault.watchdog import heartbeat

    try:
        while reason == ConvergenceReason.NOT_CONVERGED:
            # The streamed-GLM preemption site: a killed fit restarts from
            # the last published mid-fit snapshot (the descent:kill analog).
            fault_point("stream:kill", iteration=it)
            # Preemption-aware shutdown (SIGTERM, or the injected `preempt`
            # site): the loop state is consistent here, so snapshot it NOW
            # — off the checkpoint_every cadence if need be — drain the
            # publisher so the save is durably published, and exit with
            # the distinct preemption error the driver maps to exit 75.
            consume_preempt_injection(it)
            if preemption_requested():
                if checkpointer is not None:
                    checkpointer.save(snapshot(completed=False))
                    checkpointer.drain()
                    hint = "resume with --resume auto"
                else:
                    hint = ("no checkpointer configured — a restart begins "
                            "from scratch (set --checkpoint-dir)")
                raise PreemptedError(
                    f"preempted ({preemption_reason()}) before streamed "
                    f"L-BFGS iteration {it}; {hint}"
                )
            heartbeat("stream.iteration")
            reason, w, f, g, S, Y, rho, num_pairs, insert_pos, gamma, it = (
                _stream_lbfgs_step(
                    objective, config, direction, m, dtype, reason, w, f, g,
                    gnorm0, S, Y, rho, num_pairs, insert_pos, gamma, it,
                    hv, hg, hvalid,
                )
            )
            if (checkpointer is not None and checkpoint_every
                    and reason == ConvergenceReason.NOT_CONVERGED
                    and it % checkpoint_every == 0):
                checkpointer.save(snapshot(completed=False))
    except BaseException:
        if checkpointer is not None:
            checkpointer.drain(reraise=False)
        raise
    finally:
        # Retire the iteration heartbeat: a finished (or dead) fit going
        # quiet is not a stall the watchdog should flag.
        from photon_tpu.fault.watchdog import complete

        complete("stream.iteration")
    if checkpointer is not None:
        # Final snapshot: resume rebuilds the finished result without a
        # single streamed pass; the drain is the final-iteration barrier.
        checkpointer.save(snapshot(completed=True))
        checkpointer.drain()

    return OptimizerResult(
        w=w,
        value=jnp.asarray(f),
        grad_norm=jnp.linalg.norm(g),
        iterations=jnp.asarray(it, jnp.int32),
        converged=jnp.asarray(_stream_converged(reason)),
        reason=jnp.asarray(reason, jnp.int32),
        history_value=jnp.asarray(hv),
        history_grad_norm=jnp.asarray(hg),
        history_valid=jnp.asarray(hvalid),
    )


def _stream_converged(reason) -> bool:
    """The ONE definition of 'this streamed fit converged' — shared by the
    live loop's result and the completed-checkpoint rebuild, so the two can
    never drift apart on what counts as converged."""
    return reason in (
        ConvergenceReason.GRADIENT_TOLERANCE,
        ConvergenceReason.FUNCTION_VALUES_TOLERANCE,
    )


def _result_from_stream_state(state) -> OptimizerResult:
    """OptimizerResult rebuilt from a ``completed`` stream snapshot."""
    reason = int(state.reason)
    g = np.asarray(state.arrays["g"])
    return OptimizerResult(
        w=jnp.asarray(state.arrays["w"]),
        value=jnp.asarray(float(state.scalars["f"])),
        grad_norm=jnp.asarray(float(np.linalg.norm(g))),
        iterations=jnp.asarray(state.iteration, jnp.int32),
        converged=jnp.asarray(_stream_converged(reason)),
        reason=jnp.asarray(reason, jnp.int32),
        history_value=jnp.asarray(state.arrays["hv"]),
        history_grad_norm=jnp.asarray(state.arrays["hg"]),
        history_valid=jnp.asarray(state.arrays["hvalid"]),
    )


def _stream_lbfgs_step(
    objective, config, direction, m, dtype, reason, w, f, g, gnorm0,
    S, Y, rho, num_pairs, insert_pos, gamma, it, hv, hg, hvalid,
):
    """One host-loop L-BFGS iteration (direction, line search, pair
    update, convergence check); history buffers mutate in place."""
    while True:  # single pass; structured as a loop for early breaks
        dvec = direction(
            g, S, Y, rho,
            jnp.asarray(num_pairs, jnp.int32),
            jnp.asarray(insert_pos, jnp.int32),
            jnp.asarray(gamma, dtype), m,
        )
        dir_deriv = float(jnp.dot(g, dvec))
        if dir_deriv >= 0.0:
            dvec = -g
            dir_deriv = -float(jnp.dot(g, g))
        t = 1.0 if num_pairs else 1.0 / max(float(jnp.linalg.norm(g)), 1.0)

        ls_ok = False
        for _ in range(config.max_line_search):
            w_try = w + t * dvec
            f_try, g_try = objective.value_and_grad(w_try)
            f_try = float(f_try)
            if np.isfinite(f_try) and f_try <= f + 1e-4 * t * dir_deriv:
                ls_ok = True
                break
            t *= 0.5
        if not ls_ok:
            reason = ConvergenceReason.OBJECTIVE_NOT_IMPROVING
            break

        svec = w_try - w
        yvec = g_try - g
        sy = float(jnp.dot(svec, yvec))
        if sy > 1e-10:
            S = S.at[insert_pos].set(svec)
            Y = Y.at[insert_pos].set(yvec)
            rho = rho.at[insert_pos].set(1.0 / sy)
            num_pairs = min(num_pairs + 1, m)
            insert_pos = (insert_pos + 1) % m
            gamma = sy / max(float(jnp.dot(yvec, yvec)), 1e-30)

        gnorm_new = float(jnp.linalg.norm(g_try))
        it += 1
        if it < hv.shape[0]:
            hv[it], hg[it], hvalid[it] = f_try, gnorm_new, True
        # Same tolerance semantics as base.check_convergence.
        if gnorm_new <= config.gradient_tolerance * max(gnorm0, 1.0):
            reason = ConvergenceReason.GRADIENT_TOLERANCE
        elif abs(f - f_try) / max(abs(f), 1e-12) <= config.tolerance:
            reason = ConvergenceReason.FUNCTION_VALUES_TOLERANCE
        elif it >= config.max_iterations:
            reason = ConvergenceReason.MAX_ITERATIONS
        w, f, g = w_try, f_try, g_try
        break

    return reason, w, f, g, S, Y, rho, num_pairs, insert_pos, gamma, it


def _scan_rows_nnz(path: str) -> tuple[int, int]:
    """(row count, max nnz per row) without materializing values — the
    metadata-only pass used when the feature dimension is already known.
    Uses the native line indexer when available (the Python fallback is
    the measurable cost of the metadata phase at 10M-row scale)."""
    try:
        from photon_tpu.native import libsvm_native

        meta = libsvm_native.scan_meta(path)
        if meta is not None:
            return meta
    except Exception:  # noqa: BLE001 — metadata must not depend on the .so
        pass
    rows, max_nnz = 0, 0
    with open(path, "rb") as f:
        for raw in f:
            line = raw.split(b"#", 1)[0].strip()
            if not line:
                continue
            rows += 1
            max_nnz = max(max_nnz, line.count(b":"))
    return rows, max_nnz


class LibsvmFileSource:
    """Streamed LIBSVM input: one chunk per file, re-parsed each pass.

    A cheap metadata scan (native parser) fixes the global feature
    dimension and nonzero capacity up front so every chunk shares one
    padded layout (one XLA program).  Each objective evaluation then
    re-streams the files — the disk-persisted-RDD behavior of the
    reference's scans, with parse/transfer overlapped via
    :func:`stream_chunks`.
    """

    def __init__(
        self,
        files: Sequence[str],
        intercept: bool = True,
        binary_labels: bool = True,
        feature_dim: Optional[int] = None,
        telemetry=None,
    ):
        """Metadata must cover the GLOBAL file list (multi-process runs
        shard files AFTER construction via :meth:`with_files` — scanning a
        local shard would give hosts divergent coefficient dimensions).

        With ``feature_dim`` given (e.g. from a feature-indexing job's index
        map), only a cheap row/nnz line scan runs; otherwise each file is
        parsed once to discover the max feature id.  ``telemetry`` receives
        the per-part ``io.retries`` counter of the retried chunk loads.
        """
        if not files:
            raise ValueError("LibsvmFileSource needs at least one file")
        self.files = list(files)
        self.intercept = intercept
        self.binary_labels = binary_labels
        self.telemetry = telemetry
        dim, capacity, total = feature_dim or 0, 1, 0
        if feature_dim is None:
            from photon_tpu.data.libsvm import parse_libsvm
            from photon_tpu.utils.io_pool import io_threads, map_ordered

            def _meta(f):
                # Reduce INSIDE the worker: the pool's result window then
                # holds 3-int tuples, not whole parsed files.
                from photon_tpu.data.libsvm import parse_csr_or_none

                csr = parse_csr_or_none(f)
                if csr is not None:
                    _, row_ptr, _, _, fdim = csr
                    counts = np.diff(row_ptr)
                    cap = int(counts.max()) if counts.size else 1
                    return fdim, max(cap, 1), int(row_ptr.shape[0]) - 1
                data = parse_libsvm(f)
                cap = max((len(r[0]) for r in data.rows), default=1)
                return data.dim, cap, data.num_examples

            # Each in-progress parse holds a whole file transiently: cap
            # the concurrency (same rationale as the validate-data pass).
            for fdim, fcap, fn_rows in map_ordered(
                _meta, self.files, workers=min(io_threads(), 4)
            ):
                dim = max(dim, fdim)
                capacity = max(capacity, fcap)
                total += fn_rows
        else:
            for f in self.files:
                rows, max_nnz = _scan_rows_nnz(f)
                capacity = max(capacity, max_nnz)
                total += rows
        self.feature_dim = dim
        self.capacity = capacity + (1 if intercept else 0)
        self.num_examples = total
        self.dim = dim + (1 if intercept else 0)

    def with_files(self, files: Sequence[str]) -> "LibsvmFileSource":
        """Same (global) metadata, restricted stream list — each process
        calls this with its shard from :func:`shard_files_for_process`."""
        import copy

        out = copy.copy(self)
        out.files = list(files)
        return out

    def _load_chunk(self, i: int) -> SparseBatch:
        from photon_tpu.data.libsvm import load_sparse_batch
        from photon_tpu.fault.injection import fault_point
        from photon_tpu.fault.retry import retry_call

        def _load():
            # Flat-CSR fast path inside (skips per-row numpy views, which
            # cost more than the C++ parse at streaming scale);
            # self.capacity already counts the appended intercept column.
            fault_point("io:read", path=self.files[i])
            return load_sparse_batch(
                self.files[i],
                dim=self.feature_dim,
                intercept=self.intercept,
                capacity=self.capacity,
                binary_labels=self.binary_labels,
            )

        # Part-file re-parses happen once per objective pass: a transient
        # storage error mid-pass must cost a backoff, not the whole
        # streamed fit (io.retries counts recoveries).
        batch, _, _ = retry_call(
            _load, site="libsvm:read", telemetry=self.telemetry
        )
        from photon_tpu.data.stream_layouts import (
            attach_stream_aux,
            stream_kernel,
        )

        if stream_kernel() != "autodiff":
            # Fast-kernel layouts for streamed chunks (VERDICT r5 item
            # 3): built once per file on first touch, cached, then
            # re-attached per pass at stat+load cost.
            batch = attach_stream_aux(batch, self.dim, self.files[i])
        return batch

    def chunk_iter_factory(self) -> Iterable[SparseBatch]:
        # PHOTON_STREAM_PREFETCH raises the in-flight chunk window (each
        # chunk is device-resident, so this trades device memory for host
        # parse parallelism on multi-core hosts — see stream_chunks).
        from photon_tpu.utils.env import env_int

        return stream_chunks(
            self._load_chunk, len(self.files),
            prefetch=env_int("PHOTON_STREAM_PREFETCH", 2, minimum=1),
        )


# ---------------------------------------------------------------------------
# Multi-host assembly
# ---------------------------------------------------------------------------


def make_global_batch(local_batch: SparseBatch, mesh, axis: str = "data",
                      aligned_dim: Optional[int] = None):
    """Assemble per-process local rows into one globally-sharded batch
    (``jax.make_array_from_process_local_data`` over the mesh's data axis —
    the multi-host path SURVEY.md §7 names).  Single-process meshes reduce
    to a plain shard placement.

    With ``aligned_dim`` (and the kernel selector wanting them — same
    gate as ``shard_batch``), each process builds the aligned/xchg aux
    for ITS local row blocks, with the padded geometry and balanced
    block census agreed GLOBALLY via a process allgather — so the
    per-process stacked aux leaves concatenate into one uniformly-shaped
    global array and the fast kernels run per shard on every host
    (VERDICT r5 item 2, multi-process leg).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    def build(leaf):
        sharding = NamedSharding(
            mesh, P(axis, *([None] * (leaf.ndim - 1)))
        )
        return jax.make_array_from_process_local_data(
            sharding, np.asarray(leaf)
        )

    def build_tree(aux):
        return jax.tree.map(build, aux)

    core = SparseBatch(*(build(leaf) for leaf in local_batch[:5]))
    local_shards = int(mesh.local_mesh.shape[axis])

    def gather_geometry(local_arr: np.ndarray) -> np.ndarray:
        if jax.process_count() == 1:
            return local_arr
        from jax.experimental import multihost_utils

        return multihost_utils.process_allgather(local_arr, tiled=True)

    wants_aligned = False
    global_entries = None
    if aligned_dim is not None and local_batch.ids.ndim == 2:
        from photon_tpu.ops.sparse_grad_select import aligned_layout_wanted

        # Collective-agreement discipline: every decision that gates a
        # collective must itself be computed from GLOBALLY-agreed
        # inputs.  ``aligned_dim`` must be passed uniformly by every
        # process (caller contract, like the mesh itself); the entry
        # count is allgathered so the branch below is identical on
        # every host.
        shapes = np.asarray(gather_geometry(
            np.asarray([list(local_batch.ids.shape)], np.int64)
        ), np.int64)
        if len({tuple(row) for row in shapes.tolist()}) != 1:
            # make_array_from_process_local_data requires uniform
            # per-process contributions for P(axis) row sharding; with
            # unequal [n, k] SHAPES (entry counts alone could
            # coincide, e.g. 100x2 vs 50x4) the per-process aux (and
            # core) leaves would diverge into a cross-host hang.  The
            # gathered shapes are identical on every host, so every
            # process raises this SAME error — loud, not a deadlock.
            raise ValueError(
                f"make_global_batch requires equal local batch shapes "
                f"across processes (got {shapes.tolist()}); pad local "
                "batches first"
            )
        global_entries = int(shapes.prod(axis=1).sum())
        if (
            jax.process_count() > 1
            and os.environ.get("PHOTON_SPARSE_GRAD", "auto") == "auto"
        ):
            # Mirror DistributedGlmObjective._sparse_kernel's multi-
            # process auto pin: the objective will run autodiff, so
            # building (and shipping to HBM) aux it will never touch is
            # pure waste — AND this pin is what makes every remaining
            # gate host-uniform: the forced modes that can still reach
            # the attach resolve aligned_layout_wanted/xchg_route_wanted
            # from the env alone (no per-host probes or native-lib
            # loads), so no host can diverge around the geometry
            # collectives.  PHOTON_SPARSE_GRAD must be set uniformly
            # across processes (caller contract, like the mesh).
            wants_aligned = False
        else:
            wants_aligned = aligned_layout_wanted(global_entries)
    rebuilt = False
    if wants_aligned or (
        local_batch.fm is not None
        and int(local_batch.fm.ids.shape[0]) != local_shards
    ):
        # Rebuild the aux at the right granularity (one block per local
        # device) — and, when eligible, with the aligned/xchg layouts.
        from photon_tpu.data.batch import attach_feature_major

        local_batch = attach_feature_major(
            local_batch._replace(fm=None, al=None, al_t=None, xchg=None),
            shards=local_shards,
            aligned_dim=aligned_dim if wants_aligned else None,
            geometry_gather=gather_geometry,
            global_entries=global_entries,
        )
        rebuilt = True
    if local_batch.fm is not None:
        core = core._replace(
            fm=type(local_batch.fm)(*(build(leaf) for leaf in local_batch.fm))
        )
    if rebuilt:
        # Forward ONLY aux this assembly built (stacked, with globally
        # agreed geometry).  Caller-attached single-block aux cannot be
        # row-sharded — it is stripped above, exactly as before round 5.
        for aux_name in ("al", "al_t", "xchg"):
            aux = getattr(local_batch, aux_name, None)
            if aux is not None:
                core = core._replace(**{aux_name: build_tree(aux)})
    return core
