"""Model persistence: name/term-keyed coefficient export, Avro-compatible.

Rebuild of the reference's ``ModelProcessingUtils.saveGameModelToHDFS`` /
model loading (photon-client .../data/avro — SURVEY.md §5 'Checkpoint'):
coefficients are keyed by their (name, term) feature strings, so models are
portable across feature-index rebuilds; loading joins the stored keys against
the current index map.

Formats:
- ``avro`` (default): Object Container File with a Bayesian-linear-model
  record (modelClass, means[], variances[] as name/term/value records),
  mirroring the reference's published schema shape.
- ``json``: same content as plain JSON (debuggable, diff-able).
"""

from __future__ import annotations

import json
import os
from typing import Optional

import jax.numpy as jnp
import numpy as np

from photon_tpu.data import avro_codec
from photon_tpu.data.index_map import DELIMITER, INTERCEPT_KEY, IndexMap
from photon_tpu.models.glm import Coefficients, GeneralizedLinearModel, model_for_task

NAME_TERM_VALUE_SCHEMA = {
    "type": "record",
    "name": "NameTermValueAvro",
    "namespace": "photon_tpu.generated",
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string"},
        {"name": "value", "type": "double"},
    ],
}

GLM_MODEL_SCHEMA = {
    "type": "record",
    "name": "BayesianLinearModelAvro",
    "namespace": "photon_tpu.generated",
    "fields": [
        {"name": "modelClass", "type": "string"},
        {"name": "means", "type": {"type": "array", "items": NAME_TERM_VALUE_SCHEMA}},
        {
            "name": "variances",
            "type": ["null", {"type": "array", "items": "NameTermValueAvro"}],
            "default": None,
        },
        {"name": "lossFunction", "type": ["null", "string"], "default": None},
    ],
}


def _split_key(key: str) -> tuple[str, str]:
    if DELIMITER in key:
        name, term = key.split(DELIMITER, 1)
        return name, term
    return key, ""


def _ntv_list(values: np.ndarray, index_map: IndexMap, sparse_threshold: float = 0.0):
    out = []
    for i, v in enumerate(values):
        if abs(float(v)) <= sparse_threshold and index_map.get_key(i) != INTERCEPT_KEY:
            continue
        name, term = _split_key(index_map.get_key(i))
        out.append({"name": name, "term": term, "value": float(v)})
    return out


def save_glm_model(
    path: str,
    model: GeneralizedLinearModel,
    index_map: IndexMap,
    fmt: str = "avro",
) -> None:
    """Write a single GLM as one name/term-keyed record.

    Zero coefficients are dropped (sparse storage, as the reference does for
    OWL-QN models); the intercept is always kept.
    """
    means = np.asarray(model.coefficients.means)
    record = {
        "modelClass": model.task_type,
        "means": _ntv_list(means, index_map),
        "variances": (
            None
            if model.coefficients.variances is None
            else _ntv_list(np.asarray(model.coefficients.variances), index_map)
        ),
        "lossFunction": model.loss.name,
    }
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if fmt == "avro":
        avro_codec.write_container(path, GLM_MODEL_SCHEMA, [record])
    elif fmt == "json":
        with open(path, "w") as f:
            json.dump(record, f, indent=1)
    else:
        raise ValueError(f"unknown model format {fmt!r}")


def load_glm_model(
    path: str,
    index_map: IndexMap,
    fmt: Optional[str] = None,
) -> GeneralizedLinearModel:
    """Load a GLM, joining stored (name, term) keys onto ``index_map``.

    Keys absent from the map are dropped (feature-index rebuild semantics,
    as in the reference's model loader).
    """
    if fmt is None:
        with open(path, "rb") as f:
            fmt = "avro" if f.read(4) == avro_codec.MAGIC else "json"
    if fmt == "avro":
        _, records = avro_codec.read_container(path)
        record = records[0]
    else:
        with open(path) as f:
            record = json.load(f)

    def to_vector(ntvs) -> np.ndarray:
        vec = np.zeros(len(index_map), np.float32)
        for ntv in ntvs:
            key = (
                f"{ntv['name']}{DELIMITER}{ntv['term']}" if ntv["term"] else ntv["name"]
            )
            idx = index_map.get_id(key)
            if idx >= 0:
                vec[idx] = ntv["value"]
        return vec

    means = jnp.asarray(to_vector(record["means"]))
    variances = (
        None
        if record.get("variances") is None
        else jnp.asarray(to_vector(record["variances"]))
    )
    return model_for_task(record["modelClass"], Coefficients(means, variances))
