"""photon_tpu — a TPU-native framework for large-scale GLMs and GAME/GLMix models.

A from-scratch JAX/XLA rebuild of the capabilities of Photon ML
(reference: dchen40/photon-ml, a fork of linkedin/photon-ml):

- Generalized Linear Models: logistic, linear, Poisson, smoothed-hinge,
  with L1/L2/elastic-net regularization.
- Batch second-order optimizers (L-BFGS, OWL-QN, TRON) expressed as
  jit-compiled ``lax.while_loop`` state machines.
- GAME (Generalized Additive Mixed Effect) models: a fixed effect plus
  per-entity random effects trained by coordinate descent, with the
  fixed effect data-parallel over a device mesh (psum over ICI) and
  random-effect local solves vmapped + sharded across chips.

Layer map (mirrors the reference's photon-lib / photon-api / photon-client
split — see SURVEY.md §1):

- :mod:`photon_tpu.core`       — math core (losses, objectives, optimizers,
                                 normalization, stats)  ≙ photon-lib
- :mod:`photon_tpu.models`     — GLM + GAME model classes ≙ supervised/model
- :mod:`photon_tpu.data`       — readers (LIBSVM/Avro), index maps, sparse
                                 batches, GAME data pipeline ≙ data/avro + data
- :mod:`photon_tpu.parallel`   — mesh / sharding / collectives ≙ Spark runtime
- :mod:`photon_tpu.game`       — CoordinateDescent, coordinates, estimator
                                 ≙ photon-api algorithm/estimators
- :mod:`photon_tpu.evaluation` — evaluators (AUC, RMSE, …) ≙ evaluation
- :mod:`photon_tpu.drivers`    — CLI train/score drivers ≙ photon-client
- :mod:`photon_tpu.ops`        — Pallas TPU kernels for hot ops
- :mod:`photon_tpu.telemetry`  — metrics registry, tracing spans, run
                                 reports ≙ driver logs / Spark UI
"""

__version__ = "0.1.0"

from photon_tpu.core import losses  # noqa: F401
