"""Random and Bayesian (GP) hyperparameter search.

Rebuild of the reference's hyperparameter package (photon-lib
``hyperparameter/``: ``RandomSearch``, ``GaussianProcessSearch`` — a
Gaussian-process surrogate with a Matérn-5/2 kernel and an
expected-improvement acquisition — and the ``EvaluationFunction`` contract;
SURVEY.md §2.1 and §3.5).  The reference searches regularization weights in
log space over a full GAME fit per trial; the search machinery itself is
model-agnostic.

TPU-native shape: the GP math (kernel, Cholesky solve, EI) is pure JAX and
jit-compiled; trials are Python-side because each trial IS a full training
run.  Candidate acquisition is maximized over a sampled candidate set — a
quasi-random sweep is robust in the low-dimensional spaces (1-4 reg weights)
this is used for, and avoids a second optimizer in the loop.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Dict, List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class SearchDimension:
    """One hyperparameter: a (low, high) range, optionally log-scaled
    (regularization weights are log-scaled in the reference)."""

    name: str
    low: float
    high: float
    log_scale: bool = False

    def __post_init__(self):
        if not self.high > self.low:
            raise ValueError(f"{self.name}: high must exceed low")
        if self.log_scale and self.low <= 0:
            raise ValueError(f"{self.name}: log scale needs low > 0")

    def to_unit(self, value: float) -> float:
        if self.log_scale:
            return (math.log(value) - math.log(self.low)) / (
                math.log(self.high) - math.log(self.low)
            )
        return (value - self.low) / (self.high - self.low)

    def from_unit(self, u: float) -> float:
        u = min(max(u, 0.0), 1.0)
        if self.log_scale:
            return math.exp(
                math.log(self.low) + u * (math.log(self.high) - math.log(self.low))
            )
        return self.low + u * (self.high - self.low)


@dataclasses.dataclass(frozen=True)
class SearchSpace:
    dimensions: Sequence[SearchDimension]

    @property
    def ndim(self) -> int:
        return len(self.dimensions)

    def to_unit(self, params: Dict[str, float]) -> np.ndarray:
        return np.asarray(
            [d.to_unit(params[d.name]) for d in self.dimensions], np.float64
        )

    def from_unit(self, u: np.ndarray) -> Dict[str, float]:
        return {d.name: d.from_unit(float(x)) for d, x in zip(self.dimensions, u)}


@dataclasses.dataclass
class EvaluationRecord:
    params: Dict[str, float]
    value: float


class _SearchBase:
    """Shared trial loop: propose → evaluate → record → track best.

    ``evaluation_function`` maps a params dict to a scalar metric (the
    reference's EvaluationFunction runs a full GameEstimator.fit per call —
    SURVEY.md §3.5); ``maximize`` gives the metric direction.
    """

    def __init__(
        self,
        space: SearchSpace,
        evaluation_function: Callable[[Dict[str, float]], float],
        maximize: bool = False,
        seed: int = 0,
    ):
        self.space = space
        self.fn = evaluation_function
        self.maximize = maximize
        self.rng = np.random.default_rng(seed)
        self.history: List[EvaluationRecord] = []

    # Internally everything MINIMIZES (negate for maximize).
    def _observed(self) -> tuple[np.ndarray, np.ndarray]:
        x = np.stack([self.space.to_unit(r.params) for r in self.history])
        y = np.asarray([r.value for r in self.history], np.float64)
        return x, (-y if self.maximize else y)

    def _evaluate(self, unit_x: np.ndarray) -> EvaluationRecord:
        params = self.space.from_unit(unit_x)
        record = EvaluationRecord(params, float(self.fn(params)))
        self.history.append(record)
        return record

    @property
    def best(self) -> EvaluationRecord:
        if not self.history:
            raise RuntimeError("no trials evaluated yet")
        pick = max if self.maximize else min
        return pick(self.history, key=lambda r: r.value)

    def _propose(self, trial_index: int) -> np.ndarray:
        raise NotImplementedError

    def find(self, num_trials: int) -> EvaluationRecord:
        for t in range(num_trials):
            self._evaluate(self._propose(len(self.history)))
        return self.best


class RandomSearch(_SearchBase):
    """Uniform sampling in the unit cube (log-uniform for log dims)."""

    def _propose(self, trial_index: int) -> np.ndarray:
        return self.rng.random(self.space.ndim)


# ---------------------------------------------------------------------------
# Gaussian-process surrogate (Matérn-5/2) + expected improvement
# ---------------------------------------------------------------------------


@jax.jit
def _matern52(x1: Array, x2: Array, lengthscale: Array, amplitude: Array) -> Array:
    """Matérn-5/2 kernel matrix (the reference GP's covariance choice)."""
    d2 = jnp.sum((x1[:, None, :] - x2[None, :, :]) ** 2, axis=-1)
    r = jnp.sqrt(jnp.maximum(d2, 1e-30)) / lengthscale
    s5r = jnp.sqrt(5.0) * r
    return amplitude * (1.0 + s5r + 5.0 * d2 / (3.0 * lengthscale**2)) * jnp.exp(-s5r)


@jax.jit
def _gp_log_marginal(x: Array, y: Array, lengthscale: Array, amplitude: Array,
                     noise: Array) -> Array:
    n = x.shape[0]
    k = _matern52(x, x, lengthscale, amplitude) + noise * jnp.eye(n)
    chol = jnp.linalg.cholesky(k)
    alpha = jax.scipy.linalg.cho_solve((chol, True), y)
    return (
        -0.5 * jnp.dot(y, alpha)
        - jnp.sum(jnp.log(jnp.diagonal(chol)))
        - 0.5 * n * jnp.log(2.0 * jnp.pi)
    )


@jax.jit
def _gp_posterior(
    x: Array, y: Array, candidates: Array,
    lengthscale: Array, amplitude: Array, noise: Array,
) -> tuple[Array, Array]:
    """Posterior mean + stddev at candidate points."""
    n = x.shape[0]
    k = _matern52(x, x, lengthscale, amplitude) + noise * jnp.eye(n)
    chol = jnp.linalg.cholesky(k)
    k_star = _matern52(candidates, x, lengthscale, amplitude)
    alpha = jax.scipy.linalg.cho_solve((chol, True), y)
    mean = k_star @ alpha
    v = jax.scipy.linalg.solve_triangular(chol, k_star.T, lower=True)
    var = amplitude - jnp.sum(v * v, axis=0)
    return mean, jnp.sqrt(jnp.maximum(var, 1e-12))


@jax.jit
def _expected_improvement(mean: Array, std: Array, best: Array) -> Array:
    """EI for MINIMIZATION: E[max(best - f, 0)]."""
    z = (best - mean) / std
    cdf = 0.5 * (1.0 + jax.scipy.special.erf(z / jnp.sqrt(2.0)))
    pdf = jnp.exp(-0.5 * z * z) / jnp.sqrt(2.0 * jnp.pi)
    return (best - mean) * cdf + std * pdf


class GaussianProcessSearch(_SearchBase):
    """Bayesian search: Matérn-5/2 GP surrogate + EI acquisition.

    Reference semantics (GaussianProcessSearch [K?], SURVEY.md §2.1): first
    ``num_seed`` trials are random, then each proposal fits the GP to the
    standardized observations (lengthscale chosen by marginal likelihood over
    a log grid) and picks the EI-argmax over a fresh random candidate set.
    """

    def __init__(
        self,
        space: SearchSpace,
        evaluation_function: Callable[[Dict[str, float]], float],
        maximize: bool = False,
        seed: int = 0,
        num_seed_trials: int = 3,
        num_candidates: int = 2048,
        noise: float = 1e-6,
    ):
        super().__init__(space, evaluation_function, maximize, seed)
        self.num_seed_trials = max(2, num_seed_trials)
        self.num_candidates = num_candidates
        self.noise = noise
        self._lengthscale_grid = np.geomspace(0.05, 2.0, 8)

    def _propose(self, trial_index: int) -> np.ndarray:
        if trial_index < self.num_seed_trials:
            return self.rng.random(self.space.ndim)

        x, y = self._observed()
        # Standardize targets so fixed amplitude=1 is a reasonable prior.
        y_mean, y_std = y.mean(), max(y.std(), 1e-12)
        y_n = (y - y_mean) / y_std

        xj = jnp.asarray(x)
        yj = jnp.asarray(y_n)
        amplitude = jnp.asarray(1.0)
        noise = jnp.asarray(self.noise)
        best_ls, best_ml = None, -np.inf
        for ls in self._lengthscale_grid:
            ml = float(_gp_log_marginal(xj, yj, jnp.asarray(ls), amplitude, noise))
            if np.isfinite(ml) and ml > best_ml:
                best_ls, best_ml = ls, ml
        if best_ls is None:  # degenerate observations: fall back to random
            return self.rng.random(self.space.ndim)

        candidates = self.rng.random((self.num_candidates, self.space.ndim))
        mean, std = _gp_posterior(
            xj, yj, jnp.asarray(candidates), jnp.asarray(best_ls), amplitude, noise
        )
        ei = _expected_improvement(mean, std, jnp.asarray(y_n.min()))
        return candidates[int(np.argmax(np.asarray(ei)))]
