"""Hyperparameter search (reference: photon-lib ``hyperparameter/`` —
``RandomSearch``, ``GaussianProcessSearch`` with a Matérn-5/2 GP and
expected-improvement acquisition; SURVEY.md §2.1, §3.5)."""

from photon_tpu.hyperparameter.search import (
    EvaluationRecord,
    GaussianProcessSearch,
    RandomSearch,
    SearchDimension,
    SearchSpace,
)

__all__ = [
    "EvaluationRecord",
    "GaussianProcessSearch",
    "RandomSearch",
    "SearchDimension",
    "SearchSpace",
]
