"""Fault tolerance for GAME training: checkpoint/resume, fault injection,
retry with backoff, and quarantine-based graceful degradation.

The pieces (see each module's docstring for the full story):

- :mod:`photon_tpu.fault.checkpoint` — preemption-safe per-outer-iteration
  descent checkpoints (atomic, versioned, manifest-hashed) + resume.
- :mod:`photon_tpu.fault.injection` — deterministic, seedable
  :class:`FaultPlan` (``PHOTON_FAULTS`` / ``--faults``) injecting IO errors,
  inter-iteration kills, and NaN solves at named sites, so the recovery
  paths are CI-testable.
- :mod:`photon_tpu.fault.retry` — jittered, capped, telemetry-counted
  exponential backoff around guarded IO (with optional per-attempt stall
  timeouts escalating hung calls to retriable failures).
- :mod:`photon_tpu.fault.atomic` — write-to-temp + fsync + rename
  publication and content-hash manifests.
- :mod:`photon_tpu.fault.preemption` — SIGTERM/SIGINT → checkpoint at the
  next iteration boundary → exit :data:`PREEMPTED_EXIT_CODE` (the elastic
  spot/preemptible-capacity story; ``--on-preempt``).
- :mod:`photon_tpu.fault.watchdog` — heartbeat-based stall detection
  (``watchdog.stalled`` telemetry) and the guarded-IO timeout
  (``--stall-timeout``).

:class:`QuarantineBudgetError` is raised by the descent loop when more
buckets/coordinates were quarantined (non-finite solves or score rows kept
at their previous iterate) than the run's ``--max-quarantined`` budget
allows.
"""

from photon_tpu.fault.atomic import (  # noqa: F401
    CorruptArtifactError,
    atomic_dir,
    atomic_write_bytes,
    atomic_write_json,
    verify_manifest,
    write_manifest,
)
from photon_tpu.fault.checkpoint import (  # noqa: F401
    AsyncPublisher,
    CheckpointError,
    DescentCheckpointer,
    DescentState,
    StreamCheckpointer,
    StreamState,
    has_published_checkpoint,
    resolve_checkpoint_async,
)
from photon_tpu.fault.injection import (  # noqa: F401
    KNOWN_FAULT_SITES,
    FaultPlan,
    InjectedFaultError,
    InjectedIOError,
    InjectedKillError,
    active_plan,
    consume_nan_injection,
    fault_point,
    install_from_args,
    set_plan,
)
from photon_tpu.fault.preemption import (  # noqa: F401
    PREEMPTED_EXIT_CODE,
    PreemptedError,
    PreemptionHandler,
    clear_preemption,
    preemption_requested,
    request_preemption,
)
from photon_tpu.fault.retry import (  # noqa: F401
    RETRY_TOTALS,
    RetryPolicy,
    default_policy,
    retry_call,
)
from photon_tpu.fault.watchdog import (  # noqa: F401
    IOStallTimeoutError,
    Watchdog,
    call_with_timeout,
    heartbeat,
)


class QuarantineBudgetError(RuntimeError):
    """More non-finite solves/score rows were quarantined than the
    ``--max-quarantined`` budget tolerates; the run fails loudly instead of
    silently training a mostly-frozen model."""
