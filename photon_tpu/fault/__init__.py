"""Fault tolerance for GAME training: checkpoint/resume, fault injection,
retry with backoff, and quarantine-based graceful degradation.

The pieces (see each module's docstring for the full story):

- :mod:`photon_tpu.fault.checkpoint` — preemption-safe per-outer-iteration
  descent checkpoints (atomic, versioned, manifest-hashed) + resume.
- :mod:`photon_tpu.fault.injection` — deterministic, seedable
  :class:`FaultPlan` (``PHOTON_FAULTS`` / ``--faults``) injecting IO errors,
  inter-iteration kills, and NaN solves at named sites, so the recovery
  paths are CI-testable.
- :mod:`photon_tpu.fault.retry` — jittered, capped, telemetry-counted
  exponential backoff around guarded IO.
- :mod:`photon_tpu.fault.atomic` — write-to-temp + fsync + rename
  publication and content-hash manifests.

:class:`QuarantineBudgetError` is raised by the descent loop when more
buckets/coordinates were quarantined (non-finite solves or score rows kept
at their previous iterate) than the run's ``--max-quarantined`` budget
allows.
"""

from photon_tpu.fault.atomic import (  # noqa: F401
    CorruptArtifactError,
    atomic_dir,
    atomic_write_bytes,
    atomic_write_json,
    verify_manifest,
    write_manifest,
)
from photon_tpu.fault.checkpoint import (  # noqa: F401
    AsyncPublisher,
    CheckpointError,
    DescentCheckpointer,
    DescentState,
    StreamCheckpointer,
    StreamState,
    has_published_checkpoint,
    resolve_checkpoint_async,
)
from photon_tpu.fault.injection import (  # noqa: F401
    FaultPlan,
    InjectedFaultError,
    InjectedIOError,
    InjectedKillError,
    active_plan,
    consume_nan_injection,
    fault_point,
    install_from_args,
    set_plan,
)
from photon_tpu.fault.retry import (  # noqa: F401
    RETRY_TOTALS,
    RetryPolicy,
    default_policy,
    retry_call,
)


class QuarantineBudgetError(RuntimeError):
    """More non-finite solves/score rows were quarantined than the
    ``--max-quarantined`` budget tolerates; the run fails loudly instead of
    silently training a mostly-frozen model."""
