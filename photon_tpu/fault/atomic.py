"""Atomic, torn-write-proof filesystem publication.

Every durable artifact the fault-tolerance layer owns (checkpoints, model
directories, pointer files) is published with the same protocol: build the
content somewhere invisible, fsync it, then make it visible with ONE atomic
``rename`` — so a kill at any instant leaves either the previous complete
artifact or the new complete artifact, never a torn hybrid.  Directory
artifacts additionally carry a ``manifest.json`` of content hashes written
LAST, so a reader can verify completeness (and bit-rot) before trusting a
checkpoint.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import shutil
import tempfile
from typing import Dict, Iterator, Optional

MANIFEST_NAME = "manifest.json"


class CorruptArtifactError(RuntimeError):
    """A directory artifact failed manifest verification."""


def fsync_dir(path: str) -> None:
    """fsync a directory so a completed rename survives power loss (no-op
    on filesystems that reject directory fds)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Write ``path`` via temp file + fsync + rename in the same directory."""
    parent = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(
        dir=parent, prefix=f".{os.path.basename(path)}.tmp-"
    )
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.remove(tmp)
        raise
    fsync_dir(parent)


def atomic_write_json(path: str, obj) -> None:
    atomic_write_bytes(path, json.dumps(obj, indent=1).encode())


def file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def _walk_files(dir_path: str) -> Iterator[str]:
    for root, _, files in os.walk(dir_path):
        for name in sorted(files):
            yield os.path.relpath(os.path.join(root, name), dir_path)


def write_manifest(dir_path: str, extra: Optional[dict] = None) -> dict:
    """Hash every file under ``dir_path`` into ``manifest.json`` (written
    last, atomically) — the completeness marker of a directory artifact."""
    files: Dict[str, str] = {
        rel: file_sha256(os.path.join(dir_path, rel))
        for rel in _walk_files(dir_path)
        if rel != MANIFEST_NAME
    }
    manifest = {"version": 1, "files": files}
    if extra:
        manifest["extra"] = extra
    atomic_write_json(os.path.join(dir_path, MANIFEST_NAME), manifest)
    return manifest


def verify_manifest(dir_path: str) -> dict:
    """Check ``dir_path`` against its manifest; returns the manifest.
    Raises :class:`CorruptArtifactError` on a missing manifest, missing
    file, or content-hash mismatch."""
    mpath = os.path.join(dir_path, MANIFEST_NAME)
    if not os.path.isfile(mpath):
        raise CorruptArtifactError(f"{dir_path}: no {MANIFEST_NAME}")
    with open(mpath) as f:
        manifest = json.load(f)
    for rel, digest in manifest.get("files", {}).items():
        fpath = os.path.join(dir_path, rel)
        if not os.path.isfile(fpath):
            raise CorruptArtifactError(f"{dir_path}: missing {rel}")
        if file_sha256(fpath) != digest:
            raise CorruptArtifactError(f"{dir_path}: content mismatch in {rel}")
    return manifest


def _fsync_tree(dir_path: str) -> None:
    for rel in _walk_files(dir_path):
        try:
            fd = os.open(os.path.join(dir_path, rel), os.O_RDONLY)
        except OSError:
            continue
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    fsync_dir(dir_path)


@contextlib.contextmanager
def atomic_dir(final_path: str) -> Iterator[str]:
    """Build a directory artifact atomically: yields a temp build dir next
    to ``final_path``; on clean exit the tree is fsynced and renamed into
    place (an existing destination is parked aside first and removed only
    after the new directory is live).  On error the temp dir is removed and
    the previous artifact is untouched.

    A kill during the body leaves only an invisible ``.tmp-*`` dir; a kill
    between the aside-rename and the publish-rename leaves the destination
    briefly missing but both complete trees on disk — never a torn mix.
    An in-process publish failure in that window renames the previous
    artifact back into place before re-raising.
    """
    final_path = os.path.abspath(final_path)
    parent = os.path.dirname(final_path)
    os.makedirs(parent, exist_ok=True)
    tmp = tempfile.mkdtemp(
        dir=parent, prefix=f".tmp-{os.path.basename(final_path)}-"
    )
    try:
        yield tmp
        _fsync_tree(tmp)
        aside = None
        if os.path.lexists(final_path):
            aside = tempfile.mktemp(
                dir=parent, prefix=f".old-{os.path.basename(final_path)}-"
            )
            os.rename(final_path, aside)
        try:
            os.rename(tmp, final_path)
        except BaseException:
            if aside is not None:
                # Publish failed after the previous artifact was parked
                # aside: put it back so the published path never loses its
                # last complete copy to an in-process error.
                with contextlib.suppress(OSError):
                    os.rename(aside, final_path)
            raise
        fsync_dir(parent)
        if aside is not None:
            shutil.rmtree(aside, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
